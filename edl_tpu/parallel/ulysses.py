"""Ulysses sequence parallelism — all-to-all head/sequence exchange.

The second long-context strategy (SURVEY §5 lists both: "ring-attention
over ICI neighbor exchange; Ulysses-style all-to-all within a slice";
the reference has neither). Inputs arrive sequence-sharded over the
``sp`` axis; an all-to-all re-shards them over attention heads so every
device computes *full-sequence* attention for ``H / sp`` heads, and a
second all-to-all restores sequence sharding. Two collectives per
attention call (vs one ppermute per ring step) but each device sees the
whole sequence, so any attention kernel — including the pallas flash
kernel — drops in unchanged.

Trade-off vs ring attention: Ulysses is bandwidth-cheaper for moderate
sequence lengths inside one slice (all-to-all rides full ICI bisection),
while ring attention overlaps compute with neighbor exchange and scales
past the head-count limit (sp must divide n_heads here).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from jax import shard_map

from edl_tpu.parallel.ring_attention import reference_attention


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Attention over sequence-sharded [B, S, H, d] q/k/v.

    S is the *global* sequence length (each device holds S/sp); H must
    be divisible by the ``axis`` size. Returns output with the same
    sequence sharding as q.
    """
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(f"n_heads={q.shape[2]} not divisible by {axis}={n}")
    # GQA: K/V ride the all-to-all at their (smaller) kv-head width and
    # expand only locally, after the exchange — when kv_heads divides
    # the axis; otherwise expand up front (correct, more bytes)
    kv_heads = k.shape[2]
    if kv_heads % n and q.shape[2] != kv_heads:
        rep = q.shape[2] // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # batch dim keeps whatever data-axis sharding it has (as ring_attention)
    other = tuple(a for a in mesh.axis_names if a != axis)
    spec = P(tuple(a for a in other if a in ("dp", "fsdp")) or None, axis, None, None)

    def local(q, k, v):
        out_dtype = q.dtype

        # [B, S/n, H, d] --all-to-all--> [B, S, H/n, d]  (activation-dtype
        # bytes on the wire; the f32 upcast happens after the exchange)
        def scatter_heads(x):
            return jax.lax.all_to_all(
                x, axis, split_axis=2, concat_axis=1, tiled=True
            )

        q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
        s_global = q.shape[1]
        from edl_tpu.ops.flash_attention import attention_auto, flash_supported

        if jax.devices()[0].platform == "tpu" and flash_supported(s_global):
            # full-sequence attention on the local head shard via the
            # blockwise pallas kernel (GQA-native, O(S) memory) — the
            # whole point of Ulysses: any single-device kernel drops in
            o = attention_auto(q, k, v, causal=causal)
        else:
            # oracle fallback (tests / unsupported lengths): f32
            # softmax (the bf16-drift guard ring_attention documents),
            # O(S^2) scores — fine at test scale only
            if k.shape[2] != q.shape[2]:  # expand GQA groups
                k = jnp.repeat(k, q.shape[2] // k.shape[2], axis=2)
                v = jnp.repeat(v, q.shape[2] // v.shape[2], axis=2)
            o = reference_attention(
                q.astype(jnp.float32),
                k.astype(jnp.float32),
                v.astype(jnp.float32),
                causal=causal,
            ).astype(out_dtype)
        # [B, S, H/n, d] --all-to-all--> [B, S/n, H, d]
        return jax.lax.all_to_all(o, axis, split_axis=1, concat_axis=2, tiled=True)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
