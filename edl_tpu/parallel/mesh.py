"""Device mesh construction — the parallelism axes of the framework.

The reference's only parallelism is pserver data-parallelism over TCP
(SURVEY §2.5); here every strategy is a first-class mesh axis over
ICI/DCN, consumed by ``jax.jit`` shardings, ``shard_map`` collectives,
or both:

    dp    pure data parallel (params replicated, grads all-reduced)
    pp    pipeline stages (ppermute neighbor transfer)
    fsdp  fully-sharded data parallel (ZeRO-3: params/grads/opt sharded)
    sp    sequence/context parallel (ring attention)
    ep    expert parallel (MoE all-to-all)
    tp    tensor parallel (innermost: highest-bandwidth ICI)

Axis order is fixed outermost→innermost so that tp lands on the
fastest ICI neighbors and dp/pp can cross DCN (the scaling-book
layout recipe).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.api.job import BATCH_AXES, MeshSpec

AXIS_ORDER: Tuple[str, ...] = ("dp", "pp", "fsdp", "sp", "ep", "tp")


@dataclass(frozen=True)
class MeshPlan:
    """A named-axis factorization of a device count."""

    axes: Tuple[Tuple[str, int], ...]  # ordered (name, size), all sizes >= 1

    @classmethod
    def create(cls, **sizes: int) -> "MeshPlan":
        bad = set(sizes) - set(AXIS_ORDER)
        if bad:
            raise ValueError(f"unknown mesh axes {sorted(bad)}")
        axes = tuple((a, int(sizes.get(a, 1))) for a in AXIS_ORDER if sizes.get(a, 1) > 1)
        return cls(axes=axes if axes else (("dp", 1),))

    @classmethod
    def from_spec(cls, spec: MeshSpec, n_devices: int) -> "MeshPlan":
        """Complete a user MeshSpec against an actual device count: the
        given axes must divide ``n_devices``; the remainder goes to dp
        (elastic growth lands on the data axis)."""
        sizes = spec.axis_sizes()
        prod = math.prod(sizes.values()) if sizes else 1
        if n_devices % prod:
            raise ValueError(
                f"mesh axes {sizes} (={prod}) do not divide {n_devices} devices"
            )
        rest = n_devices // prod
        sizes["dp"] = sizes.get("dp", 1) * rest
        return cls.create(**sizes)

    @classmethod
    def parse(cls, mesh: str, n_devices: int) -> "MeshPlan":
        """Parse an elastic mesh string against a device count.

        Grammar: comma-separated axis terms. ``axis=K`` pins a fixed
        size; a bare ``axis`` name declares the GROWTH axis that
        absorbs whatever device count the elastic membership currently
        provides (default ``dp``). Examples::

            "dp"             all devices data-parallel
            "fsdp"           all devices ZeRO-3 (the flagship config)
            "fsdp,tp=2"      tp pinned at 2, fsdp grows with the job
            "fsdp=2,tp=2"    both pinned; remainder grows on dp

        This is the EDL_MESH env contract consumed by the worker
        runtime (the TPU analog of the reference's fixed
        --trainer_count, docker/paddle_k8s:206 — here the axis layout
        survives elastic rescale because one axis is declared free).
        """
        s = (mesh or "dp").strip()
        fixed: Dict[str, int] = {}
        grow = "dp"
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                k, v = part.split("=", 1)
                size = int(v)
                if size < 1:
                    raise ValueError(
                        f"mesh axis size must be >= 1: {part!r} in {s!r}"
                    )
                fixed[k.strip()] = size
            else:
                grow = part
        unknown = (set(fixed) | {grow}) - set(AXIS_ORDER)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)} in {s!r}")
        if grow in fixed:
            raise ValueError(f"axis {grow!r} is both fixed and the growth axis")
        if grow not in BATCH_AXES:
            # a non-batch growth axis would change _local_rows without
            # changing the queue chunk, silently truncating every leased
            # task after a rescale — only batch axes may absorb
            # membership change
            raise ValueError(
                f"growth axis must be one of {BATCH_AXES}, got {grow!r}"
            )
        prod = math.prod(fixed.values()) if fixed else 1
        if n_devices % prod:
            raise ValueError(
                f"fixed mesh axes {fixed} (={prod}) do not divide "
                f"{n_devices} devices"
            )
        sizes = dict(fixed)
        sizes[grow] = n_devices // prod
        return cls.create(**sizes)

    @classmethod
    def data_parallel(cls, n_devices: int) -> "MeshPlan":
        return cls.create(dp=n_devices)

    @classmethod
    def fsdp_only(cls, n_devices: int) -> "MeshPlan":
        return cls.create(fsdp=n_devices)

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(a for a, _ in self.axes)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(s for _, s in self.axes)

    def size(self) -> int:
        return math.prod(self.shape)

    def axis_size(self, name: str) -> int:
        for a, s in self.axes:
            if a == name:
                return s
        return 1

    def batch_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in self.names if a in BATCH_AXES)

    def batch_shards(self) -> int:
        return math.prod(self.axis_size(a) for a in self.batch_axes()) or 1

    # -- construction ------------------------------------------------------

    # axes that may cross a slice boundary (ride DCN): the batch-ish
    # outer axes, whose collectives are an all-reduce per step (dp) or a
    # once-per-microbatch neighbor transfer (pp). Everything inner
    # (fsdp/sp/ep/tp) does per-layer collectives and must stay on ICI.
    DCN_AXES: Tuple[str, ...] = ("dp", "pp")

    def build(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        slices: Optional[Sequence[int]] = None,
    ) -> Mesh:
        """Materialize a ``jax.sharding.Mesh``. Devices default to all
        local devices; an elastic reshard passes the surviving subset.

        Multi-slice topology (SURVEY §2.5 comm-backend row, §7(c)):
        when the devices span >1 TPU slice — detected from each
        device's ``slice_index``, or declared via ``slices`` (a
        parallel list of slice ids, the virtual-topology hook for
        tests/dryruns) — devices are ordered slice-major so the
        DCN-tolerant outer axes (dp, pp — first in AXIS_ORDER) vary
        ACROSS slices while fsdp/sp/ep/tp stay inside one slice's ICI.
        The build fails loudly if an inner-axis block would straddle a
        slice boundary (a per-layer collective over DCN is a config
        error, not a degraded mode)."""
        devs = list(devices) if devices is not None else list(jax.devices())
        n = self.size()
        if len(devs) < n:
            raise ValueError(f"mesh needs {n} devices, have {len(devs)}")
        if slices is not None:
            if len(slices) != len(devs):
                raise ValueError(
                    f"slices has {len(slices)} entries for {len(devs)} devices"
                )
            slice_of = dict(zip([id(d) for d in devs], slices))
            get_slice = lambda d: slice_of[id(d)]
        else:
            get_slice = lambda d: getattr(d, "slice_index", None)
        marks = [get_slice(d) for d in devs]
        multi = len({m for m in marks if m is not None}) > 1
        if multi:
            # slice-major order: a stable sort keeps the intra-slice
            # device order (ICI neighbors stay adjacent)
            devs = sorted(devs, key=lambda d: (get_slice(d) is None, get_slice(d)))
        devs = devs[:n]
        arr = np.array(devs).reshape(self.shape)
        if multi:
            self._check_slice_alignment(arr, get_slice)
        return Mesh(arr, self.names)

    def _check_slice_alignment(self, arr: np.ndarray, get_slice) -> None:
        """Every inner-axis block (all axes after dp/pp) must live in
        ONE slice; dp/pp coordinates may map to different slices."""
        outer = math.prod(
            s for a, s in self.axes if a in self.DCN_AXES
        ) or 1
        flat = arr.reshape(outer, -1)
        for row in range(flat.shape[0]):
            row_slices = {get_slice(d) for d in flat[row]}
            if len(row_slices) > 1:
                raise ValueError(
                    f"mesh axes {dict(self.axes)} straddle a slice "
                    f"boundary: inner (ICI) axes map onto slices "
                    f"{sorted(map(str, row_slices))}. Only "
                    f"{self.DCN_AXES} may cross slices — shrink the "
                    f"inner axes to fit one slice or grow dp/pp"
                )

    def slice_layout(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        slices: Optional[Sequence[int]] = None,
    ) -> Dict[str, int]:
        """{slice id -> device count} for observability/docs."""
        devs = list(devices) if devices is not None else list(jax.devices())
        if slices is None:
            marks = [getattr(d, "slice_index", None) for d in devs]
        else:
            marks = list(slices)
        out: Dict[str, int] = {}
        for m in marks:
            out[str(m)] = out.get(str(m), 0) + 1
        return out

    # -- shardings ---------------------------------------------------------

    def batch_pspec(self) -> P:
        """Batch dimension split over every batch axis, rest replicated."""
        ba = self.batch_axes()
        return P(ba if ba else None)

    def batch_sharding(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.batch_pspec())

    def sequence_pspec(self, rank: int = 2) -> P:
        """[B, T, ...] activations: batch dim over the batch axes, the
        sequence dim over ``sp`` (context parallelism), rest replicated.
        This is the activation layout of an sp-sharded training step —
        models apply it via ``with_sharding_constraint`` right after the
        embedding lookup so every downstream op (and the ring/Ulysses
        attention shard_map) sees sequence-sharded activations."""
        ba = self.batch_axes()
        sp = "sp" if self.axis_size("sp") > 1 else None
        return P(ba if ba else None, sp, *(None,) * (rank - 2))

    def sequence_sharding(self, mesh: Mesh, rank: int = 2) -> NamedSharding:
        return NamedSharding(mesh, self.sequence_pspec(rank))

    def replicated(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, P())

    def describe(self) -> Dict[str, int]:
        return dict(self.axes)
