"""Pipeline parallelism — GPipe microbatch schedule over the ``pp`` axis.

Absent from the reference (SURVEY §2.5: "Pipeline parallelism: NO").
Stage parameters carry a leading [n_stages] axis sharded over pp (each
device materializes only its stage); activations flow stage-to-stage
with ``ppermute`` (ICI neighbor transfer). The schedule is the classic
GPipe fill-drain loop: n_micro + n_stages - 1 ticks, bubble fraction
(n_stages-1)/(n_micro+n_stages-1).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    mesh: Mesh,
    axis: str = "pp",
    data_axes: tuple = (),
):
    """Run ``x`` through n_stages of ``stage_fn`` spread over the pp axis.

    stage_params : pytree whose leaves have leading dim n_stages
                   (sharded P(axis, ...)).
    x : [n_micro, mb, ...] microbatched input. With ``data_axes`` (e.g.
        ``("dp", "fsdp")``) the mb dim stays sharded over those mesh
        axes — each dp group runs its own pipeline on its own rows, so
        pp composes with data parallelism without gathering the batch.
    Returns [n_micro, mb, ...] outputs of the last stage, same sharding
    as ``x`` (replicated over pp).
    """
    n = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n:
            raise ValueError(
                f"stage param leading dim {leaf.shape[0]} != pp axis size {n}"
            )

    pspec = jax.tree_util.tree_map(
        lambda l: P(axis, *(None,) * (l.ndim - 1)), stage_params
    )
    da = tuple(a for a in data_axes if a in mesh.axis_names and mesh.shape[a] > 1)
    xspec = P(None, da or None, *(None,) * (x.ndim - 2))

    def local(params, xm):
        # params leaves: [1, ...] (this device's stage); squeeze
        p = jax.tree_util.tree_map(lambda l: l[0], params)
        stage = jax.lax.axis_index(axis)
        n_micro = xm.shape[0]
        total = n_micro + n - 1
        mb_shape = xm.shape[1:]
        perm_fwd = [(j, (j + 1) % n) for j in range(n)]

        def tick(t, carry):
            buf, out = carry
            # stage 0 feeds microbatch t (while available); others take
            # the activation passed from the previous stage
            feed = xm[jnp.minimum(t, n_micro - 1)]
            inp = jnp.where(stage == 0, feed, buf)
            y = stage_fn(p, inp)
            # last stage collects finished microbatch t-(n-1)
            idx = t - (n - 1)
            out = jax.lax.cond(
                idx >= 0,
                lambda o: jax.lax.dynamic_update_index_in_dim(o, y, jnp.maximum(idx, 0), 0),
                lambda o: o,
                out,
            )
            # pass activations to the next stage
            buf = jax.lax.ppermute(y, axis, perm_fwd)
            return buf, out

        buf0 = jnp.zeros(mb_shape, xm.dtype)
        out0 = jnp.zeros((n_micro,) + mb_shape, xm.dtype)
        _, out = jax.lax.fori_loop(0, total, tick, (buf0, out0))
        # `out` is populated only on the last stage; replicate it to all
        # stages (zero elsewhere, so a psum is a broadcast)
        mask = (stage == n - 1).astype(out.dtype)
        return jax.lax.psum(out * mask, axis)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(pspec, xspec),
        out_specs=xspec,
        check_vma=False,
    )(stage_params, x)
