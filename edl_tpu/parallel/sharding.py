"""Parameter/activation sharding rules.

Replaces the reference's pserver parameter blocks (parameters split
round-robin across pserver processes, SURVEY §2.5 "proto-TP") with
XLA-native named shardings: each array gets a PartitionSpec derived
from the mesh plan, XLA inserts the collectives. FSDP here is the
ZeRO-3 analog the reference lacks (required for the Llama elastic-FSDP
baseline config).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.parallel.mesh import MeshPlan


def fsdp_pspec(shape, fsdp_size: int, tp_size: int = 1, axis: str = "fsdp") -> P:
    """ZeRO-3 placement for one param: shard the largest dimension
    divisible by the fsdp axis; replicate if nothing divides (small
    params — biases, norm scales — stay replicated, which is what
    you want on TPU: no gather traffic for tiny arrays)."""
    if fsdp_size <= 1 or not shape:
        return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % fsdp_size == 0:
            spec: list = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


def fit_pspec(plan: MeshPlan, shape, *axes) -> P:
    """PartitionSpec placing each dim on its requested axis ONLY when
    the axis divides that dim — an elastic world is not always a power
    of two, and a 6-way fsdp mesh must still compile; the undivisible
    dim is replicated on that axis instead. Shared by every model's
    param_pspecs (models/llama.py, models/moe.py)."""
    parts = []
    for dim, ax in zip(shape, axes):
        ok = ax is not None and dim % plan.axis_size(ax) == 0
        parts.append(ax if ok else None)
    return P(*parts)


def param_pspecs(params, plan: MeshPlan) -> Any:
    """Pytree of PartitionSpecs for a param tree: fsdp sharding when the
    plan has an fsdp axis, else fully replicated (dp). Models with tensor
    parallelism provide their own specs instead (see models/llama.py)."""
    fsdp = plan.axis_size("fsdp")
    return jax.tree_util.tree_map(
        lambda p: fsdp_pspec(getattr(p, "shape", ()), fsdp), params
    )


def named(tree, mesh: Mesh):
    """PartitionSpec tree → NamedSharding tree."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_tree(tree, mesh: Mesh, pspecs) -> Any:
    """Place a host/device pytree onto the mesh with the given specs
    (the reshard primitive: jax.device_put with NamedSharding moves or
    re-slices as needed)."""
    shardings = named(pspecs, mesh)
    # single whole-tree device_put: all host→device transfers are
    # dispatched before any result is awaited (per-leaf puts serialize)
    return jax.device_put(tree, shardings)


_CHUNK_BYTES = 8 << 20  # split large leaves into ~8 MB transfer streams
_CHUNK_WINDOW = 8  # in-flight chunks per leaf; bounds extra HBM to ~64 MB


def _is_single_device(x) -> bool:
    sharding = getattr(x, "sharding", None)
    return sharding is not None and len(sharding.device_set) == 1


def to_host(tree) -> Any:
    """Fetch a (possibly sharded) pytree fully to host memory — the
    checkpoint-in-RAM half of the reshard protocol. Ordinary leaves go
    through one whole-tree ``jax.device_get`` so their device→host
    copies are issued asynchronously before any blocks (per-leaf
    fetches serialize). Large single-device leaves are streamed in
    ~8 MB row chunks, round-robin across leaves with a bounded
    in-flight window: concurrent transfer streams on slow links, at
    most ~_CHUNK_WINDOW chunks of extra HBM, and each chunk lands
    directly in a preallocated host buffer (no concat double-copy).
    Sharded arrays always fetch shard-direct and whole: slicing them
    would insert collectives."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    chunked = {}  # leaf index -> row step
    to_fetch: list = []
    for i, x in enumerate(leaves):
        nbytes = getattr(x, "nbytes", 0)
        shape = getattr(x, "shape", ())
        if (
            nbytes > 2 * _CHUNK_BYTES
            and shape
            and shape[0] > 1
            and _is_single_device(x)
        ):
            n = min(shape[0], max(2, nbytes // _CHUNK_BYTES))
            chunked[i] = -(-shape[0] // n)  # ceil: rows per chunk
            to_fetch.append(None)
        else:
            to_fetch.append(x)

    # Round-robin (leaf, row_start) schedule so every chunked leaf's
    # stream makes progress inside the window, not one leaf at a time.
    tasks: list = []
    cursors = {i: 0 for i in chunked}
    while cursors:
        for i in list(cursors):
            s = cursors[i]
            if s >= leaves[i].shape[0]:
                del cursors[i]
                continue
            tasks.append((i, s))
            cursors[i] = s + chunked[i]

    outs = {
        i: np.empty(leaves[i].shape, leaves[i].dtype) for i in chunked
    }
    pending: list = []  # (leaf index, row start, device chunk)

    def _land(i: int, s: int, chunk) -> None:
        outs[i][s : s + chunked[i]] = np.asarray(chunk)

    # Prime the window before the blocking whole-tree get so chunk
    # streams overlap the ordinary-leaf transfers.
    head, rest = tasks[:_CHUNK_WINDOW], tasks[_CHUNK_WINDOW:]
    for i, s in head:
        c = jax.lax.slice_in_dim(
            leaves[i], s, min(s + chunked[i], leaves[i].shape[0]), axis=0
        )
        c.copy_to_host_async()
        pending.append((i, s, c))
    fetched = jax.device_get(to_fetch)
    for i, s in rest:
        c = jax.lax.slice_in_dim(
            leaves[i], s, min(s + chunked[i], leaves[i].shape[0]), axis=0
        )
        c.copy_to_host_async()
        pending.append((i, s, c))
        if len(pending) >= _CHUNK_WINDOW:
            _land(*pending.pop(0))
    for entry in pending:
        _land(*entry)

    for i in chunked:
        fetched[i] = outs[i]
    return jax.tree_util.tree_unflatten(
        treedef, [np.asarray(x) for x in fetched]
    )


def _dim0_parts(sh, shape) -> int:
    """How many ways the target sharding splits dimension 0."""
    if not shape:
        return 1
    try:
        return max(1, shape[0] // sh.shard_shape(tuple(shape))[0])
    # edl: no-lint[silent-failure] sharding-geometry probe: 1 (unsplit) is the safe fallback answer
    except Exception:
        return 1


def stream_reshard(leaves, sh_leaves) -> list:
    """Device → host → device as one overlapped pipeline — the host
    fallback of the reshard protocol (the ``to_host`` + ``shard_tree``
    pair collapsed so uploads of landed pieces overlap the remaining
    downloads on a full-duplex link; stall → max(d2h, h2d), not sum).

    Policies shared with :func:`to_host`: big SINGLE-device leaves are
    row-split into ~``_CHUNK_BYTES`` pieces with at most
    ``_CHUNK_WINDOW`` device→host copies in flight; multi-device
    (sharded) leaves always move whole and shard-direct — slicing them
    would compile a cross-device gather on the very mesh being
    evacuated. Piece row counts are rounded up to the TARGET sharding's
    dim-0 partition count so every per-piece ``device_put`` divides
    evenly (an fsdp-sharded destination rejects ragged pieces).
    """
    schedule = []  # (leaf_idx, row_start, row_end) — None row = whole
    for i, x in enumerate(leaves):
        nbytes = getattr(x, "nbytes", 0)
        shape = getattr(x, "shape", ())
        rows = None
        if nbytes > 2 * _CHUNK_BYTES and shape and shape[0] > 1 and (
            _is_single_device(x)
        ):
            n = min(shape[0], max(2, nbytes // _CHUNK_BYTES))
            rows = -(-shape[0] // n)
            div = _dim0_parts(sh_leaves[i], shape)
            if shape[0] % div == 0:
                rows = -(-rows // div) * div  # piece splits evenly
            else:  # ragged target split: give up on piecing this leaf
                rows = None
        if rows is None or rows >= shape[0]:
            schedule.append((i, None, None))
        else:
            for s in range(0, shape[0], rows):
                schedule.append((i, s, min(s + rows, shape[0])))

    uploaded: dict = {}
    pending: list = []  # (leaf_idx, device_piece)

    def _land() -> None:
        i, p = pending.pop(0)
        h = np.asarray(p)  # blocks for THIS piece only
        uploaded.setdefault(i, []).append(jax.device_put(h, sh_leaves[i]))

    for i, s, e in schedule:
        if len(pending) >= _CHUNK_WINDOW:
            _land()
        p = (
            leaves[i]
            if s is None
            else jax.lax.slice_in_dim(leaves[i], s, e, axis=0)
        )
        if hasattr(p, "copy_to_host_async"):
            p.copy_to_host_async()
        pending.append((i, p))
    while pending:
        _land()

    out = []
    for i in range(len(leaves)):
        parts = uploaded[i]
        if len(parts) == 1:
            out.append(parts[0])
        else:
            # concat runs on the target devices (HBM-speed); re-put pins
            # the exact target sharding (concat's inferred may differ)
            out.append(
                jax.device_put(
                    jax.numpy.concatenate(parts, axis=0), sh_leaves[i]
                )
            )
    return out
