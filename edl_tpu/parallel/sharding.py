"""Parameter/activation sharding rules.

Replaces the reference's pserver parameter blocks (parameters split
round-robin across pserver processes, SURVEY §2.5 "proto-TP") with
XLA-native named shardings: each array gets a PartitionSpec derived
from the mesh plan, XLA inserts the collectives. FSDP here is the
ZeRO-3 analog the reference lacks (required for the Llama elastic-FSDP
baseline config).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.parallel.mesh import MeshPlan


def fsdp_pspec(shape, fsdp_size: int, tp_size: int = 1, axis: str = "fsdp") -> P:
    """ZeRO-3 placement for one param: shard the largest dimension
    divisible by the fsdp axis; replicate if nothing divides (small
    params — biases, norm scales — stay replicated, which is what
    you want on TPU: no gather traffic for tiny arrays)."""
    if fsdp_size <= 1 or not shape:
        return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % fsdp_size == 0:
            spec: list = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


def param_pspecs(params, plan: MeshPlan) -> Any:
    """Pytree of PartitionSpecs for a param tree: fsdp sharding when the
    plan has an fsdp axis, else fully replicated (dp). Models with tensor
    parallelism provide their own specs instead (see models/llama.py)."""
    fsdp = plan.axis_size("fsdp")
    return jax.tree_util.tree_map(
        lambda p: fsdp_pspec(getattr(p, "shape", ()), fsdp), params
    )


def named(tree, mesh: Mesh):
    """PartitionSpec tree → NamedSharding tree."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_tree(tree, mesh: Mesh, pspecs) -> Any:
    """Place a host/device pytree onto the mesh with the given specs
    (the reshard primitive: jax.device_put with NamedSharding moves or
    re-slices as needed)."""
    shardings = named(pspecs, mesh)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), tree, shardings
    )


def to_host(tree) -> Any:
    """Fetch a (possibly sharded) pytree fully to host memory — the
    checkpoint-in-RAM half of the reshard protocol."""
    return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)
