"""Ring attention — sequence/context parallelism over the ``sp`` axis.

Long-context support the reference entirely lacks (SURVEY §5:
"Long-context / sequence parallelism: absent"). Q/K/V are sharded along
the sequence dimension across the ring; each step every device computes
blockwise attention of its local queries against the K/V block currently
resident, then rotates K/V to its ring neighbor with ``ppermute`` (ICI
neighbor exchange — bandwidth-optimal on a TPU torus). Softmax is
accumulated online (flash-style running max / sum), so the full score
matrix never materializes and sequence length scales with the ring size.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def _block_attn(q, k, v, scale, mask):
    """One blockwise score pass. q [B,t,H,d]; k,v [B,s,KV,d] with
    H = KV·groups (GQA: each KV head serves a group of query heads —
    K/V travel the ring at KV width and only expand here, inside the
    block kernel); mask [t,s] bool (True = attend). Returns
    (o_unnorm [B,t,H,d], m [B,t,H] block max, l [B,t,H] block sum)."""
    h, kv = q.shape[2], k.shape[2]
    if h != kv:
        k = jnp.repeat(k, h // kv, axis=2)
        v = jnp.repeat(v, h // kv, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,t]
    # guard fully-masked rows: exp(-inf - -inf) -> exp(0); zero them via l
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,t]
    o = jnp.einsum("bhts,bshd->bthd", p, v)
    return o, jnp.swapaxes(m, 1, 2), jnp.swapaxes(l, 1, 2)  # m,l -> [B,t,H]


def _merge(o1, m1, l1, o2, m2, l2):
    """Combine two online-softmax partial results."""
    m = jnp.maximum(m1, m2)
    safe = lambda mm: jnp.where(jnp.isfinite(mm), mm, 0.0)
    a1 = jnp.exp(safe(m1) - safe(m))
    a1 = jnp.where(jnp.isfinite(m1), a1, 0.0)
    a2 = jnp.exp(safe(m2) - safe(m))
    a2 = jnp.where(jnp.isfinite(m2), a2, 0.0)
    o = o1 * a1[..., None] + o2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return o, m, l


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Global causal attention with seq-sharded q/k/v [B, T, H, d]
    (T divided over ``axis``). Returns [B, T, H, d] with the same
    sharding. Non-sp mesh axes pass through untouched (batch may be
    dp/fsdp-sharded on dim 0)."""
    n = mesh.shape[axis]
    scale = 1.0 / np.sqrt(q.shape[-1])
    other = tuple(a for a in mesh.axis_names if a != axis)
    # batch dim keeps whatever data-axis sharding it has
    bspec = P(tuple(a for a in other if a in ("dp", "fsdp")) or None, axis, None, None)

    def local(q, k, v):
        out_dtype = q.dtype
        # f32 accumulation: the online-softmax carry (o, m, l) compounds
        # over ring steps; bf16 carries drift ~1% at long T
        q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
        t = q.shape[1]
        my = jax.lax.axis_index(axis)

        def step(i, carry):
            o, m, l, kk, vv = carry
            # kk/vv originated on ring position (my - i) mod n
            src = (my - i) % n
            if causal:
                # full block if src < my; diagonal block causal; else empty
                base = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
                mask = jnp.where(
                    src == my,
                    base,
                    jnp.where(src < my, jnp.ones((t, t), bool), jnp.zeros((t, t), bool)),
                )
            else:
                mask = jnp.ones((t, t), bool)
            bo, bm, bl = _block_attn(q, kk, vv, scale, mask)
            o, m, l = _merge(o, m, l, bo, bm, bl)
            # rotate K/V to the next ring position (ICI neighbor exchange)
            perm = [(j, (j + 1) % n) for j in range(n)]
            kk = jax.lax.ppermute(kk, axis, perm)
            vv = jax.lax.ppermute(vv, axis, perm)
            return o, m, l, kk, vv

        b, _, h, d = q.shape
        o0 = jnp.zeros_like(q)
        m0 = jnp.full((b, t, h), -jnp.inf, q.dtype)
        l0 = jnp.zeros((b, t, h), q.dtype)
        o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
        return (o / jnp.maximum(l, 1e-20)[..., None]).astype(out_dtype)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(bspec, bspec, bspec),
        out_specs=bspec,
        check_vma=False,
    )(q, k, v)


def reference_attention(q, k, v, causal: bool = True) -> jnp.ndarray:
    """Unsharded attention, the correctness oracle for the ring."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)
