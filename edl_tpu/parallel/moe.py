"""Mixture-of-Experts layer with expert parallelism over the ``ep`` axis.

Absent from the reference (SURVEY §2.5: "Expert parallelism: NO").
Top-k token routing with capacity-bounded dispatch expressed as dense
einsums — the XLA-native formulation: with the expert dimension of the
weights sharded over ``ep``, the dispatch/combine einsums lower to
all-to-all-style collectives over ICI, with no per-token scatter loops
(which would kill the MXU pipeline).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


def init_moe_params(
    key: jax.Array, d_model: int, d_ff: int, n_experts: int, dtype=jnp.float32
) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "router": jax.random.normal(k1, (d_model, n_experts), dtype) * 0.02,
        "w_in": jax.random.normal(k2, (n_experts, d_model, d_ff), dtype)
        * np.sqrt(2.0 / d_model),
        "w_out": jax.random.normal(k3, (n_experts, d_ff, d_model), dtype)
        * np.sqrt(1.0 / d_ff),
    }


def moe_pspecs(plan) -> Dict:
    """Experts sharded over ep; expert-internal dims over tp/fsdp if
    present."""
    ep = "ep" if plan.axis_size("ep") > 1 else None
    tp = "tp" if plan.axis_size("tp") > 1 else None
    return {
        "router": P(None, None),
        "w_in": P(ep, None, tp),
        "w_out": P(ep, tp, None),
    }


def moe_ffn(
    params: Dict,
    x: jnp.ndarray,
    k: int = 2,
    capacity_factor: float = 1.25,
    int8_mxu: bool = False,
    int8_wgrad_bf16: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k routed expert FFN. x [B, T, D] → (y [B, T, D], aux_loss).

    aux_loss is the standard load-balance loss (mean_prob · mean_assign
    · n_experts), to be added to the training loss.

    ``int8_mxu`` runs the two expert batched matmuls on the MXU's
    double-rate int8 path (ops/int8_matmul.int8_batched_matmul) —
    the routing/dispatch einsums stay full precision (they are
    bandwidth-shaped one-hot contractions, not FLOPs).
    ``int8_wgrad_bf16`` keeps their wgrad on the bf16 path (the
    outlier-resolution escape hatch, same contract as
    ``LlamaConfig.int8_wgrad_bf16``).
    """
    b, t, d = x.shape
    n_tokens = b * t
    n_experts = params["router"].shape[-1]
    capacity = int(np.ceil(capacity_factor * k * n_tokens / n_experts))

    flat = x.reshape(n_tokens, d)
    logits = flat @ params["router"]  # [N, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # top-k choice per token
    topk_prob, topk_idx = jax.lax.top_k(probs, k)  # [N, k]
    # position of each token within its expert's queue (capacity cutoff)
    onehot = jax.nn.one_hot(topk_idx, n_experts, dtype=jnp.float32)  # [N,k,E]
    # priority: expert slots filled in token order, k-th choices after
    flat_choice = onehot.reshape(n_tokens * k, n_experts)
    position = jnp.cumsum(flat_choice, axis=0) - flat_choice  # [N*k, E]
    within_cap = (position < capacity) * flat_choice
    slot = jnp.einsum("ne,ne->n", position, flat_choice).astype(jnp.int32)
    keep = jnp.einsum("ne,ne->n", within_cap, flat_choice) > 0

    # dispatch tensor [N, k, E, C]
    slot_onehot = jax.nn.one_hot(slot.reshape(n_tokens, k), capacity, dtype=x.dtype)
    dispatch = (
        onehot.astype(x.dtype)
        * keep.reshape(n_tokens, k, 1).astype(x.dtype)
    )[..., None] * slot_onehot[:, :, None, :]
    dispatch = dispatch.sum(axis=1)  # [N, E, C]

    # combine weights: renormalized top-k prob at the token's slot
    weights = (
        (topk_prob / jnp.maximum(topk_prob.sum(-1, keepdims=True), 1e-9))
        .astype(x.dtype)
        .reshape(n_tokens, k, 1, 1)
        * onehot.astype(x.dtype)[..., None]
        * slot_onehot[:, :, None, :]
        * keep.reshape(n_tokens, k, 1, 1).astype(x.dtype)
    ).sum(axis=1)  # [N, E, C]

    # expert compute: [E, C, D] batched matmuls (MXU-friendly)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, flat)
    if int8_mxu:
        from edl_tpu.ops.int8_matmul import int8_batched_matmul

        h = jax.nn.relu(
            int8_batched_matmul(
                expert_in, params["w_in"], wgrad_bf16=int8_wgrad_bf16
            )
        )
        expert_out = int8_batched_matmul(
            h, params["w_out"], wgrad_bf16=int8_wgrad_bf16
        )
    else:
        h = jax.nn.relu(
            jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"])
        )
        expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    y = jnp.einsum("nec,ecd->nd", weights, expert_out)

    # load-balance auxiliary loss
    assign_frac = jnp.mean(
        jax.nn.one_hot(topk_idx[:, 0], n_experts, dtype=jnp.float32), axis=0
    )
    prob_frac = jnp.mean(probs, axis=0)
    aux = jnp.sum(assign_frac * prob_frac) * n_experts

    return y.reshape(b, t, d), aux
