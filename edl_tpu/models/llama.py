"""Llama-3-family decoder — the flagship model (BASELINE config:
"Llama-3-8B elastic FSDP across growing TPU slice").

No reference analog (the reference's models are 2018-era CTR/word2vec,
SURVEY §5); built TPU-first:

- layers are scan-stacked ([L, ...] params + ``lax.scan``) so compile
  time is O(1) in depth and pipeline stages can slice the leading axis;
- explicit 2D TP×FSDP partition specs per parameter (attention heads /
  ffn width over tp, the other big dim over fsdp) — the standard
  ICI-friendly layout;
- RoPE, GQA (grouped KV heads), RMSNorm, SwiGLU — Llama-3 architecture;
- bfloat16 activations with float32 params/optimizer (MXU-native).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from edl_tpu.parallel.mesh import MeshPlan


@dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16  # activation dtype (params stay f32)
    use_flash: bool = False  # pallas flash attention (TPU, T % 128 == 0)
    # rematerialize each layer in the backward pass: only the [B,T,d]
    # layer inputs are saved across the scan, trading ~33% more forward
    # FLOPs for O(L·B·T·d) instead of O(L·B·T·(d+ff+heads)) activation
    # HBM — what lets non-toy configs train on one chip
    remat: bool = False
    # what the remat saves besides layer inputs — the FLOPs/HBM dial:
    #   "full": recompute everything (min memory, +2 fwd-matmul units
    #           of the 6-unit fwd+bwd budget)
    #   "attn": also save the flash-attention output + logsumexp —
    #           the backward reuses them instead of re-running the
    #           (VPU-bound) softmax kernel; q/k/v reprojections stay
    #           cheap matmul recomputes. ~2·d bf16 bytes/token/layer.
    #   "mlp":  also save the SwiGLU gate/up products [B,T,d_ff] —
    #           skips recomputing w1/w3, half the layer's recompute,
    #           for 2·d_ff bf16 bytes/token/layer of HBM
    #   "dots": save every weight-matmul output (near-zero recompute,
    #           most HBM — jax dots_with_no_batch_dims_saveable)
    remat_policy: str = "full"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def tiny(cls, vocab: int = 256) -> "LlamaConfig":
        """Test/dry-run size: same architecture, toy dims."""
        return cls(
            vocab=vocab,
            d_model=64,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            d_ff=128,
            dtype=jnp.float32,
        )


def init_params(key: jax.Array, cfg: LlamaConfig) -> Dict:
    """Scan-stacked parameter tree: every per-layer weight carries a
    leading [n_layers] axis."""
    k = jax.random.split(key, 10)
    d, h, kv, hd, ff, L = (
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.n_layers,
    )

    def norm_init(kk, *shape, scale):
        return jax.random.normal(kk, shape, jnp.float32) * scale

    return {
        "embed": norm_init(k[0], cfg.vocab, d, scale=0.02),
        "layers": {
            "ln1": jnp.ones((L, d), jnp.float32),
            "wq": norm_init(k[1], L, d, h * hd, scale=d**-0.5),
            "wk": norm_init(k[2], L, d, kv * hd, scale=d**-0.5),
            "wv": norm_init(k[3], L, d, kv * hd, scale=d**-0.5),
            "wo": norm_init(k[4], L, h * hd, d, scale=(h * hd) ** -0.5),
            "ln2": jnp.ones((L, d), jnp.float32),
            "w1": norm_init(k[5], L, d, ff, scale=d**-0.5),  # gate
            "w3": norm_init(k[6], L, d, ff, scale=d**-0.5),  # up
            "w2": norm_init(k[7], L, ff, d, scale=ff**-0.5),  # down
        },
        "ln_f": jnp.ones((d,), jnp.float32),
        "lm_head": norm_init(k[8], d, cfg.vocab, scale=d**-0.5),
    }


def param_pspecs(cfg: LlamaConfig, plan: MeshPlan) -> Dict:
    """2D TP×FSDP layout: tp on head/ffn width, fsdp on the other large
    dim; vocab-dim tp for embed/lm_head. Falls back gracefully when an
    axis is absent, and drops an axis from any dimension it does not
    divide (elastic worlds are not always powers of two — a 6-way fsdp
    mesh must still compile; the undivisible param is replicated on
    that axis instead, exactly what the generic rule in
    parallel/sharding.py does)."""
    tp = "tp" if plan.axis_size("tp") > 1 else None
    fs = "fsdp" if plan.axis_size("fsdp") > 1 else None
    d, h, kv, hd, ff, L, V = (
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.n_layers,
        cfg.vocab,
    )

    from edl_tpu.parallel.sharding import fit_pspec

    def fit(shape, *axes):
        return fit_pspec(plan, shape, *axes)

    return {
        "embed": fit((V, d), tp, fs),
        "layers": {
            "ln1": P(None, None),
            "wq": fit((L, d, h * hd), None, fs, tp),
            "wk": fit((L, d, kv * hd), None, fs, tp),
            "wv": fit((L, d, kv * hd), None, fs, tp),
            "wo": fit((L, h * hd, d), None, tp, fs),
            "ln2": P(None, None),
            "w1": fit((L, d, ff), None, fs, tp),
            "w3": fit((L, d, ff), None, fs, tp),
            "w2": fit((L, ff, d), None, tp, fs),
        },
        "ln_f": P(None),
        "lm_head": fit((d, V), fs, tp),
    }


def _rmsnorm(x: jnp.ndarray, scale: jnp.ndarray, eps: float) -> jnp.ndarray:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale.astype(x.dtype)


def _rope(x: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary embedding over [B, T, H, hd]."""
    _, t, _, hd = x.shape
    freqs = 1.0 / (theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    angles = jnp.arange(t, dtype=jnp.float32)[:, None] * freqs[None, :]  # [T, hd/2]
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, cfg: LlamaConfig
) -> jnp.ndarray:
    """Causal GQA attention. q [B,T,H,hd]; k,v [B,T,KV,hd]."""
    b, t, h, hd = q.shape
    if cfg.use_flash:
        from edl_tpu.ops.flash_attention import attention_auto, flash_supported

        if flash_supported(t):
            # kernel handles GQA natively (no K/V repeat) and falls back
            # to interpret mode off-TPU
            return attention_auto(q, k, v, causal=True)
    groups = h // k.shape[2]
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)
    scores = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(hd)
    mask = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(mask[None, None], scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def _layer(cfg: LlamaConfig, x: jnp.ndarray, lp: Dict) -> jnp.ndarray:
    b, t, d = x.shape
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    dt = x.dtype
    # attention block
    a = _rmsnorm(x, lp["ln1"], cfg.norm_eps)
    q = (a @ lp["wq"].astype(dt)).reshape(b, t, h, hd)
    k = (a @ lp["wk"].astype(dt)).reshape(b, t, kv, hd)
    v = (a @ lp["wv"].astype(dt)).reshape(b, t, kv, hd)
    q, k = _rope(q, cfg.rope_theta), _rope(k, cfg.rope_theta)
    o = attention(q, k, v, cfg).reshape(b, t, h * hd)
    x = x + o @ lp["wo"].astype(dt)
    # mlp block (SwiGLU)
    m = _rmsnorm(x, lp["ln2"], cfg.norm_eps)
    gate = checkpoint_name(jax.nn.silu(m @ lp["w1"].astype(dt)), "mlp_gate")
    up = checkpoint_name(m @ lp["w3"].astype(dt), "mlp_up")
    return x + (gate * up) @ lp["w2"].astype(dt)


def forward(params: Dict, tokens: jnp.ndarray, cfg: LlamaConfig) -> jnp.ndarray:
    """tokens [B, T] int32 → logits [B, T, vocab]."""
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)

    def body(carry, lp):
        return _layer(cfg, carry, lp), None

    if cfg.remat:
        if cfg.remat_policy == "mlp":
            policy = jax.checkpoint_policies.save_only_these_names(
                "mlp_gate", "mlp_up"
            )
        elif cfg.remat_policy == "attn":
            if not cfg.use_flash:
                raise ValueError(
                    'remat_policy="attn" saves the flash kernel\'s named '
                    "residuals; without use_flash there is nothing to "
                    "save and the policy would silently degrade to full "
                    "rematerialization"
                )
            policy = jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"
            )
        elif cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif cfg.remat_policy == "full":
            policy = None
        else:
            raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}")
        body = jax.checkpoint(body, policy=policy)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _rmsnorm(x, params["ln_f"], cfg.norm_eps)
    return (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)


def train_flops_per_token(cfg: LlamaConfig, seq: int) -> float:
    """Model FLOPs per trained token (fwd+bwd), the MFU numerator:
    6 × matmul params (embedding lookup excluded, lm_head included)
    plus causal attention 12·L·(T/2)·d_attn. Remat recompute is NOT
    counted (MFU convention: model FLOPs, not hardware FLOPs)."""
    hd = cfg.head_dim
    per_layer = (
        cfg.d_model * cfg.n_heads * hd  # wq
        + 2 * cfg.d_model * cfg.n_kv_heads * hd  # wk, wv
        + cfg.n_heads * hd * cfg.d_model  # wo
        + 3 * cfg.d_model * cfg.d_ff  # w1, w3, w2
    )
    n_matmul = cfg.n_layers * per_layer + cfg.d_model * cfg.vocab
    attn = 12.0 * cfg.n_layers * (seq / 2.0) * (cfg.n_heads * hd)
    return 6.0 * n_matmul + attn


def make_loss_fn(cfg: LlamaConfig):
    """Next-token cross entropy; batch = {tokens [B, T+1]}."""

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        logits = forward(params, tokens[:, :-1], cfg)
        targets = tokens[:, 1:]
        # fused CE (logsumexp - target logit): two reductions over the
        # vocab axis instead of materializing the full [B,T,V]
        # log-softmax (4+ GB of f32 at the bench config)
        import optax

        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        )

    return loss_fn


def synthetic_tokens(
    rng: np.random.RandomState, batch: int, seq: int, vocab: int
) -> Dict[str, np.ndarray]:
    """Markov-ish synthetic text: next token correlates with current, so
    the loss curve has signal."""
    toks = np.zeros((batch, seq + 1), np.int32)
    toks[:, 0] = rng.randint(0, vocab, batch)
    drift = rng.randint(1, 7, (batch,))
    for t in range(1, seq + 1):
        noise = rng.rand(batch) < 0.1
        toks[:, t] = np.where(
            noise, rng.randint(0, vocab, batch), (toks[:, t - 1] + drift) % vocab
        )
    return {"tokens": toks}
