"""Llama-3-family decoder — the flagship model (BASELINE config:
"Llama-3-8B elastic FSDP across growing TPU slice").

No reference analog (the reference's models are 2018-era CTR/word2vec,
SURVEY §5); built TPU-first:

- layers are scan-stacked ([L, ...] params + ``lax.scan``) so compile
  time is O(1) in depth and pipeline stages can slice the leading axis;
- explicit 2D TP×FSDP partition specs per parameter (attention heads /
  ffn width over tp, the other big dim over fsdp) — the standard
  ICI-friendly layout;
- RoPE, GQA (grouped KV heads), RMSNorm, SwiGLU — Llama-3 architecture;
- bfloat16 activations with float32 params/optimizer (MXU-native).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from edl_tpu.obs import compilewatch
from edl_tpu.obs import costmodel as _costmodel
from edl_tpu.parallel.mesh import MeshPlan


@dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16  # activation dtype (params stay f32)
    use_flash: bool = False  # pallas flash attention (TPU, T % 128 == 0)
    # rematerialize each layer in the backward pass: only the [B,T,d]
    # layer inputs are saved across the scan, trading ~33% more forward
    # FLOPs for O(L·B·T·d) instead of O(L·B·T·(d+ff+heads)) activation
    # HBM — what lets non-toy configs train on one chip
    remat: bool = False
    # what the remat saves besides layer inputs — the FLOPs/HBM dial:
    #   "full": recompute everything (min memory, +2 fwd-matmul units
    #           of the 6-unit fwd+bwd budget)
    #   "attn": also save the flash-attention output + logsumexp —
    #           the backward reuses them instead of re-running the
    #           (VPU-bound) softmax kernel; q/k/v reprojections stay
    #           cheap matmul recomputes. ~2·d bf16 bytes/token/layer.
    #   "mlp":  also save the SwiGLU gate/up products [B,T,d_ff] —
    #           skips recomputing w1/w3, half the layer's recompute,
    #           for 2·d_ff bf16 bytes/token/layer of HBM
    #   "dots": save every weight-matmul output (near-zero recompute,
    #           most HBM — jax dots_with_no_batch_dims_saveable)
    remat_policy: str = "full"
    # sequence/context parallelism implementation when the mesh plan has
    # an sp axis: "ring" (ppermute neighbor exchange, scales past the
    # head count) or "ulysses" (two all-to-alls, full-sequence attention
    # on H/sp heads). Ignored when sp == 1.
    sp_impl: str = "ring"
    # GPipe microbatch count when the plan has a pp axis (0 = one
    # microbatch per stage). Bubble fraction (pp-1)/(n_micro+pp-1).
    pp_microbatches: int = 0
    # run the seven per-layer projection matmuls on the MXU's
    # double-rate int8 path (ops/int8_matmul.py: dynamic absmax
    # quantization of both operands in flight, STE gradients, fwd +
    # dgrad + wgrad all int8). Master weights/optimizer/attention/
    # lm_head stay full precision; training-only (never rides
    # to_meta — exports are dense, serving unaffected).
    int8_mxu: bool = False
    # with int8_mxu: keep wgrad (a^T @ g) on the bf16 MXU path while
    # fwd/dgrad stay int8 (ADVICE r6) — gradients are heavy-tailed and
    # wgrad contracts the batch·seq axis, so one outlier crushes a
    # whole slice's absmax resolution; this caps long-run update noise
    # at bf16 rounding for ~1/6 of the 2x rate win. Training-only,
    # ignored without int8_mxu, never rides to_meta.
    int8_wgrad_bf16: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def to_meta(self) -> Dict:
        """JSON-safe architecture record (rides export manifests so a
        serving consumer can rebuild the config; runtime/export.py)."""
        return {
            "family": "llama",
            "vocab": self.vocab,
            "d_model": self.d_model,
            "n_layers": self.n_layers,
            "n_heads": self.n_heads,
            "n_kv_heads": self.n_kv_heads,
            "d_ff": self.d_ff,
            "rope_theta": self.rope_theta,
            "norm_eps": self.norm_eps,
            "dtype": jnp.dtype(self.dtype).name,
            "use_flash": self.use_flash,
        }

    @classmethod
    def from_meta(cls, meta: Dict) -> "LlamaConfig":
        if meta.get("family") != "llama":
            raise ValueError(f"not a llama export: family={meta.get('family')!r}")
        return cls(
            vocab=int(meta["vocab"]),
            d_model=int(meta["d_model"]),
            n_layers=int(meta["n_layers"]),
            n_heads=int(meta["n_heads"]),
            n_kv_heads=int(meta["n_kv_heads"]),
            d_ff=int(meta["d_ff"]),
            rope_theta=float(meta["rope_theta"]),
            norm_eps=float(meta["norm_eps"]),
            dtype=jnp.dtype(meta["dtype"]),
            use_flash=bool(meta.get("use_flash", False)),
        )

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def tiny(cls, vocab: int = 256) -> "LlamaConfig":
        """Test/dry-run size: same architecture, toy dims."""
        return cls(
            vocab=vocab,
            d_model=64,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            d_ff=128,
            dtype=jnp.float32,
        )


def init_params(key: jax.Array, cfg: LlamaConfig) -> Dict:
    """Scan-stacked parameter tree: every per-layer weight carries a
    leading [n_layers] axis."""
    k = jax.random.split(key, 10)
    d, h, kv, hd, ff, L = (
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.n_layers,
    )

    def norm_init(kk, *shape, scale):
        return jax.random.normal(kk, shape, jnp.float32) * scale

    return {
        "embed": norm_init(k[0], cfg.vocab, d, scale=0.02),
        "layers": {
            "ln1": jnp.ones((L, d), jnp.float32),
            "wq": norm_init(k[1], L, d, h * hd, scale=d**-0.5),
            "wk": norm_init(k[2], L, d, kv * hd, scale=d**-0.5),
            "wv": norm_init(k[3], L, d, kv * hd, scale=d**-0.5),
            "wo": norm_init(k[4], L, h * hd, d, scale=(h * hd) ** -0.5),
            "ln2": jnp.ones((L, d), jnp.float32),
            "w1": norm_init(k[5], L, d, ff, scale=d**-0.5),  # gate
            "w3": norm_init(k[6], L, d, ff, scale=d**-0.5),  # up
            "w2": norm_init(k[7], L, ff, d, scale=ff**-0.5),  # down
        },
        "ln_f": jnp.ones((d,), jnp.float32),
        "lm_head": norm_init(k[8], d, cfg.vocab, scale=d**-0.5),
    }


def param_pspecs(cfg: LlamaConfig, plan: MeshPlan) -> Dict:
    """2D TP×FSDP layout: tp on head/ffn width, fsdp on the other large
    dim; vocab-dim tp for embed/lm_head. Falls back gracefully when an
    axis is absent, and drops an axis from any dimension it does not
    divide (elastic worlds are not always powers of two — a 6-way fsdp
    mesh must still compile; the undivisible param is replicated on
    that axis instead, exactly what the generic rule in
    parallel/sharding.py does)."""
    tp = "tp" if plan.axis_size("tp") > 1 else None
    fs = "fsdp" if plan.axis_size("fsdp") > 1 else None
    # pipeline stages: the scan-stacked layer axis shards over pp, so
    # each stage's devices hold only their own layers at rest; the
    # pipeline shard_map gathers the fs/tp dims per step (ZeRO-style)
    pp = "pp" if plan.axis_size("pp") > 1 else None
    d, h, kv, hd, ff, L, V = (
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.n_layers,
        cfg.vocab,
    )

    from edl_tpu.parallel.sharding import fit_pspec

    def fit(shape, *axes):
        return fit_pspec(plan, shape, *axes)

    return {
        "embed": fit((V, d), tp, fs),
        "layers": {
            "ln1": fit((L, d), pp, None),
            "wq": fit((L, d, h * hd), pp, fs, tp),
            "wk": fit((L, d, kv * hd), pp, fs, tp),
            "wv": fit((L, d, kv * hd), pp, fs, tp),
            "wo": fit((L, h * hd, d), pp, tp, fs),
            "ln2": fit((L, d), pp, None),
            "w1": fit((L, d, ff), pp, fs, tp),
            "w3": fit((L, d, ff), pp, fs, tp),
            "w2": fit((L, ff, d), pp, tp, fs),
        },
        "ln_f": P(None),
        "lm_head": fit((d, V), fs, tp),
    }


def _rmsnorm(x: jnp.ndarray, scale: jnp.ndarray, eps: float) -> jnp.ndarray:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale.astype(x.dtype)


def _rope(
    x: jnp.ndarray, theta: float, positions: Optional[jnp.ndarray] = None
) -> jnp.ndarray:
    """Rotary embedding over [B, T, H, hd]. ``positions`` [T] overrides
    the default 0..T-1 (the decode path rotates single tokens at their
    absolute position); a [B, T] positions array rotates each batch row
    at its OWN absolute positions (the continuous-batching slot decode,
    where concurrent requests sit at different depths)."""
    _, t, _, hd = x.shape
    freqs = 1.0 / (theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    if positions is None:
        positions = jnp.arange(t, dtype=jnp.float32)
    angles = positions.astype(jnp.float32)[..., :, None] * freqs  # [..., T, hd/2]
    if angles.ndim == 2:
        angles = angles[None]  # shared positions broadcast over B
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    cfg: LlamaConfig,
    mesh=None,
    sp: int = 1,
) -> jnp.ndarray:
    """Causal GQA attention. q [B,T,H,hd]; k,v [B,T,KV,hd].

    With ``sp > 1`` the sequence dim arrives sharded over the mesh's
    ``sp`` axis and attention goes through ring attention (ppermute
    K/V rotation) or Ulysses (head/sequence all-to-all) per
    ``cfg.sp_impl`` — the long-context path (SURVEY §5)."""
    b, t, h, hd = q.shape
    if sp > 1:
        if mesh is None:
            raise ValueError("sp attention needs the mesh")
        # both sp kernels are GQA-aware: K/V travel the collectives at
        # kv-head width and expand inside the local block compute
        if cfg.sp_impl == "ring":
            from edl_tpu.parallel.ring_attention import ring_attention

            return ring_attention(q, k, v, mesh, axis="sp", causal=True)
        elif cfg.sp_impl == "ulysses":
            from edl_tpu.parallel.ulysses import ulysses_attention

            return ulysses_attention(q, k, v, mesh, axis="sp", causal=True)
        raise ValueError(f"unknown sp_impl {cfg.sp_impl!r}")
    if cfg.use_flash:
        from edl_tpu.ops.flash_attention import attention_auto, flash_supported

        if flash_supported(t):
            # kernel handles GQA natively (no K/V repeat) and falls back
            # to interpret mode off-TPU
            return attention_auto(q, k, v, causal=True)
    groups = h // k.shape[2]
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)
    scores = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(hd)
    mask = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(mask[None, None], scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


_INT8_WEIGHTS = ("wq", "wk", "wv", "wo", "w1", "w2", "w3")


def _matw(
    a: jnp.ndarray, p, int8_mxu: bool = False, wgrad_bf16: bool = False
) -> jnp.ndarray:
    """``a @ W`` where ``W`` is a plain weight array or a weight-only
    int8 record ``{"q8", "s8"}`` from :func:`quantize_params_int8`.

    The int8 record form computes ``(a @ q8) * s8`` — mathematically
    equal to ``a @ (q8 * s8)`` because ``s8`` is constant along the
    contraction axis — so the dot's rhs is a bare ``convert(int8→dt)``
    that XLA fuses into the operand read: HBM streams the int8 bytes
    and no dequantized weight temp is ever materialized. That halved
    traffic is the whole point — small-batch decode is
    weight-bandwidth-bound (see bench.py ``_decode_step_bytes``).

    ``int8_mxu`` (training, ``LlamaConfig.int8_mxu``) instead runs the
    dense matmul on the MXU's double-rate int8 path with dynamic
    quantization of BOTH operands and STE gradients
    (``ops/int8_matmul.py``) — a throughput lever, not a memory one."""
    dt = a.dtype
    if isinstance(p, dict):
        # the column-scale multiply stays f32: casting s8 to bf16 first
        # would truncate each scale to an 8-bit mantissa, stacking up to
        # ~0.2% systematic error on top of the colmax/254 quantization
        # bound (ADVICE r5)
        return ((a @ p["q8"].astype(dt)).astype(jnp.float32) * p["s8"]).astype(dt)
    if int8_mxu:
        from edl_tpu.ops.int8_matmul import int8_matmul

        # no dtype cast: quantization reads the f32 MASTER weight (a
        # bf16 pre-cast would stack ~2^-9 truncation under the int8
        # noise and materialize a bf16 weight copy per step)
        return int8_matmul(a, p, wgrad_bf16=wgrad_bf16)
    return a @ p.astype(dt)


def quantize_params_int8(params: Dict) -> Dict:
    """Weight-only int8 for the serving/decode path (the quantization
    lever of VERDICT r4 #3): every matmul weight the decode step
    streams — the seven per-layer projection matrices and ``lm_head``
    — becomes ``{"q8": int8 [..., din, dout], "s8": f32 [..., dout]}``
    with symmetric per-output-column absmax scales, so the max error
    per element is ``colmax/254``. Master weights are untouched; the
    embedding stays dense (decode gathers B rows of it per step, not
    the whole table, so quantizing it buys no bandwidth) and norm
    scales are vectors. The returned tree feeds ``generate``/
    ``forward`` unchanged — ``_matw`` dispatches on the record."""

    from edl_tpu.ops.int8_matmul import absmax_quant

    def q(w):
        q8, s = absmax_quant(w, -2)  # absmax over din: per-out-column
        return {"q8": q8, "s8": s[..., 0, :]}

    out = dict(params)
    out["layers"] = {
        k: (q(v) if k in _INT8_WEIGHTS else v)
        for k, v in params["layers"].items()
    }
    out["lm_head"] = q(params["lm_head"])
    return out


def _qkv(cfg: LlamaConfig, a: jnp.ndarray, lp: Dict, positions=None):
    """Projections + RoPE — shared by the training layer and the
    KV-cache decode so the model math cannot diverge between them."""
    b, t, _ = a.shape
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    i8, wb = cfg.int8_mxu, cfg.int8_wgrad_bf16
    q = _matw(a, lp["wq"], i8, wb).reshape(b, t, h, hd)
    k = _matw(a, lp["wk"], i8, wb).reshape(b, t, kv, hd)
    v = _matw(a, lp["wv"], i8, wb).reshape(b, t, kv, hd)
    q = _rope(q, cfg.rope_theta, positions)
    k = _rope(k, cfg.rope_theta, positions)
    return q, k, v


def _mlp(cfg: LlamaConfig, x: jnp.ndarray, lp: Dict) -> jnp.ndarray:
    """Post-attention SwiGLU block (residual included) — shared by the
    training layer and the decode step."""
    i8, wb = cfg.int8_mxu, cfg.int8_wgrad_bf16
    m = _rmsnorm(x, lp["ln2"], cfg.norm_eps)
    gate = checkpoint_name(jax.nn.silu(_matw(m, lp["w1"], i8, wb)), "mlp_gate")
    up = checkpoint_name(_matw(m, lp["w3"], i8, wb), "mlp_up")
    return x + _matw(gate * up, lp["w2"], i8, wb)


def _layer(
    cfg: LlamaConfig,
    x: jnp.ndarray,
    lp: Dict,
    mesh=None,
    sp: int = 1,
    with_kv: bool = False,
):
    """One decoder layer. ``with_kv`` also returns this layer's (k, v)
    — the prefill path collects them into the decode cache; the
    training path must NOT set it (materializing every layer's K/V
    across the scan costs O(L·B·T) HBM)."""
    b, t, d = x.shape
    a = _rmsnorm(x, lp["ln1"], cfg.norm_eps)
    q, k, v = _qkv(cfg, a, lp)
    o = attention(q, k, v, cfg, mesh=mesh, sp=sp).reshape(b, t, -1)
    x = x + _matw(o, lp["wo"], cfg.int8_mxu, cfg.int8_wgrad_bf16)
    out = _mlp(cfg, x, lp)
    return (out, k, v) if with_kv else out


def _remat_policy(cfg: LlamaConfig):
    """The remat FLOPs/HBM dial (see LlamaConfig.remat_policy)."""
    if cfg.remat_policy == "mlp":
        return jax.checkpoint_policies.save_only_these_names(
            "mlp_gate", "mlp_up"
        )
    if cfg.remat_policy == "attn":
        if not cfg.use_flash:
            raise ValueError(
                'remat_policy="attn" saves the flash kernel\'s named '
                "residuals; without use_flash there is nothing to "
                "save and the policy would silently degrade to full "
                "rematerialization"
            )
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse"
        )
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if cfg.remat_policy == "full":
        return None
    raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}")


def forward(
    params: Dict,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    mesh=None,
    plan: Optional[MeshPlan] = None,
) -> jnp.ndarray:
    """tokens [B, T] int32 → logits [B, T, vocab].

    ``plan``/``mesh`` activate the parallel strategies beyond what GSPMD
    infers from param shardings alone:

    - ``sp > 1``: activations are sequence-sharded right after the
      embedding (``plan.sequence_pspec``) and attention runs ring or
      Ulysses over the sp axis — long-context training where no single
      device ever holds a full-sequence activation.
    - ``pp > 1``: the scan-stacked layer axis splits into pp stages
      driven by the GPipe schedule (``parallel.pipeline.pipeline_apply``)
      with microbatched activations flowing over ppermute.
    """
    sp = plan.axis_size("sp") if plan is not None else 1
    pp = plan.axis_size("pp") if plan is not None else 1
    if (sp > 1 or pp > 1) and mesh is None:
        raise ValueError("sp/pp forward needs the mesh")
    if sp > 1 and pp > 1:
        # ring/ulysses attention is itself a shard_map; nesting it inside
        # the pipeline shard_map is not supported by jax
        raise ValueError("sp and pp cannot be combined in one llama mesh")
    if (
        sp == 1  # the pp path also runs the flash kernel per stage
        and cfg.remat
        and cfg.remat_policy == "attn"
        and cfg.use_flash
    ):
        from edl_tpu.ops.flash_attention import flash_supported

        if not flash_supported(tokens.shape[1]):
            # attention() would silently take the dense XLA path, the
            # flash_out/flash_lse names would never exist, and the
            # policy would degrade to FULL remat — the exact failure
            # the use_flash guard in _remat_policy documents
            raise ValueError(
                f'remat_policy="attn" needs the flash kernel, but '
                f"seq len {tokens.shape[1]} is not flash-supported "
                f"(flash_supported() is False) — pad T or switch policy"
            )
    if sp > 1 and cfg.remat and cfg.remat_policy == "attn":
        # the sp paths never run the flash kernel, so the flash_out /
        # flash_lse names the policy saves would not exist — the policy
        # would silently degrade to full remat (the failure its
        # use_flash guard documents)
        raise ValueError(
            'remat_policy="attn" requires the flash kernel, which the '
            "sp (ring/Ulysses) attention paths do not use"
        )
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    if sp > 1:
        if tokens.shape[1] % sp:
            raise ValueError(
                f"sequence {tokens.shape[1]} not divisible by sp={sp}"
            )
        x = jax.lax.with_sharding_constraint(
            x, plan.sequence_sharding(mesh, rank=3)
        )

    def body(carry, lp):
        return _layer(cfg, carry, lp, mesh=mesh, sp=sp), None

    if cfg.remat:
        body = jax.checkpoint(body, policy=_remat_policy(cfg))

    if pp > 1:
        from edl_tpu.parallel.pipeline import pipeline_apply

        L, b = cfg.n_layers, x.shape[0]
        if L % pp:
            raise ValueError(f"n_layers {L} not divisible by pp={pp}")
        n_micro = cfg.pp_microbatches or pp
        if b % n_micro:
            raise ValueError(f"batch {b} not divisible by n_micro={n_micro}")
        shards = plan.batch_shards()
        if (b // n_micro) % shards:
            raise ValueError(
                f"microbatch rows {b // n_micro} do not divide over the "
                f"{shards} data shards (dp×fsdp) — lower pp_microbatches "
                f"or raise the batch"
            )
        stage_params = jax.tree_util.tree_map(
            lambda l: l.reshape((pp, L // pp) + l.shape[1:]), params["layers"]
        )

        def stage_fn(sp_params, xm):
            y, _ = jax.lax.scan(body, xm, sp_params)
            return y

        xm = x.reshape((n_micro, b // n_micro) + x.shape[1:])
        xm = pipeline_apply(
            stage_fn, stage_params, xm, mesh,
            data_axes=plan.batch_axes(),
        )
        x = xm.reshape((b,) + xm.shape[2:])
    else:
        x, _ = jax.lax.scan(body, x, params["layers"])
    x = _rmsnorm(x, params["ln_f"], cfg.norm_eps)
    return _matw(x, params["lm_head"]).astype(jnp.float32)


# -- inference: KV-cache decode ---------------------------------------------
#
# The serving half of the export story (runtime/export.py publishes the
# params; this consumes them). TPU-first: prefill is one full forward
# whose per-layer K/V are collected by the SAME lax.scan that runs the
# layers, and the decode loop is a single lax.scan over positions with
# the cache as carry — one compiled program for the whole generation,
# no per-token dispatch, static [B, max_len] shapes throughout.


def _prefill(params: Dict, tokens: jnp.ndarray, cfg: LlamaConfig):
    """Forward over the prompt, returning (logits_last [B, V],
    k_cache, v_cache [L, B, T, KV, hd]). Runs the SAME ``_layer`` as
    training (``with_kv=True`` collects the cache)."""
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)

    def body(carry, lp):
        y, k, v = _layer(cfg, carry, lp, with_kv=True)
        return y, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, params["layers"])
    x = _rmsnorm(x, params["ln_f"], cfg.norm_eps)
    logits = _matw(x[:, -1], params["lm_head"]).astype(jnp.float32)
    return logits, ks, vs


def _decode_step(params: Dict, tok: jnp.ndarray, pos, kc, vc, cfg: LlamaConfig):
    """One cached decode step. tok [B] int32; kc/vc [L, B, S, KV, hd]
    (S = max_len); pos = index this token writes. Returns
    (logits [B, V], kc, vc).

    The layer loop is UNROLLED with static layer indices, and each
    layer writes ONLY its new token's row into the stacked cache
    (``dynamic_update_slice`` at a static layer offset). This is what
    lets XLA keep every cache update in place: the earlier scan-based
    body carried the caches as scan xs/ys, which re-stacked — read AND
    wrote — the entire cache every token. Measured on the flagship at
    B=8 (wide-window differencing, best-of-6): 1.45x faster at
    T0=512, 2.15x at T0=2048 — the S-slope drops ~4x once the restack
    is gone. Four alternatives measured SLOWER (doc/design.md
    "Serving"): cache-as-scan-carry with traced-index slicing,
    per-layer cache leaves, int8 KV, and a pallas single-query flash
    kernel — XLA's dense cached attention is already efficient once
    the restack is gone. Unrolling costs O(L) compile once per
    (cfg, shape) — the memoized ``generate`` program."""
    b = tok.shape[0]
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    groups = h // kv
    s = kc.shape[2]
    x = jnp.take(params["embed"], tok[:, None], axis=0).astype(cfg.dtype)
    positions = jnp.full((1,), pos)
    for i in range(cfg.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[i], params["layers"])
        dt = x.dtype
        a = _rmsnorm(x, lp["ln1"], cfg.norm_eps)
        # same projections/RoPE as training (_qkv); only the
        # cache-update + masked-dense attention differ by construction
        q, knew, vnew = _qkv(cfg, a, lp, positions)
        kc = jax.lax.dynamic_update_slice(kc, knew[None], (i, 0, pos, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, vnew[None], (i, 0, pos, 0, 0))
        kci, vci = kc[i], vc[i]  # static-index slices of the carry
        # GQA-native: group the query heads against the un-repeated
        # cache — no groups-fold bandwidth multiplier on the
        # token-latency-critical path
        qg = q.reshape(b, 1, kv, groups, hd)
        scores = jnp.einsum("btkgd,bskd->bkgts", qg, kci) / np.sqrt(hd)
        mask = (jnp.arange(s) <= pos)[None, None, None, None, :]
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        o = jnp.einsum("bkgts,bskd->btkgd", probs, vci).reshape(b, 1, h * hd)
        x = x + _matw(o, lp["wo"])
        x = _mlp(cfg, x, lp)
    x = _rmsnorm(x, params["ln_f"], cfg.norm_eps)
    logits = _matw(x[:, 0], params["lm_head"]).astype(jnp.float32)
    return logits, kc, vc


def prefill_padded(params: Dict, tokens: jnp.ndarray, last, cfg: LlamaConfig):
    """Prefill over an END-padded prompt batch [B, Tb], returning the
    logits at each row's ``last`` index (its final REAL token) plus the
    K/V cache [L, B, Tb, KV, hd].

    Causality makes end-padding invisible to every real position: pad
    rows attend backward into the prompt but no real row ever attends
    forward into a pad, so logits and cache rows at positions <= last
    are exactly an unpadded prefill's. This is what lets the serving
    engine prefill mixed-length prompts into a handful of power-of-two
    buckets — O(log max_prompt) compiled programs instead of one per
    prompt length. ``last`` is a traced scalar or [B] vector, so every
    length inside a bucket reuses one program."""
    b = tokens.shape[0]
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)

    def body(carry, lp):
        y, k, v = _layer(cfg, carry, lp, with_kv=True)
        return y, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, params["layers"])
    x = _rmsnorm(x, params["ln_f"], cfg.norm_eps)
    xl = x[jnp.arange(b), last]  # [B, d] — each row's last real token
    logits = _matw(xl, params["lm_head"]).astype(jnp.float32)
    return logits, ks, vs


def decode_step_slots(
    params: Dict,
    tok: jnp.ndarray,
    pos: jnp.ndarray,
    kc: jnp.ndarray,
    vc: jnp.ndarray,
    cfg: LlamaConfig,
):
    """One continuous-batching decode step over B independent KV slots.
    tok [B] int32 (each slot's previous token); pos [B] int32 (the
    cache position each slot writes this step); kc/vc [L, B, S, KV, hd].
    Returns (logits [B, V], kc, vc).

    Per-row math is IDENTICAL to :func:`_decode_step` — same unrolled
    layer loop, shared ``_qkv``/``_mlp``, the same GQA-grouped cached
    attention — except positions, cache writes, and the causal mask are
    per-row, so requests at different depths decode in one batched step
    (the serving engine's slot table, ``edl_tpu/serving/engine.py``).
    The cache write is a per-row scatter at (row, pos[row]) — unique
    indices, so XLA keeps it in place like the dynamic_update_slice of
    the uniform-position path. Rows the caller considers inactive
    should be fed (tok=0, pos=0) and their outputs ignored: they
    re-write slot position 0 each step, which the next prefill-insert
    overwrites before it is ever unmasked."""
    b = tok.shape[0]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    groups = h // kvh
    s = kc.shape[2]
    rows = jnp.arange(b)
    x = jnp.take(params["embed"], tok[:, None], axis=0).astype(cfg.dtype)
    for i in range(cfg.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[i], params["layers"])
        dt = x.dtype
        a = _rmsnorm(x, lp["ln1"], cfg.norm_eps)
        q, knew, vnew = _qkv(cfg, a, lp, pos[:, None])
        kc = kc.at[i, rows, pos].set(knew[:, 0])
        vc = vc.at[i, rows, pos].set(vnew[:, 0])
        kci, vci = kc[i], vc[i]  # static-index slices of the carry
        qg = q.reshape(b, 1, kvh, groups, hd)
        scores = jnp.einsum("btkgd,bskd->bkgts", qg, kci) / np.sqrt(hd)
        mask = (jnp.arange(s)[None, :] <= pos[:, None])[:, None, None, None, :]
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        o = jnp.einsum("bkgts,bskd->btkgd", probs, vci).reshape(b, 1, h * hd)
        x = x + _matw(o, lp["wo"])
        x = _mlp(cfg, x, lp)
    x = _rmsnorm(x, params["ln_f"], cfg.norm_eps)
    logits = _matw(x[:, 0], params["lm_head"]).astype(jnp.float32)
    return logits, kc, vc


def decode_horizon_slots(
    params: Dict,
    tok: jnp.ndarray,
    pos: jnp.ndarray,
    active: jnp.ndarray,
    rem: jnp.ndarray,
    eosv: jnp.ndarray,
    kc: jnp.ndarray,
    vc: jnp.ndarray,
    cfg: LlamaConfig,
    horizon: int,
    key: Optional[jax.Array] = None,
    temperature=None,
    sampling: bool = False,
):
    """A fused HORIZON of ``horizon`` slot-decode steps in one program —
    ``lax.scan`` over :func:`decode_step_slots` with per-slot
    termination handled ON DEVICE, so the serving engine pays one
    dispatch (and one host sync, deferrable) per H tokens per slot
    instead of one per token.

    Per-slot device state (all [B], the scan carry alongside the KV
    cache): ``tok`` the previous token, ``pos`` the cache position the
    next step writes, ``active`` whether the slot is still decoding,
    ``rem`` tokens the slot may still emit, ``eosv`` its stop token
    (-1 = none; read-only here — only admission changes it). Each step
    every row runs the SAME batched math (the program never changes
    shape); a row that emits its ``eosv`` token or exhausts ``rem``
    FREEZES: tok/pos/rem stop advancing and its output lanes read -1.
    A frozen row keeps re-running the identical step — its cache
    rewrite at the frozen ``pos`` is idempotent (same token, same
    position, same visible cache ⇒ bit-identical K/V) and strictly
    row-local, so active rows decode exactly as if the frozen row had
    been evicted. Greedy output is therefore token-identical to
    stepping :func:`decode_step_slots` one position at a time, which
    is itself per-row identical to sequential :func:`generate` — the
    contract ``tests/test_serving.py`` pins at H ∈ {1, 4, 16}.

    Returns ``(toks [B, horizon], tok, pos, active, rem, kc, vc)`` —
    ``toks`` rows are emitted tokens with -1 in frozen lanes, and the
    non-cache carries come back as device arrays so the engine can
    dispatch the NEXT block without ever syncing them to the host (the
    double-buffered pipeline in ``serving/engine.py``).

    ``sampling`` (static) draws from ``logits / temperature`` with a
    per-step key split from ``key``; greedy ignores both."""

    def step(carry, k):
        tok, pos, active, rem, kc, vc = carry
        logits, kc, vc = decode_step_slots(params, tok, pos, kc, vc, cfg)
        if sampling:
            nxt = jax.random.categorical(k, logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        nxt = jnp.where(active, nxt.astype(jnp.int32), tok)
        out = jnp.where(active, nxt, -1)
        pos = jnp.where(active, pos + 1, pos)
        rem = jnp.where(active, rem - 1, rem)
        hit = active & (eosv >= 0) & (nxt == eosv)
        active = active & ~hit & (rem > 0)
        return (nxt, pos, active, rem, kc, vc), out

    keys = jax.random.split(
        key if key is not None else jax.random.PRNGKey(0), horizon
    )
    (tok, pos, active, rem, kc, vc), outs = jax.lax.scan(
        step, (tok, pos, active, rem, kc, vc), keys
    )
    return jnp.swapaxes(outs, 0, 1), tok, pos, active, rem, kc, vc


# -- inference: paged KV cache (block tables) --------------------------------
#
# The vLLM-PagedAttention memory layout adapted to the donated-buffer,
# fused-horizon programs above: instead of one contiguous
# [L, B, max_len, KV, hd] region, K/V live in a POOL of fixed-size
# blocks [L, n_blocks, block_size, KV, hd] and each slot carries a
# BLOCK TABLE row mapping its logical positions to physical blocks —
# logical position p of row r lives at
# (table[r, p // block_size], p % block_size). The serving engine
# allocates blocks on demand as each request grows, frees them on
# finish, and can map LEADING table entries of different rows to the
# SAME physical block (refcounted shared-prefix reuse) — HBM scales
# with tokens actually resident, not slots × max_len.
#
# Program-stability contract is unchanged: one compiled program per
# (cfg, shapes); the table is a TRACED int32 operand, so allocation,
# sharing, and frees are host bookkeeping — membership and mapping
# changes never retrace. Physical block 0 is reserved by the engine as
# a SCRATCH block: inactive/frozen lanes and prompt-bucket padding
# route their writes there, and no live table entry ever maps to it,
# so colliding scratch writes are never read back.
#
# -- paged KV quantization (kv_quant = "int8" | "int4") ----------------------
#
# Decode is KV-bandwidth-bound once weights are int8 (BENCH_r05: b=1
# at ~99.5% of peak HBM BW), so the paged pool can optionally store
# QUANTIZED K/V: int8 (or packed int4) values with per-block-per-kv-
# head f32 absmax scales — the fixed-size block is the quantization
# unit, which is what lets quantization compose with refcounted CoW
# prefix sharing (a block copy carries its scale entry with it).
#
# The dequantize follows ``_matw``'s int8-weight discipline: the scale
# never touches the contraction —
#
# * K side: the scale is constant along the contracted ``hd`` axis, so
#   ``scores = einsum(q, kq.astype(dt)) * ks`` — XLA fuses the
#   convert(int8→dt) into the operand read and HBM streams int8 bytes;
#   the f32 scale multiply lands on the [.., S] scores, not on a
#   dequantized [S, KV, hd] temp;
# * V side: the scale varies along the contracted ``s`` axis but is
#   indexed exactly like the softmax probs, so it folds into them:
#   ``o = einsum((probs * vs).astype(dt), vq.astype(dt))``.
#
# Writes quantize ON THE FLY inside the same program that computes the
# fresh K/V (decode lanes, verify lanes, prefill chunks — one shared
# scatter discipline, :func:`_kvq_store`): per dispatch, each written
# block's scale is grown to cover the new values' absmax (scatter-max),
# RESET when the write lands at block offset 0 (a block's first write
# is always offset 0 — decode crosses boundaries at offset 0, prefill
# starts block-aligned, and the CoW full-hit rewrite targets the last
# offset of a COPIED block that brought its scale along), and resident
# block content is rescaled under the grown scale so earlier tokens
# stay consistent. Scales only grow between resets, so the rescale
# ratio is <= 1 and an idempotent frozen-lane rewrite is exact
# (ratio 1). Exact greedy token identity cannot survive quantization;
# the serving engine keeps ``kv_quant="off"`` byte-identical to the
# unquantized path (these branches are trace-time, the "off" programs
# and memo keys are untouched) and gates the quantized path on output
# tolerance + the speculative acceptance EMA (engine-side).

_KVQ_QMAX = {"int8": 127.0, "int4": 7.0}


def kvq_packed_head_dim(kv_quant: str, head_dim: int) -> int:
    """Innermost stored dim of one pool entry: int4 packs two 4-bit
    values per int8 byte along ``hd`` (requires even head_dim)."""
    if kv_quant == "int4":
        if head_dim % 2:
            raise ValueError(
                f"kv_quant int4 needs an even head_dim, got {head_dim}"
            )
        return head_dim // 2
    return head_dim


def _kvq_pack(q: jnp.ndarray, kv_quant: str) -> jnp.ndarray:
    """Rounded/clipped quantized values (f32 in [-qmax, qmax]) ->
    stored int8. int4 packs index pairs along the last axis: even
    index = low nibble, odd = high nibble."""
    qi = q.astype(jnp.int32)
    if kv_quant == "int8":
        return qi.astype(jnp.int8)
    lo = qi[..., 0::2]
    hi = qi[..., 1::2]
    return ((hi << 4) | (lo & 0xF)).astype(jnp.int8)


def _kvq_unpack(p: jnp.ndarray, kv_quant: str) -> jnp.ndarray:
    """Stored int8 -> quantized values as f32 in [-qmax, qmax]."""
    if kv_quant == "int8":
        return p.astype(jnp.float32)
    x = p.astype(jnp.int32)
    hi = x >> 4  # arithmetic shift sign-extends the high nibble
    lo = ((x & 0xF) ^ 8) - 8  # sign-extend the low nibble
    both = jnp.stack([lo, hi], axis=-1)  # [..., hd/2, 2]
    return both.reshape(*p.shape[:-1], p.shape[-1] * 2).astype(
        jnp.float32
    )


def _kvq_store(
    pool: jnp.ndarray,
    scale: jnp.ndarray,
    i: int,
    wblk: jnp.ndarray,
    woff: jnp.ndarray,
    new: jnp.ndarray,
    kv_quant: str,
):
    """Quantize ``new`` [N, KV, hd] lane writes into layer ``i`` of the
    packed ``pool`` [L, nb, bs, KV, hdp] at (``wblk``, ``woff``) [N],
    maintaining per-(block, kv-head) f32 ``scale`` [L, nb, KV].

    Per dispatch: (1) scatter-max the new values' absmax into per-block
    scale proposals; (2) a write at offset 0 marks its block FRESH —
    the scale resets instead of inheriting a freed previous tenant's
    (a block's first real write is always offset 0, see the section
    comment); (3) touched blocks' resident content is rescaled under
    the grown scale (gather-modify-scatter of the written blocks only;
    duplicate block indices carry identical payloads, so the scatter is
    deterministic; fresh blocks' stale content is zeroed); (4) the new
    values quantize under the final scale and land at their offsets.
    Only refcount-1 blocks are ever written (the engine copy-on-writes
    shared blocks first), so no two rows contend for one block — except
    SCRATCH, whose content and scale are never read."""
    qmax = _KVQ_QMAX[kv_quant]
    newf = new.astype(jnp.float32)
    amax = jnp.max(jnp.abs(newf), axis=-1)  # [N, KV]
    nb = scale.shape[1]
    s_old = scale[i]  # [nb, KV]
    prop = jnp.zeros_like(s_old).at[wblk].max(amax)
    fresh = (
        jnp.zeros((nb,), jnp.int32)
        .at[wblk]
        .max((woff == 0).astype(jnp.int32))
        > 0
    )
    touched = jnp.zeros((nb,), bool).at[wblk].set(True)
    s_new = jnp.maximum(
        jnp.where(fresh[:, None], 0.0, s_old), prop / qmax
    )
    s_new = jnp.where(touched[:, None], s_new, s_old)
    s_safe = jnp.where(s_new > 0.0, s_new, 1.0)
    # resident-content rescale: exact identity (ratio 1) when the scale
    # did not move; fresh blocks' stale previous-tenant content zeroes
    ratio = (jnp.where(fresh[:, None], 0.0, s_old) / s_safe)[wblk]
    cur = _kvq_unpack(pool[i][wblk], kv_quant)  # [N, bs, KV, hd]
    resc = jnp.clip(
        jnp.round(cur * ratio[:, None, :, None]), -qmax, qmax
    )
    pool = pool.at[i, wblk].set(_kvq_pack(resc, kv_quant))
    qnew = jnp.clip(
        jnp.round(newf / s_safe[wblk][:, :, None]), -qmax, qmax
    )
    pool = pool.at[i, wblk, woff].set(_kvq_pack(qnew, kv_quant))
    scale = scale.at[i].set(s_new)
    return pool, scale


def _kvq_scale_strip(scale_i: jnp.ndarray, table: jnp.ndarray, bs: int):
    """Per-position scale strip for the attention gather: gather the
    [.., M, KV] block scales through the table and expand to
    [.., KV, 1, 1, S], broadcastable against the ``bkgts`` score/prob
    layout (block j's scale covers positions j*bs .. (j+1)*bs - 1)."""
    sc = jnp.repeat(scale_i[table], bs, axis=-2)  # [.., S, KV]
    sc = jnp.swapaxes(sc, -1, -2)  # [.., KV, S]
    if sc.ndim == 2:  # single-slot table (prefill): add the batch axis
        sc = sc[None]
    return sc[:, :, None, None, :]


def decode_step_slots_paged(
    params: Dict,
    tok: jnp.ndarray,
    pos: jnp.ndarray,
    table: jnp.ndarray,
    kc: jnp.ndarray,
    vc: jnp.ndarray,
    cfg: LlamaConfig,
    block_size: int,
    kv_quant: str = "off",
    ks: Optional[jnp.ndarray] = None,
    vs: Optional[jnp.ndarray] = None,
):
    """One slot-decode step over the paged pool. tok/pos [B] int32;
    table [B, M] int32 physical block ids; kc/vc
    [L, n_blocks, block_size, KV, hd]. Returns (logits [B, V], kc, vc).

    Per-row math is IDENTICAL to :func:`decode_step_slots` — the only
    differences are the scatter target (the row's CURRENT block at
    ``pos % block_size`` instead of cache row ``pos``) and the
    attention read (a table gather reassembles each row's logical
    [M·bs, KV, hd] view; the ``arange(S) <= pos`` mask hides garbage
    in covered-but-unwritten and scratch-mapped positions exactly as
    it hides the contiguous cache's tail). Greedy output is therefore
    token-identical to the contiguous path whenever the engine's
    tables cover every written position — the contract
    tests/test_paged_kv.py pins at H ∈ {1, 4, 16}.

    ``kv_quant`` != "off" switches the pool to quantized storage (int8
    or packed int4 entries + per-block-per-kv-head f32 scales ``ks``/
    ``vs`` [L, nb, KV], see the section comment): lane writes quantize
    on the fly, the gather dequantizes via the factored scale multiply,
    and the returned tuple grows ``(ks, vs)``. The "off" path is
    byte-identical to before the knob existed — the branch is
    trace-time."""
    b = tok.shape[0]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    groups = h // kvh
    bs = block_size
    m = table.shape[1]
    s = m * bs
    rows = jnp.arange(b)
    quant = kv_quant != "off"
    # rows whose pos ran past the table (a frozen lane parked one past
    # its last token, or a stale lane the host stopped tracking) write
    # to the scratch block — a clamped gather would alias the LAST real
    # block and corrupt it
    inb = pos < s
    blk = jnp.where(
        inb, table[rows, jnp.clip(pos // bs, 0, m - 1)], 0
    )  # [B] physical block per row
    off = jnp.where(inb, pos % bs, 0)
    x = jnp.take(params["embed"], tok[:, None], axis=0).astype(cfg.dtype)
    for i in range(cfg.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[i], params["layers"])
        dt = x.dtype
        a = _rmsnorm(x, lp["ln1"], cfg.norm_eps)
        q, knew, vnew = _qkv(cfg, a, lp, pos[:, None])
        if quant:
            kc, ks = _kvq_store(kc, ks, i, blk, off, knew[:, 0], kv_quant)
            vc, vs = _kvq_store(vc, vs, i, blk, off, vnew[:, 0], kv_quant)
            kci = _kvq_unpack(kc[i][table], kv_quant).reshape(
                b, s, kvh, hd
            ).astype(dt)
            vci = _kvq_unpack(vc[i][table], kv_quant).reshape(
                b, s, kvh, hd
            ).astype(dt)
            ksc = _kvq_scale_strip(ks[i], table, bs)  # [B, KV, 1, 1, S]
            vsc = _kvq_scale_strip(vs[i], table, bs)
        else:
            kc = kc.at[i, blk, off].set(knew[:, 0])
            vc = vc.at[i, blk, off].set(vnew[:, 0])
            # table gather: [n_blocks, bs, KV, hd][table] -> the row's
            # logical [B, M, bs, KV, hd] view, flat to [B, S, KV, hd]
            kci = kc[i][table].reshape(b, s, kvh, hd)
            vci = vc[i][table].reshape(b, s, kvh, hd)
        qg = q.reshape(b, 1, kvh, groups, hd)
        scores = jnp.einsum("btkgd,bskd->bkgts", qg, kci) / np.sqrt(hd)
        if quant:
            # the K-side dequant: per-(block, kv-head) scale lands on
            # the f32 scores (constant along the contracted hd axis),
            # never on a dequantized [S, KV, hd] temp — _matw's
            # discipline, the f32 multiply included
            scores = scores.astype(jnp.float32) * ksc
        mask = (jnp.arange(s)[None, :] <= pos[:, None])[:, None, None, None, :]
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        if quant:
            # the V-side dequant folds into the probs (scale varies
            # along the contracted s axis but indexes like the probs)
            probs = probs * vsc
        probs = probs.astype(dt)
        o = jnp.einsum("bkgts,bskd->btkgd", probs, vci).reshape(b, 1, h * hd)
        x = x + _matw(o, lp["wo"])
        x = _mlp(cfg, x, lp)
    x = _rmsnorm(x, params["ln_f"], cfg.norm_eps)
    logits = _matw(x[:, 0], params["lm_head"]).astype(jnp.float32)
    if quant:
        return logits, kc, vc, ks, vs
    return logits, kc, vc


def decode_horizon_slots_paged(
    params: Dict,
    tok: jnp.ndarray,
    pos: jnp.ndarray,
    active: jnp.ndarray,
    rem: jnp.ndarray,
    eosv: jnp.ndarray,
    table: jnp.ndarray,
    kc: jnp.ndarray,
    vc: jnp.ndarray,
    cfg: LlamaConfig,
    block_size: int,
    horizon: int,
    key: Optional[jax.Array] = None,
    temperature=None,
    sampling: bool = False,
    kv_quant: str = "off",
    ks: Optional[jnp.ndarray] = None,
    vs: Optional[jnp.ndarray] = None,
):
    """The paged twin of :func:`decode_horizon_slots`: a fused horizon
    of ``horizon`` :func:`decode_step_slots_paged` steps with the SAME
    on-device freeze semantics (frozen lanes emit -1, rewrite their
    frozen position idempotently, and never disturb other rows). The
    block table is READ-ONLY across the horizon — the engine covers
    every position the horizon can write before dispatching, so no
    mid-horizon allocation is ever needed on device.

    Under ``kv_quant`` != "off" the scan carry grows the scale planes
    and the return tuple ends in ``(..., kc, vc, ks, vs)``."""
    quant = kv_quant != "off"

    def step(carry, k):
        if quant:
            tok, pos, active, rem, kc, vc, ks, vs = carry
            logits, kc, vc, ks, vs = decode_step_slots_paged(
                params, tok, pos, table, kc, vc, cfg, block_size,
                kv_quant=kv_quant, ks=ks, vs=vs,
            )
        else:
            tok, pos, active, rem, kc, vc = carry
            logits, kc, vc = decode_step_slots_paged(
                params, tok, pos, table, kc, vc, cfg, block_size
            )
        if sampling:
            nxt = jax.random.categorical(k, logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        nxt = jnp.where(active, nxt.astype(jnp.int32), tok)
        out = jnp.where(active, nxt, -1)
        pos = jnp.where(active, pos + 1, pos)
        rem = jnp.where(active, rem - 1, rem)
        hit = active & (eosv >= 0) & (nxt == eosv)
        active = active & ~hit & (rem > 0)
        if quant:
            return (nxt, pos, active, rem, kc, vc, ks, vs), out
        return (nxt, pos, active, rem, kc, vc), out

    keys = jax.random.split(
        key if key is not None else jax.random.PRNGKey(0), horizon
    )
    if quant:
        (tok, pos, active, rem, kc, vc, ks, vs), outs = jax.lax.scan(
            step, (tok, pos, active, rem, kc, vc, ks, vs), keys
        )
        return (
            jnp.swapaxes(outs, 0, 1), tok, pos, active, rem, kc, vc, ks, vs
        )
    (tok, pos, active, rem, kc, vc), outs = jax.lax.scan(
        step, (tok, pos, active, rem, kc, vc), keys
    )
    return jnp.swapaxes(outs, 0, 1), tok, pos, active, rem, kc, vc


def prefill_paged(
    params: Dict,
    tokens: jnp.ndarray,
    start,
    last,
    table: jnp.ndarray,
    kc: jnp.ndarray,
    vc: jnp.ndarray,
    cfg: LlamaConfig,
    block_size: int,
    kv_quant: str = "off",
    ks: Optional[jnp.ndarray] = None,
    vs: Optional[jnp.ndarray] = None,
):
    """Prefill one CHUNK of one slot's prompt into the paged pool.

    ``tokens`` [1, Tb] covers logical positions ``start .. start+Tb-1``
    with real tokens only through local index ``last`` (end-padding,
    same bucket contract as :func:`prefill_padded`); positions below
    ``start`` must already be resident in the pool (earlier chunks, or
    shared prefix blocks another request prefilled). ``table`` [M] is
    the ONE slot's block-table row. Returns (logits [1, V] at ``last``,
    kc, vc).

    This one function serves admission prefill (start = prefix-hit
    length), CHUNKED prefill of long prompts (each bounded chunk is a
    separate dispatch, interleaved with decode blocks), and the
    crash-recovery replay. Queries attend causally to the pool —
    chunk token t sees every position <= start + t, which includes the
    chunk's own K/V because the scatter lands before the gather. Pad
    tokens (t > last) write to the scratch block (never read) and
    their query rows are discarded by the caller taking ``last``'s
    logits only.

    Under ``kv_quant`` != "off" the whole chunk quantizes on the fly
    (one :func:`_kvq_store` per layer per plane — the chunk's writes to
    a block land together, so its scale converges in one step) and the
    return tuple grows ``(ks, vs)``."""
    b, tb = tokens.shape
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    groups = h // kvh
    bs = block_size
    m = table.shape[0]
    s = m * bs
    positions = start + jnp.arange(tb)  # [Tb] absolute positions
    tpos = jnp.arange(tb)
    real = tpos <= last
    # per-token write targets; pads route to the scratch block so a
    # bucket overhanging the covered table never writes out of range
    wblk = jnp.where(
        real, table[jnp.clip(positions // bs, 0, m - 1)], 0
    )
    woff = jnp.where(real, positions % bs, 0)
    quant = kv_quant != "off"
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    qmask = (jnp.arange(s)[None, :] <= positions[:, None])[
        None, None, None, :, :
    ]  # [1,1,1,Tb,S]: query t sees pool positions <= start + t
    for i in range(cfg.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[i], params["layers"])
        dt = x.dtype
        a = _rmsnorm(x, lp["ln1"], cfg.norm_eps)
        q, knew, vnew = _qkv(cfg, a, lp, positions)
        if quant:
            kc, ks = _kvq_store(kc, ks, i, wblk, woff, knew[0], kv_quant)
            vc, vs = _kvq_store(vc, vs, i, wblk, woff, vnew[0], kv_quant)
            kci = _kvq_unpack(kc[i][table], kv_quant).reshape(
                1, s, kvh, hd
            ).astype(dt)
            vci = _kvq_unpack(vc[i][table], kv_quant).reshape(
                1, s, kvh, hd
            ).astype(dt)
            ksc = _kvq_scale_strip(ks[i], table, bs)
            vsc = _kvq_scale_strip(vs[i], table, bs)
        else:
            kc = kc.at[i, wblk, woff].set(knew[0])
            vc = vc.at[i, wblk, woff].set(vnew[0])
            kci = kc[i][table].reshape(1, s, kvh, hd)
            vci = vc[i][table].reshape(1, s, kvh, hd)
        qg = q.reshape(b, tb, kvh, groups, hd)
        scores = jnp.einsum("btkgd,bskd->bkgts", qg, kci) / np.sqrt(hd)
        if quant:
            scores = scores.astype(jnp.float32) * ksc
        scores = jnp.where(qmask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        if quant:
            probs = probs * vsc
        probs = probs.astype(dt)
        o = jnp.einsum("bkgts,bskd->btkgd", probs, vci).reshape(b, tb, h * hd)
        x = x + _matw(o, lp["wo"])
        x = _mlp(cfg, x, lp)
    x = _rmsnorm(x, params["ln_f"], cfg.norm_eps)
    xl = x[jnp.arange(b), last]  # [1, d] — the chunk's last real token
    logits = _matw(xl, params["lm_head"]).astype(jnp.float32)
    if quant:
        return logits, kc, vc, ks, vs
    return logits, kc, vc


# -- inference: speculative decoding (draft-verify) --------------------------
#
# Single-stream decode is weight-bandwidth-bound (BENCH_r05: int8 b=1
# already at ~99.5% of peak HBM bandwidth), so the only remaining
# latency lever is emitting MORE THAN ONE token per weight pass. The
# verify programs below score K = D+1 query lanes per slot in one
# dispatch — the pending token plus D host-drafted continuation
# guesses — under length-K masked attention over the same KV cache the
# horizon programs use. Greedy acceptance keeps the stream
# token-identical to sequential decode: lane j's argmax is the true
# next token after consuming lanes 0..j, so the longest draft prefix
# matching argmax can be committed, plus the first non-matching argmax
# as a bonus token (always >= 1 token per dispatch — a rejected draft
# degrades to exactly one plain decode step, never worse).
#
# KV discipline: lane j writes its token's K/V at position pos+j
# BEFORE the gather, so causal lanes see their own prefix. Rejected
# lanes leave garbage at positions past the accepted run — safe under
# the same overwrite-before-unmask invariant the horizon path uses:
# the next dispatch re-writes every position it unmasks before reading
# it (its lane 0 rewrites the new pending token's position, lane j its
# own). Out-of-range writes (a row near the end of its cache) are
# DROPPED (mode="drop"), matching the frozen-row behavior of
# ``decode_step_slots`` at pos == S.


def verify_step_slots(
    params: Dict,
    tok: jnp.ndarray,
    draft: jnp.ndarray,
    pos: jnp.ndarray,
    active: jnp.ndarray,
    rem: jnp.ndarray,
    eosv: jnp.ndarray,
    kc: jnp.ndarray,
    vc: jnp.ndarray,
    cfg: LlamaConfig,
):
    """One speculative draft–verify step over B independent KV slots:
    score the pending token plus D drafted tokens in ONE dispatch and
    commit the longest greedy-consistent prefix ON DEVICE.

    tok [B] int32 (each slot's pending token, K/V not yet written);
    draft [B, D] int32 host-proposed continuations, -1 = no draft in
    that lane (-1 never matches an argmax, so a row with all -1 drafts
    degrades to exactly one plain decode step — per-slot drafting is
    disabled by feeding sentinels, membership never changes the
    program); pos/rem/eosv [B] int32 and active [B] bool with the SAME
    semantics as :func:`decode_horizon_slots`. kc/vc
    [L, B, S, KV, hd]. Returns ``(outs [B, K], tok, pos, active, rem,
    kc, vc)`` with K = D+1 — ``outs`` rows are the committed tokens in
    emission order with -1 tails (frozen lanes, rejected drafts,
    post-EOS lanes), the exact drain contract of the horizon programs.

    Lane j embeds token j of ``[tok, draft]`` at position pos+j,
    writes its K/V there, and attends causally to positions <= pos+j
    (its own write and earlier lanes' writes land before the gather).
    Lane j's argmax is therefore the true greedy successor of the
    sequence ``... tok draft[0..j-1]`` — if every draft before lane j
    matched argmax, lane j's argmax is exactly what sequential decode
    would emit. Acceptance commits ``a`` = longest matching draft
    prefix plus lane a's argmax as the bonus token (1 <= emitted <=
    K), truncated by the row's remaining budget and cut AFTER the
    first emitted EOS (the EOS itself is emitted, mid-verify, exactly
    like the horizon's on-device EOS freeze). Frozen rows emit
    nothing and keep their state; their lane-0 rewrite at the frozen
    ``pos`` is idempotent and later lanes drop or are overwritten
    before unmask. Greedy output is token-identical to sequential
    :func:`generate` under EVERY acceptance outcome — the contract
    tests/test_serving_spec.py pins."""
    b, d = draft.shape
    k = d + 1
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    groups = h // kvh
    s = kc.shape[2]
    rows = jnp.arange(b)
    # -1 sentinels embed as token 0; their lanes are never accepted
    # (argmax >= 0 never equals -1), so the embedded value is dead
    toks = jnp.concatenate([tok[:, None], jnp.maximum(draft, 0)], axis=1)
    qpos = pos[:, None] + jnp.arange(k)[None, :]  # [B, K] absolute
    x = jnp.take(params["embed"], toks, axis=0).astype(cfg.dtype)
    # lane j sees cache positions <= pos+j — its own write included,
    # garbage beyond masked exactly like the decode step's tail
    qmask = (jnp.arange(s)[None, None, :] <= qpos[:, :, None])[
        :, None, None, :, :
    ]  # [B,1,1,K,S]
    for i in range(cfg.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[i], params["layers"])
        dt = x.dtype
        a = _rmsnorm(x, lp["ln1"], cfg.norm_eps)
        q, knew, vnew = _qkv(cfg, a, lp, qpos)
        # per-row K-lane scatter; rows[:, None] broadcasts against the
        # [B, K] positions. Writes past S drop (frozen rows parked at
        # the cache end), never clamp — a clamp would alias S-1.
        kc = kc.at[i, rows[:, None], qpos].set(knew, mode="drop")
        vc = vc.at[i, rows[:, None], qpos].set(vnew, mode="drop")
        kci, vci = kc[i], vc[i]
        qg = q.reshape(b, k, kvh, groups, hd)
        scores = jnp.einsum("btkgd,bskd->bkgts", qg, kci) / np.sqrt(hd)
        scores = jnp.where(qmask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        o = jnp.einsum("bkgts,bskd->btkgd", probs, vci).reshape(b, k, h * hd)
        x = x + _matw(o, lp["wo"])
        x = _mlp(cfg, x, lp)
    x = _rmsnorm(x, params["ln_f"], cfg.norm_eps)
    logits = _matw(x, params["lm_head"]).astype(jnp.float32)  # [B, K, V]
    out = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K]
    return _spec_accept(tok, draft, out, pos, active, rem, eosv, kc, vc)


def _spec_accept(tok, draft, out, pos, active, rem, eosv, kc, vc):
    """On-device acceptance shared by the contiguous and paged verify
    steps: commit the longest draft prefix matching greedy argmax plus
    one bonus token, truncated by the remaining budget and cut after
    the first emitted EOS. Pure slot-state bookkeeping — the K/V for
    every committed position was already written by the verify lanes
    (committed lane j's input token IS the matched draft)."""
    b, d = draft.shape
    k = d + 1
    rows = jnp.arange(b)
    # a = accepted draft prefix length: drafts match out shifted by one
    # (out[:, j] is the successor of the sequence THROUGH draft[j-1])
    match = (draft == out[:, :d]).astype(jnp.int32)
    a = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [B] in 0..D
    idx = jnp.arange(k)[None, :]
    emit = (
        active[:, None]
        & (idx < (a + 1)[:, None])  # accepted run + bonus token
        & (idx < rem[:, None])  # budget truncation, same as horizon rem
    )
    is_eos = (eosv[:, None] >= 0) & (out == eosv[:, None])
    eos_emitted = emit & is_eos
    # lanes strictly AFTER the first emitted EOS are cut; the EOS
    # itself is emitted (exclusive running count: cumsum minus self)
    before = jnp.cumsum(eos_emitted.astype(jnp.int32), axis=1) - (
        eos_emitted.astype(jnp.int32)
    )
    emit = emit & (before == 0)
    e = jnp.sum(emit.astype(jnp.int32), axis=1)  # [B] emitted count
    outs = jnp.where(emit, out, -1)
    # the new pending token is the LAST emitted one (its K/V is not
    # yet written — the next dispatch's lane 0 writes it, the same
    # pending-token contract every decode program shares)
    tok = jnp.where(e > 0, out[rows, jnp.clip(e - 1, 0, k - 1)], tok)
    pos = pos + e
    rem = rem - e
    hit = jnp.any(eos_emitted & emit, axis=1)
    active = active & ~hit & (rem > 0)
    return outs, tok, pos, active, rem, kc, vc


def verify_step_slots_paged(
    params: Dict,
    tok: jnp.ndarray,
    draft: jnp.ndarray,
    pos: jnp.ndarray,
    active: jnp.ndarray,
    rem: jnp.ndarray,
    eosv: jnp.ndarray,
    table: jnp.ndarray,
    kc: jnp.ndarray,
    vc: jnp.ndarray,
    cfg: LlamaConfig,
    block_size: int,
    kv_quant: str = "off",
    ks: Optional[jnp.ndarray] = None,
    vs: Optional[jnp.ndarray] = None,
):
    """The paged twin of :func:`verify_step_slots`: K = D+1 query lanes
    per row routed through the [B, M] block table, same on-device
    acceptance. Lane writes target (table[row, (pos+j) // bs],
    (pos+j) % bs); out-of-table lanes and uncovered positions route to
    the scratch block (collisions there are never read). The engine
    covers every position the accepted run can commit before
    dispatching (``_ensure_cover`` sized to max(horizon, K)), so
    committed lanes always land in mapped private blocks — uncovered
    garbage from rejected lanes dies in scratch or is overwritten
    before its position is ever unmasked.

    Under ``kv_quant`` != "off" the [B, K] lane writes flatten into one
    :func:`_kvq_store` per plane per layer (rejected-lane garbage can
    only GROW a resident block's scale — a monotone rescale, never a
    corruption; the garbage values themselves are overwritten before
    their positions unmask) and the return tuple grows ``(ks, vs)``."""
    b, d = draft.shape
    k = d + 1
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    groups = h // kvh
    bs = block_size
    m = table.shape[1]
    s = m * bs
    rows = jnp.arange(b)
    toks = jnp.concatenate([tok[:, None], jnp.maximum(draft, 0)], axis=1)
    qpos = pos[:, None] + jnp.arange(k)[None, :]  # [B, K]
    inb = qpos < s
    # per-lane physical write targets; lanes past the table go to
    # scratch like the decode step's frozen/stale rows
    wblk = jnp.where(
        inb, table[rows[:, None], jnp.clip(qpos // bs, 0, m - 1)], 0
    )
    woff = jnp.where(inb, qpos % bs, 0)
    quant = kv_quant != "off"
    x = jnp.take(params["embed"], toks, axis=0).astype(cfg.dtype)
    qmask = (jnp.arange(s)[None, None, :] <= qpos[:, :, None])[
        :, None, None, :, :
    ]
    for i in range(cfg.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[i], params["layers"])
        dt = x.dtype
        a = _rmsnorm(x, lp["ln1"], cfg.norm_eps)
        q, knew, vnew = _qkv(cfg, a, lp, qpos)
        if quant:
            kc, ks = _kvq_store(
                kc, ks, i, wblk.reshape(-1), woff.reshape(-1),
                knew.reshape(b * k, kvh, hd), kv_quant,
            )
            vc, vs = _kvq_store(
                vc, vs, i, wblk.reshape(-1), woff.reshape(-1),
                vnew.reshape(b * k, kvh, hd), kv_quant,
            )
            kci = _kvq_unpack(kc[i][table], kv_quant).reshape(
                b, s, kvh, hd
            ).astype(dt)
            vci = _kvq_unpack(vc[i][table], kv_quant).reshape(
                b, s, kvh, hd
            ).astype(dt)
            ksc = _kvq_scale_strip(ks[i], table, bs)
            vsc = _kvq_scale_strip(vs[i], table, bs)
        else:
            kc = kc.at[i, wblk, woff].set(knew)
            vc = vc.at[i, wblk, woff].set(vnew)
            kci = kc[i][table].reshape(b, s, kvh, hd)
            vci = vc[i][table].reshape(b, s, kvh, hd)
        qg = q.reshape(b, k, kvh, groups, hd)
        scores = jnp.einsum("btkgd,bskd->bkgts", qg, kci) / np.sqrt(hd)
        if quant:
            scores = scores.astype(jnp.float32) * ksc
        scores = jnp.where(qmask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        if quant:
            probs = probs * vsc
        probs = probs.astype(dt)
        o = jnp.einsum("bkgts,bskd->btkgd", probs, vci).reshape(b, k, h * hd)
        x = x + _matw(o, lp["wo"])
        x = _mlp(cfg, x, lp)
    x = _rmsnorm(x, params["ln_f"], cfg.norm_eps)
    logits = _matw(x, params["lm_head"]).astype(jnp.float32)
    out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    acc = _spec_accept(tok, draft, out, pos, active, rem, eosv, kc, vc)
    if quant:
        return acc + (ks, vs)
    return acc


def generate(
    params: Dict,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    max_new: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Autoregressive generation from a prompt [B, T0] → [B, max_new].

    Greedy at ``temperature == 0`` (the default), categorical sampling
    otherwise (``key`` required), with the standard serving controls:
    ``top_k > 0`` restricts sampling to the k most likely tokens,
    ``top_p < 1`` to the smallest nucleus whose probability mass
    reaches p (the first token always stays eligible). Both compose
    (k-truncate, then nucleus within it). One jit per (shape, cfg,
    top_k, top_p-active): prefill + a ``lax.scan`` decode loop over
    positions with the KV cache as carry; temperature and p are traced
    scalars (sweeping them costs zero recompiles). Accepts params
    straight from ``runtime.export.load_export`` (cast float leaves to
    ``cfg.dtype``-compatible types first if the export was bf16 and
    you want f32 math)."""
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if temperature <= 0 and (top_k or top_p < 1.0):
        # greedy argmax ignores the sampling filters — raising mirrors
        # the CLI's rejection so library callers get the same signal
        # instead of silently-inert arguments (ADVICE r5)
        raise ValueError(
            "top_k/top_p require temperature > 0 "
            "(greedy decoding ignores them)"
        )
    if max_new < 1:
        raise ValueError(f"max_new must be >= 1, got {max_new}")
    if top_k < 0 or top_k > cfg.vocab:
        raise ValueError(f"top_k must be in [0, vocab], got {top_k}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if cfg.int8_mxu:
        # training-only throughput flag: left on it would dynamically
        # quantize SOME decode matmuls (the _qkv/_mlp shared ones) but
        # not others — silently inconsistent numerics on the serving
        # path. Serving quantization is quantize_params_int8 instead.
        import dataclasses

        cfg = dataclasses.replace(cfg, int8_mxu=False, int8_wgrad_bf16=False)
    b, t0 = tokens.shape
    run = _generate_program(
        cfg, b, t0, int(max_new), temperature > 0, int(top_k), top_p < 1.0
    )
    return run(
        params,
        tokens,
        key if key is not None else jax.random.PRNGKey(0),
        jnp.float32(temperature if temperature > 0 else 1.0),
        jnp.float32(top_p),
    )


_generate_programs: "OrderedDict" = OrderedDict()
_GENERATE_PROGRAM_CAP = 64


def _generate_program(cfg: LlamaConfig, b: int, t0: int, max_new: int,
                      sampling: bool, top_k: int, use_top_p: bool):
    """Memoized jit program per (cfg, shapes, greedy-vs-sampling,
    top_k, top_p-active) — repeat generate() calls reuse the compiled
    prefill+decode scan instead of re-tracing (a full-size model pays
    minutes per compile). Temperature and the nucleus threshold are
    TRACED scalars: sweeping them costs zero recompiles; only the
    top_k VALUE is static (it sets the truncated shape).

    The cache is LRU (move-to-end on hit, evict-oldest at the cap):
    the previous clear-everything eviction dropped the HOT serving
    program the moment a 65th shape appeared, re-paying a full-size
    compile mid-traffic."""
    cache_key = (cfg, b, t0, max_new, sampling, top_k, use_top_p)
    run = _generate_programs.get(cache_key)
    if run is not None:
        _generate_programs.move_to_end(cache_key)
        return run
    kvh, hd, L = cfg.n_kv_heads, cfg.head_dim, cfg.n_layers
    max_len = t0 + max_new

    @jax.jit
    def run(params, tokens, key, temperature, top_p):
        logits, ks, vs = _prefill(params, tokens, cfg)
        pad = jnp.zeros((L, b, max_len - t0, kvh, hd), ks.dtype)
        kc = jnp.concatenate([ks, pad], axis=2)
        vc = jnp.concatenate([vs, pad], axis=2)

        def sample(logits, k):
            if not sampling:
                return jnp.argmax(logits, axis=-1)
            if not top_k and not use_top_p:
                return jax.random.categorical(k, logits / temperature, axis=-1)
            # truncate to the top-m subspace (descending), sample the
            # INDEX within it, then map back through the gathered ids —
            # nucleus filtering only ever sees the sorted tail
            m = top_k if top_k else logits.shape[-1]
            vals, idx = jax.lax.top_k(logits, m)  # [B, m] descending
            scaled = vals / temperature
            if use_top_p:
                probs = jax.nn.softmax(scaled, axis=-1)
                # exclusive cumulative mass: the first token's mass is
                # 0, so it is always eligible (top_p -> 0 degenerates
                # to greedy, never to an empty support)
                cum = jnp.cumsum(probs, axis=-1) - probs
                scaled = jnp.where(cum < top_p, scaled, -jnp.inf)
            j = jax.random.categorical(k, scaled, axis=-1)
            return jnp.take_along_axis(idx, j[:, None], axis=-1)[:, 0]

        def step(carry, i):
            logits, kc, vc, k = carry
            k, sub = jax.random.split(k)
            tok = sample(logits, sub).astype(jnp.int32)
            logits, kc, vc = _decode_step(params, tok, t0 + i, kc, vc, cfg)
            return (logits, kc, vc, k), tok

        (_, _, _, _), toks = jax.lax.scan(
            step, (logits, kc, vc, key), jnp.arange(max_new)
        )
        return jnp.swapaxes(toks, 0, 1)  # [B, max_new]

    # compile watch: each cache key is one distinct program — its first
    # call is timed into edl_compile_seconds{program="llama.generate"}
    # and flagged as obs.recompile once the process declared warmup over
    run = compilewatch.wrap(run, "llama.generate")

    while len(_generate_programs) >= _GENERATE_PROGRAM_CAP:
        _generate_programs.popitem(last=False)  # evict least-recent
    _generate_programs[cache_key] = run
    return run


def train_flops_per_token(cfg: LlamaConfig, seq: int) -> float:
    """Model FLOPs per trained token (fwd+bwd), the MFU numerator:
    6 × matmul params (embedding lookup excluded, lm_head included)
    plus causal attention 12·L·(T/2)·d_attn. Remat recompute is NOT
    counted (MFU convention: model FLOPs, not hardware FLOPs).

    The formula itself lives in ``obs/costmodel.py`` — the ONE analytic
    cost model bench.py, exp_mfu, and the live efficiency gauges share
    (tests/test_costmodel.py pins the call sites agree)."""
    return _costmodel.train_flops_per_token(cfg, seq)


def make_loss_fn(cfg: LlamaConfig, plan: Optional[MeshPlan] = None, mesh=None):
    """Next-token cross entropy; batch = {tokens [B, T+1]}.

    ``plan``/``mesh`` flow through to :func:`forward` to activate sp/pp
    (the trainable-strategy contract: the worker runtime builds the loss
    via ``Workload.make_loss(plan, mesh)`` after every rendezvous, so
    the program matches the current elastic mesh). The [B, T+1] token
    feed stays batch-sharded — int32 tokens are negligible bytes; the
    sp sharding starts at the embedding output inside ``forward``."""

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = forward(params, inputs, cfg, mesh=mesh, plan=plan)
        # fused CE (logsumexp - target logit): two reductions over the
        # vocab axis instead of materializing the full [B,T,V]
        # log-softmax (4+ GB of f32 at the bench config)
        import optax

        if plan is not None and plan.axis_size("sp") > 1:
            # align targets with the sequence-sharded logits so the CE
            # stays local to each sp shard (the mean is global)
            targets = jax.lax.with_sharding_constraint(
                targets, plan.sequence_sharding(mesh, rank=2)
            )
        from edl_tpu.models.losses import row_mean

        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        # per-row mean over T, then the runtime's real-row weighting
        # (identical to the global mean when no "_w" rides the batch)
        return row_mean(jnp.mean(ce, axis=-1), batch)

    return loss_fn


def synthetic_tokens(
    rng: np.random.RandomState, batch: int, seq: int, vocab: int
) -> Dict[str, np.ndarray]:
    """Markov-ish synthetic text: next token correlates with current, so
    the loss curve has signal."""
    toks = np.zeros((batch, seq + 1), np.int32)
    toks[:, 0] = rng.randint(0, vocab, batch)
    drift = rng.randint(1, 7, (batch,))
    for t in range(1, seq + 1):
        noise = rng.rand(batch) < 0.1
        toks[:, t] = np.where(
            noise, rng.randint(0, vocab, batch), (toks[:, t - 1] + drift) % vocab
        )
    return {"tokens": toks}
