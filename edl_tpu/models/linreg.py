"""fit_a_line — linear regression on the UCI-housing-shaped problem.

Port of the reference's canonical workload
(reference: example/fit_a_line/train_ft.py:40-118,
 example/fit_a_line/train_local.py:41-106): a single dense layer
regressing 13 features to 1 target under squared error. Synthetic data
generation replaces the imikolov/uci RecordIO shards baked into the
example image (reference: example/fit_a_line/Dockerfile:1-8).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

N_FEATURES = 13  # uci_housing feature width (reference: train_ft.py:44)


def init_params(key: jax.Array) -> Dict[str, jnp.ndarray]:
    wk, _ = jax.random.split(key)
    return {
        "w": jax.random.normal(wk, (N_FEATURES, 1), jnp.float32) * 0.01,
        "b": jnp.zeros((1,), jnp.float32),
    }


def predict(params, x: jnp.ndarray) -> jnp.ndarray:
    return x @ params["w"] + params["b"]


def loss_fn(params, batch) -> jnp.ndarray:
    """Mean squared error (reference: square_error_cost, train_ft.py:93)."""
    from edl_tpu.models.losses import row_mean

    pred = predict(params, batch["x"])
    return row_mean(jnp.mean((pred - batch["y"]) ** 2, axis=-1), batch)


def synthetic_dataset(
    n: int, seed: int = 0, noise: float = 0.1
) -> Tuple[np.ndarray, np.ndarray]:
    """A fixed random linear problem so loss-goes-down is testable."""
    rng = np.random.RandomState(seed)
    w_true = rng.randn(N_FEATURES, 1).astype(np.float32)
    x = rng.randn(n, N_FEATURES).astype(np.float32)
    y = x @ w_true + noise * rng.randn(n, 1).astype(np.float32)
    return x, y
