"""BERT-class bidirectional encoder — masked-language-model pretraining.

The "BERT-class elastic DP" workload of the build plan (SURVEY §7.8);
no reference analog (its models are 2018-era CTR/word2vec, SURVEY §5).
Same TPU-first construction as models/llama.py: scan-stacked layers
(O(1) compile in depth), explicit TP×FSDP partition specs, bfloat16
activations over float32 params. Architectural differences from the
decoder: bidirectional attention (no causal mask), learned positional
embeddings, LayerNorm with bias, GELU MLP, and an MLM loss computed
only at masked positions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from edl_tpu.parallel.mesh import MeshPlan

MASK_TOKEN = 0  # convention: id 0 is [MASK]


@dataclass(frozen=True)
class BertConfig:
    vocab: int = 30522
    max_seq: int = 512
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16


    def to_meta(self) -> dict:
        """JSON-safe architecture record for export manifests
        (the one shared rule: models/meta.py)."""
        from edl_tpu.models.meta import dataclass_meta

        return dataclass_meta(self, "bert")

    @classmethod
    def from_meta(cls, meta: dict) -> "BertConfig":
        from edl_tpu.models.meta import dataclass_from_meta

        return dataclass_from_meta(cls, meta, "bert")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def tiny(cls, vocab: int = 256) -> "BertConfig":
        return cls(
            vocab=vocab,
            max_seq=64,
            d_model=64,
            n_layers=2,
            n_heads=4,
            d_ff=128,
            dtype=jnp.float32,
        )


def init_params(key: jax.Array, cfg: BertConfig) -> Dict:
    k = jax.random.split(key, 8)
    d, h, hd, ff, L = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers

    def g(kk, *shape, scale):
        return jax.random.normal(kk, shape, jnp.float32) * scale

    return {
        "embed": g(k[0], cfg.vocab, d, scale=0.02),
        "pos_embed": g(k[1], cfg.max_seq, d, scale=0.02),
        "layers": {
            "ln1_g": jnp.ones((L, d), jnp.float32),
            "ln1_b": jnp.zeros((L, d), jnp.float32),
            "wqkv": g(k[2], L, d, 3 * h * hd, scale=d**-0.5),
            "wo": g(k[3], L, h * hd, d, scale=(h * hd) ** -0.5),
            "ln2_g": jnp.ones((L, d), jnp.float32),
            "ln2_b": jnp.zeros((L, d), jnp.float32),
            "w_up": g(k[4], L, d, ff, scale=d**-0.5),
            "b_up": jnp.zeros((L, ff), jnp.float32),
            "w_down": g(k[5], L, ff, d, scale=ff**-0.5),
            "b_down": jnp.zeros((L, d), jnp.float32),
        },
        "ln_f_g": jnp.ones((d,), jnp.float32),
        "ln_f_b": jnp.zeros((d,), jnp.float32),
        "mlm_head": g(k[6], d, cfg.vocab, scale=d**-0.5),
    }


def param_pspecs(cfg: BertConfig, plan: MeshPlan) -> Dict:
    """TP on head/ffn width, FSDP on the other large dim (llama layout)."""
    tp = "tp" if plan.axis_size("tp") > 1 else None
    fs = "fsdp" if plan.axis_size("fsdp") > 1 else None
    return {
        "embed": P(tp, fs),
        "pos_embed": P(None, fs),
        "layers": {
            "ln1_g": P(None, None),
            "ln1_b": P(None, None),
            "wqkv": P(None, fs, tp),
            "wo": P(None, tp, fs),
            "ln2_g": P(None, None),
            "ln2_b": P(None, None),
            "w_up": P(None, fs, tp),
            "b_up": P(None, tp),
            "w_down": P(None, tp, fs),
            "b_down": P(None, None),
        },
        "ln_f_g": P(None),
        "ln_f_b": P(None),
        "mlm_head": P(fs, tp),
    }


def _layernorm(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * g + b).astype(x.dtype)


def _layer(cfg: BertConfig, x: jnp.ndarray, lp: Dict) -> jnp.ndarray:
    b, t, d = x.shape
    h, hd = cfg.n_heads, cfg.head_dim
    dt = x.dtype
    a = _layernorm(x, lp["ln1_g"].astype(dt), lp["ln1_b"].astype(dt), cfg.norm_eps)
    qkv = (a @ lp["wqkv"].astype(dt)).reshape(b, t, 3, h, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    scores = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(hd)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
    o = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(b, t, h * hd)
    x = x + o @ lp["wo"].astype(dt)
    m = _layernorm(x, lp["ln2_g"].astype(dt), lp["ln2_b"].astype(dt), cfg.norm_eps)
    up = jax.nn.gelu(m @ lp["w_up"].astype(dt) + lp["b_up"].astype(dt))
    return x + (up @ lp["w_down"].astype(dt) + lp["b_down"].astype(dt))


def forward(params: Dict, tokens: jnp.ndarray, cfg: BertConfig) -> jnp.ndarray:
    """tokens [B, T] int32 → logits [B, T, vocab] (pre-norm encoder)."""
    t = tokens.shape[1]
    x = (
        jnp.take(params["embed"], tokens, axis=0)
        + params["pos_embed"][None, :t]
    ).astype(cfg.dtype)

    def body(carry, lp):
        return _layer(cfg, carry, lp), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _layernorm(x, params["ln_f_g"], params["ln_f_b"], cfg.norm_eps)
    return (x @ params["mlm_head"].astype(cfg.dtype)).astype(jnp.float32)


def make_loss_fn(cfg: BertConfig):
    """MLM cross entropy at masked positions.

    batch = {tokens [B,T] (with MASK_TOKEN holes), targets [B,T]
    (original ids), mask [B,T] float (1 at masked positions)}.
    """

    def loss_fn(params, batch):
        logits = forward(params, batch["tokens"], cfg)
        # fused CE (see models/llama.py): no [B,T,V] log-softmax
        # materialization
        import optax

        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["targets"]
        )
        mask = batch["mask"].astype(jnp.float32)
        w = batch.get("_w")
        if w is not None:
            # runtime real-row weights: padded/replayed rows contribute
            # zero (models/losses.py contract)
            mask = mask * w[:, None].astype(jnp.float32)
        return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    return loss_fn


def synthetic_mlm_batch(
    rng: np.random.RandomState, batch: int, seq: int, vocab: int,
    mask_prob: float = 0.15,
) -> Dict[str, np.ndarray]:
    """Position-structured token stream (token id cycles with position)
    so MLM loss is quickly learnable, with ``mask_prob`` of positions
    replaced by MASK_TOKEN."""
    pos = np.arange(seq, dtype=np.int32)[None, :]
    targets = np.broadcast_to((pos % (vocab - 1)) + 1, (batch, seq))
    mask = rng.rand(batch, seq) < mask_prob
    tokens = np.where(mask, MASK_TOKEN, targets).astype(np.int32)
    return {
        "tokens": tokens,
        "targets": targets.astype(np.int32),
        "mask": mask.astype(np.float32),
    }
