"""Mixture-of-Experts transformer — the expert-parallel model family.

The reference has no MoE anywhere (SURVEY §2.5: "Expert parallelism:
NO — optional"); this makes EP a full model family rather than just a
layer: a Llama-style decoder whose SwiGLU FFN is replaced by a top-k
routed expert FFN (parallel/moe.py), with the expert dimension of
every expert weight sharded over the ``ep`` mesh axis so the
dispatch/combine einsums lower to all-to-all-style collectives over
ICI. Attention, RoPE, rmsnorm, and the flash kernel are shared with
models/llama.py — one implementation of the hot path.

The load-balance auxiliary loss (standard mean-prob x mean-assign) is
folded into the training loss with coefficient ``aux_coef``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from edl_tpu.models import llama as _ll
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.parallel.moe import moe_ffn

# shared synthetic data: the loss-curve contract is the same
synthetic_tokens = _ll.synthetic_tokens


@dataclass(frozen=True)
class MoEConfig:
    vocab: int = 32768
    d_model: int = 1024
    n_layers: int = 8
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 2816  # per-expert hidden
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_coef: float = 0.01
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    use_flash: bool = False
    remat: bool = False
    # run the attention projections + expert batched matmuls on the
    # MXU's double-rate int8 path (training-only, same contract as
    # LlamaConfig.int8_mxu: weights at rest stay dense, the flag never
    # rides the export record)
    int8_mxu: bool = False
    # with int8_mxu: keep wgrad on the bf16 path (same contract as
    # LlamaConfig.int8_wgrad_bf16 — the outlier-resolution escape
    # hatch; training-only, never rides the export record)
    int8_wgrad_bf16: bool = False

    def to_meta(self) -> dict:
        """JSON-safe architecture record for export manifests
        (the one shared rule: models/meta.py)."""
        from edl_tpu.models.meta import dataclass_meta

        meta = dataclass_meta(self, "moe")
        meta.pop("int8_mxu")  # training-only: never a load contract
        meta.pop("int8_wgrad_bf16")
        return meta

    @classmethod
    def from_meta(cls, meta: dict) -> "MoEConfig":
        from edl_tpu.models.meta import dataclass_from_meta

        return dataclass_from_meta(cls, meta, "moe")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def tiny(cls, vocab: int = 256) -> "MoEConfig":
        return cls(
            vocab=vocab,
            d_model=64,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            d_ff=96,
            n_experts=4,
            dtype=jnp.float32,
        )

    def _llama_view(self) -> _ll.LlamaConfig:
        """The attention-relevant subset, for reusing llama's blocks."""
        return _ll.LlamaConfig(
            vocab=self.vocab,
            d_model=self.d_model,
            n_layers=self.n_layers,
            n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads,
            d_ff=self.d_ff,
            rope_theta=self.rope_theta,
            norm_eps=self.norm_eps,
            dtype=self.dtype,
            use_flash=self.use_flash,
            remat=self.remat,
        )


def init_params(key: jax.Array, cfg: MoEConfig) -> Dict:
    """Scan-stacked tree: per-layer weights carry a leading [L] axis;
    expert weights carry [L, E, ...]."""
    k = jax.random.split(key, 12)
    d, h, kv, hd, ff, L, E = (
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.n_layers,
        cfg.n_experts,
    )

    def norm_init(kk, *shape, scale):
        return jax.random.normal(kk, shape, jnp.float32) * scale

    return {
        "embed": norm_init(k[0], cfg.vocab, d, scale=0.02),
        "layers": {
            "ln1": jnp.ones((L, d), jnp.float32),
            "wq": norm_init(k[1], L, d, h * hd, scale=d**-0.5),
            "wk": norm_init(k[2], L, d, kv * hd, scale=d**-0.5),
            "wv": norm_init(k[3], L, d, kv * hd, scale=d**-0.5),
            "wo": norm_init(k[4], L, h * hd, d, scale=(h * hd) ** -0.5),
            "ln2": jnp.ones((L, d), jnp.float32),
            "router": norm_init(k[5], L, d, E, scale=0.02),
            "w_in": norm_init(k[6], L, E, d, ff, scale=d**-0.5),
            "w_out": norm_init(k[7], L, E, ff, d, scale=ff**-0.5),
        },
        "ln_f": jnp.ones((d,), jnp.float32),
        "lm_head": norm_init(k[8], d, cfg.vocab, scale=d**-0.5),
    }


def param_pspecs(cfg: MoEConfig, plan: MeshPlan) -> Dict:
    """Experts over ep, expert-internal width over tp, dense dims over
    fsdp — with llama's divisibility fallback (replicate on any axis
    that does not divide)."""
    tp = "tp" if plan.axis_size("tp") > 1 else None
    fs = "fsdp" if plan.axis_size("fsdp") > 1 else None
    ep = "ep" if plan.axis_size("ep") > 1 else None
    d, h, kv, hd, ff, L, E, V = (
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.n_layers,
        cfg.n_experts,
        cfg.vocab,
    )

    from edl_tpu.parallel.sharding import fit_pspec

    def fit(shape, *axes):
        return fit_pspec(plan, shape, *axes)

    return {
        "embed": fit((V, d), tp, fs),
        "layers": {
            "ln1": P(None, None),
            "wq": fit((L, d, h * hd), None, fs, tp),
            "wk": fit((L, d, kv * hd), None, fs, tp),
            "wv": fit((L, d, kv * hd), None, fs, tp),
            "wo": fit((L, h * hd, d), None, tp, fs),
            "ln2": P(None, None),
            "router": fit((L, d, E), None, fs, None),
            "w_in": fit((L, E, d, ff), None, ep, fs, tp),
            "w_out": fit((L, E, ff, d), None, ep, tp, fs),
        },
        "ln_f": P(None),
        "lm_head": fit((d, V), fs, tp),
    }


def _layer(cfg: MoEConfig, x: jnp.ndarray, lp: Dict):
    lcfg = cfg._llama_view()
    dt = x.dtype
    b, t, d = x.shape
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    i8, wb = cfg.int8_mxu, cfg.int8_wgrad_bf16
    # attention block — llama's, verbatim building blocks (_matw
    # routes through the int8 MXU path when the flag is set)
    a = _ll._rmsnorm(x, lp["ln1"], cfg.norm_eps)
    q = _ll._matw(a, lp["wq"], i8, wb).reshape(b, t, h, hd)
    k = _ll._matw(a, lp["wk"], i8, wb).reshape(b, t, kv, hd)
    v = _ll._matw(a, lp["wv"], i8, wb).reshape(b, t, kv, hd)
    q, k = _ll._rope(q, cfg.rope_theta), _ll._rope(k, cfg.rope_theta)
    o = _ll.attention(q, k, v, lcfg).reshape(b, t, h * hd)
    x = x + _ll._matw(o, lp["wo"], i8, wb)
    # routed expert FFN
    m = _ll._rmsnorm(x, lp["ln2"], cfg.norm_eps)
    y, aux = moe_ffn(
        {
            "router": lp["router"].astype(dt),
            "w_in": lp["w_in"] if i8 else lp["w_in"].astype(dt),
            "w_out": lp["w_out"] if i8 else lp["w_out"].astype(dt),
        },
        m,
        k=cfg.top_k,
        capacity_factor=cfg.capacity_factor,
        int8_mxu=i8,
        int8_wgrad_bf16=wb,
    )
    return x + y, aux


def forward(params: Dict, tokens: jnp.ndarray, cfg: MoEConfig):
    """tokens [B, T] int32 → (logits [B, T, vocab], aux scalar)."""
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)

    def body(carry, lp):
        x, aux = carry
        x, a = _layer(cfg, x, lp)
        return (x, aux + a), None

    if cfg.remat:
        body = jax.checkpoint(body)
    (x, aux), _ = jax.lax.scan(body, (x, jnp.zeros((), jnp.float32)), params["layers"])
    x = _ll._rmsnorm(x, params["ln_f"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, aux / cfg.n_layers


def make_loss_fn(cfg: MoEConfig):
    """Next-token CE + load-balance aux; batch = {tokens [B, T+1]}."""

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        logits, aux = forward(params, tokens[:, :-1], cfg)
        targets = tokens[:, 1:]
        # fused CE (see models/llama.py): no [B,T,V] log-softmax
        # materialization
        import optax

        from edl_tpu.models.losses import row_mean

        ce = row_mean(
            jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits, targets
                ),
                axis=-1,
            ),
            batch,
        )
        # aux (load-balance regularizer over gate statistics) stays
        # unweighted: it is a router-health term, not a data loss
        return ce + cfg.aux_coef * aux

    return loss_fn
