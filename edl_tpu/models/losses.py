"""Shared loss reducers.

``row_mean`` is the one rule for averaging per-row losses against the
elastic runtime's real-row weights: at the ragged tail of a dataset a
worker pads its batch (wrap-repeat) or replays its previous batch to
keep SPMD shapes aligned across peers (runtime/worker_main.py
``_pad_to``/``_local_batch``), and those filler rows arrive with
``batch["_w"] == 0`` so they contribute ZERO gradient — the global
update equals the gradient over real rows only (VERDICT r2 Weak #5).
Without ``_w`` (examples, notebooks, tests) it is a plain mean.
"""

from __future__ import annotations

import jax.numpy as jnp


def row_mean(per_row: jnp.ndarray, batch) -> jnp.ndarray:
    """Weighted mean of a [B] per-row loss by ``batch["_w"]`` (float
    [B], 1 = real row, 0 = padding/replay), or the plain mean when no
    weights ride the batch. A globally all-zero weight vector (every
    peer replaying — a queue-drain corner) yields loss 0 and zero
    gradients: a harmless no-op step instead of 0/0 NaNs."""
    w = batch.get("_w")
    if w is None:
        return jnp.mean(per_row)
    w = w.astype(per_row.dtype)
    return jnp.sum(per_row * w) / jnp.maximum(jnp.sum(w), 1.0)
