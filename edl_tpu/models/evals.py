"""Shared chunked evaluation math — ONE implementation of the
held-out metrics that both the in-job eval hook (runtime/workloads.py
-> runtime/eval_hook.py) and the offline serving consumer
(runtime/predict.py, `edl predict`) publish. If these diverged, the
in-job ``eval_metric`` and an offline re-score of the same export
would silently disagree.

Everything is chunked: LM heads emit [rows, T, vocab] f32 logits — one
unchunked call over a real split would OOM the host driving it."""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np

CHUNK = 64  # rows per forward


def lm_scan(
    logits_fn: Callable, params, toks: np.ndarray, chunk: int = CHUNK
) -> Tuple[np.ndarray, float, int]:
    """One chunked pass over ``toks [N, T]``: (greedy next token after
    the last position [N], total next-token CE, CE count). CE covers
    positions 0..T-2 predicting 1..T-1 (empty when T < 2)."""
    import jax.numpy as jnp
    import optax

    toks = np.asarray(toks)
    nxt = []
    total, count = 0.0, 0
    for s in range(0, len(toks), chunk):
        t = jnp.asarray(toks[s : s + chunk])
        logits = logits_fn(params, t)
        nxt.append(np.asarray(jnp.argmax(logits[:, -1], -1)))
        if toks.shape[1] >= 2:
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], t[:, 1:]
            )
            total += float(jnp.sum(ce))
            count += ce.size
    return np.concatenate(nxt) if nxt else np.zeros((0,), np.int32), total, count


def lm_ppl(logits_fn: Callable, params, toks: np.ndarray, chunk: int = CHUNK) -> float:
    """Next-token perplexity over ``toks [N, T]`` (the in-job LM
    eval_metric; reference parity: metric fetched in the train loop,
    example/ctr/ctr/train.py:161-167)."""
    _, total, count = lm_scan(logits_fn, params, toks, chunk)
    return float(np.exp(total / max(count, 1)))


def masked_top1(
    logits_fn: Callable, params, rows: Dict[str, np.ndarray], chunk: int = CHUNK
) -> Tuple[float, np.ndarray]:
    """(masked top-1 accuracy, per-position predictions [N, T]) over
    ``{tokens, mask, targets}`` MLM rows — accuracy counted only where
    mask > 0; 0.0 when nothing is masked."""
    import jax.numpy as jnp

    toks = np.asarray(rows["tokens"])
    preds = []
    correct = total = 0
    for s in range(0, len(toks), chunk):
        sl = slice(s, s + chunk)
        logits = logits_fn(params, jnp.asarray(toks[sl]))
        pred = np.asarray(jnp.argmax(logits, -1))
        preds.append(pred)
        if "mask" in rows and "targets" in rows:
            mask = np.asarray(rows["mask"][sl]) > 0
            correct += int(
                (pred[mask] == np.asarray(rows["targets"][sl])[mask]).sum()
            )
            total += int(mask.sum())
    return (
        correct / max(total, 1) if total else 0.0,
        np.concatenate(preds) if preds else np.zeros_like(toks),
    )
