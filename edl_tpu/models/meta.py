"""Shared architecture-record helper for export manifests.

One implementation of the "config dataclass → JSON-safe dict" rule
(bert/resnet/moe use it verbatim; llama hand-picks its serving-relevant
fields because its record is also a load contract — from_meta)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax.numpy as jnp


def dataclass_meta(cfg: Any, family: str) -> Dict[str, Any]:
    out: Dict[str, Any] = {"family": family}
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if f.name == "dtype":
            v = jnp.dtype(v).name
        out[f.name] = v
    return out
