"""Shared architecture-record helper for export manifests.

One implementation of the "config dataclass → JSON-safe dict" rule
(bert/resnet/moe use it verbatim; llama hand-picks its serving-relevant
fields because its record is also a load contract — from_meta)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax.numpy as jnp


def dataclass_meta(cfg: Any, family: str) -> Dict[str, Any]:
    out: Dict[str, Any] = {"family": family}
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if f.name == "dtype":
            v = jnp.dtype(v).name
        elif isinstance(v, tuple):
            v = list(v)  # JSON round-trip safe
        out[f.name] = v
    return out


def dataclass_from_meta(cls, meta: Dict[str, Any], family: str):
    """Rebuild a config dataclass from its export architecture record —
    the inverse of :func:`dataclass_meta` (serving consumers:
    runtime/predict.py). Unknown keys are ignored (forward compat);
    a family mismatch is a hard error so a consumer can never run the
    wrong forward over an export's weights."""
    got = meta.get("family")
    if got != family:
        raise ValueError(f"not a {family} export: family={got!r}")
    kwargs: Dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        if f.name not in meta:
            continue
        v = meta[f.name]
        if f.name == "dtype":
            v = jnp.dtype(v)
        elif isinstance(v, list):
            v = tuple(v)
        kwargs[f.name] = v
    return cls(**kwargs)
