"""CTR deep model — Criteo-style click-through-rate predictor.

Port of the reference's production workload (reference:
example/ctr/ctr/train.py:183-235: ``ctr_dnn_model(embedding_size,
sparse_feature_dim)`` — 13 dense features, 26 hashed sparse slots with
a shared embedding table, 400-400-400 MLP, sigmoid + log loss + AUC).
TPU-first differences: the embedding table is a dense array sharded
over the mesh (vocab dimension) instead of Paddle's is_sparse pserver
rows — gathers ride ICI; and the batch stays fully on-device in
bfloat16-friendly shapes for the MXU.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.ops.embedding import embedding_lookup

N_DENSE = 13  # Criteo dense feature count
N_SPARSE = 26  # Criteo categorical slots
DEFAULT_EMBEDDING = 16  # reference default 10 (train.py:46-49); 16 tiles MXU lanes
DEFAULT_VOCAB = 2**20  # reference: sparse_feature_dim hash space (train.py:61-64)
MLP_DIMS = (400, 400, 400)  # reference network_conf fc stack


def init_params(
    key: jax.Array,
    vocab: int = DEFAULT_VOCAB,
    emb: int = DEFAULT_EMBEDDING,
    mlp_dims: Tuple[int, ...] = MLP_DIMS,
    dtype=jnp.float32,
) -> Dict:
    keys = jax.random.split(key, len(mlp_dims) + 2)
    params: Dict = {
        "embedding": (
            jax.random.normal(keys[0], (vocab, emb), dtype) / np.sqrt(emb)
        ),
    }
    in_dim = N_SPARSE * emb + N_DENSE
    layers = []
    for i, out_dim in enumerate(mlp_dims):
        layers.append(
            {
                "w": jax.random.normal(keys[i + 1], (in_dim, out_dim), dtype)
                * np.sqrt(2.0 / in_dim),
                "b": jnp.zeros((out_dim,), dtype),
            }
        )
        in_dim = out_dim
    params["mlp"] = layers
    params["out"] = {
        "w": jax.random.normal(keys[-1], (in_dim, 1), dtype) * np.sqrt(1.0 / in_dim),
        "b": jnp.zeros((1,), dtype),
    }
    return params


def forward(params, dense: jnp.ndarray, sparse: jnp.ndarray) -> jnp.ndarray:
    """Logits for a batch. dense [B, 13] float, sparse [B, 26] int32 ids."""
    emb = embedding_lookup(params["embedding"], sparse)  # [B, 26, E]
    x = jnp.concatenate([emb.reshape(emb.shape[0], -1), dense], axis=-1)
    for layer in params["mlp"]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    return (x @ params["out"]["w"] + params["out"]["b"])[:, 0]  # [B]


def make_loss_fn(compute_dtype=jnp.float32):
    """Loss with a cast-to-``compute_dtype`` forward (bfloat16 feeds the
    MXU at full rate; params/optimizer stay float32). Loss is always
    accumulated in float32."""

    def _loss(params, batch):
        if compute_dtype != jnp.float32:
            # every float leaf, biases included — one f32 leaf in a
            # bias-add would promote the whole activation back to f32
            params = jax.tree_util.tree_map(
                lambda x: x.astype(compute_dtype), params
            )
        logits = forward(
            params, batch["dense"].astype(compute_dtype), batch["sparse"]
        ).astype(jnp.float32)
        # reshape, not broadcast: a [N, 1] label column against [N]
        # logits would silently blow per_row up to [N, N]
        labels = batch["label"].astype(jnp.float32).reshape(logits.shape)
        from edl_tpu.models.losses import row_mean

        per_row = (
            jnp.maximum(logits, 0)
            - logits * labels
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        )
        return row_mean(per_row, batch)

    return _loss


# Sigmoid cross-entropy at f32 (reference: log loss on the ctr_dnn
# output) — the default loss; bfloat16 variants via make_loss_fn.
loss_fn = make_loss_fn()


def batch_auc(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Batch AUC via the rank statistic (reference tracks batch_auc_var,
    train.py:120-176). Labels are flattened to [N]: a [N, 1] column
    (how tabular pipelines often store targets) would silently
    broadcast the rank sum to [N, N] and report nonsense > 1."""
    labels = labels.reshape(-1)
    if labels.shape[0] != logits.shape[0]:
        raise ValueError(
            f"labels {labels.shape} do not match logits {logits.shape}"
        )
    order = jnp.argsort(logits)
    ranks = jnp.empty_like(order).at[order].set(jnp.arange(logits.shape[0]))
    pos = labels > 0.5
    n_pos = jnp.sum(pos)
    n_neg = logits.shape[0] - n_pos
    sum_pos_ranks = jnp.sum(jnp.where(pos, ranks, 0))
    auc = (sum_pos_ranks - n_pos * (n_pos - 1) / 2.0) / jnp.maximum(n_pos * n_neg, 1)
    return jnp.where((n_pos == 0) | (n_neg == 0), 0.5, auc)


def synthetic_batch(
    rng: np.random.RandomState, batch: int, vocab: int = DEFAULT_VOCAB
) -> Dict[str, np.ndarray]:
    """Click-biased synthetic Criteo-shaped data (the reference downloads
    per-trainer Criteo shards, train.py:222-227; synthetic keeps the bench
    hermetic). Label correlates with dense feature 0 so AUC is learnable."""
    dense = rng.rand(batch, N_DENSE).astype(np.float32)
    sparse = rng.randint(0, vocab, size=(batch, N_SPARSE), dtype=np.int32)
    click_prob = 1.0 / (1.0 + np.exp(-(8.0 * dense[:, 0] - 4.0)))
    label = (rng.rand(batch) < click_prob).astype(np.int32)
    return {"dense": dense, "sparse": sparse, "label": label}
