"""ResNet-class convolutional classifier — the vision elastic-DP workload.

The "ResNet-class elastic DP" entry of the build plan (SURVEY §7.8).
TPU-first choices: NHWC layout (XLA's native TPU conv layout), GroupNorm
instead of BatchNorm (stateless → purely functional train step, and no
cross-replica batch-stat sync on the elastic dp axis), bfloat16 compute.
Convolutions lower onto the MXU as implicit GEMMs; channel widths are
multiples of 128 at full size to tile the systolic array.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from edl_tpu.parallel.mesh import MeshPlan


@dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    widths: Tuple[int, ...] = (256, 512, 1024, 2048)
    blocks_per_stage: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50-ish
    stem_width: int = 128
    groups: int = 32  # GroupNorm groups
    dtype: Any = jnp.bfloat16


    def to_meta(self) -> dict:
        """JSON-safe architecture record for export manifests
        (the one shared rule: models/meta.py)."""
        from edl_tpu.models.meta import dataclass_meta

        return dataclass_meta(self, "resnet")

    @classmethod
    def from_meta(cls, meta: dict) -> "ResNetConfig":
        from edl_tpu.models.meta import dataclass_from_meta

        return dataclass_from_meta(cls, meta, "resnet")

    @classmethod
    def resnet50(cls) -> "ResNetConfig":
        return cls()

    @classmethod
    def tiny(cls, num_classes: int = 10) -> "ResNetConfig":
        return cls(
            num_classes=num_classes,
            widths=(16, 32),
            blocks_per_stage=(1, 1),
            stem_width=16,
            groups=4,
            dtype=jnp.float32,
        )


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * np.sqrt(
        2.0 / fan_in
    )


def init_params(key: jax.Array, cfg: ResNetConfig) -> Dict:
    keys = iter(jax.random.split(key, 4 + 4 * sum(cfg.blocks_per_stage)))
    params: Dict = {
        "stem": _conv_init(next(keys), 3, 3, 3, cfg.stem_width),
        "stem_gn": {"g": jnp.ones((cfg.stem_width,)), "b": jnp.zeros((cfg.stem_width,))},
        "stages": [],
    }
    cin = cfg.stem_width
    for width, n_blocks in zip(cfg.widths, cfg.blocks_per_stage):
        stage = []
        for b in range(n_blocks):
            blk = {
                "conv1": _conv_init(next(keys), 3, 3, cin, width),
                "gn1": {"g": jnp.ones((width,)), "b": jnp.zeros((width,))},
                "conv2": _conv_init(next(keys), 3, 3, width, width),
                "gn2": {"g": jnp.ones((width,)), "b": jnp.zeros((width,))},
            }
            if cin != width:
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, width)
            stage.append(blk)
            cin = width
        params["stages"].append(stage)
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, cfg.num_classes), jnp.float32)
        * np.sqrt(1.0 / cin),
        "b": jnp.zeros((cfg.num_classes,)),
    }
    return params


def param_pspecs(cfg: ResNetConfig, plan: MeshPlan) -> Dict:
    """Conv kernels shard output channels over fsdp (widths are
    power-of-two multiples); head shards its input dim. Replicated when
    the axis is absent. Mirrors init_params' cin-tracking loop so the
    spec tree always matches the param tree."""
    fs = "fsdp" if plan.axis_size("fsdp") > 1 else None

    def gn_spec():
        return {"g": P(fs), "b": P(fs)}

    stages = []
    cin = cfg.stem_width
    for width, n_blocks in zip(cfg.widths, cfg.blocks_per_stage):
        stage = []
        for _ in range(n_blocks):
            blk = {
                "conv1": P(None, None, None, fs),
                "gn1": gn_spec(),
                "conv2": P(None, None, None, fs),
                "gn2": gn_spec(),
            }
            if cin != width:
                blk["proj"] = P(None, None, None, fs)
            stage.append(blk)
            cin = width
        stages.append(stage)
    return {
        "stem": P(None, None, None, fs),
        "stem_gn": gn_spec(),
        "stages": stages,
        "head": {"w": P(fs, None), "b": P(None)},
    }


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _groupnorm(x, g, b, groups, eps=1e-5):
    n, h, w, c = x.shape
    xf = x.astype(jnp.float32).reshape(n, h, w, groups, c // groups)
    mu = jnp.mean(xf, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(xf, axis=(1, 2, 4), keepdims=True)
    y = ((xf - mu) * jax.lax.rsqrt(var + eps)).reshape(n, h, w, c)
    return (y * g + b).astype(x.dtype)


def forward(params: Dict, images: jnp.ndarray, cfg: ResNetConfig) -> jnp.ndarray:
    """images [B, H, W, 3] → logits [B, num_classes]."""
    x = images.astype(cfg.dtype)
    x = _conv(x, params["stem"])
    x = jax.nn.relu(
        _groupnorm(x, params["stem_gn"]["g"], params["stem_gn"]["b"], cfg.groups)
    )
    for si, stage in enumerate(params["stages"]):
        for bi, blk in enumerate(stage):
            stride = 2 if bi == 0 and si > 0 else 1
            y = _conv(x, blk["conv1"], stride=stride)
            y = jax.nn.relu(_groupnorm(y, blk["gn1"]["g"], blk["gn1"]["b"], cfg.groups))
            y = _conv(y, blk["conv2"])
            y = _groupnorm(y, blk["gn2"]["g"], blk["gn2"]["b"], cfg.groups)
            sc = x
            if "proj" in blk:
                sc = _conv(sc, blk["proj"], stride=stride)
            elif stride != 1:
                sc = sc[:, ::stride, ::stride]
            x = jax.nn.relu(y + sc)
    x = jnp.mean(x, axis=(1, 2))  # global average pool
    head = params["head"]
    return (x @ head["w"].astype(x.dtype) + head["b"].astype(x.dtype)).astype(
        jnp.float32
    )


def make_loss_fn(cfg: ResNetConfig):
    """Softmax cross entropy; batch = {images [B,H,W,3], label [B]}."""

    def loss_fn(params, batch):
        logits = forward(params, batch["images"], cfg)
        import optax

        from edl_tpu.models.losses import row_mean

        return row_mean(
            optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["label"]
            ),
            batch,
        )

    return loss_fn


def synthetic_batch(
    rng: np.random.RandomState, batch: int, size: int = 32, num_classes: int = 10
) -> Dict[str, np.ndarray]:
    """Class-dependent brightness pattern so the loss is learnable."""
    label = rng.randint(0, num_classes, size=batch, dtype=np.int32)
    images = rng.rand(batch, size, size, 3).astype(np.float32)
    images += (label / num_classes)[:, None, None, None]
    return {"images": images, "label": label}
