"""Lease-holder subprocess for the multi-process chaos lane.

``python -m edl_tpu.elasticity.holder`` is one chip-lease holder as a
real OS process: it connects to a running ``edl-coordinator``, takes a
lease through :class:`~edl_tpu.elasticity.distbroker.DistributedChipBroker`,
and then behaves per ``--mode``:

* ``confirm`` — the well-behaved holder: confirm on a short heartbeat
  for ``--hold-s`` seconds, then recall+free its own lease and exit 0.
  If the broker restarts underneath it, the client's reconnect window
  absorbs the gap and the re-confirm ends the RECOVERING window.
* ``die`` — grant, report the lease on stdout, then ``os._exit`` while
  still holding it (the SIGKILL analog): the chips come back only via
  the broker's recovery reaper or an explicit ``LCRASH``.
* ``zombie`` — a holder restarted with STALE memory: adopt the
  ``--lease-id``/``--epoch`` it remembers and confirm. The broker must
  fence it (exit 0 iff fenced) — the process-level proof that a
  force-released holder cannot keep computing on chips it lost.

``--events-out`` dumps this process's flight ring as JSONL on the way
out so the parent (``scripts/exp_elasticity.py --dist-chaos``) can
merge every process's timeline into one ``edl postmortem`` input.

Stdout protocol (parent-parsed, one line):
    ``LEASE <lease_id> <epoch> <chips>`` after a successful grant, or
    ``FENCED <reason-bool>`` from a zombie.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from edl_tpu.elasticity.broker import LeaseError
from edl_tpu.elasticity.distbroker import DistributedChipBroker
from edl_tpu.obs import events as flight
from edl_tpu.runtime.coordinator import CoordinatorClient


def _dump_events(path: str) -> None:
    if not path:
        return
    with open(path, "w") as f:
        f.write(flight.default_recorder().to_jsonl())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="edl-lease-holder",
        description="one chip-lease holder process (chaos-lane actor)",
    )
    ap.add_argument("--coordinator", required=True, help="HOST:PORT")
    ap.add_argument("--holder", required=True, help="side:name holder id")
    ap.add_argument("--chips", type=int, default=1)
    ap.add_argument("--total", type=int, default=8, help="pool size (LINIT)")
    ap.add_argument(
        "--mode", choices=("confirm", "die", "zombie"), default="confirm"
    )
    ap.add_argument(
        "--hold-s", type=float, default=1.0,
        help="confirm mode: seconds to hold before freeing",
    )
    ap.add_argument(
        "--confirm-every", type=float, default=0.05,
        help="confirm mode: heartbeat period",
    )
    ap.add_argument(
        "--lease-id", default="",
        help="zombie mode: the lease this holder remembers",
    )
    ap.add_argument(
        "--epoch", type=int, default=-1,
        help="zombie mode: the (stale) epoch this holder remembers",
    )
    ap.add_argument("--events-out", default="", help="flight-ring JSONL dump")
    args = ap.parse_args(argv)

    host, port = args.coordinator.rsplit(":", 1)
    flight.default_recorder().set_context(worker=args.holder)
    cli = CoordinatorClient(host, int(port))
    broker = DistributedChipBroker(cli, args.total)

    if args.mode == "zombie":
        # re-attach with remembered (possibly stale) state, then ask
        # the fence; a zombie MUST come back fenced
        lease = broker.adopt(
            args.lease_id, args.holder, args.chips, args.epoch
        )
        ok = broker.confirm(lease.lease_id)
        _dump_events(args.events_out)
        print(f"FENCED {not ok}", flush=True)
        return 0 if not ok else 4

    lease = broker.grant(args.holder, args.chips)
    print(f"LEASE {lease.lease_id} {lease.epoch} {lease.chips}", flush=True)

    if args.mode == "die":
        # flush the timeline first — a SIGKILLed process can't
        _dump_events(args.events_out)
        sys.stdout.flush()
        os._exit(9)

    deadline = time.monotonic() + args.hold_s
    while time.monotonic() < deadline:
        if not broker.confirm(lease.lease_id):
            _dump_events(args.events_out)
            return 3  # fenced mid-hold: stop using the chips
        time.sleep(args.confirm_every)
    try:
        broker.recall(lease.lease_id)
        broker.free(lease.lease_id)
    except LeaseError:
        pass  # settled from the other side (recall race) — chips safe
    _dump_events(args.events_out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
