"""Train⇄serve chip elasticity: lease-brokered inventory + the
diurnal handover policy loop.

The paper's control plane continuously re-targets jobs between min and
max instances as cluster load shifts; this package is the fusion of
the repo's two independently-elastic sides. :mod:`broker` owns the
chip inventory as first-class leases (GRANTED→RECALLING→FREED, epochs
monotonic), :mod:`distbroker` is the same contract fronted by the
coordinator (WAL-persisted leases, epoch fencing, broker-restart
recovery), :mod:`controller` is the policy loop that recalls from one
side and grants to the other through the autoscaler's shared
hysteresis gate, and :mod:`weightpush` is the p2p warm-start plane
that lets a freshly granted serving replica pull live weights over
the shard-server protocol instead of cold-loading an export.
"""

from edl_tpu.elasticity.broker import (  # noqa: F401
    FREED,
    GRANTED,
    RECALLING,
    ChipLeaseBroker,
    Lease,
    LeaseError,
)
from edl_tpu.elasticity.controller import (  # noqa: F401
    ElasticityController,
    ServePort,
    TrainPort,
)
from edl_tpu.elasticity.distbroker import (  # noqa: F401
    DistributedChipBroker,
)
