"""DistributedChipBroker — the chip market behind the coordinator.

The client adapter that makes a coordinator-fronted lease pool look
exactly like the in-process :class:`~edl_tpu.elasticity.broker.ChipLeaseBroker`:
``grant``/``recall``/``free``/``holder_crashed``/``check_conservation``/
``free_chips``/``epoch`` with the same :class:`Lease` objects, the same
flight events, and the same gauges — so ``ElasticityController``,
``ElasticTrainer.apply_chip_grant``, and the serving fleet's warm-start
path run unchanged whether the broker lives in this process or behind
``edl-coordinator`` on another host.

What the distributed version adds on top of the in-process contract:

* **Crash-safe persistence** — every transition is WAL-logged by the
  coordinator, so a SIGKILLed broker restarts with exact accounting
  and the adapter's :meth:`resync` re-confirms this process's leases
  through the RECOVERING window.
* **Epoch fencing** — :meth:`confirm` carries the lease epoch; a stale
  holder (force-released during recovery, or beaten by a newer grant)
  gets ``FENCED`` back, ``edl_lease_fenced_total{reason}`` ticks, and
  a ``lease.fence`` event lands on the timeline.
* **Reconnect/backoff** — RPCs ride :class:`CoordinatorClient`'s
  reconnect window (decorrelated-jitter backoff), so a broker restart
  inside a handover is a stall, not a failure.

Fault sites on the real paths: ``lease.rpc`` ahead of every round
trip, ``lease.confirm`` in the fencing handshake, plus the in-process
broker's ``lease.recall`` for chaos parity. The multi-process chaos
lane (``scripts/exp_elasticity.py --dist-chaos``) arms all three and
gates on ``edl postmortem --assert-recovered --sites lease.``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import replace
from typing import Dict, List, Optional

from edl_tpu.elasticity.broker import (
    FREED,
    GRANTED,
    RECALLING,
    Lease,
    LeaseError,
)
from edl_tpu.obs import events as flight
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils import faults
from edl_tpu.utils.logging import kv_logger

log = kv_logger("distlease")

# coordinator wire states (lease_table.GRANTED/...) -> broker states
_STATE = {0: GRANTED, 1: RECALLING, 2: FREED}


class DistributedChipBroker:
    """ChipLeaseBroker-compatible adapter over a coordinator's lease
    plane (``NativeCoordinator``, ``PyCoordinator``, or a
    ``CoordinatorClient`` to a remote ``edl-coordinator``)."""

    def __init__(
        self,
        coord,
        total_chips: int,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        clock=time.monotonic,
    ):
        if total_chips <= 0:
            raise ValueError(f"total_chips must be >= 1, got {total_chips}")
        self.coord = coord
        self.total_chips = total_chips
        self.clock = clock
        self._lock = threading.Lock()
        # local mirror: the leases THIS process granted/settled (other
        # processes' leases are visible through lease_snap, not here)
        self._leases: Dict[str, Lease] = {}
        self._sides: set = set()
        reg = registry or obs_metrics.default_registry()
        self._g_chips = reg.gauge(
            "edl_lease_chips",
            "chips under live (GRANTED/RECALLING) leases, by holder side",
            ("side",),
        )
        self._g_free = reg.gauge(
            "edl_lease_chips_free", "chips in the broker pool, unleased"
        )
        self._g_leases = reg.gauge(
            "edl_leases", "lease count by state", ("state",)
        )
        self._g_epoch = reg.gauge(
            "edl_lease_epoch", "broker lease epoch (bumps on every grant)"
        )
        self._c_fenced = reg.counter(
            "edl_lease_fenced_total",
            "lease confirms rejected by the epoch fence",
            ("reason",),
        )
        self._c_recovered = reg.counter(
            "edl_lease_recoveries_total",
            "broker-restart recoveries completed (RECOVERING -> steady)",
        )
        ok = self._rpc(lambda: coord.lease_init(total_chips))
        if ok is None:
            raise LeaseError(
                "coordinator does not speak the lease protocol "
                "(old server binary — use the in-process broker)"
            )
        if not ok:
            raise LeaseError(
                f"lease pool busy: live leases exist under a total other "
                f"than {total_chips}"
            )
        self._publish_snap()

    # -- plumbing ------------------------------------------------------------

    def _rpc(self, fn):
        # chaos site: an armed drop raises ConnectionError here,
        # exercising the same retry contract as a real partition
        # between this holder and the broker
        faults.fault_point("lease.rpc")
        return fn()

    def _snap(self) -> Dict:
        snap = self._rpc(self.coord.lease_snap)
        if snap is None:
            raise LeaseError("coordinator does not speak the lease protocol")
        return snap

    def _publish_snap(self) -> Dict:
        """Gauges come from the coordinator's snapshot — the shared
        pool's truth — not the local mirror, so N adapter processes
        all report the same conserved totals."""
        snap = self._snap()
        by_side: Dict[str, int] = {side: 0 for side in self._sides}
        by_state = {GRANTED: 0, RECALLING: 0, FREED: 0}
        for l in snap["leases"]:
            state = _STATE[l["state"]]
            by_state[state] += 1
            if state != FREED:
                side = l["holder"].split(":", 1)[0]
                by_side[side] = by_side.get(side, 0) + l["chips"]
        self._g_free.set(snap["free"])
        self._g_epoch.set(snap["epoch"])
        for side, chips in by_side.items():
            self._g_chips.set(chips, side=side)
        for state, n in by_state.items():
            self._g_leases.set(n, state=state)
        return snap

    @staticmethod
    def _sid(int_id: int) -> str:
        return f"L{int_id:04d}"

    @staticmethod
    def _iid(lease_id: str) -> int:
        return int(str(lease_id).lstrip("L"))

    def _mirror_locked(self, lease_id: str) -> Optional[Lease]:
        return self._leases.get(lease_id)

    # -- queries (ChipLeaseBroker parity) ------------------------------------

    @property
    def free_chips(self) -> int:
        return self._snap()["free"]

    @property
    def epoch(self) -> int:
        return self._snap()["epoch"]

    @property
    def recovering(self) -> bool:
        return self._snap()["recovering"]

    def get(self, lease_id: str) -> Optional[Lease]:
        with self._lock:
            lease = self._leases.get(lease_id)
            return replace(lease) if lease is not None else None

    def snapshot(self) -> List[Lease]:
        """The WHOLE pool's leases (every holder process), as broker
        Lease copies built from the coordinator snapshot."""
        out = []
        for l in self._snap()["leases"]:
            out.append(
                Lease(
                    lease_id=self._sid(l["id"]),
                    holder=l["holder"],
                    chips=l["chips"],
                    epoch=l["epoch"],
                    state=_STATE[l["state"]],
                )
            )
        return out

    def live(self, holder: Optional[str] = None) -> List[Lease]:
        return [
            l for l in self.snapshot()
            if l.state != FREED and (holder is None or l.holder == holder)
        ]

    def check_conservation(self) -> bool:
        """live chips + free == pool, judged at the coordinator — the
        shared-pool truth across every holder process."""
        snap = self._snap()
        live = sum(
            l["chips"] for l in snap["leases"] if _STATE[l["state"]] != FREED
        )
        return live + snap["free"] == snap["pool"]

    # -- transitions ---------------------------------------------------------

    def grant(self, holder: str, chips: int) -> Lease:
        """Lease ``chips`` to ``holder`` from the shared pool. The
        client token makes a retried grant (reply lost to a broker
        crash) return the original lease instead of double-granting."""
        if chips <= 0:
            raise ValueError(f"grant chips must be >= 1, got {chips}")
        res = self._rpc(lambda: self.coord.lease_grant(holder, chips))
        if res is None:
            raise LeaseError("coordinator does not speak the lease protocol")
        if not res["ok"]:
            raise LeaseError(
                f"grant({holder}, {chips}): {res['reason']} "
                f"({res['free']}/{self.total_chips} chips free)"
            )
        lease = Lease(
            lease_id=self._sid(res["id"]),
            holder=holder,
            chips=res["chips"],
            epoch=res["epoch"],
            granted_t=self.clock(),
        )
        with self._lock:
            self._leases[lease.lease_id] = lease
            self._sides.add(lease.side)
        snap = self._publish_snap()
        flight.emit(
            "lease.grant",
            site="lease.grant",
            worker=holder,
            reshard_epoch=lease.epoch,
            lease=lease.lease_id,
            chips=lease.chips,
            free=snap["free"],
        )
        log.info("grant", lease=lease.lease_id, holder=holder,
                 chips=lease.chips, epoch=lease.epoch, free=snap["free"])
        return replace(lease)

    def recall(self, lease_id: str) -> Lease:
        """GRANTED → RECALLING at the coordinator. Idempotent while
        RECALLING, same as the in-process broker."""
        # chaos parity with ChipLeaseBroker.recall: the same site the
        # controller's _recall_with_retry recovers from
        faults.fault_point("lease.recall")
        rc = self._rpc(lambda: self.coord.lease_recall(self._iid(lease_id)))
        if rc is None:
            raise LeaseError("coordinator does not speak the lease protocol")
        if rc == "unknown":
            raise LeaseError(f"recall: unknown lease {lease_id}")
        if rc == "freed":
            raise LeaseError(f"recall: lease {lease_id} already FREED")
        with self._lock:
            lease = self._leases.get(lease_id)
            already = lease is not None and lease.state == RECALLING
            if lease is not None and lease.state == GRANTED:
                lease.state = RECALLING
                lease.recalled_t = self.clock()
            out = replace(lease) if lease is not None else None
        if out is None:
            # recalling a lease another process granted: mirror it from
            # the pool snapshot so the caller still gets a Lease back
            out = next(
                (l for l in self.snapshot() if l.lease_id == lease_id), None
            )
            if out is None:  # pragma: no cover - racing a concurrent free
                raise LeaseError(f"recall: unknown lease {lease_id}")
            already = False
        if already:
            return out  # idempotent retry: no second event
        self._publish_snap()
        flight.emit(
            "lease.recall",
            site="lease.recall",
            worker=out.holder,
            reshard_epoch=out.epoch,
            lease=out.lease_id,
            chips=out.chips,
        )
        log.info("recall", lease=out.lease_id, holder=out.holder,
                 chips=out.chips)
        return out

    def free(self, lease_id: str) -> int:
        """Settle at the coordinator: chips return to the shared pool.
        Returns chips freed (0 on an idempotent repeat)."""
        chips = self._rpc(lambda: self.coord.lease_free(self._iid(lease_id)))
        if chips is None:
            raise LeaseError("coordinator does not speak the lease protocol")
        if chips == -1:
            raise LeaseError(f"free: unknown lease {lease_id}")
        if chips == -2:
            return 0
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is not None and lease.state != FREED:
                lease.state = FREED
                lease.freed_t = self.clock()
            out = replace(lease) if lease is not None else None
        snap = self._publish_snap()
        flight.emit(
            "lease.freed",
            site="lease.freed",
            worker=out.holder if out else "remote",
            reshard_epoch=out.epoch if out else snap["epoch"],
            lease=lease_id,
            chips=chips,
            free=snap["free"],
        )
        log.info("freed", lease=lease_id, chips=chips, free=snap["free"])
        return chips

    def holder_crashed(self, holder: str) -> List[Lease]:
        """Settle a dead holder's leases pool-wide (LCRASH). The dead
        list comes from the coordinator's snapshot, not the local
        mirror — the corpse may have been another process entirely."""
        doomed = self.live(holder)
        chips = self._rpc(lambda: self.coord.lease_crashed(holder))
        if chips is None:
            raise LeaseError("coordinator does not speak the lease protocol")
        with self._lock:
            now = self.clock()
            dead = []
            for lease in doomed:
                lease.state = FREED
                lease.freed_t = now
                dead.append(lease)
                mirrored = self._leases.get(lease.lease_id)
                if mirrored is not None and mirrored.state != FREED:
                    mirrored.state = FREED
                    mirrored.freed_t = now
        if not chips:
            return dead
        snap = self._publish_snap()
        for lease in dead:
            flight.emit(
                "lease.freed",
                severity="warn",
                site="lease.freed",
                worker=holder,
                reshard_epoch=lease.epoch,
                lease=lease.lease_id,
                chips=lease.chips,
                crashed=True,
                free=snap["free"],
            )
        log.warn("holder_crashed", holder=holder, chips=chips)
        return dead

    # -- fencing + recovery --------------------------------------------------

    def adopt(self, lease_id: str, holder: str, chips: int, epoch: int) -> Lease:
        """Mirror a lease this holder believes it already holds — the
        holder-restart path: re-attach from the holder's own persisted
        state, then :meth:`confirm` asks the broker whether it still
        agrees. A holder whose memory is stale (force-released during
        recovery, then re-granted) gets fenced right there instead of
        silently computing on chips it no longer owns."""
        lease = Lease(
            lease_id=lease_id,
            holder=holder,
            chips=chips,
            epoch=epoch,
            granted_t=self.clock(),
        )
        with self._lock:
            self._leases[lease.lease_id] = lease
            self._sides.add(lease.side)
        return replace(lease)

    def confirm(self, lease_id: str) -> bool:
        """Present this holder's lease epoch to the fence. True when
        the broker still recognises the lease at that epoch; False
        when fenced — the holder must release and re-grant, it may NOT
        keep using the chips."""
        with self._lock:
            lease = self._leases.get(lease_id)
        if lease is None:
            raise LeaseError(f"confirm: unknown lease {lease_id}")
        # chaos site: the confirm leg of the handshake, distinct from
        # lease.rpc so a partition BETWEEN confirm and grant is armable
        faults.fault_point("lease.confirm")
        rc = self._rpc(
            lambda: self.coord.lease_confirm(self._iid(lease_id), lease.epoch)
        )
        if rc is None or rc == "ok":
            return True  # old server: nothing to confirm against
        self._c_fenced.inc(reason=rc)
        flight.emit(
            "lease.fence",
            severity="warn",
            site="lease.confirm",
            worker=lease.holder,
            reshard_epoch=lease.epoch,
            lease=lease_id,
            reason=rc,
        )
        log.warn("fenced", lease=lease_id, holder=lease.holder, reason=rc)
        with self._lock:
            mirrored = self._leases.get(lease_id)
            if mirrored is not None and mirrored.state != FREED:
                # the coordinator no longer honors this lease — the
                # local mirror must not keep counting its chips
                mirrored.state = FREED
                mirrored.freed_t = self.clock()
        return False

    def resync(self) -> Dict:
        """Re-attach after a broker restart: re-confirm every live
        lease this process holds, then run the recovery reaper. Emits
        ``lease.recover`` (closing the postmortem fault chain) when the
        broker leaves RECOVERING."""
        before = self._snap()
        with self._lock:
            mine = [
                replace(l) for l in self._leases.values() if l.state != FREED
            ]
        fenced = [
            lease.lease_id for lease in mine if not self.confirm(lease.lease_id)
        ]
        expire = self._rpc(self.coord.lease_expire) or (0, 0)
        snap = self._publish_snap()
        if before["recovering"] and not snap["recovering"]:
            self._c_recovered.inc()
            flight.emit(
                "lease.recover",
                site="lease.rpc",
                worker="broker",
                reshard_epoch=snap["epoch"],
                rids=[],
                confirmed=len(mine) - len(fenced),
                fenced=len(fenced),
                force_released=expire[0],
            )
            log.info("recovered", confirmed=len(mine) - len(fenced),
                     fenced=len(fenced), force_released=expire[0])
        return {
            "fenced": fenced,
            "force_released": expire[0],
            "recovering": bool(snap["recovering"]),
        }
