"""P2P weight push — warm-start a serving replica from live RAM.

When the elasticity controller grants chips to serving, the new
replica's weights already exist in host RAM on the pushing side (the
trainer's snapshot, or any process holding the params). Cold-loading
them from the export dir costs a full disk round trip; this module
instead serves a params-only :class:`~edl_tpu.runtime.checkpoint.
LocalSnapshot` over the existing shard-server wire protocol
(``runtime/shard_server.py`` — the 1.47 GB/s ``p2p_bw_gbs`` path) and
lets the replica pull it on spawn (``edl fleet --replica --warm-from
p2p --warm-addr host:port``).

The model-architecture doc rides along as a ``__config__`` piece
(JSON bytes as a uint8 array), so the puller rebuilds the matching
module with no side channel — the same self-describing trick the
export manifest plays, but over the wire.

Failure is loud by design: a replica asked to warm-start MUST NOT fall
back to a silent cold init — it would come up serving *different
weights* than the fleet believes it has. ``fetch_params`` raises; the
replica exits nonzero; the supervisor's spawn retry handles it.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Tuple

import numpy as np

from edl_tpu.runtime.checkpoint import (
    LocalSnapshot,
    _leaf_keys,
    _parse_piece_key,
)
from edl_tpu.runtime.shard_server import RemotePieces, ShardServer, fetch_index
from edl_tpu.utils.logging import kv_logger

log = kv_logger("weightpush")

CONFIG_KEY = "__config__"


def params_snapshot(
    params: Any, config_doc: Dict[str, Any], step: int = 0
) -> LocalSnapshot:
    """Params-only snapshot: every leaf as one full-extent piece at
    zero offset (host-RAM copies), plus the ``__config__`` piece.
    Leaf keys carry the ``p:`` prefix shared with the checkpoint
    formats, so a full training ShardServer and this one are
    interchangeable sources for the params subset."""
    items = [(f"p:{k}", np.ascontiguousarray(v))
             for k, v in _leaf_keys(params)]
    cfg = np.frombuffer(json.dumps(config_doc).encode(), dtype=np.uint8)
    items.append((CONFIG_KEY, cfg))
    pieces: Dict[str, Any] = {}
    primary: Dict[str, Any] = {}
    shapes: Dict[str, Tuple[int, ...]] = {}
    dtypes: Dict[str, str] = {}
    host_only: Dict[str, bool] = {}
    for key, arr in items:
        off = tuple(0 for _ in arr.shape)
        pieces[key] = [(off, arr)]
        primary[key] = [off]
        shapes[key] = tuple(arr.shape)
        dtypes[key] = arr.dtype.name
        host_only[key] = True
    return LocalSnapshot(
        step=step,
        pieces=pieces,
        primary=primary,
        shapes=shapes,
        dtypes=dtypes,
        host_only=host_only,
    )


def serve_params(
    params: Any,
    config_doc: Dict[str, Any],
    *,
    step: int = 0,
    token: Optional[str] = None,
    host: Optional[str] = None,
) -> ShardServer:
    """Stand up a ShardServer over a params snapshot taken NOW (the
    snapshot is fixed — rolling weight generations restart the server).
    Returns the live server; ``.port`` is the ephemeral bind."""
    snap = params_snapshot(params, config_doc, step=step)
    check = (lambda t: t == token) if token is not None else None
    srv = ShardServer(lambda: snap, check_token=check, host=host)
    log.info("serving params", port=srv.port, step=step,
             leaves=len(snap.pieces) - 1)
    return srv


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Rebuild the nested dict tree from the '/'-joined leaf keys.
    Dict-structured pytrees only — which is what every model in
    edl_tpu.models ships (stacked-layer dicts, no lists/tuples)."""
    out: Dict[str, Any] = {}
    for key, arr in flat.items():
        node = out
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return out


def fetch_params(
    addr: str,
    *,
    token: Optional[str] = None,
    timeout_s: float = 5.0,
    nconn: Optional[int] = None,
) -> Tuple[Dict[str, Any], Optional[Dict[str, Any]], int]:
    """Pull ``(params, config_doc, step)`` from a peer's ShardServer.

    Works against a dedicated :func:`serve_params` server *or* a full
    training-snapshot server (the ``o:`` optimizer leaves are simply
    skipped; ``config_doc`` is then None and the caller supplies the
    architecture). Raises ``ConnectionError`` when the peer is
    unreachable — never a silent empty tree."""
    idx = fetch_index(addr, timeout_s=timeout_s, token=token)
    if idx is None:
        raise ConnectionError(f"no shard server answering at {addr}")
    step, entries = idx
    want = {
        e: dt
        for e, dt in entries.items()
        if e.startswith("p:") or e.startswith(CONFIG_KEY + "@")
    }
    if not any(e.startswith("p:") for e in want):
        raise ConnectionError(
            f"shard server at {addr} holds no param pieces "
            f"({len(entries)} entries)"
        )
    src = RemotePieces(addr, want, token=token, nconn=nconn)
    try:
        got = src.get_many(want.keys())
    finally:
        src.close()
    config_doc: Optional[Dict[str, Any]] = None
    flat: Dict[str, np.ndarray] = {}
    for entry, arr in got.items():
        key, off, _shape = _parse_piece_key(entry)
        if key == CONFIG_KEY:
            config_doc = json.loads(arr.tobytes().decode())
            continue
        if any(off):
            # a sharded training server may expose partial pieces; the
            # warm path only supports full-extent leaves (the pusher
            # holds whole params) — loud, not wrong
            raise ValueError(
                f"partial piece {entry}: p2p warm-start needs "
                "full-extent leaves (use a params_snapshot server)"
            )
        flat[key[2:]] = arr
    log.info("fetched params", addr=addr, leaves=len(flat), step=step,
             bytes=sum(int(a.nbytes) for a in flat.values()))
    return _unflatten(flat), config_doc, step
