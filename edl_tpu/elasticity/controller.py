"""ElasticityController — the train⇄serve handover policy loop.

Watches the serving side's load/SLO signals and decides, once per
tick, whether chips should move:

* serving is drowning (load above ``load_high`` or the TTFT SLO
  breached) and the trainer can spare a replica's worth of chips above
  its floor → ``to_serve``: recall the train lease, shrink the trainer
  in place (the ``runtime/elastic.py`` reshard path), free, re-grant
  the remainder to the trainer and a replica's slice to serving, spawn
  the replica (warm via the p2p weight push when wired);
* serving is idle (load below ``load_low``) and above its replica
  floor → ``to_train``: drain-before-evict a replica, free its lease,
  recall+regrow the train lease, grow-reshard the trainer.

Decisions run through the autoscaler's shared
:class:`~edl_tpu.scheduler.autoscaler.ScaleGate` — the same damped
decide→gate→act→record pipeline the serving ``FleetScaler`` uses — so
a marginal diurnal signal can't thrash handovers; an SLO breach
bypasses the cooldown.

The controller is deliberately jax-free and fleet-free: it drives the
real sides through :class:`TrainPort`/:class:`ServePort` adapters
(plain callables), so the policy is testable with fakes and the demo
(`scripts/exp_elasticity.py`) wires in a live ``ElasticTrainer`` and a
live subprocess fleet.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from edl_tpu.elasticity.broker import ChipLeaseBroker, Lease
from edl_tpu.obs import disttrace
from edl_tpu.obs import events as flight
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.scheduler.autoscaler import ScaleGate
from edl_tpu.utils import faults, tracing
from edl_tpu.utils.logging import kv_logger

log = kv_logger("elasticity")


@dataclass
class TrainPort:
    """What the controller needs from the trainer: how many chips it
    holds, and a way to retarget that total (which drives the in-place
    reshard — ``ElasticTrainer.apply_chip_grant``)."""

    chips: Callable[[], int]
    apply_chips: Callable[[int], None]
    min_chips: int = 1


@dataclass
class ServePort:
    """What the controller needs from the serving fleet. ``load`` is
    queue depth + inflight per READY replica (the FleetScaler signal);
    ``add_replica`` spawns one replica (warm, when the fleet spec says
    so) and blocks until READY, returning the ramp seconds;
    ``remove_replica`` drains-before-evicts one."""

    replicas: Callable[[], int]
    load: Callable[[], float]
    slo_breached: Callable[[], bool]
    add_replica: Callable[[], float]
    remove_replica: Callable[[], None]
    min_replicas: int = 1


@dataclass
class Handover:
    """Ledger row for one completed handover."""

    n: int
    direction: str
    wall_s: float
    epoch: int
    ramp_s: Optional[float] = None
    recall_retries: int = 0


class ElasticityController:
    """One policy loop instance: a broker, the two side ports, and the
    damped gate. Call :meth:`bootstrap` once (leases whatever the
    sides already hold), then :meth:`tick` per control period."""

    def __init__(
        self,
        broker: ChipLeaseBroker,
        train: TrainPort,
        serve: ServePort,
        *,
        chips_per_replica: int = 1,
        load_high: float = 4.0,
        load_low: float = 0.5,
        cooldown_s: float = 30.0,
        recall_retries: int = 3,
        clock=time.monotonic,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
    ):
        if chips_per_replica < 1:
            raise ValueError(
                f"chips_per_replica must be >= 1, got {chips_per_replica}"
            )
        if load_low >= load_high:
            raise ValueError(
                f"load_low {load_low} must be < load_high {load_high}"
            )
        self.broker = broker
        self.train = train
        self.serve = serve
        self.chips_per_replica = chips_per_replica
        self.load_high = load_high
        self.load_low = load_low
        self.recall_retries = recall_retries
        self.clock = clock
        self.gate = ScaleGate(
            "elasticity", cooldown_s, clock=clock, bypass=serve.slo_breached
        )
        self.ledger: List[Handover] = []
        self._train_lease: Optional[Lease] = None
        self._serve_leases: List[Lease] = []
        self._n = 0
        self._pending_retries = 0
        reg = registry or obs_metrics.default_registry()
        self._c_handover = reg.counter(
            "edl_lease_handovers_total",
            "completed train<->serve chip handovers",
            ("direction",),
        )

    # -- setup ---------------------------------------------------------------

    def bootstrap(self) -> None:
        """Lease the inventory the sides already hold, so day one of
        the loop starts from a conserved ledger."""
        n = self.train.chips()
        if n:
            self._train_lease = self.broker.grant("train:job", n)
        for i in range(self.serve.replicas()):
            self._serve_leases.append(
                self.broker.grant(f"serve:r{i}", self.chips_per_replica)
            )

    # -- policy --------------------------------------------------------------

    def decide(self) -> Optional[str]:
        """Pure decision: "to_serve", "to_train", or None. No side
        effects, no cooldown (that's :meth:`tick`)."""
        load = self.serve.load()
        breach = self.serve.slo_breached()
        train_chips = self._train_lease.chips if self._train_lease else 0
        if (
            (load > self.load_high or breach)
            and train_chips - self.chips_per_replica >= self.train.min_chips
        ):
            return "to_serve"
        if (
            load < self.load_low
            and not breach
            and len(self._serve_leases) > self.serve.min_replicas
        ):
            return "to_train"
        return None

    def tick(self) -> Optional[str]:
        """One damped decision through the shared gate. Returns the
        handover direction applied, or None."""
        return self.gate.apply(self.decide, self._handover)

    # -- mechanics -----------------------------------------------------------

    def _recall_with_retry(self, lease_id: str) -> Lease:
        """Recall, surviving a transiently failing recall RPC (the
        ``lease.recall`` chaos site). A successful retry emits
        ``lease.recover`` so ``edl postmortem --assert-recovered
        --sites lease.`` can close the fault chain; ``rids`` is empty
        because a lease recall carries no serving requests — losing
        the RPC loses nothing a client sees."""
        last: Optional[BaseException] = None
        for attempt in range(self.recall_retries + 1):
            try:
                lease = self.broker.recall(lease_id)
            except (faults.InjectedFault, ConnectionError, OSError) as e:
                last = e
                log.warn("recall failed", lease=lease_id,
                         attempt=attempt, err=str(e))
                continue
            if attempt:
                flight.emit(
                    "lease.recover",
                    site="lease.recall",
                    worker=lease.holder,
                    reshard_epoch=lease.epoch,
                    lease=lease.lease_id,
                    rids=[],
                    retried=attempt,
                )
                self._pending_retries += attempt
            return lease
        raise LeaseRecallFailed(
            f"recall {lease_id} failed after "
            f"{self.recall_retries + 1} attempts"
        ) from last

    def _handover(self, direction: str) -> None:
        self._n += 1
        n = self._n
        t0 = self.clock()
        self._pending_retries = 0
        # every span/event of one handover shares a derived trace id,
        # same convention as ("reshard", ep) in runtime/elastic.py
        with disttrace.root("handover", n):
            with tracing.span("elasticity.handover", direction=direction,
                              n=n):
                flight.emit(
                    "handover.begin",
                    site="handover.begin",
                    reshard_epoch=self.broker.epoch,
                    direction=direction,
                    n=n,
                )
                if direction == "to_serve":
                    ramp = self._train_to_serve()
                else:
                    ramp = self._serve_to_train()
                wall = self.clock() - t0
                flight.emit(
                    "handover.end",
                    site="handover.end",
                    reshard_epoch=self.broker.epoch,
                    direction=direction,
                    n=n,
                    wall_s=wall,
                )
        self._c_handover.inc(direction=direction)
        self.ledger.append(
            Handover(
                n=n,
                direction=direction,
                wall_s=wall,
                epoch=self.broker.epoch,
                ramp_s=ramp,
                recall_retries=self._pending_retries,
            )
        )
        log.info("handover", n=n, direction=direction,
                 wall_s=round(wall, 3), epoch=self.broker.epoch)

    def _train_to_serve(self) -> Optional[float]:
        """Recall train chips → shrink-reshard → free → re-grant the
        smaller train lease + one serving slice → spawn the replica."""
        old = self._train_lease
        assert old is not None  # decide() guarantees it
        self._recall_with_retry(old.lease_id)
        remain = old.chips - self.chips_per_replica
        self.train.apply_chips(remain)  # shrink happens inside RECALLING
        self.broker.free(old.lease_id)
        self._train_lease = (
            self.broker.grant("train:job", remain) if remain else None
        )
        lease = self.broker.grant(
            f"serve:r{len(self._serve_leases)}", self.chips_per_replica
        )
        self._serve_leases.append(lease)
        return self.serve.add_replica()

    def _serve_to_train(self) -> Optional[float]:
        """Drain-before-evict one replica → free its lease → regrow the
        train lease → grow-reshard."""
        victim = self._serve_leases.pop()
        self._recall_with_retry(victim.lease_id)
        self.serve.remove_replica()  # drain + evict inside RECALLING
        self.broker.free(victim.lease_id)
        old = self._train_lease
        grow = (old.chips if old else 0) + self.chips_per_replica
        if old is not None:
            self._recall_with_retry(old.lease_id)
            self.broker.free(old.lease_id)
        self._train_lease = self.broker.grant("train:job", grow)
        self.train.apply_chips(grow)
        return None


class LeaseRecallFailed(RuntimeError):
    """Recall retries exhausted — the handover did not start."""
