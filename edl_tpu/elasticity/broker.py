"""ChipLeaseBroker — the cluster's chip inventory as first-class leases.

Every chip the elasticity plane can move belongs to exactly one of two
places at any instant: the broker's free pool, or a live lease held by
a side of the system (``train:*`` or ``serve:*`` holders). A lease
walks a one-way state machine:

    GRANTED ──recall()──▶ RECALLING ──free()──▶ FREED

``recall`` is the broker asking the holder to give the chips back (the
holder then shrinks — a trainer reshard or a replica drain — and calls
``free``); it is idempotent while RECALLING so a retried recall RPC is
safe. ``free`` returns the chips to the pool. A holder that dies
mid-conversation is settled by :meth:`ChipLeaseBroker.holder_crashed`:
whatever it held (GRANTED or stuck RECALLING) returns to the pool,
because the recall ack will never come.

Epochs are globally monotonic — every grant bumps the broker epoch and
stamps the lease with it, so any two leases are ordered and a stale
grant can never be mistaken for a current one (the lease analog of the
reshard epoch in ``runtime/elastic.py``).

Concurrency: one ``_lock`` guards the table, the free count, and the
epoch. State is mutated under the lock; flight events and gauge
updates are published after release (no I/O under the table lock).
The ``lease-broker`` schedcheck harness (analysis/harnesses.py) proves
the discipline race-free under the deterministic scheduler, and
``mut-lease-broker`` proves the lock is load-bearing.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, replace
from typing import Dict, List, Optional

from edl_tpu.obs import events as flight
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils import faults
from edl_tpu.utils.logging import kv_logger

log = kv_logger("lease")

GRANTED = "GRANTED"
RECALLING = "RECALLING"
FREED = "FREED"


class LeaseError(RuntimeError):
    """Illegal lease transition or an unsatisfiable grant."""


@dataclass
class Lease:
    """One chip allocation. ``holder`` is ``side:name`` (``train:job0``,
    ``serve:r3``); the side prefix keys the per-side gauge."""

    lease_id: str
    holder: str
    chips: int
    epoch: int
    state: str = GRANTED
    granted_t: float = 0.0
    recalled_t: Optional[float] = None
    freed_t: Optional[float] = None

    @property
    def side(self) -> str:
        return self.holder.split(":", 1)[0]


class ChipLeaseBroker:
    """Grant/recall/free chip leases against a fixed ``total_chips``
    inventory. Conservation is the core invariant: at every quiescent
    point, chips under live (non-FREED) leases plus the free pool equal
    the inventory — :meth:`check_conservation` asserts it, the tests
    and the schedcheck harness lean on it."""

    def __init__(
        self,
        total_chips: int,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        clock=time.monotonic,
    ):
        if total_chips <= 0:
            raise ValueError(f"total_chips must be >= 1, got {total_chips}")
        self.total_chips = total_chips
        self.clock = clock
        self._lock = threading.Lock()
        self._leases: Dict[str, Lease] = {}
        self._free = total_chips
        self._epoch = 0
        self._sides: set = set()  # sides ever seen: zero their gauges
        reg = registry or obs_metrics.default_registry()
        self._g_chips = reg.gauge(
            "edl_lease_chips",
            "chips under live (GRANTED/RECALLING) leases, by holder side",
            ("side",),
        )
        self._g_free = reg.gauge(
            "edl_lease_chips_free", "chips in the broker pool, unleased"
        )
        self._g_leases = reg.gauge(
            "edl_leases", "lease count by state", ("state",)
        )
        self._g_epoch = reg.gauge(
            "edl_lease_epoch", "broker lease epoch (bumps on every grant)"
        )
        with self._lock:
            doc = self._gauges_locked()
        self._publish(doc)

    # -- queries -------------------------------------------------------------

    @property
    def free_chips(self) -> int:
        with self._lock:
            return self._free

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def get(self, lease_id: str) -> Optional[Lease]:
        with self._lock:
            lease = self._leases.get(lease_id)
            return replace(lease) if lease is not None else None

    def snapshot(self) -> List[Lease]:
        """Copies — callers can't mutate broker state through them."""
        with self._lock:
            return [replace(l) for l in self._leases.values()]

    def live(self, holder: Optional[str] = None) -> List[Lease]:
        """Non-FREED leases, optionally for one holder."""
        with self._lock:
            return [
                replace(l)
                for l in self._leases.values()
                if l.state != FREED
                and (holder is None or l.holder == holder)
            ]

    def check_conservation(self) -> bool:
        """granted + free == total — the invariant every transition
        must preserve."""
        with self._lock:
            leased = sum(
                l.chips for l in self._leases.values() if l.state != FREED
            )
            return leased + self._free == self.total_chips

    # -- transitions ---------------------------------------------------------

    def grant(self, holder: str, chips: int) -> Lease:
        """Lease ``chips`` to ``holder``. Raises :class:`LeaseError`
        when the pool can't cover it — a double grant of the same chips
        is structurally impossible because the pool is debited under
        the lock before the lease exists."""
        if chips <= 0:
            raise ValueError(f"grant chips must be >= 1, got {chips}")
        with self._lock:
            if chips > self._free:
                raise LeaseError(
                    f"grant({holder}, {chips}): only {self._free}/"
                    f"{self.total_chips} chips free"
                )
            self._free -= chips
            self._epoch += 1
            lease = Lease(
                lease_id=f"L{self._epoch:04d}",
                holder=holder,
                chips=chips,
                epoch=self._epoch,
                granted_t=self.clock(),
            )
            self._leases[lease.lease_id] = lease
            self._sides.add(lease.side)
            doc = self._gauges_locked()
        self._publish(doc)
        flight.emit(
            "lease.grant",
            site="lease.grant",
            worker=holder,
            reshard_epoch=lease.epoch,
            lease=lease.lease_id,
            chips=chips,
            free=doc["free"],
        )
        log.info("grant", lease=lease.lease_id, holder=holder, chips=chips,
                 epoch=lease.epoch, free=doc["free"])
        return replace(lease)

    def recall(self, lease_id: str) -> Lease:
        """GRANTED → RECALLING: ask the holder for the chips back.
        Idempotent while RECALLING (a retried recall is a no-op)."""
        # chaos site: an injected raise here models the recall RPC
        # failing before any state moved — the lease is untouched, so
        # the caller's retry is safe (scripts/exp_elasticity.py arms
        # ``lease.recall`` and the controller's retry emits
        # ``lease.recover``)
        faults.fault_point("lease.recall")
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise LeaseError(f"recall: unknown lease {lease_id}")
            if lease.state == FREED:
                raise LeaseError(f"recall: lease {lease_id} already FREED")
            already = lease.state == RECALLING
            if not already:
                lease.state = RECALLING
                lease.recalled_t = self.clock()
            doc = self._gauges_locked()
            out = replace(lease)
        if already:
            return out  # idempotent retry: no second event
        self._publish(doc)
        flight.emit(
            "lease.recall",
            site="lease.recall",
            worker=out.holder,
            reshard_epoch=out.epoch,
            lease=out.lease_id,
            chips=out.chips,
        )
        log.info("recall", lease=out.lease_id, holder=out.holder,
                 chips=out.chips)
        return out

    def free(self, lease_id: str) -> int:
        """RECALLING → FREED: the holder has shrunk; chips return to
        the pool. Returns the chips freed (0 on an idempotent repeat).
        A GRANTED lease must be recalled first — the two-step keeps the
        holder's shrink inside the RECALLING window where the broker
        won't re-grant those chips."""
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise LeaseError(f"free: unknown lease {lease_id}")
            if lease.state == FREED:
                return 0
            if lease.state != RECALLING:
                raise LeaseError(
                    f"free: lease {lease_id} is {lease.state}, "
                    "not RECALLING (recall first)"
                )
            lease.state = FREED
            lease.freed_t = self.clock()
            self._free += lease.chips
            doc = self._gauges_locked()
            out = replace(lease)
        self._publish(doc)
        flight.emit(
            "lease.freed",
            site="lease.freed",
            worker=out.holder,
            reshard_epoch=out.epoch,
            lease=out.lease_id,
            chips=out.chips,
            free=doc["free"],
        )
        log.info("freed", lease=out.lease_id, holder=out.holder,
                 chips=out.chips, free=doc["free"])
        return out.chips

    def holder_crashed(self, holder: str) -> List[Lease]:
        """Settle a dead holder: every lease it held — GRANTED or stuck
        mid-RECALLING (the ack will never come) — returns to the pool
        in one transition."""
        with self._lock:
            now = self.clock()
            dead = []
            for lease in self._leases.values():
                if lease.holder == holder and lease.state != FREED:
                    lease.state = FREED
                    lease.freed_t = now
                    self._free += lease.chips
                    dead.append(replace(lease))
            doc = self._gauges_locked()
        if not dead:
            return []
        self._publish(doc)
        for lease in dead:
            flight.emit(
                "lease.freed",
                severity="warn",
                site="lease.freed",
                worker=holder,
                reshard_epoch=lease.epoch,
                lease=lease.lease_id,
                chips=lease.chips,
                crashed=True,
                free=doc["free"],
            )
        log.warn("holder_crashed", holder=holder, leases=len(dead),
                 chips=sum(l.chips for l in dead))
        return dead

    # -- observability -------------------------------------------------------

    def _gauges_locked(self) -> Dict:
        by_side = {side: 0 for side in self._sides}
        by_state = {GRANTED: 0, RECALLING: 0, FREED: 0}
        for lease in self._leases.values():
            by_state[lease.state] += 1
            if lease.state != FREED:
                by_side[lease.side] = by_side.get(lease.side, 0) + lease.chips
        return {
            "free": self._free,
            "epoch": self._epoch,
            "by_side": by_side,
            "by_state": by_state,
        }

    def _publish(self, doc: Dict) -> None:
        self._g_free.set(doc["free"])
        self._g_epoch.set(doc["epoch"])
        for side, chips in doc["by_side"].items():
            self._g_chips.set(chips, side=side)
        for state, n in doc["by_state"].items():
            self._g_leases.set(n, state=state)
