"""JobUpdater — per-job lifecycle state machine.

Unified port of the reference's TrainingJobUpdater
(reference: pkg/updater/trainingJobUpdater.go:44-481): parse →
create resources awaited-ready → running → periodic status convert →
terminal release, plus delete draining everything. Differences by
design: the awaited children are coordinator + worker group (no
pserver), the state machine is driven by explicit ``step()`` calls
(the controller owns the clock — no goroutine per job), and a
``SCALING`` phase surfaces in-flight reshards.
"""

from __future__ import annotations

import time
from typing import List, Optional

from edl_tpu.api.job import JobPhase, ResourceState, TrainingJob
from edl_tpu.api.parser import JobParser, ValidationError
from edl_tpu.cluster.base import Cluster
from edl_tpu.utils.logging import kv_logger

log = kv_logger("updater")

CONVERT_INTERVAL_S = 10.0  # reference: convertedTimerTicker :22
CONFIRM_INTERVAL_S = 5.0  # reference: confirmResourceTicker :23
CREATE_TIMEOUT_S = 600.0  # await-ready bound (reference polls forever)


class JobUpdater:
    """Drives one TrainingJob none→creating→running→succeeded/failed.

    ``step()`` advances the machine; call it from the controller loop
    (reference: start() goroutine + tickers,
    trainingJobUpdater.go:453-481).
    """

    def __init__(
        self,
        job: TrainingJob,
        cluster: Cluster,
        parser: Optional[JobParser] = None,
        create_timeout_s: float = CREATE_TIMEOUT_S,
    ):
        self.job = job
        self.cluster = cluster
        self.parser = parser or JobParser()
        self.create_timeout_s = create_timeout_s
        self.warnings: List[str] = []
        self._create_deadline: Optional[float] = None
        self._released = False
        # True while an in-process runtime (runtime/local.py) drives this
        # job and will report reshard completion itself; when False the
        # control plane infers completion from pod convergence.
        self.runtime_attached = False
        self._scaling_since: Optional[float] = None

    # -- phase helpers -----------------------------------------------------

    @property
    def phase(self) -> JobPhase:
        return self.job.status.phase

    def _set_phase(self, phase: JobPhase, reason: str = "") -> None:
        if self.job.status.phase != phase:
            log.info(
                "phase transition",
                job=self.job.name,
                prev=self.job.status.phase.value or "none",
                next=phase.value,
                reason=reason,
            )
        self.job.status.phase = phase
        self.job.status.reason = reason

    # -- lifecycle ---------------------------------------------------------

    def step(self) -> JobPhase:
        """Advance one notch. Safe to call repeatedly at any cadence."""
        if self.phase == JobPhase.NONE:
            self._parse()
        if self.phase == JobPhase.CREATING:
            self._create()
        if self.phase in (JobPhase.RUNNING, JobPhase.SCALING):
            self.convert()
        if self.phase.terminal():
            self.release_resources()
        return self.phase

    def _parse(self) -> None:
        """reference: parseTrainingJob via InitResource :417-429."""
        try:
            self.warnings = self.parser.validate(self.job)
        except ValidationError as e:
            self._set_phase(JobPhase.FAILED, f"validation error: {e}")
            return
        self._set_phase(JobPhase.CREATING)

    def _create(self) -> None:
        """Create coordinator (fault-tolerant jobs only, like the
        reference's master, trainingJobUpdater.go:283-287), await it
        ready, then create the worker group
        (reference: createTrainingJob :282-293, createResource :209-257)."""
        ns = self.job.namespace
        if self._create_deadline is None:
            self._create_deadline = time.monotonic() + self.create_timeout_s

        if self.job.spec.fault_tolerant:
            cplan = self.parser.parse_to_coordinator(self.job)
            try:
                coord = self.cluster.get_coordinator(ns, cplan.name)
                if coord.endpoint.endswith(":0"):
                    # Deployment exists but the paired Service is
                    # missing (a prior create died between the two
                    # POSTs): re-run create, which is idempotent per
                    # resource and fills in whichever half is absent.
                    coord = self.cluster.create_coordinator(cplan)
            except KeyError:
                coord = self.cluster.create_coordinator(cplan)
            self.job.status.master.state = ResourceState.CREATING
            if coord.ready_replicas < coord.replicas:
                if time.monotonic() > self._create_deadline:
                    self._set_phase(JobPhase.FAILED, "coordinator never became ready")
                return  # await ready; retry on next step
            self.job.status.master.state = ResourceState.READY
            self.job.status.master.ready_replicas = coord.ready_replicas

        wplan = self.parser.parse_to_workers(self.job)
        try:
            group = self.cluster.get_worker_group(self.job)
        except KeyError:
            group = self.cluster.create_worker_group(wplan)
        self.job.status.worker.state = ResourceState.CREATING
        self.job.status.worker.replicas = group.parallelism
        self.job.status.parallelism = group.parallelism
        # reference: createTrainer flips phase to running immediately :259-280
        self._set_phase(JobPhase.RUNNING)

    def convert(self) -> None:
        """Fold worker-group status into the job phase
        (reference: Convert + GetStatus :343-414)."""
        try:
            group = self.cluster.get_worker_group(self.job)
        except KeyError:
            self._set_phase(JobPhase.FAILED, "worker group disappeared")
            return
        st = self.job.status
        st.worker.replicas = group.parallelism
        st.worker.ready_replicas = group.active
        st.worker.succeeded = group.succeeded
        st.worker.failed = group.failed
        st.parallelism = group.parallelism

        # Without an attached runtime to call on_reshard_done, the control
        # plane marks a rescale complete once the pod set converges on the
        # new target (stall then measures pod churn, not array resharding).
        if (
            self.phase == JobPhase.SCALING
            and not self.runtime_attached
            and group.parallelism > 0
            and group.active == group.parallelism
        ):
            since = self._scaling_since
            self.on_reshard_done(0.0 if since is None else time.monotonic() - since)

        if self.job.spec.fault_tolerant:
            # FT jobs fail only when ALL workers are dead with none
            # succeeded (reference :361-370 compares cumulative Failed
            # against Parallelism, which false-fails a healthy job after
            # replacements or a scale-down; live-count semantics instead).
            if group.failed > 0 and group.active == 0 and group.succeeded == 0:
                self._set_phase(JobPhase.FAILED, "all workers have failed")
            elif group.succeeded > 0 and group.active == 0:
                self._set_phase(JobPhase.SUCCEEDED, "success")
        else:
            # non-FT jobs fail on ANY worker failure (reference :371-380)
            if group.failed > 0:
                self._set_phase(JobPhase.FAILED, "at least one worker failed")
            elif group.succeeded >= group.parallelism and group.active == 0:
                self._set_phase(JobPhase.SUCCEEDED, "all workers have succeeded")

    def on_scale(self, new_parallelism: int) -> None:
        """Autoscaler retarget notification: surface the reshard window
        (new in the TPU design; the reference has no visible state for
        an in-flight rescale)."""
        if self.phase == JobPhase.RUNNING:
            self._set_phase(JobPhase.SCALING, f"resharding to {new_parallelism}")
            self.job.status.reshard_count += 1
            self._scaling_since = time.monotonic()

    def on_reshard_done(self, stall_s: float, fallback: bool = False) -> None:
        if self.phase == JobPhase.SCALING:
            self.job.status.last_reshard_stall_s = stall_s
            if fallback:
                self.job.status.reshard_fallbacks += 1
            self._scaling_since = None
            self._set_phase(JobPhase.RUNNING)

    def release_resources(self) -> None:
        """Terminal-state release: coordinator goes away, the worker group
        record remains for status (reference: Convert's release of
        master/pserver :400-412 — trainer Job is already done)."""
        if self._released:
            return
        ns = self.job.namespace
        try:
            self.cluster.delete_coordinator(ns, f"{self.job.name}-coordinator")
        except KeyError:
            pass
        self._released = True

    def delete(self) -> None:
        """Full teardown on job deletion
        (reference: deleteTrainingJob :156-207)."""
        ns = self.job.namespace
        self.cluster.delete_worker_group(ns, f"{self.job.name}-worker")
        try:
            self.cluster.delete_coordinator(ns, f"{self.job.name}-coordinator")
        except KeyError:
            pass
        self._released = True
        log.info("deleted training job", job=self.job.name)
