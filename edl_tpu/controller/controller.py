"""Controller — watches TrainingJobs, owns per-job updaters, feeds the
autoscaler.

Unified port of the reference's two generations (SURVEY §0): the legacy
controller's watch→create→autoscale wiring
(reference: pkg/controller.go:44-161) driving the CRD updater's
lifecycle state machine (reference: pkg/updater/trainingJobUpdater.go).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from edl_tpu.api.job import Event, JobPhase, TrainingJob
from edl_tpu.api.parser import JobParser
from edl_tpu.cluster import topology
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.controller.updater import JobUpdater
from edl_tpu.scheduler.autoscaler import Autoscaler
from edl_tpu.utils.logging import kv_logger

log = kv_logger("controller")


class Controller:
    """reference: New + Run, pkg/controller.go:51-76."""

    def __init__(
        self,
        cluster,
        max_load_desired: float = 0.97,  # reference flag default, cmd/edl/edl.go:19
        slice_policy: topology.SlicePolicy = topology.flexible,
        rescale_cooldown_s: float = 0.0,
        autoscaler: Optional[Autoscaler] = None,
    ):
        self.cluster = cluster
        self.parser = JobParser()
        self.autoscaler = autoscaler or Autoscaler(
            cluster,
            max_load_desired=max_load_desired,
            slice_policy=slice_policy,
            rescale_cooldown_s=rescale_cooldown_s,
        )
        self.updaters: Dict[str, JobUpdater] = {}
        # watch events land on the cluster's watch thread while the
        # updater ticker iterates on its own thread: every access to
        # the updaters map goes through this lock (found by `edl check`
        # lockset-race; pinned by test_controller concurrency test)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list = []
        if hasattr(cluster, "watch_jobs"):
            cluster.watch_jobs(self.handle_event)
        if hasattr(cluster, "scale_listeners"):
            cluster.scale_listeners.append(self._on_scale)

    # -- event handling (reference: onAdd/onUpdate/onDelete :110-161) ------

    def handle_event(self, ev: Event) -> None:
        if ev.type == Event.Type.ADD:
            self.on_add(ev.job)
        elif ev.type == Event.Type.UPDATE:
            self.on_update(ev.job)
        elif ev.type == Event.Type.DEL:
            self.on_delete(ev.job)

    def on_add(self, job: TrainingJob) -> None:
        """reference: onAdd parses + creates child resources and notifies
        the autoscaler (pkg/controller.go:110-148); here resource creation
        is delegated to the updater's state machine."""
        updater = JobUpdater(job, self.cluster, self.parser)
        with self._lock:
            if job.qualified_name in self.updaters:
                return
            self.updaters[job.qualified_name] = updater
        log.info("job added", job=job.qualified_name)
        updater.step()  # parse + begin creating (outside the map lock)
        self.autoscaler.on_add(job)

    def on_update(self, job: TrainingJob) -> None:
        with self._lock:
            u = self.updaters.get(job.qualified_name)
        if u is None:
            self.on_add(job)
            return
        u.job.spec = job.spec  # reference: Modify event, updater :95-97
        self.autoscaler.on_update(job)

    def on_delete(self, job: TrainingJob) -> None:
        with self._lock:
            u = self.updaters.pop(job.qualified_name, None)
        if u is not None:
            u.delete()
        self.autoscaler.on_del(job)
        log.info("job deleted", job=job.qualified_name)

    def _on_scale(self, job_name: str, new_parallelism: int) -> None:
        with self._lock:
            u = self.updaters.get(job_name)
        if u is not None:
            u.on_scale(new_parallelism)

    # -- loop --------------------------------------------------------------

    def step(self) -> None:
        """One convert pass over all updaters (the 10 s ticker analog,
        reference: trainingJobUpdater.go:471-478). Errors are isolated
        per updater: one job that fails every tick (bad manifest,
        cluster 4xx) must not starve reconciliation of the others."""
        with self._lock:
            updaters = list(self.updaters.values())
        for u in updaters:
            try:
                u.step()
            except Exception as e:
                log.error(
                    "updater step failed",
                    job=u.job.qualified_name,
                    error=str(e),
                )

    def run(self, updater_interval_s: float = 1.0) -> None:
        """Run autoscaler + updater loops in threads
        (reference: Controller.Run spawns WatchTrainingJobs +
        autoscaler.Run goroutines, pkg/controller.go:64-76)."""
        t_asc = threading.Thread(target=self.autoscaler.run, daemon=True)
        t_asc.start()
        self._threads.append(t_asc)

        def _updater_loop():
            while not self._stop.is_set():
                self.step()
                time.sleep(updater_interval_s)

        t_upd = threading.Thread(target=_updater_loop, daemon=True)
        t_upd.start()
        self._threads.append(t_upd)

    def stop(self) -> None:
        self._stop.set()
        self.autoscaler.stop()
        for t in self._threads:
            t.join(timeout=5)

    # -- convenience -------------------------------------------------------

    def phase_of(self, job_name: str) -> JobPhase:
        """job_name is the qualified name (bare name in the default
        namespace)."""
        with self._lock:
            u = self.updaters.get(job_name)
        return u.phase if u else JobPhase.NONE
