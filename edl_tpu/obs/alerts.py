"""Alerting over metric history — threshold, multi-window burn-rate,
and anomaly rules evaluated against a :class:`~edl_tpu.obs.tsdb.TSDB`
(stdlib-only, no jax import).

Three rule families, one engine:

* **threshold** — an aggregate (``avg/min/max/last``) of any scalar
  series over a trailing window compared against a constant, with an
  optional ``for_s`` debounce (condition must hold continuously).
* **burn_rate** — the SRE-workbook multi-window multi-burn-rate shape
  over an *ok-ratio* gauge (``edl_slo_ttft_ok_ratio``,
  ``edl_slo_goodput_fraction``): error fraction ``1 - ratio`` averaged
  over a SHORT and a LONG trailing window, both expressed as multiples
  of the error budget ``1 - objective``. The alert fires only when
  BOTH windows burn faster than ``factor`` — the long window keeps a
  blip from paging, the short window makes the page resolve promptly
  once the burn stops. Convention: a fast pair (5m/1h, factor 14.4)
  pages; a slow pair (1h/6h, factor 6) warns.
* **anomaly** — a watchdog for series with no crisp objective (queue
  wait p99, reshard stall, push-failure rate): EWMA mean over the
  trailing window plus a MAD band; the newest sample fires when its
  robust z-score ``|x - ewma| / (1.4826 * MAD + floor)`` exceeds
  ``z``. ``mode`` picks the observed value: the raw sample
  (``value``), the per-step counter increase (``increase``, reset
  clamped), or a histogram percentile (``hist_p99``/``hist_p50``).

Every window in a rules doc is scaled by ``time_scale`` so the SAME
rules file runs against production cadences and the CI lane's
seconds-long replays. Alert transitions are observable three ways:
``alert.fire`` / ``alert.resolve`` flight-recorder events (site
``alert.<rule>``, so ``edl postmortem --sites alert.`` chains them),
the ``edl_alerts_active{severity}`` / ``edl_alerts_fired_total{rule}``
series, and :meth:`AlertEngine.to_block` for `edl monitor --json`.
"""

from __future__ import annotations

import json
import math
from typing import Any, Callable, Dict, List, Optional

from . import events as obs_events
from . import metrics as obs_metrics

__all__ = [
    "AlertEngine",
    "AnomalyRule",
    "BurnRateRule",
    "DEFAULT_RULES",
    "ThresholdRule",
    "engine_from_doc",
    "load_rules_doc",
    "parse_rules",
]

# rule severity -> flight-recorder severity (a page is an error on
# the incident timeline; a warn is a warn)
_EVENT_SEVERITY = {"page": "error", "warn": "warn", "info": "info"}

# The shipped default rules file, as a pure literal so `edl check`'s
# telemetry-conventions rule can statically verify every referenced
# series against the registered metric catalog. `edl watch` with no
# --rules evaluates exactly this doc.
DEFAULT_RULES = {
    "time_scale": 1.0,
    "rules": [
        {
            "type": "burn_rate",
            "name": "slo_ttft_fast_burn",
            "series": "edl_slo_ttft_ok_ratio",
            "labels": {"slo_class": "interactive"},
            "objective": 0.99,
            "short_s": 300.0,
            "long_s": 3600.0,
            "factor": 14.4,
            "severity": "page",
        },
        {
            "type": "burn_rate",
            "name": "slo_ttft_slow_burn",
            "series": "edl_slo_ttft_ok_ratio",
            "labels": {"slo_class": "interactive"},
            "objective": 0.99,
            "short_s": 3600.0,
            "long_s": 21600.0,
            "factor": 6.0,
            "severity": "warn",
        },
        {
            "type": "burn_rate",
            "name": "goodput_fast_burn",
            "series": "edl_slo_goodput_fraction",
            "labels": {},
            "objective": 0.95,
            "short_s": 300.0,
            "long_s": 3600.0,
            "factor": 14.4,
            "severity": "page",
        },
        {
            "type": "threshold",
            "name": "hbm_crosscheck_drift",
            "series": "edl_hbm_crosscheck_drift_bytes",
            "labels": {},
            "op": ">",
            "value": 16777216.0,
            "window_s": 120.0,
            "agg": "max",
            "severity": "warn",
        },
        {
            "type": "anomaly",
            "name": "queue_wait_anomaly",
            "series": "edl_serving_queue_wait_seconds",
            "labels": {},
            "mode": "hist_p99",
            "window_s": 600.0,
            "z": 8.0,
            "severity": "warn",
        },
        {
            "type": "anomaly",
            "name": "reshard_stall_anomaly",
            "series": "edl_reshard_stall_seconds",
            "labels": {},
            "mode": "hist_p99",
            "window_s": 3600.0,
            "z": 8.0,
            "severity": "warn",
        },
        {
            "type": "anomaly",
            "name": "push_failure_anomaly",
            "series": "edl_metrics_push_failures_total",
            "labels": {},
            "mode": "increase",
            "window_s": 600.0,
            "z": 8.0,
            "severity": "warn",
        },
        {
            # a spike of stale-epoch fences means holders are acting on
            # leases the broker no longer honors — split-brain in the
            # chip inventory; one or two after a broker restart is the
            # recovery window working, a burst is an incident
            "type": "anomaly",
            "name": "lease_fence_anomaly",
            "series": "edl_lease_fenced_total",
            "labels": {"reason": "stale_epoch"},
            "mode": "increase",
            "window_s": 600.0,
            "z": 8.0,
            "severity": "warn",
        },
    ],
}


class Rule:
    """One named condition over history. ``firing(db, now)`` returns
    a detail dict while the condition holds, None otherwise (including
    "not enough data yet" — an alert must never fire on an empty
    window). The engine layers the fire/resolve state machine and the
    ``for_s`` debounce on top."""

    def __init__(self, name: str, severity: str = "warn",
                 for_s: float = 0.0):
        if severity not in _EVENT_SEVERITY:
            raise ValueError(
                f"rule {name!r}: severity must be one of "
                f"{tuple(_EVENT_SEVERITY)}, got {severity!r}"
            )
        self.name = name
        self.severity = severity
        self.for_s = float(for_s)

    def scale(self, time_scale: float) -> None:
        self.for_s *= time_scale

    def firing(self, db: Any, now: float) -> Optional[Dict[str, float]]:
        raise NotImplementedError


_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


class ThresholdRule(Rule):
    def __init__(self, name: str, series: str,
                 labels: Optional[Dict[str, str]] = None, *,
                 op: str = ">", value: float = 0.0,
                 window_s: float = 60.0, agg: str = "avg",
                 severity: str = "warn", for_s: float = 0.0):
        super().__init__(name, severity, for_s)
        if op not in _OPS:
            raise ValueError(f"rule {name!r}: unknown op {op!r}")
        if agg not in ("avg", "min", "max", "last"):
            raise ValueError(f"rule {name!r}: unknown agg {agg!r}")
        self.series = series
        self.labels = dict(labels or {})
        self.op = op
        self.value = float(value)
        self.window_s = float(window_s)
        self.agg = agg

    def scale(self, time_scale: float) -> None:
        super().scale(time_scale)
        self.window_s *= time_scale

    def firing(self, db, now):
        # step=None: ONE aggregate over the whole trailing window (a
        # stepped query would put the window-edge sample in a bucket
        # of its own)
        buckets = db.series(
            self.series, self.labels, now - self.window_s, now,
        )
        if not buckets:
            return None
        observed = buckets[-1][self.agg]
        if _OPS[self.op](observed, self.value):
            return {"value": observed, "threshold": self.value,
                    "window_s": self.window_s}
        return None


class BurnRateRule(Rule):
    def __init__(self, name: str, series: str,
                 labels: Optional[Dict[str, str]] = None, *,
                 objective: float = 0.99, short_s: float = 300.0,
                 long_s: float = 3600.0, factor: float = 14.4,
                 severity: str = "page", for_s: float = 0.0):
        super().__init__(name, severity, for_s)
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"rule {name!r}: objective must be in (0, 1), "
                f"got {objective}"
            )
        if short_s >= long_s:
            raise ValueError(
                f"rule {name!r}: short window {short_s} must be < "
                f"long window {long_s}"
            )
        self.series = series
        self.labels = dict(labels or {})
        self.objective = float(objective)
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        self.factor = float(factor)

    def scale(self, time_scale: float) -> None:
        super().scale(time_scale)
        self.short_s *= time_scale
        self.long_s *= time_scale

    def _burn(self, db, t0: float, t1: float) -> Optional[float]:
        # step=None: one aggregate over the whole window
        buckets = db.series(self.series, self.labels, t0, t1)
        if not buckets:
            return None
        err = 1.0 - min(1.0, max(0.0, buckets[0]["avg"]))
        return err / max(1e-9, 1.0 - self.objective)

    def firing(self, db, now):
        b_short = self._burn(db, now - self.short_s, now)
        b_long = self._burn(db, now - self.long_s, now)
        if b_short is None or b_long is None:
            return None
        if b_short > self.factor and b_long > self.factor:
            return {"burn_short": b_short, "burn_long": b_long,
                    "threshold": self.factor,
                    "window_s": self.long_s, "value": b_short}
        return None


class AnomalyRule(Rule):
    """EWMA + MAD watchdog: robust to the odd outlier in the history
    (median absolute deviation, not stddev) and to slow drift (the
    EWMA tracks it). The band floor (``0.1% of |ewma|`` + epsilon)
    keeps a perfectly flat series from paging on float jitter."""

    _MODES = ("value", "increase", "hist_p99", "hist_p50")

    def __init__(self, name: str, series: str,
                 labels: Optional[Dict[str, str]] = None, *,
                 mode: str = "value", window_s: float = 600.0,
                 z: float = 8.0, min_points: int = 12,
                 alpha: float = 0.3, severity: str = "warn",
                 for_s: float = 0.0):
        super().__init__(name, severity, for_s)
        if mode not in self._MODES:
            raise ValueError(
                f"rule {name!r}: mode must be one of {self._MODES}, "
                f"got {mode!r}"
            )
        self.series = series
        self.labels = dict(labels or {})
        self.mode = mode
        self.window_s = float(window_s)
        self.z = float(z)
        self.min_points = int(min_points)
        self.alpha = float(alpha)

    def scale(self, time_scale: float) -> None:
        super().scale(time_scale)
        self.window_s *= time_scale

    def _values(self, db, now: float) -> List[float]:
        t0 = now - self.window_s
        if self.mode in ("hist_p99", "hist_p50"):
            q = 0.99 if self.mode == "hist_p99" else 0.50
            out = []
            for _, h in db.hist_points(self.series, self.labels, t0, now):
                edges = list(h.get("buckets") or []) + [math.inf]
                pairs, cum = [], 0.0  # per-bucket -> cumulative `le`
                for e, c in zip(edges, h["counts"]):
                    cum += c
                    pairs.append((
                        {"le": "+Inf" if not math.isfinite(e) else repr(e)},
                        cum,
                    ))
                out.append(obs_metrics.percentile_from_buckets(pairs, q))
            return out
        pts = db.points(self.series, self.labels, t0, now)
        vs = [v for _, v in pts]
        if self.mode == "increase":
            return [cur - prev if cur >= prev else cur
                    for prev, cur in zip(vs, vs[1:])]
        return vs

    def firing(self, db, now):
        vs = [v for v in self._values(db, now) if math.isfinite(v)]
        if len(vs) < max(3, self.min_points):
            return None
        history, current = vs[:-1], vs[-1]
        ewma = history[0]
        resids = []
        for v in history[1:]:
            resids.append(v - ewma)
            ewma = self.alpha * v + (1.0 - self.alpha) * ewma
        med = sorted(resids)[len(resids) // 2] if resids else 0.0
        mad = (sorted(abs(r - med) for r in resids)[len(resids) // 2]
               if resids else 0.0)
        band = 1.4826 * mad + 1e-9 + 1e-3 * abs(ewma)
        rz = abs(current - ewma) / band
        if rz > self.z:
            return {"value": current, "ewma": ewma, "robust_z": rz,
                    "threshold": self.z, "window_s": self.window_s}
        return None


_RULE_TYPES = {
    "threshold": ThresholdRule,
    "burn_rate": BurnRateRule,
    "anomaly": AnomalyRule,
}


def parse_rules(doc: Dict[str, Any]) -> List[Rule]:
    """Build rule objects from a rules doc (the JSON file / the
    DEFAULT_RULES literal). Unknown rule types and duplicate names are
    errors — a typo'd rule silently never firing is the worst failure
    mode an alerting layer can have."""
    out: List[Rule] = []
    seen = set()
    for spec in doc.get("rules", []):
        spec = dict(spec)
        rtype = spec.pop("type", None)
        cls = _RULE_TYPES.get(rtype)
        if cls is None:
            raise ValueError(
                f"unknown rule type {rtype!r} (want one of "
                f"{tuple(_RULE_TYPES)})"
            )
        name = spec.pop("name", None)
        if not name:
            raise ValueError("every rule needs a name")
        if name in seen:
            raise ValueError(f"duplicate rule name {name!r}")
        seen.add(name)
        series = spec.pop("series", None)
        if not series:
            raise ValueError(f"rule {name!r} names no series")
        labels = spec.pop("labels", None)
        out.append(cls(name, series, labels, **spec))
    return out


def load_rules_doc(path: Optional[str] = None) -> Dict[str, Any]:
    """The rules doc ``edl watch``/``edl monitor`` evaluate: the JSON
    file at ``path``, or a deep copy of :data:`DEFAULT_RULES`."""
    if path is None:
        return json.loads(json.dumps(DEFAULT_RULES))
    with open(path) as f:
        return json.load(f)


def engine_from_doc(
    doc: Dict[str, Any],
    *,
    time_scale: Optional[float] = None,
    registry: Optional[obs_metrics.MetricsRegistry] = None,
    recorder: Any = None,
) -> "AlertEngine":
    rules = parse_rules(doc)
    scale = float(doc.get("time_scale", 1.0)
                  if time_scale is None else time_scale)
    return AlertEngine(rules, time_scale=scale, registry=registry,
                       recorder=recorder)


class AlertEngine:
    """The fire/resolve state machine over a rule set. One engine per
    watcher (a `edl watch` process, the coordinator supervision loop,
    a monitor collector); evaluation is driven by the caller's clock
    so a recorded directory replays deterministically."""

    def __init__(self, rules: List[Rule], *, time_scale: float = 1.0,
                 registry: Optional[obs_metrics.MetricsRegistry] = None,
                 recorder: Any = None):
        if time_scale <= 0:
            raise ValueError(f"time_scale must be > 0, got {time_scale}")
        self.rules = list(rules)
        for r in self.rules:
            r.scale(float(time_scale))
        self.time_scale = float(time_scale)
        self._registry = registry
        self._recorder = recorder
        self._active: Dict[str, Dict[str, Any]] = {}
        self._pending_since: Dict[str, float] = {}
        self._fired_total = 0
        self._last_transition: Optional[Dict[str, Any]] = None

    # -- state -------------------------------------------------------

    def active(self) -> List[Dict[str, Any]]:
        return [dict(a) for _, a in sorted(self._active.items())]

    def pages(self) -> int:
        return sum(1 for a in self._active.values()
                   if a["severity"] == "page")

    def to_block(self) -> Dict[str, Any]:
        """The ``alerts`` block `edl monitor --json` carries per
        sample: what is firing now plus the most recent transition."""
        return {
            "active": self.active(),
            "fired_total": self._fired_total,
            "last_transition": (dict(self._last_transition)
                                if self._last_transition else None),
        }

    # -- evaluation --------------------------------------------------

    def evaluate(self, db: Any, now: float) -> List[Dict[str, Any]]:
        """One pass over every rule at time ``now``; returns the
        transitions (fire/resolve) this pass produced. A rule whose
        evaluation raises is skipped for the pass — one broken rule
        must not blind the rest of the watchdog."""
        transitions: List[Dict[str, Any]] = []
        for rule in self.rules:
            try:
                detail = rule.firing(db, now)
            except Exception:  # edl: no-lint[silent-failure] one bad rule must not take down the watch loop; the rule simply reports not-firing this pass
                detail = None
            if detail is not None:
                since = self._pending_since.setdefault(rule.name, now)
                if rule.name not in self._active and (
                        now - since >= rule.for_s):
                    transitions.append(self._fire(rule, detail, now))
                elif rule.name in self._active:
                    self._active[rule.name].update(
                        {k: v for k, v in detail.items()
                         if isinstance(v, (int, float))})
            else:
                self._pending_since.pop(rule.name, None)
                if rule.name in self._active:
                    transitions.append(self._resolve(rule, now))
        self._publish_gauges()
        return transitions

    def _fire(self, rule: Rule, detail: Dict[str, float],
              now: float) -> Dict[str, Any]:
        rec = {
            "transition": "fire",
            "rule": rule.name,
            "severity": rule.severity,
            "t": now,
            **{k: v for k, v in detail.items()
               if isinstance(v, (int, float))},
        }
        self._active[rule.name] = {
            "rule": rule.name, "severity": rule.severity, "since": now,
            **{k: v for k, v in detail.items()
               if isinstance(v, (int, float))},
        }
        self._fired_total += 1
        self._last_transition = rec
        if self._registry is not None:
            self._registry.counter(
                "edl_alerts_fired_total",
                "alert fire transitions by rule name",
                ("rule",),
            ).inc(rule=rule.name)
        self._emit("alert.fire", _EVENT_SEVERITY[rule.severity],
                   rule, detail)
        return rec

    def _resolve(self, rule: Rule, now: float) -> Dict[str, Any]:
        prior = self._active.pop(rule.name)
        rec = {
            "transition": "resolve",
            "rule": rule.name,
            "severity": rule.severity,
            "t": now,
            "active_s": now - prior.get("since", now),
        }
        self._last_transition = rec
        self._emit("alert.resolve", "info", rule,
                   {"active_s": rec["active_s"]})
        return rec

    def _emit(self, kind: str, severity: str, rule: Rule,
              detail: Dict[str, float]) -> None:
        attrs = {k: v for k, v in detail.items()
                 if isinstance(v, (int, float))}
        emit = (self._recorder.emit if self._recorder is not None
                else obs_events.emit)
        emit(kind, severity=severity, site=f"alert.{rule.name}",
             rule=rule.name, alert_severity=rule.severity, **attrs)

    def _publish_gauges(self) -> None:
        if self._registry is None:
            return
        g = self._registry.gauge(
            "edl_alerts_active",
            "alerts currently firing by severity (page/warn/info)",
            ("severity",),
        )
        counts = {"page": 0, "warn": 0, "info": 0}
        for a in self._active.values():
            counts[a["severity"]] += 1
        for sev, n in counts.items():
            g.set(float(n), severity=sev)
