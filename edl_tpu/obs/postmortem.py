"""``edl postmortem`` — reconstruct timelines and incidents from a
flight-recorder dump.

The recorder (obs/events.py) captures WHAT happened; this module
answers WHY a specific request/job misbehaved, after the fact, from a
dump file, a crash-dump black box, or a live ``/events`` endpoint:

* **per-request timelines** — every event correlated to a ``rid``
  (submit → admit → prefill → … → finish) with inter-event gaps, so a
  9-second TTFT decomposes into "8.7 s queued, 0.3 s prefill";
* **incident summary** — injected faults and what followed each within
  a window, recovery passes and the requests they replayed, timeout
  chains (shed + evicted), reshard stalls, heartbeat degradation,
  mirrored error logs, and ring truncation;
* **CI assertions** — ``--assert-recovered`` proves every injected
  serving fault is followed by a recorded recovery whose affected
  requests were re-prefilled and finished (the chaos lane's
  postmortem verification pass); ``--assert-no-incidents`` proves a
  fault-free lane produced a clean timeline.

Operates on plain event RECORDS (dicts) so a loaded JSONL dump and a
live ``FlightRecorder.records()`` analyze identically. jax-free,
stdlib-only — the CLI imports this at verb dispatch.
"""

from __future__ import annotations

from collections import Counter, OrderedDict
from typing import Any, Dict, List, Optional

__all__ = [
    "load_events",
    "by_rid",
    "incidents",
    "fault_chains",
    "alert_chains",
    "verify_recovered",
    "verify_no_incidents",
    "render_report",
]

# terminal serving outcomes that count as "the request was served"
_SERVED = ("done", "eos")
# event kinds that make a timeline an incident timeline
_INCIDENT_FINISHES = ("timeout", "failed")


def _order(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Causal order: wall time first (multi-process merges), sequence
    number as the intra-process tiebreak."""
    return sorted(
        events,
        key=lambda e: (e.get("t_wall", 0.0), e.get("seq", 0)),
    )


def load_events(source: str) -> List[Dict[str, Any]]:
    """Load events from a JSONL dump path, raw JSONL text, or a live
    exporter URL / ``host:port`` (scrapes ``/events``)."""
    import os

    if source.startswith(("http://", "https://")) or (
        not os.path.exists(source)
        and "\n" not in source
        and ":" in source
        and source.rsplit(":", 1)[-1].isdigit()
    ):
        from urllib.parse import urlparse

        from edl_tpu.obs.exporter import scrape

        # accept both the exporter root and a pasted .../events URL
        # (with or without ?rid=/?kind= filters already applied)
        url = source if source.startswith("http") else f"http://{source}"
        path = urlparse(url).path.rstrip("/")
        text = scrape(source, "" if path.endswith("/events") else "/events")
    else:
        text = source
    from edl_tpu.obs.events import load_jsonl

    return _order(load_jsonl(text))


def by_rid(events: List[Dict[str, Any]]) -> "OrderedDict[str, List[dict]]":
    """Per-request timelines, keyed by rid in first-seen order."""
    out: "OrderedDict[str, List[dict]]" = OrderedDict()
    for e in _order(events):
        rid = (e.get("corr") or {}).get("rid")
        if rid is not None:
            out.setdefault(str(rid), []).append(e)
    return out


def ring_dropped(events: List[Dict[str, Any]]) -> int:
    return max(
        (int((e.get("attrs") or {}).get("_ring_dropped", 0)) for e in events),
        default=0,
    )


# ---------------------------------------------------------------------------
# incidents


def incidents(
    events: List[Dict[str, Any]], window_s: float = 5.0
) -> Dict[str, Any]:
    """Summarize everything abnormal on the timeline. ``window_s``
    bounds the what-followed window attached to each injected fault."""
    evs = _order(events)
    faults: List[Dict[str, Any]] = []
    recoveries: List[Dict[str, Any]] = []
    reshards: List[Dict[str, Any]] = []
    timeouts = {"shed": [], "evicted": []}
    failed: List[str] = []
    degraded: List[Dict[str, Any]] = []
    errors: List[Dict[str, Any]] = []
    for i, e in enumerate(evs):
        kind = e.get("kind", "")
        corr = e.get("corr") or {}
        attrs = e.get("attrs") or {}
        if kind == "fault.injected":
            t = e.get("t_wall", 0.0)
            follow = [
                x for x in evs[i + 1:]
                if x.get("t_wall", t) - t <= window_s
                and x.get("kind") not in ("serve.block",)
            ]
            faults.append({"event": e, "followed": follow[:12]})
        elif kind.endswith(".recover"):
            recoveries.append(e)
        elif kind == "reshard.end":
            reshards.append(e)
        elif kind == "serve.reject" and attrs.get("reason") == "timeout":
            timeouts["shed"].append(corr.get("rid"))
        elif kind == "serve.finish":
            if attrs.get("outcome") == "timeout":
                timeouts["evicted"].append(corr.get("rid"))
            elif attrs.get("outcome") == "failed":
                failed.append(corr.get("rid"))
        elif kind == "worker.heartbeat_degraded":
            degraded.append(e)
        elif e.get("severity") == "error":
            errors.append(e)
    return {
        "faults": faults,
        "recoveries": recoveries,
        "reshards": reshards,
        "timeouts": timeouts,
        "failed": failed,
        "degraded": degraded,
        "errors": errors,
        "ring_dropped": ring_dropped(evs),
    }


def _recover_rids(rec: Dict[str, Any]) -> List[str]:
    attrs = rec.get("attrs") or {}
    rids = [str(r) for r in attrs.get("rids", [])]
    if attrs.get("requeued"):
        rids.append(str(attrs["requeued"]))
    return rids


def fault_chains(
    events: List[Dict[str, Any]], site_prefix: str = "serve."
) -> List[Dict[str, Any]]:
    """For every injected fault at a matching site, the causal chain
    the recovery contract promises: fault → next recovery → per-rid
    re-prefill → terminal finish. Each entry carries ``ok`` plus the
    specific missing links, which is what ``--assert-recovered``
    reports on failure."""
    evs = _order(events)
    chains: List[Dict[str, Any]] = []
    for i, e in enumerate(evs):
        if e.get("kind") != "fault.injected":
            continue
        site = (e.get("corr") or {}).get("site", "")
        if not str(site).startswith(site_prefix):
            continue
        rest = evs[i + 1:]
        rec = next(
            (x for x in rest if str(x.get("kind", "")).endswith(".recover")),
            None,
        )
        chain: Dict[str, Any] = {
            "fault": e,
            "site": site,
            "recover": rec,
            "rids": [],
            "problems": [],
        }
        if rec is None:
            chain["problems"].append(
                f"fault at {site} (seq {e.get('seq')}) has no recovery event"
            )
        else:
            after = [x for x in rest if x.get("seq", 0) > rec.get("seq", 0)
                     or x.get("t_wall", 0) > rec.get("t_wall", 0)]
            for rid in _recover_rids(rec):
                replayed = any(
                    x.get("kind") in ("serve.prefill", "serve.admit")
                    and (x.get("corr") or {}).get("rid") == rid
                    for x in after
                )
                fin = next(
                    (x for x in after
                     if x.get("kind") == "serve.finish"
                     and (x.get("corr") or {}).get("rid") == rid),
                    None,
                )
                outcome = (fin.get("attrs") or {}).get("outcome") if fin else None
                chain["rids"].append(
                    {"rid": rid, "replayed": replayed, "outcome": outcome}
                )
                if not replayed:
                    chain["problems"].append(
                        f"{rid}: no re-prefill after recovery "
                        f"(fault seq {e.get('seq')})"
                    )
                if outcome not in _SERVED:
                    chain["problems"].append(
                        f"{rid}: finished {outcome!r} after recovery, "
                        f"expected one of {_SERVED}"
                    )
        chain["ok"] = not chain["problems"]
        chains.append(chain)
    return chains


def alert_chains(
    events: List[Dict[str, Any]], site_prefix: str = "alert."
) -> List[Dict[str, Any]]:
    """The alert-lifecycle analog of :func:`fault_chains`: every
    ``alert.fire`` whose site (``alert.<rule>``, obs/alerts.py)
    matches the prefix must be followed by an ``alert.resolve`` for
    the SAME site — a page that never resolved is an open incident,
    and the CI fault lane asserts the injected breach both fired and
    cleared."""
    evs = _order(events)
    chains: List[Dict[str, Any]] = []
    for i, e in enumerate(evs):
        if e.get("kind") != "alert.fire":
            continue
        site = str((e.get("corr") or {}).get("site", ""))
        if not site.startswith(site_prefix):
            continue
        res = next(
            (x for x in evs[i + 1:]
             if x.get("kind") == "alert.resolve"
             and str((x.get("corr") or {}).get("site", "")) == site),
            None,
        )
        problems = [] if res is not None else [
            f"alert {site} fired (seq {e.get('seq')}) but never resolved"
        ]
        chains.append({
            "fire": e,
            "site": site,
            "resolve": res,
            "problems": problems,
            "ok": not problems,
        })
    return chains


def verify_recovered(
    events: List[Dict[str, Any]], site_prefix: str = "serve."
) -> List[str]:
    """CI assertion: every injected fault at ``site_prefix*`` is
    followed by a recorded recovery whose affected requests were
    re-prefilled and served, and every fired alert at a matching site
    resolved. Returns problems (empty = pass). A dump with NO matching
    faults or alerts is itself a problem — a chaos lane whose faults
    never fired tested nothing (``--sites alert.`` asserts the alert
    lifecycle the same way)."""
    chains = fault_chains(events, site_prefix)
    achains = alert_chains(events, site_prefix)
    if not chains and not achains:
        return [
            f"no injected faults or fired alerts at sites "
            f"{site_prefix}* in this dump"
        ]
    problems: List[str] = []
    for c in chains:
        problems.extend(c["problems"])
    for c in achains:
        problems.extend(c["problems"])
    return problems


def verify_no_incidents(events: List[Dict[str, Any]]) -> List[str]:
    """CI assertion for the fault-free lane: no injections, no
    recoveries, no error-severity events, no timeout/failed outcomes,
    no heartbeat degradation. Returns problems (empty = pass)."""
    inc = incidents(events)
    problems: List[str] = []
    if inc["faults"]:
        problems.append(f"{len(inc['faults'])} injected fault(s) recorded")
    if inc["recoveries"]:
        problems.append(f"{len(inc['recoveries'])} recovery pass(es) recorded")
    if inc["errors"]:
        first = inc["errors"][0]
        problems.append(
            f"{len(inc['errors'])} error event(s), first: "
            f"{first.get('kind')} {(first.get('attrs') or {}).get('msg', '')}"
        )
    shed, evicted = inc["timeouts"]["shed"], inc["timeouts"]["evicted"]
    if shed or evicted:
        problems.append(
            f"timeouts: {len(shed)} shed, {len(evicted)} evicted"
        )
    if inc["failed"]:
        problems.append(f"requests failed: {inc['failed']}")
    if inc["degraded"]:
        problems.append(
            f"{len(inc['degraded'])} heartbeat-degraded transition(s)"
        )
    return problems


# ---------------------------------------------------------------------------
# rendering


def _fmt_gap(dt: float) -> str:
    return f"+{dt * 1e3:.1f}ms" if dt < 1.0 else f"+{dt:.2f}s"


def _fmt_event(e: Dict[str, Any], t_base: float, prev_t: float) -> str:
    from edl_tpu.obs.disttrace import without_ids

    # trace ids correlate /trace with /events but are noise in a human
    # timeline (use `edl trace` for the span view of the same ids)
    corr = {
        k: v for k, v in without_ids(e.get("corr") or {}).items()
        if k != "rid"
    }
    attrs = e.get("attrs") or {}
    kv = " ".join(
        f"{k}={v}" for k, v in list(corr.items()) + list(attrs.items())
        if not str(k).startswith("_")
    )
    t = e.get("t_wall", t_base)
    gap = f" ({_fmt_gap(t - prev_t)})" if prev_t and t >= prev_t else ""
    sev = e.get("severity", "info")
    mark = "" if sev == "info" else f" [{sev.upper()}]"
    return (
        f"  t{_fmt_gap(t - t_base):>10}  {e.get('kind', '?'):<24}"
        f"{mark} {kv}".rstrip() + gap
    )


def render_timeline(rid: str, evs: List[Dict[str, Any]]) -> List[str]:
    lines = [f"-- request {rid} ({len(evs)} events) --"]
    t_base = evs[0].get("t_wall", 0.0) if evs else 0.0
    prev = 0.0
    for e in evs:
        lines.append(_fmt_event(e, t_base, prev))
        prev = e.get("t_wall", prev)
    return lines


def render_report(
    events: List[Dict[str, Any]],
    rid: Optional[str] = None,
    window_s: float = 5.0,
    max_timelines: int = 8,
) -> str:
    """The human postmortem: incident summary, fault→recovery chains,
    and per-request timelines (all of them for --rid, else the
    incident-affected ones, capped)."""
    evs = _order(events)
    inc = incidents(evs, window_s=window_s)
    chains = fault_chains(evs)
    kinds = Counter(e.get("kind", "?") for e in evs)
    lines: List[str] = []
    span = (
        evs[-1].get("t_wall", 0.0) - evs[0].get("t_wall", 0.0) if evs else 0.0
    )
    lines.append(
        f"flight recorder: {len(evs)} events over {span:.2f}s, "
        f"{len(kinds)} kinds, ring_dropped={inc['ring_dropped']}"
    )
    top = ", ".join(f"{k}={n}" for k, n in kinds.most_common(6))
    lines.append(f"  kinds: {top}")

    lines.append("")
    lines.append("== incidents ==")
    shed, evicted = inc["timeouts"]["shed"], inc["timeouts"]["evicted"]
    lines.append(
        f"faults_injected={len(inc['faults'])} "
        f"recoveries={len(inc['recoveries'])} "
        f"timeouts_shed={len(shed)} timeouts_evicted={len(evicted)} "
        f"failed={len(inc['failed'])} errors={len(inc['errors'])} "
        f"hb_degraded={len(inc['degraded'])} reshards={len(inc['reshards'])}"
    )
    for r in inc["reshards"]:
        a = r.get("attrs") or {}
        lines.append(
            f"  reshard_epoch={(r.get('corr') or {}).get('reshard_epoch')} "
            f"{a.get('from_workers')}->{a.get('to_workers')} "
            f"stall={a.get('stall_s')}s path={a.get('path')}"
        )

    affected: List[str] = []
    if chains:
        lines.append("")
        lines.append("== fault -> recovery chains ==")
        for c in chains:
            f = c["fault"]
            status = "OK" if c["ok"] else "BROKEN"
            rids = ",".join(r["rid"] for r in c["rids"]) or "-"
            lines.append(
                f"[{status}] seq {f.get('seq')} {c['site']} "
                f"(call #{(f.get('attrs') or {}).get('nth', '?')}) -> "
                f"recover -> rids [{rids}]"
            )
            for r in c["rids"]:
                lines.append(
                    f"    {r['rid']}: replayed={r['replayed']} "
                    f"outcome={r['outcome']}"
                )
                if r["rid"] not in affected:
                    affected.append(r["rid"])
            for p in c["problems"]:
                lines.append(f"    !! {p}")

    timelines = by_rid(evs)
    if rid is not None:
        wanted = [rid] if rid in timelines else []
        if not wanted:
            lines.append(f"\n(no events for rid {rid!r})")
    else:
        wanted = [r for r in affected if r in timelines]
        wanted += [
            r for r in timelines
            if r not in wanted and any(
                e.get("kind") == "serve.finish"
                and (e.get("attrs") or {}).get("outcome")
                in _INCIDENT_FINISHES
                for e in timelines[r]
            )
        ]
        wanted = wanted[:max_timelines]
    if wanted:
        lines.append("")
        lines.append("== request timelines ==")
        for r in wanted:
            lines.extend(render_timeline(r, timelines[r]))
    return "\n".join(lines)
