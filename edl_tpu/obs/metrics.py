"""Metrics core — thread-safe registry of counters, gauges, and
fixed-bucket histograms with Prometheus text exposition.

The unified telemetry layer the control plane scrapes (the reference
collector polls CPU/chip utilization every 10 s and retargets jobs from
the census; here the same census — plus TTFT percentiles, step-time
breakdowns, and reshard stalls — is pull-exposed in the Prometheus text
format, and push-snapshotted through the job coordinator's KV for
fleet aggregation; see obs/fleet.py).

Design constraints, in order:

* **jax-free, stdlib-only** — monitor/ and cli/ import this and must
  stay device-free; a scrape must never trigger a compile.
* **cheap on the hot path** — one lock acquire + a dict hit + (for
  histograms) a bisect per observation. The step loop and the serving
  drain call these per iteration; overhead budget is <=1% of a CPU
  dryrun serving step (ISSUE 3 acceptance).
* **snapshot/merge round-trips** — ``MetricsRegistry.snapshot()`` is a
  JSON-able dict and ``merge_snapshot`` folds one registry's snapshot
  into another under extra labels (worker id), which is how the
  coordinator aggregates the fleet.

Histograms are fixed-bucket (Prometheus-style cumulative ``le``
edges) so merging across workers is exact bucket-count addition, and
p50/p95/p99 are linear interpolation inside the owning bucket — the
same estimate a PromQL ``histogram_quantile`` would give.
"""

from __future__ import annotations

import bisect
import json
import math
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# Prometheus' default latency ladder extended to reshard-stall scale
# (the BASELINE north-star is "<30 s per reshard" — the 30/60 edges
# exist so a stall regression lands in a bucket, not in +Inf).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _fnum(v: float) -> str:
    """Prometheus sample-value formatting: integral floats print as
    ints (``3`` not ``3.0``), everything else as repr."""
    f = float(v)
    if math.isfinite(f) and f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label(str(v))}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Family:
    """One named metric family: a kind, a label schema, and a dict of
    per-label-value samples. Base for Counter/Gauge/Histogram."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._samples: Dict[Tuple[str, ...], Any] = {}
        if not self.labelnames:
            # eager unlabeled sample: the series renders a concrete
            # value from registration on (a scraper sees the catalog
            # even before the first observation)
            self._samples[()] = self._new_sample()

    def _new_sample(self):
        raise NotImplementedError

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        # hot path: no intermediate set allocations — a gauge set /
        # counter inc runs once per engine step
        if not labels:
            if self.labelnames:
                raise ValueError(
                    f"{self.name}: expected labels {self.labelnames}"
                )
            return ()
        if len(labels) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            )
        try:
            return tuple(str(labels[n]) for n in self.labelnames)
        except KeyError:
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            ) from None

    def _sample_locked(self, labels: Dict[str, str]):
        key = self._key(labels)
        s = self._samples.get(key)
        if s is None:
            s = self._samples.setdefault(key, self._new_sample())
        return s

    def samples(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return list(self._samples.items())


class Counter(_Family):
    """Monotonic counter (name it ``*_total``)."""

    kind = "counter"

    def _new_sample(self) -> List[float]:
        return [0.0]

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (got {n})")
        with self._lock:
            self._sample_locked(labels)[0] += n

    def value(self, **labels: str) -> float:
        with self._lock:
            s = self._samples.get(self._key(labels))
            return s[0] if s else 0.0

    def render(self, out: List[str]) -> None:
        for key, s in self.samples():
            out.append(
                f"{self.name}{_label_str(self.labelnames, key)} {_fnum(s[0])}"
            )


class Gauge(_Family):
    """Set-to-current-value metric (queue depth, active slots, loss)."""

    kind = "gauge"

    def _new_sample(self) -> List[float]:
        return [0.0]

    def set(self, v: float, **labels: str) -> None:
        with self._lock:
            self._sample_locked(labels)[0] = float(v)

    def inc(self, n: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._sample_locked(labels)[0] += n

    def value(self, **labels: str) -> float:
        with self._lock:
            s = self._samples.get(self._key(labels))
            return s[0] if s else 0.0

    def render(self, out: List[str]) -> None:
        for key, s in self.samples():
            out.append(
                f"{self.name}{_label_str(self.labelnames, key)} {_fnum(s[0])}"
            )


class _HistSample:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0.0] * (n_buckets + 1)  # +1 for +Inf
        self.sum = 0.0
        self.count = 0.0


class Histogram(_Family):
    """Fixed-bucket histogram with cumulative Prometheus exposition and
    interpolated percentiles.

    ``observe(v, n=...)`` supports weighted observations: the serving
    engine drains a fused horizon block's tokens with ONE clock read,
    so inter-token latency lands as one observation of the per-token
    mean with weight n — the histogram stays exact in count and sum.
    """

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        b = tuple(sorted(float(x) for x in buckets))
        if not b or any(not math.isfinite(x) for x in b):
            raise ValueError(f"{name}: buckets must be finite and non-empty")
        self.buckets = b
        super().__init__(name, help, labelnames)

    def _new_sample(self) -> _HistSample:
        return _HistSample(len(self.buckets))

    def observe(self, v: float, n: float = 1.0, **labels: str) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            s = self._sample_locked(labels)
            s.counts[i] += n
            s.sum += v * n
            s.count += n

    def percentile(self, q: float, **labels: str) -> float:
        """Interpolated quantile estimate (same rule as PromQL
        ``histogram_quantile``): linear within the owning bucket, the
        +Inf bucket clamps to the largest finite edge. 0.0 when empty."""
        with self._lock:
            s = self._samples.get(self._key(labels))
            if s is None or s.count <= 0:
                return 0.0
            counts = list(s.counts)
            total = s.count
        target = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            prev = cum
            cum += c
            if cum >= target and c > 0:
                if i >= len(self.buckets):  # +Inf bucket
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                frac = (target - prev) / c
                return lo + frac * (hi - lo)
        return self.buckets[-1]

    def stats(self, **labels: str) -> Dict[str, float]:
        with self._lock:
            s = self._samples.get(self._key(labels))
            if s is None:
                return {"count": 0.0, "sum": 0.0}
            return {"count": s.count, "sum": s.sum}

    def render(self, out: List[str]) -> None:
        for key, s in self.samples():
            cum = 0.0
            for edge, c in zip(self.buckets, s.counts):
                cum += c
                lv = _label_str(
                    self.labelnames + ("le",), key + (str(edge),)
                )
                out.append(f"{self.name}_bucket{lv} {_fnum(cum)}")
            lv = _label_str(self.labelnames + ("le",), key + ("+Inf",))
            out.append(f"{self.name}_bucket{lv} {_fnum(s.count)}")
            ls = _label_str(self.labelnames, key)
            out.append(f"{self.name}_sum{ls} {_fnum(s.sum)}")
            out.append(f"{self.name}_count{ls} {_fnum(s.count)}")


class MetricsRegistry:
    """Thread-safe named-family registry.

    ``counter``/``gauge``/``histogram`` are get-or-create: the same
    (name, kind, labelnames) returns the existing family, so every
    instrumentation site can declare its series locally and module
    import order never matters. A name re-registered with a different
    kind or label schema raises — silent schema drift would corrupt
    the fleet merge.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: "Dict[str, _Family]" = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}, requested "
                        f"{cls.kind}{tuple(labelnames)}"
                    )
                return fam
            fam = cls(name, help, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def families(self) -> List[_Family]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    # -- exposition ---------------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: List[str] = []
        for fam in self.families():
            if fam.help:
                out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            fam.render(out)
        return "\n".join(out) + "\n"

    # -- snapshot / merge (the fleet push format) ---------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able full dump: what a worker pushes through the job
        coordinator KV (obs/fleet.py MetricsPusher)."""
        fams = []
        for fam in self.families():
            rec: Dict[str, Any] = {
                "name": fam.name,
                "kind": fam.kind,
                "help": fam.help,
                "labelnames": list(fam.labelnames),
            }
            if isinstance(fam, Histogram):
                rec["buckets"] = list(fam.buckets)
                rec["samples"] = [
                    {
                        "labels": list(key),
                        "counts": list(s.counts),
                        "sum": s.sum,
                        "count": s.count,
                    }
                    for key, s in fam.samples()
                ]
            else:
                rec["samples"] = [
                    {"labels": list(key), "value": s[0]}
                    for key, s in fam.samples()
                ]
            fams.append(rec)
        return {"v": 1, "families": fams}

    def merge_snapshot(
        self, snap: Dict[str, Any], labels: Optional[Dict[str, str]] = None
    ) -> None:
        """Fold another registry's :meth:`snapshot` into this one,
        tagging every series with ``labels`` (e.g. ``worker="w3"``) —
        the coordinator-side aggregation primitive. Counters and
        histogram buckets ADD (so repeated merges of the same worker's
        successive snapshots must go through a fresh registry per
        aggregation pass, which is what obs/fleet.py does); gauges
        overwrite."""
        extra = dict(labels or {})
        extra_names = tuple(sorted(extra))
        for rec in snap.get("families", []):
            names = tuple(rec.get("labelnames", ())) + extra_names
            kind = rec.get("kind")
            name = rec.get("name", "")
            try:
                if kind == "histogram":
                    fam = self.histogram(
                        name, rec.get("help", ""), names,
                        buckets=rec.get("buckets", DEFAULT_BUCKETS),
                    )
                elif kind == "counter":
                    fam = self.counter(name, rec.get("help", ""), names)
                elif kind == "gauge":
                    fam = self.gauge(name, rec.get("help", ""), names)
                else:
                    continue
            except ValueError:
                # schema drift across fleet versions: drop rather than
                # poison the whole scrape
                continue
            for s in rec.get("samples", []):
                lv = dict(zip(rec.get("labelnames", ()), s.get("labels", [])))
                lv.update(extra)
                if kind == "histogram":
                    if tuple(rec.get("buckets", ())) != fam.buckets:
                        continue  # incompatible edges: not mergeable
                    with fam._lock:
                        dst = fam._sample_locked(lv)
                        for i, c in enumerate(s.get("counts", [])):
                            if i < len(dst.counts):
                                dst.counts[i] += c
                        dst.sum += s.get("sum", 0.0)
                        dst.count += s.get("count", 0.0)
                elif kind == "counter":
                    fam.inc(float(s.get("value", 0.0)), **lv)
                else:
                    fam.set(float(s.get("value", 0.0)), **lv)

    def snapshot_json(self) -> str:
        return json.dumps(self.snapshot(), separators=(",", ":"))


# ---------------------------------------------------------------------------
# the process-wide default registry + the core series catalog


_default = MetricsRegistry()
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    return _default


def reset_default_registry() -> MetricsRegistry:
    """Swap in a fresh default registry (tests); returns the new one."""
    global _default
    with _default_lock:
        _default = MetricsRegistry()
    return _default


def ensure_core_series(reg: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Pre-register the core catalog so a scrape of ANY edl process
    shows the full schema — training, serving, reshard, and checkpoint
    series render (zero-valued until observed) even on a process that
    only ever serves. Keep this list in sync with
    doc/observability.md."""
    r = reg or default_registry()
    # training
    r.counter("edl_train_steps_total", "optimizer steps completed")
    r.counter("edl_train_examples_total", "training rows consumed")
    r.histogram("edl_train_step_seconds", "full step wall time (data + dispatch + sync)")
    r.histogram("edl_train_data_wait_seconds", "host wait for the next batch (data stall)")
    r.histogram("edl_train_host_block_seconds", "host blocked on device results (sync stall)")
    r.histogram("edl_train_dispatch_seconds", "train-step program dispatch (enqueue) time")
    r.gauge("edl_train_examples_per_sec", "training throughput over the last report window")
    r.gauge("edl_train_loss", "most recent training loss")
    # serving
    r.counter("edl_serving_requests_total", "request lifecycle events", ("event",))
    r.counter("edl_serving_tokens_total", "generated tokens")
    r.counter("edl_serving_dispatch_total", "device program dispatches", ("kind",))
    r.histogram("edl_serving_ttft_seconds", "time to first token (submit -> first token)")
    r.histogram("edl_serving_itl_seconds", "inter-token latency (per generated token)")
    r.histogram(
        "edl_serving_tpot_seconds",
        "user-perceived time per output token: (finish - first token) "
        "/ (tokens - 1), once per finished request",
    )
    # the latency decomposition (queue wait + prefill ~= TTFT; block =
    # the decode granule) — see doc/observability.md "SLO & goodput"
    r.histogram("edl_serving_queue_wait_seconds", "queue wait (submit -> scheduler pop)")
    r.histogram("edl_serving_prefill_seconds", "prefill phase (scheduler pop -> first token)")
    r.histogram("edl_serving_block_seconds", "fused decode block wall time (dispatch -> drain)")
    r.counter(
        "edl_serving_outcomes_total",
        "terminal request outcomes by tenant and SLO class",
        ("outcome", "tenant", "slo_class"),
    )
    # SLO burn gauges (obs/slo.py update_gauges; loadgen refreshes
    # them live during a load run)
    r.gauge(
        "edl_slo_ttft_ok_ratio",
        "fraction of served requests meeting their class TTFT SLO",
        ("slo_class",),
    )
    r.gauge(
        "edl_slo_itl_ok_ratio",
        "fraction of served requests meeting their class per-token SLO",
        ("slo_class",),
    )
    r.gauge("edl_slo_goodput_rps", "requests/s finishing within their class SLOs")
    r.gauge(
        "edl_slo_goodput_fraction",
        "good requests / all requests (shed and timeouts count against)",
    )
    r.gauge("edl_serving_queue_depth", "requests waiting for a KV slot")
    r.gauge("edl_serving_active_slots", "occupied KV slots")
    r.gauge("edl_serving_slot_occupancy", "mean active/max slots over decode steps")
    r.counter(
        "edl_serving_recoveries_total",
        "engine crash-recovery passes (device state rebuilt, live "
        "slots re-prefilled from prompt + generated)",
    )
    # robustness (doc/robustness.md)
    r.counter("edl_faults_injected_total", "injected faults by site", ("site",))
    r.counter("edl_metrics_push_failures_total", "metrics snapshot pushes that raised")
    r.gauge(
        "edl_worker_heartbeat_degraded",
        "1 while the heartbeat loop cannot reach the coordinator",
    )
    # chip-lease elasticity (elasticity/broker.py + distbroker.py)
    r.counter(
        "edl_lease_fenced_total",
        "lease confirms rejected by the epoch fence",
        ("reason",),
    )
    r.counter(
        "edl_lease_recoveries_total",
        "broker-restart recoveries completed (RECOVERING -> steady)",
    )
    # elastic / reshard (the BASELINE north-star metric, scrapeable)
    r.counter("edl_reshard_total", "elastic reshards", ("path",))
    r.histogram("edl_reshard_stall_seconds", "traffic-stopping reshard window")
    r.histogram("edl_reshard_recompile_seconds", "first-step compile on the new mesh")
    # checkpoint
    r.histogram("edl_checkpoint_save_seconds", "checkpoint write time", ("kind",))
    r.histogram("edl_checkpoint_restore_seconds", "checkpoint read/restore time", ("kind",))
    r.counter("edl_checkpoint_bytes_total", "checkpoint bytes moved", ("op",))
    # hardware efficiency (obs/costmodel.py, obs/memledger.py,
    # obs/compilewatch.py — doc/observability.md "Hardware efficiency")
    r.gauge(
        "edl_mfu",
        "achieved model FLOPs/s over peak FLOPs by phase (obs/costmodel.py)",
        ("phase",),
    )
    r.gauge(
        "edl_bw_util_ratio",
        "achieved HBM bytes/s over peak bandwidth by phase",
        ("phase",),
    )
    r.counter(
        "edl_costmodel_flops_total",
        "analytic model FLOPs completed by phase",
        ("phase",),
    )
    r.counter(
        "edl_costmodel_hbm_bytes_total",
        "analytic HBM bytes moved by phase",
        ("phase",),
    )
    r.gauge(
        "edl_hbm_bytes",
        "bytes of registered long-lived device allocations by "
        "category (obs/memledger.py)",
        ("category",),
    )
    r.gauge(
        "edl_kv_occupancy_ratio",
        "used KV-cache tokens over capacity across registered engines",
    )
    r.histogram(
        "edl_compile_seconds",
        "first-call (trace + compile) time per distinct jit program",
        ("program",),
    )
    r.counter(
        "edl_compiles_total",
        "distinct jit programs compiled, by factory",
        ("program",),
    )
    # tracing bridge (obs/fleet.py bridge_tracer)
    r.histogram("edl_span_seconds", "tracer span durations by name", ("name",))
    r.counter("edl_trace_spans_dropped_total", "spans evicted from the tracer ring buffer")
    # flight recorder (obs/events.py)
    r.counter("edl_events_total", "flight-recorder events by kind", ("kind",))
    r.counter(
        "edl_events_dropped_total",
        "flight-recorder events evicted from the bounded ring",
    )
    # history & alerting (obs/tsdb.py, obs/alerts.py —
    # doc/observability.md "History, alerting & burn rates")
    r.gauge(
        "edl_alerts_active",
        "alerts currently firing by severity (page/warn/info)",
        ("severity",),
    )
    r.counter(
        "edl_alerts_fired_total",
        "alert fire transitions by rule name",
        ("rule",),
    )
    r.gauge(
        "edl_hbm_crosscheck_drift_bytes",
        "ledger-vs-live-arrays drift from memledger.crosscheck(), "
        "refreshed on the metrics-push/tsdb-append cadence",
    )
    return r


# ---------------------------------------------------------------------------
# Prometheus text parsing (the `edl top` / test-side consumer)


def _unescape_label(v: str) -> str:
    """Invert :func:`_escape_label` in ONE left-to-right pass. The old
    chained ``.replace`` corrupted values where a literal backslash
    preceded an ``n`` or a quote: ``\\`` + ``n`` renders as ``\\\\n``,
    and replacing ``\\n`` first turns the escaped backslash's second
    character into a newline."""
    if "\\" not in v:
        return v
    out: List[str] = []
    i, n = 0, len(v)
    while i < n:
        c = v[i]
        if c == "\\" and i + 1 < n:
            nxt = v[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == '"':
                out.append('"')
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def parse_prometheus_text(
    text: str,
) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Parse exposition text into {metric_name: [(labels, value), ...]}.
    Histogram component series keep their ``_bucket``/``_sum``/
    ``_count`` suffixes — the consumer reassembles quantiles via
    :func:`percentile_from_buckets`."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # name{l1="v1",...} value  |  name value
        if "{" in line:
            name, rest = line.split("{", 1)
            labels_raw, _, val = rest.rpartition("}")
            labels: Dict[str, str] = {}
            # split on commas not inside quotes, honoring backslash
            # escapes (a \" inside a value must not close the quote)
            buf, inq, esc, parts = "", False, False, []
            for ch in labels_raw:
                if esc:
                    buf += ch
                    esc = False
                    continue
                if inq and ch == "\\":
                    buf += ch
                    esc = True
                    continue
                if ch == '"':
                    inq = not inq
                if ch == "," and not inq:
                    parts.append(buf)
                    buf = ""
                else:
                    buf += ch
            if buf:
                parts.append(buf)
            for p in parts:
                if "=" not in p:
                    continue
                k, v = p.split("=", 1)
                # exactly ONE surrounding quote pair — str.strip('"')
                # would eat a trailing quote that belongs to a \" escape
                v = v.strip()
                if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
                    v = v[1:-1]
                labels[k.strip()] = _unescape_label(v)
            try:
                fval = float(val.strip().split()[0])
            except (ValueError, IndexError):
                continue
            out.setdefault(name.strip(), []).append((labels, fval))
        else:
            parts = line.split()
            if len(parts) < 2:
                continue
            try:
                fval = float(parts[1])
            except ValueError:
                continue
            out.setdefault(parts[0], []).append(({}, fval))
    return out


def percentile_from_buckets(
    pairs: Iterable[Tuple[Dict[str, str], float]], q: float
) -> float:
    """Quantile from parsed ``*_bucket`` samples (summed across any
    non-``le`` labels, i.e. fleet-wide when workers are labels). Same
    interpolation rule as :meth:`Histogram.percentile`."""
    by_edge: Dict[float, float] = {}
    for labels, v in pairs:
        le = labels.get("le")
        if le is None:
            continue
        edge = math.inf if le == "+Inf" else float(le)
        by_edge[edge] = by_edge.get(edge, 0.0) + v
    if not by_edge:
        return 0.0
    edges = sorted(by_edge)
    total = by_edge[edges[-1]] if edges and edges[-1] == math.inf else (
        max(by_edge.values()) if by_edge else 0.0
    )
    if total <= 0:
        return 0.0
    target = q * total
    prev_cum, prev_edge = 0.0, 0.0
    finite = [e for e in edges if math.isfinite(e)]
    for e in edges:
        cum = by_edge[e]
        if cum >= target and cum > prev_cum:
            if not math.isfinite(e):
                return finite[-1] if finite else 0.0
            frac = (target - prev_cum) / (cum - prev_cum)
            return prev_edge + frac * (e - prev_edge)
        prev_cum, prev_edge = cum, (e if math.isfinite(e) else prev_edge)
    return finite[-1] if finite else 0.0
