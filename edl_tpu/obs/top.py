"""`edl top` — one-screen live view of any telemetry endpoint.

Scrapes ``/metrics`` (+ ``/healthz``) from an exporter — a serving
process, a training worker, or the coordinator's fleet aggregation —
and renders the headline series: training step-time breakdown,
serving TTFT/ITL percentiles and queue, reshard stalls, checkpoint
I/O. Works against any Prometheus endpoint that uses the edl metric
catalog (doc/observability.md); series carrying a ``worker`` label
(the aggregated fleet view) are summed/percentiled across workers.
"""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

from edl_tpu.obs.exporter import scrape
from edl_tpu.obs.metrics import parse_prometheus_text, percentile_from_buckets

_Fams = Dict[str, List[Tuple[Dict[str, str], float]]]


def _total(fams: _Fams, name: str, **match: str) -> float:
    out = 0.0
    for labels, v in fams.get(name, ()):
        if all(labels.get(k) == val for k, val in match.items()):
            out += v
    return out


def _pctls(fams: _Fams, name: str, qs=(0.5, 0.95, 0.99)) -> List[float]:
    pairs = fams.get(name + "_bucket", [])
    return [percentile_from_buckets(pairs, q) for q in qs]


def _ms(v: float) -> str:
    return f"{v * 1e3:.1f}ms" if v < 1.0 else f"{v:.2f}s"


def summarize(fams: _Fams) -> List[str]:
    """Render the parsed families into the one-screen text block."""
    lines: List[str] = []

    steps = _total(fams, "edl_train_steps_total")
    if steps or fams.get("edl_train_step_seconds_count"):
        sp = _pctls(fams, "edl_train_step_seconds")
        dw = _pctls(fams, "edl_train_data_wait_seconds", (0.5,))
        hb = _pctls(fams, "edl_train_host_block_seconds", (0.5,))
        eps = _total(fams, "edl_train_examples_per_sec")
        loss = _total(fams, "edl_train_loss")
        lines.append(
            f"TRAIN    steps={steps:.0f} "
            f"step p50/p95/p99={_ms(sp[0])}/{_ms(sp[1])}/{_ms(sp[2])} "
            f"data_wait p50={_ms(dw[0])} host_block p50={_ms(hb[0])}"
        )
        lines.append(
            f"         rows/s={eps:.1f} loss={loss:.6g} "
            f"examples={_total(fams, 'edl_train_examples_total'):.0f}"
        )

    tokens = _total(fams, "edl_serving_tokens_total")
    ttft_n = _total(fams, "edl_serving_ttft_seconds_count")
    if tokens or ttft_n:
        tp = _pctls(fams, "edl_serving_ttft_seconds")
        ip = _pctls(fams, "edl_serving_itl_seconds", (0.5,))
        op = _pctls(fams, "edl_serving_tpot_seconds", (0.5,))
        disp = _total(fams, "edl_serving_dispatch_total")
        lines.append(
            f"SERVING  ttft p50/p95/p99={_ms(tp[0])}/{_ms(tp[1])}/{_ms(tp[2])} "
            f"itl p50={_ms(ip[0])} tpot p50={_ms(op[0])} tokens={tokens:.0f}"
        )
        lines.append(
            f"         queue={_total(fams, 'edl_serving_queue_depth'):.0f} "
            f"active_slots={_total(fams, 'edl_serving_active_slots'):.0f} "
            f"dispatches={disp:.0f}"
            + (f" disp/tok={disp / tokens:.3f}" if tokens else "")
        )
        # speculation strip, only when the engine actually drafted:
        # live acceptance rate + how many tokens each verify dispatch
        # is landing (the figure --spec-k exists to raise)
        drafted = _total(fams, "edl_serving_spec_drafted_total")
        if drafted:
            accepted = _total(fams, "edl_serving_spec_accepted_total")
            vdisp = _total(fams, "edl_serving_dispatch_total",
                           kind="verify")
            lines.append(
                f"         spec accept={accepted / drafted:.1%} "
                f"drafted={drafted:.0f} accepted={accepted:.0f}"
                + (f" tok/verify={(accepted + vdisp) / vdisp:.2f}"
                   if vdisp else "")
            )
        # the TTFT decomposition, when the engine exports it: where
        # the waiting actually happened (queue vs prefill vs block)
        if _total(fams, "edl_serving_queue_wait_seconds_count"):
            qw = _pctls(fams, "edl_serving_queue_wait_seconds", (0.5, 0.99))
            pf = _pctls(fams, "edl_serving_prefill_seconds", (0.5, 0.99))
            bl = _pctls(fams, "edl_serving_block_seconds", (0.5, 0.99))
            lines.append(
                f"         phases p50/p99: queue_wait={_ms(qw[0])}/{_ms(qw[1])} "
                f"prefill={_ms(pf[0])}/{_ms(pf[1])} block={_ms(bl[0])}/{_ms(bl[1])}"
            )

    # SLO burn strip (obs/slo.py gauges; live during a loadgen run) —
    # shown whenever any class has published an attainment ratio
    slo_pairs = [
        (labels.get("slo_class", "?"), v)
        for labels, v in fams.get("edl_slo_ttft_ok_ratio", ())
        if labels.get("slo_class")
    ]
    if slo_pairs:
        itl_by_cls = {
            labels.get("slo_class"): v
            for labels, v in fams.get("edl_slo_itl_ok_ratio", ())
        }
        parts = [
            f"{cls}: ttft_ok={v:.1%} itl_ok={itl_by_cls.get(cls, 0.0):.1%}"
            for cls, v in sorted(slo_pairs)
        ]
        lines.append(
            "SLO      " + "  ".join(parts)
            + f"  goodput={_total(fams, 'edl_slo_goodput_rps'):.2f}/s"
            f" ({_total(fams, 'edl_slo_goodput_fraction'):.1%} of offered)"
        )

    # hardware-efficiency strip (obs/costmodel.py + obs/memledger.py):
    # live roofline position per phase + the HBM balance sheet — shown
    # whenever any process has published efficiency telemetry
    mfu_by_phase = {
        labels.get("phase"): v
        for labels, v in fams.get("edl_mfu", ())
        if labels.get("phase")
    }
    bw_by_phase = {
        labels.get("phase"): v
        for labels, v in fams.get("edl_bw_util_ratio", ())
        if labels.get("phase")
    }
    hbm = {
        labels.get("category"): v
        for labels, v in fams.get("edl_hbm_bytes", ())
        if labels.get("category") and v
    }
    if any(mfu_by_phase.values()) or any(bw_by_phase.values()) or hbm:
        parts = [
            f"{ph}: mfu={mfu_by_phase.get(ph, 0.0):.1%}"
            f" bw={bw_by_phase.get(ph, 0.0):.1%}"
            for ph in sorted(set(mfu_by_phase) | set(bw_by_phase))
            if mfu_by_phase.get(ph) or bw_by_phase.get(ph)
        ]
        # 8-char label like every other strip (the misspelled
        # "EFFICNCY" header shipped in PR 8; "ROOFLINE" names the same
        # surface — doc/observability.md "Hardware efficiency &
        # roofline" — and keeps the 9-column data alignment)
        lines.append("ROOFLINE " + "  ".join(parts))
        if hbm:
            gb = lambda v: f"{v / (1 << 30):.2f}G"  # noqa: E731
            occ = _total(fams, "edl_kv_occupancy_ratio")
            compiles = _total(fams, "edl_compiles_total")
            kv_bpt = _total(fams, "edl_kv_bytes_per_token")
            lines.append(
                "         hbm: "
                + " ".join(f"{c}={gb(v)}" for c, v in sorted(hbm.items()))
                + (f"  kv_used={occ:.1%}" if occ else "")
                + (f"  kv_B/tok={kv_bpt:.2f}" if kv_bpt else "")
                + (f"  compiles={compiles:.0f}" if compiles else "")
            )

    nre = _total(fams, "edl_reshard_total")
    if nre:
        rp = _pctls(fams, "edl_reshard_stall_seconds")
        host = _total(fams, "edl_reshard_total", path="host")
        lines.append(
            f"RESHARD  count={nre:.0f} "
            f"stall p50/p95/p99={rp[0]:.2f}/{rp[1]:.2f}/{rp[2]:.2f}s "
            f"host_fallbacks={host:.0f}"
        )

    saves = _total(fams, "edl_checkpoint_save_seconds_count")
    if saves:
        sp = _pctls(fams, "edl_checkpoint_save_seconds", (0.5,))
        lines.append(
            f"CKPT     saves={saves:.0f} save p50={sp[0]:.3f}s "
            f"bytes={_total(fams, 'edl_checkpoint_bytes_total'):.0f}"
        )

    # alerts strip (obs/alerts.py gauges, published by whichever
    # process runs an AlertEngine — `edl watch`, the coordinator, a
    # monitor) — shown only while something fires or has fired, same
    # quiet-fleet policy as INCIDENT below
    pages = _total(fams, "edl_alerts_active", severity="page")
    warns = _total(fams, "edl_alerts_active", severity="warn")
    fired = _total(fams, "edl_alerts_fired_total")
    if pages or warns or fired:
        by_rule = " ".join(
            f"{labels.get('rule')}={v:.0f}"
            for labels, v in sorted(
                fams.get("edl_alerts_fired_total", ()),
                key=lambda p: p[0].get("rule", ""),
            )
            if v
        )
        lines.append(
            f"ALERTS   pages={pages:.0f} warns={warns:.0f} "
            f"fired={fired:.0f}" + (f"  [{by_rule}]" if by_rule else "")
        )

    # incident strip: fleet health (sourced from the flight-recorder
    # counters + the robustness series) without opening any dumps —
    # shown only when something is actually wrong/noteworthy
    recov = _total(fams, "edl_serving_recoveries_total")
    injected = _total(fams, "edl_faults_injected_total")
    hb = _total(fams, "edl_worker_heartbeat_degraded")
    ev_dropped = _total(fams, "edl_events_dropped_total")
    log_errors = _total(fams, "edl_events_total", kind="log.error")
    if recov or injected or hb or ev_dropped or log_errors:
        lines.append(
            f"INCIDENT recoveries={recov:.0f} faults_injected={injected:.0f} "
            f"hb_degraded={hb:.0f} log_errors={log_errors:.0f} "
            f"dropped_events={ev_dropped:.0f}"
        )

    workers = _total(fams, "edl_fleet_reporting_workers")
    if workers:
        lines.append(f"FLEET    reporting_workers={workers:.0f}")
    # serving fleet strip (router + replica supervisor gauges)
    rep_up = _total(fams, "edl_fleet_replica_up")
    routed = _total(fams, "edl_fleet_requests_total")
    if rep_up or routed:
        lines.append(
            f"FLEET    replicas_up={rep_up:.0f} "
            f"qdepth={_total(fams, 'edl_fleet_replica_queue_depth'):.0f} "
            f"inflight={_total(fams, 'edl_fleet_replica_inflight'):.0f} "
            f"routed={routed:.0f} "
            f"failovers={_total(fams, 'edl_fleet_failovers_total'):.0f} "
            f"requeues={_total(fams, 'edl_fleet_requeues_total'):.0f}"
        )
    chip_total = _total(fams, "edl_fleet_chip_total")
    if chip_total:
        lines.append(
            f"FLEET    chips={_total(fams, 'edl_fleet_chip_request'):.0f}"
            f"/{chip_total:.0f} "
            f"cpu={_total(fams, 'edl_fleet_cpu_util_pct'):.1f}% "
            f"jobs={_total(fams, 'edl_fleet_jobs', state='submitted'):.0f}"
        )
    # chip-lease strip (elasticity broker gauges): who holds the
    # inventory right now, and how busy the handover plane has been
    if "edl_lease_chips_free" in fams:
        lines.append(
            f"LEASES   train={_total(fams, 'edl_lease_chips', side='train'):.0f} "
            f"serve={_total(fams, 'edl_lease_chips', side='serve'):.0f} "
            f"free={_total(fams, 'edl_lease_chips_free'):.0f} "
            f"recalling={_total(fams, 'edl_leases', state='RECALLING'):.0f} "
            f"epoch={_total(fams, 'edl_lease_epoch'):.0f} "
            f"handovers={_total(fams, 'edl_lease_handovers_total'):.0f}"
        )

    if not lines:
        lines.append("(no edl series observed yet)")
    return lines


def top_once(endpoint: str, timeout_s: float = 5.0) -> str:
    """One scrape, rendered. ``endpoint`` is host:port or a URL."""
    text = scrape(endpoint, "/metrics", timeout_s=timeout_s)
    header = endpoint
    try:
        hz = json.loads(scrape(endpoint, "/healthz", timeout_s=timeout_s))
        header = f"{endpoint}  up {hz.get('uptime_s', 0):.0f}s pid {hz.get('pid', '?')}"
    # edl: no-lint[silent-failure] /healthz is an optional endpoint; plain Prometheus targets lack it by design
    except Exception:
        pass  # /healthz is optional: any Prometheus endpoint works
    body = summarize(parse_prometheus_text(text))
    return "\n".join([f"EDL TOP  {header}"] + body)
