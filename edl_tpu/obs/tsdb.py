"""On-disk metric history — the time dimension of the observability
stack (stdlib-only, no jax import).

Every other surface in ``obs/`` is a *snapshot*: ``/metrics`` renders
the registry now, ``edl top`` paints the last scrape, ``edl profile``
reads one roofline position. Burn-rate alerting ("SLO attainment has
been below objective for 2 of the last 5 minutes") needs a durable
series, so this module stores periodic registry snapshots on disk and
answers windowed queries over them.

Layout (one directory per process/fleet):

* ``raw-NNNNNN.jsonl`` — full-resolution tier. Each line is one
  appended registry snapshot, verbatim: ``{"t": <wall>, "snap":
  <MetricsRegistry.snapshot()>}``. Segments roll at ``segment_bytes``.
* ``agg10-NNNNNN.jsonl`` / ``agg60-NNNNNN.jsonl`` — downsample tiers
  (10 s and 1 m buckets by default). Each line is one closed bucket:
  per scalar series the window's ``sum/cnt/min/max/last``, per
  histogram series the *last cumulative sample* in the window (for a
  cumulative histogram the window-edge value is the exact aggregate —
  rates and percentile bounds survive downsampling losslessly).

Retention deletes the oldest RAW segment first (its history survives
in the tiers), then the oldest 10 s segment, then 1 m — so the store
degrades in resolution, never in coverage, until ``max_bytes`` holds.

Counter semantics: processes restart, so any cumulative series can
reset to zero mid-window. :meth:`TSDB.increase` and
:meth:`TSDB.hist_delta` clamp every negative step to the post-reset
value instead of letting a windowed delta go negative — the classic
``rate()`` bug this module's tests pin.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "TSDB",
    "flatten_snapshot",
    "parse_series_key",
    "series_key",
    "snapshot_from_prometheus_text",
]

_SEG_RE = re.compile(r"^(raw|agg(\d+))-(\d{6})\.jsonl$")


def series_key(name: str, labels: Optional[Dict[str, str]] = None) -> str:
    """Canonical series identity: ``name{k=v,...}`` with sorted label
    keys — the same (name, labels) always maps to the same key, so
    downsampled aggregates line up with raw points."""
    items = sorted((labels or {}).items())
    inner = ",".join(f"{k}={v}" for k, v in items)
    return f"{name}{{{inner}}}"


def parse_series_key(key: str) -> Tuple[str, Dict[str, str]]:
    name, _, rest = key.partition("{")
    labels: Dict[str, str] = {}
    for part in rest.rstrip("}").split(","):
        if part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


def flatten_snapshot(
    snap: Dict[str, Any],
) -> Tuple[Dict[str, float], Dict[str, Dict[str, Any]]]:
    """Split one registry snapshot into ``{key: value}`` scalars
    (counters + gauges) and ``{key: {counts, sum, count, buckets}}``
    histogram samples."""
    scalars: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    for fam in snap.get("families", []):
        names = fam.get("labelnames") or []
        for s in fam.get("samples", []):
            labels = {
                k: str(v) for k, v in zip(names, s.get("labels", []))
            }
            key = series_key(fam["name"], labels)
            if fam.get("kind") == "histogram":
                hists[key] = {
                    "counts": [float(c) for c in s["counts"]],
                    "sum": float(s["sum"]),
                    "count": float(s["count"]),
                    "buckets": [float(b) for b in fam.get("buckets") or []],
                }
            else:
                scalars[key] = float(s["value"])
    return scalars, hists


def snapshot_from_prometheus_text(text: str) -> Dict[str, Any]:
    """Adapt a scraped ``/metrics`` page into the snapshot doc
    :meth:`TSDB.append` stores, so ``edl watch`` can record a live
    endpoint it can only see through text exposition. Every parsed
    series lands as a gauge-kind sample (histogram buckets arrive as
    their exploded ``_bucket{le=}`` / ``_sum`` / ``_count`` series,
    which is exactly what windowed rate queries need anyway)."""
    from .metrics import parse_prometheus_text

    fams = []
    for name, pairs in sorted(parse_prometheus_text(text).items()):
        labelnames = sorted({k for labels, _ in pairs for k in labels})
        fams.append({
            "name": name,
            "kind": "gauge",
            "labelnames": labelnames,
            "samples": [
                {
                    "labels": [labels.get(k, "") for k in labelnames],
                    "value": v,
                }
                for labels, v in pairs
            ],
        })
    return {"v": 1, "families": fams}


def _merge_scalar(agg: Optional[Dict[str, float]], v: float,
                  ) -> Dict[str, float]:
    if agg is None:
        return {"sum": v, "cnt": 1.0, "min": v, "max": v, "last": v}
    agg["sum"] += v
    agg["cnt"] += 1.0
    agg["min"] = min(agg["min"], v)
    agg["max"] = max(agg["max"], v)
    agg["last"] = v
    return agg


def _merge_agg(a: Optional[Dict[str, float]], b: Dict[str, float],
               ) -> Dict[str, float]:
    """Fold two closed-window aggregates (``b`` later than ``a``)."""
    if a is None:
        return dict(b)
    return {
        "sum": a["sum"] + b["sum"],
        "cnt": a["cnt"] + b["cnt"],
        "min": min(a["min"], b["min"]),
        "max": max(a["max"], b["max"]),
        "last": b["last"],
    }


class _Tier:
    """One open downsample tier: accumulates the current bucket in
    memory and flushes it as ONE line when time moves past its edge."""

    def __init__(self, width_s: float):
        self.width_s = float(width_s)
        self.bidx: Optional[int] = None  # open bucket index
        self.t_last: float = 0.0  # latest sample time in the bucket
        self.scalars: Dict[str, Dict[str, float]] = {}
        self.hists: Dict[str, Dict[str, Any]] = {}

    def record_name(self) -> str:
        return f"agg{int(self.width_s)}"

    def add(self, t: float, scalars, hists) -> Optional[Dict[str, Any]]:
        """Accumulate one snapshot; returns the CLOSED bucket record
        when ``t`` crosses into a new bucket, else None."""
        bidx = int(math.floor(t / self.width_s))
        closed = None
        if self.bidx is not None and bidx != self.bidx:
            closed = self.to_record()
            self.scalars, self.hists = {}, {}
        self.bidx = bidx
        self.t_last = t
        for key, v in scalars.items():
            self.scalars[key] = _merge_scalar(self.scalars.get(key), v)
        for key, h in hists.items():
            self.hists[key] = dict(h)  # cumulative: last wins
        return closed

    def to_record(self) -> Optional[Dict[str, Any]]:
        if self.bidx is None or not (self.scalars or self.hists):
            return None
        return {
            "t0": self.bidx * self.width_s,
            "t1": (self.bidx + 1) * self.width_s,
            # the latest sample actually inside the bucket: readers
            # stamp fills here, never at a t1 the writer hasn't
            # reached (an open bucket's edge is in the future)
            "tl": self.t_last,
            "w": self.width_s,
            "series": self.scalars,
            "hist": self.hists,
        }


class TSDB:
    """Append + query over one history directory. Safe for one writer
    process (appends are lock-serialized); any number of readers can
    open the same directory independently."""

    def __init__(
        self,
        path: str,
        *,
        segment_bytes: int = 1 << 20,
        max_bytes: int = 16 << 20,
        tiers: Tuple[float, ...] = (10.0, 60.0),
    ):
        if segment_bytes <= 0 or max_bytes <= 0:
            raise ValueError("segment_bytes/max_bytes must be > 0")
        self.path = path
        self.segment_bytes = int(segment_bytes)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._tiers = [_Tier(w) for w in sorted(tiers)]
        os.makedirs(path, exist_ok=True)
        # resume numbering after existing segments so a reopened dir
        # keeps appending instead of clobbering history
        self._seq: Dict[str, int] = {}
        for fname, _, _ in self._segments():
            m = _SEG_RE.match(fname)
            kind, num = m.group(1), int(m.group(3))
            self._seq[kind] = max(self._seq.get(kind, 0), num + 1)

    # -- write side --------------------------------------------------

    def append(self, snap: Any, t: Optional[float] = None) -> None:
        """Store one registry snapshot (dict or ``snapshot_json()``
        string) at wall time ``t``. Rolls/downsamples/retains as a
        side effect; never raises into the caller's telemetry loop for
        malformed snapshots — those raise ValueError loudly instead
        (an appender with a broken snapshot is a bug, not weather)."""
        if isinstance(snap, (str, bytes)):
            snap = json.loads(snap)
        if not isinstance(snap, dict) or "families" not in snap:
            raise ValueError("not a registry snapshot (no families)")
        t = float(time.time() if t is None else t)
        scalars, hists = flatten_snapshot(snap)
        line = json.dumps(
            {"t": t, "snap": snap}, separators=(",", ":")
        ) + "\n"
        with self._lock:
            self._write("raw", line)
            for tier in self._tiers:
                closed = tier.add(t, scalars, hists)
                if closed is not None:
                    self._write(
                        tier.record_name(),
                        json.dumps(closed, separators=(",", ":")) + "\n",
                    )
            self._retain()

    def flush(self) -> None:
        """Flush every open downsample bucket (stop/final-push path) —
        after this, readers of the directory see the full history the
        writer saw."""
        with self._lock:
            for tier in self._tiers:
                rec = tier.to_record()
                if rec is not None:
                    self._write(
                        tier.record_name(),
                        json.dumps(rec, separators=(",", ":")) + "\n",
                    )
                tier.bidx, tier.scalars, tier.hists = None, {}, {}
            self._retain()

    def _write(self, kind: str, line: str) -> None:
        seq = self._seq.get(kind, 0)
        fpath = os.path.join(self.path, f"{kind}-{seq:06d}.jsonl")
        with open(fpath, "a") as f:
            f.write(line)
        if os.path.getsize(fpath) >= self.segment_bytes:
            self._seq[kind] = seq + 1

    def _segments(self) -> List[Tuple[str, str, int]]:
        """(fname, kind, size) for every segment, sorted by (kind
        resolution, seq) — raw first, then finer tiers."""
        out = []
        try:
            names = sorted(os.listdir(self.path))
        except OSError:
            return []
        for fname in names:
            m = _SEG_RE.match(fname)
            if m:
                fpath = os.path.join(self.path, fname)
                try:
                    out.append((fname, m.group(1), os.path.getsize(fpath)))
                except OSError:
                    continue
        return out

    def total_bytes(self) -> int:
        return sum(size for _, _, size in self._segments())

    def _retain(self) -> None:
        """Enforce ``max_bytes``: drop the oldest segment of the
        FINEST kind that still has more than one segment (the active
        tail is never deleted) — resolution degrades, coverage stays."""
        while self.total_bytes() > self.max_bytes:
            segs = self._segments()
            by_kind: Dict[str, List[str]] = {}
            for fname, kind, _ in segs:
                by_kind.setdefault(kind, []).append(fname)
            order = ["raw"] + [t.record_name() for t in self._tiers]
            victim = None
            for kind in order:
                files = sorted(by_kind.get(kind, []))
                if len(files) > 1:
                    victim = files[0]
                    break
            if victim is None:
                break  # single active segment per kind — nothing safe to drop
            os.remove(os.path.join(self.path, victim))

    # -- read side ---------------------------------------------------

    def _iter_raw(
        self, t0: float, t1: float
    ) -> Iterable[Tuple[float, Dict[str, float], Dict[str, Any]]]:
        for fname, kind, _ in self._segments():
            if kind != "raw":
                continue
            with open(os.path.join(self.path, fname)) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # truncated tail of a crashed writer
                    t = float(rec.get("t", math.nan))
                    if t0 <= t <= t1:
                        yield (t, *flatten_snapshot(rec.get("snap", {})))

    def _iter_tier(
        self, width_s: float, t0: float, t1: float
    ) -> Iterable[Dict[str, Any]]:
        kind = f"agg{int(width_s)}"
        for fname, k, _ in self._segments():
            if k != kind:
                continue
            with open(os.path.join(self.path, fname)) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("t1", 0) > t0 and rec.get("t0", 0) < t1:
                        yield rec
        # the writer's open bucket is part of the history its own
        # process queries (alert engines run in the appender)
        for tier in self._tiers:
            if tier.width_s == width_s:
                rec = tier.to_record()
                if rec and rec["t1"] > t0 and rec["t0"] < t1:
                    yield rec

    def raw_times(
        self, t0: float = -math.inf, t1: float = math.inf
    ) -> List[float]:
        """Every raw append timestamp in range, sorted — the replay
        axis ``edl watch`` walks over a recorded directory."""
        return sorted(t for t, _, _ in self._iter_raw(t0, t1))

    def series_names(self) -> List[str]:
        names = set()
        for _, scalars, hists in self._iter_raw(-math.inf, math.inf):
            names.update(scalars)
            names.update(hists)
        for tier in self._tiers:
            for rec in self._iter_tier(tier.width_s, -math.inf, math.inf):
                names.update(rec.get("series", {}))
                names.update(rec.get("hist", {}))
        return sorted(names)

    def points(
        self,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        t0: float = -math.inf,
        t1: float = math.inf,
    ) -> List[Tuple[float, float]]:
        """Scalar samples ``[(t, v)]``: raw resolution where raw
        segments survive, tier ``last``-per-bucket (stamped at the
        bucket edge) where retention already folded raw away."""
        key = series_key(name, labels)
        pts = [
            (t, scalars[key])
            for t, scalars, _ in self._iter_raw(t0, t1)
            if key in scalars
        ]
        covered_from = min((t for t, _ in pts), default=math.inf)
        for tier in self._tiers:  # finest tier fills the gap first
            fill = [
                (ts, rec["series"][key]["last"])
                for rec in self._iter_tier(tier.width_s, t0, t1)
                if key in rec.get("series", {})
                # stamp at the bucket's true last-sample time (older
                # records predate "tl": their t1 was always reached)
                for ts in (min(rec["t1"], rec.get("tl", rec["t1"])),)
                if ts <= covered_from and t0 <= ts <= t1
            ]
            if fill:
                pts.extend(fill)
                covered_from = min(covered_from,
                                   min(t for t, _ in fill))
        return sorted(set(pts))

    def hist_points(
        self,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        t0: float = -math.inf,
        t1: float = math.inf,
    ) -> List[Tuple[float, Dict[str, Any]]]:
        """Cumulative histogram samples ``[(t, {counts,sum,count,
        buckets})]`` with the same raw-then-tier fallback as
        :meth:`points`."""
        key = series_key(name, labels)
        pts = [
            (t, hists[key])
            for t, _, hists in self._iter_raw(t0, t1)
            if key in hists
        ]
        covered_from = min((t for t, _ in pts), default=math.inf)
        for tier in self._tiers:
            fill = [
                (ts, rec["hist"][key])
                for rec in self._iter_tier(tier.width_s, t0, t1)
                if key in rec.get("hist", {})
                for ts in (min(rec["t1"], rec.get("tl", rec["t1"])),)
                if ts <= covered_from and t0 <= ts <= t1
            ]
            if fill:
                pts.extend(fill)
                covered_from = min(covered_from,
                                   min(t for t, _ in fill))
        return sorted(pts, key=lambda p: p[0])

    def series(
        self,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        t0: float = -math.inf,
        t1: float = math.inf,
        step: Optional[float] = None,
    ) -> List[Dict[str, float]]:
        """Windowed aggregate query — the alert engine's read path.
        Buckets ``[t0 + k*step, t0 + (k+1)*step)`` each carry
        ``t/sum/count/min/max/last/avg`` over the points inside.
        ``step=None`` (or a non-finite range) returns one bucket over
        the whole range. Buckets with no points are omitted."""
        pts = self.points(name, labels, t0, t1)
        if not pts:
            return []
        if step is None or not math.isfinite(t0):
            start, step_w = pts[0][0], math.inf
        else:
            start, step_w = t0, float(step)
        buckets: Dict[int, Dict[str, float]] = {}
        for t, v in pts:
            k = 0 if not math.isfinite(step_w) else int((t - start) // step_w)
            buckets[k] = _merge_scalar(buckets.get(k), v)
        out = []
        for k in sorted(buckets):
            agg = buckets[k]
            agg["t"] = start if not math.isfinite(step_w) else start + k * step_w
            agg["avg"] = agg["sum"] / agg["cnt"]
            out.append(agg)
        return out

    def increase(
        self,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        t0: float = -math.inf,
        t1: float = math.inf,
    ) -> float:
        """Counter increase over the window with RESET CLAMPING: a
        sample below its predecessor means the process restarted, so
        that step contributes the post-reset value (counting from
        zero), never a negative delta."""
        pts = self.points(name, labels, t0, t1)
        inc = 0.0
        for (_, prev), (_, cur) in zip(pts, pts[1:]):
            inc += cur - prev if cur >= prev else cur
        return inc

    def hist_delta(
        self,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        t0: float = -math.inf,
        t1: float = math.inf,
    ) -> Optional[Dict[str, Any]]:
        """Windowed delta of a cumulative histogram: per-bucket count
        increases between the window's edge samples, clamped at a
        counter reset (total count went down → the later sample IS the
        delta, the pre-reset history is gone). Returns ``{pairs, sum,
        count, buckets}`` where ``pairs`` is the
        ``[({"le": edge}, cumulative)]`` list
        :func:`~edl_tpu.obs.metrics.percentile_from_buckets` takes, or
        None with fewer than 2 samples in range."""
        pts = self.hist_points(name, labels, t0, t1)
        if len(pts) < 2:
            return None
        lo, hi = pts[0][1], pts[-1][1]
        buckets = hi.get("buckets") or lo.get("buckets") or []
        if hi["count"] < lo["count"] or len(lo["counts"]) != len(hi["counts"]):
            d_counts = list(hi["counts"])  # reset: later sample counts from 0
            d_sum, d_count = hi["sum"], hi["count"]
        else:
            d_counts = [
                max(0.0, h - l) for h, l in zip(hi["counts"], lo["counts"])
            ]
            d_sum = max(0.0, hi["sum"] - lo["sum"])
            d_count = max(0.0, hi["count"] - lo["count"])
        # registry counts are per-bucket; Prometheus `le` pairs are
        # cumulative — running-sum before handing to the quantile math
        pairs, cum = [], 0.0
        for e, c in zip(list(buckets) + [math.inf], d_counts):
            cum += c
            pairs.append(
                ({"le": "+Inf" if not math.isfinite(e) else repr(e)}, cum)
            )
        return {
            "pairs": pairs,
            "sum": d_sum,
            "count": d_count,
            "buckets": list(buckets),
        }

    # -- http --------------------------------------------------------

    def render_history(self, qs: Dict[str, List[str]]) -> str:
        """The ``/history`` endpoint body (exporter.py routes here).
        No ``name`` → the series directory; with ``name`` → points or
        ``step``-bucketed aggregates. Any unrecognized query param is
        a label matcher, so ``/history?name=edl_slo_ttft_ok_ratio&
        slo_class=interactive&step=60`` reads exactly like the query
        API."""
        def one(param: str) -> Optional[str]:
            vals = qs.get(param)
            return vals[0] if vals else None

        name = one("name")
        if not name:
            return json.dumps(
                {"series": self.series_names(),
                 "total_bytes": self.total_bytes()},
                separators=(",", ":"),
            )
        t0 = float(one("t0") or -math.inf)
        t1 = float(one("t1") or math.inf)
        step = one("step")
        labels = {
            k: vs[0] for k, vs in qs.items()
            if k not in ("name", "t0", "t1", "step") and vs
        }
        if step is not None:
            body: Any = self.series(name, labels, t0, t1, float(step))
        else:
            body = [[t, v] for t, v in self.points(name, labels, t0, t1)]
        return json.dumps(
            {"name": name, "labels": labels, "points": body},
            separators=(",", ":"),
        )
