"""Runtime compile observability — the dynamic twin of `edl check`'s
static recompile-hazard rule.

Every shared jit-program factory (the serving engine's block/prefill
memo, ``llama._generate_program``, the trainer step factories) wraps
its compiled callable here. The FIRST invocation of each distinct
program is timed into ``edl_compile_seconds{program}`` and counted in
``edl_compiles_total{program}`` — jax jit is lazy, so the first call
is where trace+compile actually happens, and each memo key IS a
distinct program, so first-call-per-wrapper measures exactly one
compile. (The timing includes the first execution; on anything bigger
than a toy, compile dominates by orders of magnitude.)

After :func:`mark_warm` — called by harnesses once their warmup pass
has paid the expected compiles — any further compile additionally
emits an ``obs.recompile`` flight-recorder event (severity ``warn``):
a steady-state serving loop that compiles is paying seconds of latency
someone should see on the incident timeline, exactly the hazard class
the static rule flags at review time. The acceptance gate asserts ZERO
such events on the steady-state serving loop (`edl profile --dryrun`).

Hot-path cost after the first call: one bool check per invocation.
Metrics go to the process default registry on purpose — compile
activity is process-level truth regardless of which private registry
an engine's serving metrics use.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from edl_tpu.obs import metrics as obs_metrics

_lock = threading.Lock()
_warm = False


def mark_warm() -> None:
    """Declare warmup over: compiles from here on are RE-compiles and
    land on the flight-recorder timeline."""
    global _warm
    with _lock:
        _warm = True


def is_warm() -> bool:
    with _lock:
        return _warm


def reset() -> None:
    """Back to warmup (tests)."""
    global _warm
    with _lock:
        _warm = False


def wrap(fn: Callable, program: str) -> Callable:
    """Instrument one compiled program. Transparent to donation and
    tracing — the wrapper only forwards ``*args``."""

    state = {"done": False}
    state_lock = threading.Lock()

    def run(*args, **kw):
        if state["done"]:
            return fn(*args, **kw)
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        dt = time.perf_counter() - t0
        with state_lock:
            if state["done"]:  # lost the race: someone else timed it
                return out
            state["done"] = True
        r = obs_metrics.default_registry()
        r.histogram(
            "edl_compile_seconds",
            "first-call (trace + compile) time per distinct jit program",
            ("program",),
        ).observe(dt, program=program)
        r.counter(
            "edl_compiles_total",
            "distinct jit programs compiled, by factory",
            ("program",),
        ).inc(program=program)
        if is_warm():
            from edl_tpu.obs import events as flight

            flight.emit(
                "obs.recompile", severity="warn",
                program=program, seconds=round(dt, 6),
            )
        return out

    run.__name__ = f"compilewatch[{program}]"
    run.__wrapped__ = fn
    return run
