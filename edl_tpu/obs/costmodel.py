"""Analytic hardware cost model — the ONE source of FLOPs/bytes truth.

BENCH_r05 says the system is already hardware-limited (int8 b=1 decode
at ~99.5% of peak HBM bandwidth, train MFU 0.59), yet until this module
every efficiency number was an ad-hoc formula: ``bench.py`` carried its
own peak tables and ``_decode_step_bytes``, ``scripts/exp_mfu.py``
hard-coded a v5e peak, and ``models/llama.py`` owned the train-FLOPs
formula. Three copies of device math drift; this module is where all of
them now live, consumed by

* ``bench.py`` (``_peak_flops`` / ``_peak_hbm_bw`` / ``_decode_step_bytes``
  delegate here),
* ``scripts/exp_mfu.py`` (peak lookup),
* ``models/llama.py`` (``train_flops_per_token`` delegates here),
* the LIVE efficiency gauges (``edl_mfu{phase}`` /
  ``edl_bw_util_ratio{phase}``) the serving engine and trainer publish
  through :class:`EfficiencyMeter`,
* ``edl profile`` / ``scripts/perf_gate.py`` (roofline reports).

jax-free by construction (the obs/ contract): config objects are duck
typed — anything with ``vocab / d_model / n_layers / n_heads /
n_kv_heads / d_ff`` works (``LlamaConfig``, ``MoEConfig``); CTR has its
own entry point. Device detection imports jax lazily and only when
asked for the local device.

FLOPs conventions (matching the published bench numbers exactly):

* **train**: model FLOPs per token = ``6 × matmul params`` (embedding
  lookup excluded, lm_head included) + causal attention
  ``12·L·(T/2)·d_attn``. Remat recompute is NOT counted (MFU counts
  model FLOPs, not hardware FLOPs).
* **prefill**: the forward third of the above over the prompt.
* **decode**: per token at context ``s``, ``2 × matmul params`` +
  ``4·L·s·d_attn``. The serving decode programs compute masked-DENSE
  attention over the full padded cache (``models/llama.py
  _decode_step``/``decode_step_slots`` einsum over ``s = max_len`` by
  construction), so the per-step cost model uses the FULL padded
  length, not the average occupancy — this is program cost, the right
  roofline denominator for what the chip actually executes.

Bytes conventions: a decode step must move every parameter byte (the
weight stream — the defining cost of small-batch decode) plus the full
padded KV cache (same formula ``bench.py`` published
``decode_pct_peak_bw`` with, KV elements at 2 bytes); activation
traffic at serving batch sizes is noise next to those two.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

from edl_tpu.obs import metrics as obs_metrics

# ---------------------------------------------------------------------------
# device peaks


@dataclass(frozen=True)
class DevicePeak:
    """Per-chip peak rates: bf16 TFLOP/s and HBM bandwidth. Spec-sheet
    values — read achieved/peak as a relative efficiency index (the
    bench chip has measured slightly ABOVE 1.0 on the b=1 decode rung,
    i.e. the table is conservative for that part)."""

    kind: str
    flops: float  # bf16 peak FLOP/s
    hbm_bytes_s: float  # peak HBM bytes/s


# ordered substring table — first match wins. The public per-chip
# numbers for each TPU generation; "v5 lite" must precede "v5" (the
# bench fleet's v5e reports device_kind "TPU v5 lite").
_PEAK_TABLE = (
    ("v6", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5 lite", 197e12, 819e9),
    ("v5lite", 197e12, 819e9),
    ("v5e", 197e12, 819e9),
    ("v5", 459e12, 2765e9),
    ("v4", 275e12, 1228e9),
)

# conservative default (v5e-class) when the kind is opaque — also what
# a CPU run uses, which keeps CPU-dryrun gauges tiny but NON-ZERO
_DEFAULT_PEAK = DevicePeak("v5e-assumed", 197e12, 819e9)


def peak_for_kind(kind: str) -> DevicePeak:
    """Spec-table lookup by device-kind substring, no env overrides —
    what the bench uses so published pct-of-peak stays comparable
    across rounds."""
    k = (kind or "").lower()
    for sub, fl, bw in _PEAK_TABLE:
        if sub in k:
            return DevicePeak(sub, fl, bw)
    return _DEFAULT_PEAK


def peak_for_device(device) -> DevicePeak:
    """Lookup from a jax device object (``device_kind`` attr)."""
    return peak_for_kind(getattr(device, "device_kind", ""))


def detect_peak(device: Any = None) -> DevicePeak:
    """The LIVE-telemetry peak: auto-detected from the local device
    (lazily importing jax; falls back to the conservative default when
    jax or devices are unavailable) with env overrides
    ``EDL_PEAK_TFLOPS`` / ``EDL_PEAK_HBM_GBS`` applied on top — the
    escape hatch for fleets whose device_kind the table predates."""
    if device is not None:
        peak = peak_for_device(device)
    else:
        try:
            import jax

            peak = peak_for_device(jax.devices()[0])
        except Exception as e:  # no jax / no devices: defaults, noted
            peak = DevicePeak(f"unknown ({type(e).__name__})",
                              _DEFAULT_PEAK.flops, _DEFAULT_PEAK.hbm_bytes_s)
    tf = os.environ.get("EDL_PEAK_TFLOPS")
    bw = os.environ.get("EDL_PEAK_HBM_GBS")
    if tf or bw:
        peak = DevicePeak(
            peak.kind + "+env",
            float(tf) * 1e12 if tf else peak.flops,
            float(bw) * 1e9 if bw else peak.hbm_bytes_s,
        )
    return peak


# ---------------------------------------------------------------------------
# FLOPs / params / bytes — transformer (llama + MoE via duck typing)


def _dims(cfg):
    hd = getattr(cfg, "head_dim", None)
    if hd is None:
        hd = cfg.d_model // cfg.n_heads
    return cfg.d_model, cfg.n_heads, cfg.n_kv_heads, hd, cfg.d_ff, \
        cfg.n_layers, cfg.vocab


def matmul_params(cfg) -> float:
    """Parameters participating in matmuls per token (embedding lookup
    excluded, lm_head included) — the ``N`` of the 6N/2N rules. MoE
    configs count the ACTIVATED expert width (top_k experts) plus the
    router — model FLOPs are per-token work actually done."""
    d, h, kv, hd, ff, L, V = _dims(cfg)
    ff_ways = getattr(cfg, "top_k", None) if hasattr(cfg, "n_experts") else None
    per_layer = (
        d * h * hd  # wq
        + 2 * d * kv * hd  # wk, wv
        + h * hd * d  # wo
        + 3 * d * ff * (ff_ways or 1)  # w1, w3, w2 (x active experts)
    )
    if hasattr(cfg, "n_experts"):
        per_layer += d * cfg.n_experts  # router projection
    return L * per_layer + d * V  # + lm_head


def n_params(cfg) -> float:
    """Total parameter count (for state sizing — MoE counts ALL
    experts here, unlike :func:`matmul_params`)."""
    d, h, kv, hd, ff, L, V = _dims(cfg)
    experts = getattr(cfg, "n_experts", 1) if hasattr(cfg, "n_experts") else 1
    per_layer = (
        2 * d  # ln1, ln2
        + d * h * hd + 2 * d * kv * hd + h * hd * d
        + 3 * d * ff * experts
    )
    if hasattr(cfg, "n_experts"):
        per_layer += d * cfg.n_experts
    return V * d + L * per_layer + d + d * V  # embed + layers + ln_f + lm_head


def attn_flops_per_token_train(cfg, seq: int) -> float:
    d, h, kv, hd, ff, L, V = _dims(cfg)
    return 12.0 * L * (seq / 2.0) * (h * hd)


def train_flops_per_token(cfg, seq: int) -> float:
    """Model FLOPs per trained token (fwd+bwd) — the MFU numerator.
    THE formula ``models/llama.py:train_flops_per_token`` and every
    bench/exp_mfu call site delegate to (BENCH_r05 pins
    ``llama_flops_per_token`` = 5637.1 MFLOPs on the flagship)."""
    return 6.0 * matmul_params(cfg) + attn_flops_per_token_train(cfg, seq)


def fwd_flops_per_token(cfg, seq: int) -> float:
    """Forward-only model FLOPs per token at sequence length ``seq``
    (causal: average context seq/2) — the prefill numerator."""
    d, h, kv, hd, ff, L, V = _dims(cfg)
    return 2.0 * matmul_params(cfg) + 4.0 * L * (seq / 2.0) * (h * hd)


def prefill_flops(cfg, t: int) -> float:
    """One prompt prefill of ``t`` tokens (forward pass, cache build)."""
    return t * fwd_flops_per_token(cfg, t)


def decode_flops_per_token(cfg, s_ctx: int) -> float:
    """One cached decode step per row at (padded) context ``s_ctx``.
    The serving programs compute masked-dense attention over the FULL
    padded cache, so callers should pass the padded length — this is
    the cost of the program as compiled, not of the useful context."""
    d, h, kv, hd, ff, L, V = _dims(cfg)
    return 2.0 * matmul_params(cfg) + 4.0 * L * s_ctx * (h * hd)


def param_bytes(cfg, bytes_per_param: int = 2) -> float:
    """Weight bytes a decode step streams (bf16 export default)."""
    return n_params(cfg) * bytes_per_param


def kv_cache_bytes(
    cfg, slots: int, max_len: int, bytes_per_el: float = 2
) -> float:
    """The [L, slots, max_len, KV, hd] K + V cache pair.
    ``bytes_per_el`` may be fractional (packed int4 KV = 0.5)."""
    d, h, kv, hd, ff, L, V = _dims(cfg)
    return 2.0 * L * slots * max_len * kv * hd * bytes_per_el


def kv_pool_bytes(
    cfg, n_blocks: int, block_size: int, bytes_per_el: float = 2
) -> float:
    """The paged [L, n_blocks, block_size, KV, hd] K + V pool pair
    (includes the reserved scratch block — it occupies real HBM)."""
    d, h, kv, hd, ff, L, V = _dims(cfg)
    return 2.0 * L * n_blocks * block_size * kv * hd * bytes_per_el


def kv_quant_bytes_per_el(kv_quant: str) -> float:
    """KV pool bytes per logical element for a serving ``--kv-quant``
    mode: bf16 2, int8 1, packed int4 0.5."""
    return {"off": 2.0, "int8": 1.0, "int4": 0.5}[kv_quant]


def kv_scale_bytes(cfg, slots: int, s_pad: int, kv_block_size: int) -> float:
    """Bytes of the per-block-per-kv-head f32 scale planes a quantized
    decode step reads alongside the values: K + V planes, one f32 per
    (layer, block, kv head) over ``ceil(s_pad / block)`` blocks per
    slot. Zero when ``kv_block_size`` is 0 (unquantized — no scales)."""
    if kv_block_size <= 0:
        return 0.0
    d, h, kv, hd, ff, L, V = _dims(cfg)
    blocks = -(-s_pad // kv_block_size)
    return 2.0 * L * slots * blocks * kv * 4.0


def decode_step_bytes(
    cfg, param_bytes_total: float, b: int, s_pad: int,
    kv_bytes_per_el: float = 2, kv_block_size: int = 0,
) -> float:
    """HBM bytes one decode step must move: every parameter byte
    (weights stream once per token — the defining cost of small-batch
    decode) plus the FULL padded KV cache (the masked-dense decode
    attention reads all S slots every step, by construction).
    Activation traffic at B<=32 is noise next to these two. The exact
    formula ``bench.py`` publishes ``decode_pct_peak_bw`` with.

    Quantized paged KV narrows the cache term (``kv_bytes_per_el`` 1
    for int8, 0.5 for packed int4) and adds the per-block f32 scale
    strips the gather reads — pass the paged ``kv_block_size`` so the
    scale term is priced honestly (it is ~1/(2·bs) of the values for
    int8, small but not zero)."""
    return (
        param_bytes_total
        + kv_cache_bytes(cfg, b, s_pad, kv_bytes_per_el)
        + kv_scale_bytes(cfg, b, s_pad, kv_block_size)
    )


def train_step_bytes(cfg, tokens_per_step: int,
                     master_bytes_per_param: int = 4) -> float:
    """Crude lower bound on HBM traffic of one optimizer step: three
    passes over the f32 master weights (read for fwd/bwd, gradient
    write+read, updated write; factored adafactor moments are noise)
    plus the remat-era activation traffic (layer inputs saved+restored
    in bf16). Context for ``edl_bw_util_ratio{phase="train"}`` — train
    is compute-bound, so this ratio is informative, not a roofline."""
    d, h, kv, hd, ff, L, V = _dims(cfg)
    weights = 3.0 * n_params(cfg) * master_bytes_per_param
    acts = 2.0 * tokens_per_step * d * (L + 1) * 2  # save + restore, bf16
    return weights + acts


# ---------------------------------------------------------------------------
# CTR (the reference production workload)


def ctr_train_flops_per_example(
    emb: int = 16, mlp_dims=(400, 400, 400, 1), n_sparse: int = 26,
    n_dense: int = 13,
) -> float:
    """6 × matmul params of the Criteo-shaped CTR tower (models/ctr.py
    defaults). The embedding gather itself is bandwidth, not FLOPs."""
    in_dim = n_dense + n_sparse * emb
    total = 0.0
    for out_dim in mlp_dims:
        total += in_dim * out_dim
        in_dim = out_dim
    return 6.0 * total


# ---------------------------------------------------------------------------
# the per-phase cost bundle


@dataclass(frozen=True)
class Cost:
    """One operation's analytic bill: model FLOPs + HBM bytes moved."""

    flops: float
    hbm_bytes: float


class CostModel:
    """A config + device peak bound together: per-phase costs and the
    achieved/peak ratios. ``param_bytes_total`` should be the ACTUAL
    loaded tree's bytes when known (int8 records halve it — the ledger
    measures, the model predicts), else the bf16 estimate is used.
    ``kv_bytes_per_el``/``kv_block_size`` describe the KV pool the
    decode programs actually read: a quantized paged engine passes
    (1, block_size) for int8 KV or (0.5, block_size) for int4, which
    narrows the cache term and adds the f32 scale strips — keeping
    the live ``edl_bw_util_ratio{phase="decode"}`` truthful when the
    cache shrinks."""

    def __init__(
        self,
        cfg,
        peak: Optional[DevicePeak] = None,
        param_bytes_total: Optional[float] = None,
        kv_bytes_per_el: float = 2,
        kv_block_size: int = 0,
    ):
        self.cfg = cfg
        self.peak = peak or detect_peak()
        self.param_bytes = (
            float(param_bytes_total)
            if param_bytes_total is not None
            else param_bytes(cfg)
        )
        self.kv_bytes_per_el = kv_bytes_per_el
        self.kv_block_size = int(kv_block_size)

    def train_step(self, batch: int, seq: int) -> Cost:
        toks = batch * seq
        return Cost(
            flops=toks * train_flops_per_token(self.cfg, seq),
            hbm_bytes=train_step_bytes(self.cfg, toks),
        )

    def prefill(self, t: int) -> Cost:
        return Cost(
            flops=prefill_flops(self.cfg, t),
            # the prefill streams the weights once and writes t cache rows
            hbm_bytes=self.param_bytes
            + kv_cache_bytes(self.cfg, 1, t, self.kv_bytes_per_el),
        )

    def decode_block(self, b: int, horizon: int, s_pad: int) -> Cost:
        """One fused horizon block as dispatched: ``horizon`` steps of
        ``b`` rows (frozen rows still compute — program cost) at the
        full padded context."""
        step_bytes = decode_step_bytes(
            self.cfg, self.param_bytes, b, s_pad, self.kv_bytes_per_el,
            self.kv_block_size,
        )
        return Cost(
            flops=horizon * b * decode_flops_per_token(self.cfg, s_pad),
            hbm_bytes=horizon * step_bytes,
        )

    def verify_block(self, b: int, k: int, s_pad: int) -> Cost:
        """One speculative verify dispatch: ``k`` query lanes per row
        (pending token + k-1 drafts) in ONE weight pass. FLOPs scale
        with ``k`` like ``k`` decode steps, but HBM traffic is a
        SINGLE step's — the weights and the full padded cache stream
        once and feed every lane. That asymmetry is the whole point of
        speculation on a bandwidth-bound decode: accepted-tokens/
        dispatch > 1 multiplies tokens per byte moved."""
        step_bytes = decode_step_bytes(
            self.cfg, self.param_bytes, b, s_pad, self.kv_bytes_per_el,
            self.kv_block_size,
        )
        return Cost(
            flops=k * b * decode_flops_per_token(self.cfg, s_pad),
            hbm_bytes=step_bytes,
        )

    def mfu(self, flops_per_s: float) -> float:
        return flops_per_s / self.peak.flops if self.peak.flops > 0 else 0.0

    def bw_util(self, bytes_per_s: float) -> float:
        return (
            bytes_per_s / self.peak.hbm_bytes_s
            if self.peak.hbm_bytes_s > 0
            else 0.0
        )


# ---------------------------------------------------------------------------
# live gauges


class EfficiencyMeter:
    """Accumulates analytic (flops, bytes, busy-seconds) per phase and
    publishes the live roofline gauges:

    * ``edl_mfu{phase}``           — analytic FLOPs/s over peak FLOPs
    * ``edl_bw_util_ratio{phase}`` — analytic bytes/s over peak HBM BW
    * ``edl_costmodel_flops_total{phase}`` /
      ``edl_costmodel_hbm_bytes_total{phase}`` — the raw integrals,
      for ``rate()``-style windowed queries a cumulative gauge can't
      answer.

    Callers pass NON-OVERLAPPING busy seconds (the serving engine
    clips block wall times against the previous drain so the double
    buffer cannot double-count time). Cumulative by design: the gauges
    answer "how efficient has this process been", the counters let a
    scraper window it. Hot-path cost per observe: one lock + a few
    dict hits (well under the 1% instrumentation budget)."""

    def __init__(
        self,
        peak: Optional[DevicePeak] = None,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
    ):
        self.peak = peak or detect_peak()
        r = registry or obs_metrics.default_registry()
        self._lock = threading.Lock()
        self._acc: Dict[str, list] = {}  # phase -> [flops, bytes, seconds]
        self._g_mfu = r.gauge(
            "edl_mfu",
            "achieved model FLOPs/s over peak FLOPs by phase (obs/costmodel.py)",
            ("phase",),
        )
        self._g_bw = r.gauge(
            "edl_bw_util_ratio",
            "achieved HBM bytes/s over peak bandwidth by phase",
            ("phase",),
        )
        self._c_flops = r.counter(
            "edl_costmodel_flops_total",
            "analytic model FLOPs completed by phase",
            ("phase",),
        )
        self._c_bytes = r.counter(
            "edl_costmodel_hbm_bytes_total",
            "analytic HBM bytes moved by phase",
            ("phase",),
        )

    def observe(self, phase: str, cost: Cost, seconds: float) -> None:
        """Account one operation's cost against ``seconds`` of busy
        wall time and refresh the phase's gauges."""
        if seconds <= 0:
            return
        with self._lock:
            acc = self._acc.setdefault(phase, [0.0, 0.0, 0.0])
            acc[0] += cost.flops
            acc[1] += cost.hbm_bytes
            acc[2] += seconds
            fl, by, s = acc
        self._c_flops.inc(cost.flops, phase=phase)
        self._c_bytes.inc(cost.hbm_bytes, phase=phase)
        self._g_mfu.set(
            fl / s / self.peak.flops if self.peak.flops else 0.0, phase=phase
        )
        self._g_bw.set(
            by / s / self.peak.hbm_bytes_s if self.peak.hbm_bytes_s else 0.0,
            phase=phase,
        )

    def set_rates(
        self, phase: str, flops_per_s: float, bytes_per_s: float
    ) -> None:
        """Direct gauge refresh from already-averaged rates (the
        trainer publishes examples/s × flops/example this way)."""
        self._g_mfu.set(
            flops_per_s / self.peak.flops if self.peak.flops else 0.0,
            phase=phase,
        )
        self._g_bw.set(
            bytes_per_s / self.peak.hbm_bytes_s
            if self.peak.hbm_bytes_s
            else 0.0,
            phase=phase,
        )


def efficiency_snapshot(
    registry: Optional[obs_metrics.MetricsRegistry] = None,
) -> Dict[str, float]:
    """Flat dict view of the live efficiency/memory gauges — what the
    monitor's EFFICIENCY strip (``edl monitor --json``) carries. Keys:
    ``mfu_<phase>``, ``bw_util_<phase>``, ``hbm_bytes_<category>``,
    ``kv_occupancy_ratio``. Empty when nothing has published yet."""
    r = registry or obs_metrics.default_registry()
    out: Dict[str, float] = {}
    for metric, prefix in (("edl_mfu", "mfu"), ("edl_bw_util_ratio", "bw_util")):
        fam = r.get(metric)
        if fam is None:
            continue
        for key, s in fam.samples():
            if key and s[0]:
                out[f"{prefix}_{key[0]}"] = s[0]
    fam = r.get("edl_hbm_bytes")
    if fam is not None:
        for key, s in fam.samples():
            if key and s[0]:
                out[f"hbm_bytes_{key[0]}"] = s[0]
    fam = r.get("edl_kv_occupancy_ratio")
    if fam is not None:
        v = fam.value()
        if v:
            out["kv_occupancy_ratio"] = v
    return out
