"""Unified telemetry: metrics registry + Prometheus/trace HTTP
exporter + fleet push/aggregation.

One coherent layer over what used to be three disconnected surfaces
(tracing spans on the elastic path, serving averages, the monitor's
human-only text table): every hot path records into a process-wide
:class:`MetricsRegistry`, an HTTP exporter pull-exposes ``/metrics``
(Prometheus text), ``/trace`` (chrome://tracing JSON), ``/healthz``,
and workers push snapshots through the job coordinator KV for the
fleet-aggregated view. See doc/observability.md for the metric
catalog and endpoint reference.

jax-free by construction — cli/monitor import this at module scope.
"""

from edl_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    ensure_core_series,
    parse_prometheus_text,
    percentile_from_buckets,
    reset_default_registry,
)
from edl_tpu.obs.exporter import (  # noqa: F401
    MetricsExporter,
    scrape,
    start_exporter,
)
from edl_tpu.obs.fleet import (  # noqa: F401
    MetricsPusher,
    aggregate_snapshots,
    bridge_tracer,
    clock_key,
    collect_fleet,
    collect_fleet_events,
    collect_fleet_trace,
    events_key,
    load_clock_offsets,
    metrics_key,
    registry_from_sample,
    straggler_pass,
    trace_key,
)
from edl_tpu.obs import disttrace  # noqa: F401  (distributed tracing)
from edl_tpu.obs.disttrace import (  # noqa: F401
    ClockSync,
    TraceContext,
    critical_path,
    merge_fleet_trace,
)
from edl_tpu.obs import events  # noqa: F401  (flight recorder)
from edl_tpu.obs.events import (  # noqa: F401
    FlightRecorder,
    crash_dump,
    default_recorder,
)
from edl_tpu.obs import slo  # noqa: F401  (goodput-under-SLO)
from edl_tpu.obs.slo import (  # noqa: F401
    SLOClass,
    compute_goodput,
    default_classes,
)
from edl_tpu.obs import compilewatch  # noqa: F401  (compile telemetry)
from edl_tpu.obs import costmodel  # noqa: F401  (roofline cost model)
from edl_tpu.obs.costmodel import (  # noqa: F401
    Cost,
    CostModel,
    DevicePeak,
    EfficiencyMeter,
    detect_peak,
    peak_for_device,
    peak_for_kind,
)
from edl_tpu.obs import memledger  # noqa: F401  (device memory ledger)
from edl_tpu.obs.memledger import (  # noqa: F401
    MemoryLedger,
    default_ledger,
    tree_nbytes,
)
from edl_tpu.obs import tsdb  # noqa: F401  (on-disk metric history)
from edl_tpu.obs.tsdb import (  # noqa: F401
    TSDB,
    series_key,
    snapshot_from_prometheus_text,
)
from edl_tpu.obs import alerts  # noqa: F401  (burn-rate/anomaly alerting)
from edl_tpu.obs.alerts import (  # noqa: F401
    DEFAULT_RULES,
    AlertEngine,
    engine_from_doc,
    load_rules_doc,
)
