"""Telemetry HTTP exporter — stdlib ``http.server`` endpoint serving

* ``/metrics``  — Prometheus text exposition (format 0.0.4) of a
  :class:`~edl_tpu.obs.metrics.MetricsRegistry` (or of a callable that
  rebuilds one per scrape — the coordinator's fleet aggregation mode);
* ``/trace``    — the process tracer's chrome://tracing JSON (load in
  Perfetto / chrome://tracing) with the flight recorder's events
  merged in as instant markers, and the ring-buffer ``dropped`` count
  in the metadata; ``?since=<seq>`` / ``?n=`` bound the span window
  (the ``/events`` paging mirror — an incremental puller reads
  ``max_seq`` from the metadata event and fetches only the delta on
  the next cadence tick). A ``trace_source`` callable replaces the
  local document entirely — the coordinator serves the offset-
  corrected FLEET merge here (obs/disttrace.py);
* ``/events``   — the flight recorder's event log as JSONL, filterable
  by ``?rid=``, ``?kind=``, ``?severity=`` and bounded by ``?n=``
  (obs/events.py; the coordinator serves the worker-labeled fleet
  union here via its events source);
* ``/history``  — windowed queries over the process's on-disk metric
  history (obs/tsdb.py) when a ``history`` store is attached:
  ``?name=&t0=&t1=&step=`` plus any label matchers; no ``name`` lists
  the recorded series (doc/observability.md "History, alerting & burn
  rates");
* ``/healthz``  — liveness JSON (status, uptime, pid).

Pull-based on purpose (the Prometheus model): the process never blocks
on a slow consumer, and a scraper outage costs nothing. The server is
a daemon-threaded ``ThreadingHTTPServer`` bound by default to
loopback; ``port=0`` binds an ephemeral port (tests, `--metrics-port
0`). Scrapes read shared registries under their own family locks — a
scrape never takes a lock the step loop holds across a dispatch.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Union
from urllib.parse import parse_qs, urlsplit

from edl_tpu.obs.metrics import MetricsRegistry, ensure_core_series
from edl_tpu.utils.logging import kv_logger

log = kv_logger("obs")

CONTENT_TYPE_METRICS = "text/plain; version=0.0.4; charset=utf-8"


class MetricsExporter:
    """Own one telemetry endpoint.

    ``source`` is a registry, or a zero-arg callable returning one
    (re-evaluated per scrape; the fleet aggregator rebuilds a merged
    registry from coordinator KV each time). ``tracer`` defaults to
    the process-wide tracer so ``/trace`` always works.
    ``events_source`` is a zero-arg callable returning event RECORDS
    (dicts) for ``/events`` — defaults to the process flight
    recorder; the coordinator passes its fleet-union collector.
    ``trace_source`` is a zero-arg callable returning a chrome-trace
    document for ``/trace`` — defaults to the local tracer+recorder
    merge; the coordinator passes the fleet trace merge.
    ``history`` is a :class:`~edl_tpu.obs.tsdb.TSDB` (or a string
    path to a history directory) served on ``/history``; absent, the
    endpoint 404s and is omitted from ``/healthz``.
    """

    def __init__(
        self,
        source: Union[MetricsRegistry, Callable[[], MetricsRegistry], None] = None,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        tracer=None,
        events_source: Optional[Callable[[], List[dict]]] = None,
        trace_source: Optional[Callable[[], dict]] = None,
        history=None,
    ):
        if isinstance(history, str):
            from edl_tpu.obs.tsdb import TSDB

            history = TSDB(history)
        self.history = history
        if source is None:
            from edl_tpu.obs.metrics import default_registry

            source = default_registry()
        self._collect: Callable[[], MetricsRegistry] = (
            source if callable(source) else (lambda: source)
        )
        if isinstance(source, MetricsRegistry):
            ensure_core_series(source)
        if tracer is None:
            from edl_tpu.utils import tracing

            tracer = tracing.tracer()
        self.tracer = tracer
        if events_source is None:
            from edl_tpu.obs import events as _events

            events_source = lambda: _events.default_recorder().records()  # noqa: E731
        self._events = events_source
        self._trace_source = trace_source
        self._host = host
        self._want_port = port
        self._t0 = time.monotonic()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("exporter not started")
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def start(self) -> "MetricsExporter":
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            server_version = "edl-obs/1"

            def do_GET(self):  # noqa: N802 (http.server API)
                parts = urlsplit(self.path)
                path = parts.path
                try:
                    if path == "/metrics":
                        body = exporter.render_metrics().encode()
                        ctype = CONTENT_TYPE_METRICS
                    elif path == "/trace":
                        body = json.dumps(
                            exporter.render_trace(parse_qs(parts.query))
                        ).encode()
                        ctype = "application/json"
                    elif path == "/events":
                        body = exporter.render_events(
                            parse_qs(parts.query)
                        ).encode()
                        ctype = "application/x-ndjson"
                    elif path == "/history":
                        if exporter.history is None:
                            self.send_error(
                                404, "no history store attached"
                            )
                            return
                        body = exporter.history.render_history(
                            parse_qs(parts.query)
                        ).encode()
                        ctype = "application/json"
                    elif path in ("/", "/healthz"):
                        endpoints = ["/metrics", "/trace", "/events"]
                        if exporter.history is not None:
                            endpoints.append("/history")
                        endpoints.append("/healthz")
                        body = json.dumps(
                            {
                                "status": "ok",
                                "uptime_s": round(
                                    time.monotonic() - exporter._t0, 3
                                ),
                                "pid": os.getpid(),
                                "endpoints": endpoints,
                            }
                        ).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404, "unknown path")
                        return
                except Exception as e:  # collection failure, not transport
                    body = f"collection failed: {e}\n".encode()
                    self.send_response(500)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # silence per-scrape stderr
                pass

        srv = ThreadingHTTPServer((self._host, self._want_port), _Handler)
        srv.daemon_threads = True
        self._server = srv
        self._thread = threading.Thread(
            target=srv.serve_forever, name="edl-metrics-exporter", daemon=True
        )
        self._thread.start()
        log.info("metrics exporter up", url=self.url)
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "MetricsExporter":
        return self.start() if self._server is None else self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- collection ---------------------------------------------------------

    def render_metrics(self) -> str:
        return self._collect().render()

    def render_trace(self, qs: Optional[dict] = None) -> dict:
        """Chrome-trace doc: tracer spans + flight-recorder events
        merged as instant markers (one Perfetto load shows both), or
        the injected ``trace_source`` document (the coordinator's
        offset-corrected fleet merge). ``?since=<seq>``/``?n=`` bound
        the local span window — mirror of ``/events`` paging — so a
        cadence puller doesn't reship the whole ring each tick."""
        if self._trace_source is not None:
            return self._trace_source()
        qs = qs or {}
        first = lambda k: (qs.get(k) or [None])[0]  # noqa: E731
        since = last_n = None
        try:
            if first("since") is not None:
                since = int(first("since"))
            if first("n") is not None:
                last_n = int(first("n"))
        except ValueError:
            pass  # malformed paging params: serve the full window
        from edl_tpu.obs import events as _events

        return _events.default_recorder().to_chrome_doc(
            self.tracer, since_seq=since or 0, last_n=last_n
        )

    def render_events(self, qs: Optional[dict] = None) -> str:
        """JSONL of the events source, filtered by ``rid``/``kind``/
        ``severity`` query params and bounded by ``n`` (newest kept)."""
        qs = qs or {}
        first = lambda k: (qs.get(k) or [None])[0]  # noqa: E731
        rid, kind, sev = first("rid"), first("kind"), first("severity")
        recs = self._events()
        if rid is not None:
            recs = [r for r in recs if (r.get("corr") or {}).get("rid") == rid]
        if kind is not None:
            recs = [r for r in recs if r.get("kind") == kind]
        if sev is not None:
            recs = [r for r in recs if r.get("severity") == sev]
        n = first("n")
        if n is not None:
            try:
                recs = recs[-max(0, int(n)):]
            except ValueError:
                pass
        return "\n".join(
            json.dumps(r, default=str, separators=(",", ":")) for r in recs
        ) + ("\n" if recs else "")


def start_exporter(
    source=None, *, port: int = 0, host: str = "127.0.0.1", tracer=None,
    events_source=None, trace_source=None, history=None,
) -> MetricsExporter:
    """Convenience: construct + start (``port=0`` = ephemeral)."""
    return MetricsExporter(
        source, port=port, host=host, tracer=tracer,
        events_source=events_source, trace_source=trace_source,
        history=history,
    ).start()


def scrape(url: str, path: str = "/metrics", timeout_s: float = 5.0) -> str:
    """GET one endpoint path and return the body text — the client
    side used by ``edl top`` and the CI scrape lane. ``url`` may be a
    bare ``host:port``."""
    from urllib.request import urlopen

    if not url.startswith("http"):
        url = f"http://{url}"
    with urlopen(url.rstrip("/") + path, timeout=timeout_s) as r:
        return r.read().decode()
