"""`edl profile` — roofline reports from live telemetry or bench JSON.

Renders where each phase sits against the chip's peak (the roofline:
MFU for compute-bound phases, bandwidth utilization for memory-bound
ones) plus the HBM balance sheet and compile activity, from either

* a live ``/metrics`` endpoint (any exporter publishing the
  ``edl_mfu{phase}`` / ``edl_bw_util_ratio{phase}`` /
  ``edl_hbm_bytes{category}`` / ``edl_compile_seconds{program}``
  families — serving process, worker, or the coordinator's fleet
  aggregation), or
* a committed ``BENCH_r*.json`` file (the offline twin: train MFU
  rungs, the decode bandwidth ladder, prefill latency).

``--dryrun`` is the CI lane (scripts/run_tests.sh): it runs a tiny
self-contained train window + serving workload on CPU, self-scrapes,
and HARD-ASSERTS the efficiency telemetry is live — non-zero
``edl_mfu{phase}`` for train/prefill/decode, non-zero
``edl_bw_util_ratio``, a non-zero KV entry on the memory ledger,
compile telemetry recorded, and ZERO ``obs.recompile`` events on the
steady-state serving loop after warmup (the runtime twin of `edl
check`'s static recompile-hazard rule).

Report structure (the ``--json`` object)::

    {"source": ..., "peak": {...},
     "phases": {phase: {"mfu": x?, "bw_util": x?}},
     "hbm_bytes": {category: bytes}, "kv_occupancy_ratio": x,
     "compiles": {program: {"count": n, "total_s": s}},
     "recompiles_after_warmup": n}

Rendering is jax-free; only the dryrun touches a device.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from edl_tpu.obs.metrics import parse_prometheus_text

_Fams = Dict[str, List[Tuple[Dict[str, str], float]]]


def _by_label(fams: _Fams, name: str, label: str) -> Dict[str, float]:
    return {
        labels[label]: v
        for labels, v in fams.get(name, ())
        if labels.get(label)
    }


def report_from_fams(fams: _Fams, source: str = "") -> dict:
    """Build the roofline report from parsed Prometheus families."""
    phases: Dict[str, dict] = {}
    for ph, v in _by_label(fams, "edl_mfu", "phase").items():
        phases.setdefault(ph, {})["mfu"] = v
    for ph, v in _by_label(fams, "edl_bw_util_ratio", "phase").items():
        phases.setdefault(ph, {})["bw_util"] = v
    hbm = {
        c: v
        for c, v in _by_label(fams, "edl_hbm_bytes", "category").items()
        if v
    }
    compiles: Dict[str, dict] = {}
    for pg, n in _by_label(fams, "edl_compile_seconds_count", "program").items():
        if n:
            compiles[pg] = {"count": n}
    for pg, s in _by_label(fams, "edl_compile_seconds_sum", "program").items():
        if pg in compiles:
            compiles[pg]["total_s"] = s
    occ = sum(v for _, v in fams.get("edl_kv_occupancy_ratio", ()))
    recompiles = sum(
        v
        for labels, v in fams.get("edl_events_total", ())
        if labels.get("kind") == "obs.recompile"
    )
    return {
        "source": source,
        "peak": None,  # live gauges are already ratios; peak is implicit
        "phases": phases,
        "hbm_bytes": hbm,
        "kv_occupancy_ratio": occ,
        "compiles": compiles,
        "recompiles_after_warmup": recompiles,
    }


def report_from_endpoint(endpoint: str, timeout_s: float = 5.0) -> dict:
    from edl_tpu.obs.exporter import scrape

    text = scrape(endpoint, "/metrics", timeout_s=timeout_s)
    return report_from_fams(parse_prometheus_text(text), source=endpoint)


def report_from_bench(path: str) -> dict:
    """The offline twin: map a BENCH_r*.json round's published figures
    onto roofline rows (train MFU rungs; the decode bandwidth ladder
    whose pct-of-peak the shared cost model computed; prefill)."""
    with open(path) as f:
        doc = json.load(f)
    doc = doc.get("parsed", doc)  # driver wrapper or a bare bench line
    phases: Dict[str, dict] = {}
    for key, phase in (
        ("mfu", "train"),
        ("int8_mfu", "train_int8"),
        ("long_mfu", "train_long"),
        ("int8_long_mfu", "train_int8_long"),
    ):
        v = doc.get(key)
        if v is not None and v > 0:
            phases[phase] = {"mfu": v}
    for rung in doc.get("decode_ladder", []):
        if rung.get("decode_pct_peak_bw", -1) > 0:
            phases[f"decode_b{rung['b']}"] = {
                "bw_util": rung["decode_pct_peak_bw"],
                "tokens_per_s": rung.get("decode_tokens_per_sec"),
            }
    for key, phase in (
        ("decode_int8_pct_peak_bw", "decode_int8"),
        ("decode_int8_b1_pct_peak_bw", "decode_int8_b1"),
    ):
        v = doc.get(key)
        if v is not None and v > 0:
            phases[phase] = {"bw_util": v}
    if doc.get("prefill_s", -1) > 0:
        phases["prefill"] = {"seconds": doc["prefill_s"]}
    peak = None
    if doc.get("peak_tflops"):
        peak = {"tflops": doc["peak_tflops"]}
    hbm = {}
    if doc.get("flagship_state_gb"):
        hbm["train_state"] = doc["flagship_state_gb"] * (1 << 30)
    return {
        "source": path,
        "peak": peak,
        "phases": phases,
        "hbm_bytes": hbm,
        "kv_occupancy_ratio": 0.0,
        "compiles": (
            {"bench.ctr_multistep": {"count": 1, "total_s": doc["compile_s"]}}
            if doc.get("compile_s")
            else {}
        ),
        "recompiles_after_warmup": 0,
    }


def render_report(report: dict) -> str:
    lines = [f"EDL ROOFLINE  {report.get('source', '')}"]
    peak = report.get("peak")
    if peak and peak.get("tflops"):
        lines.append(f"peak: {peak['tflops']:.1f} TFLOP/s (bf16, spec)")
    phases = report.get("phases", {})
    if phases:
        lines.append(f"{'phase':<16} {'mfu':>8} {'bw_util':>8} {'notes':>14}")
        for ph in sorted(phases):
            row = phases[ph]
            mfu = row.get("mfu")
            bw = row.get("bw_util")
            notes = ""
            if row.get("tokens_per_s"):
                notes = f"{row['tokens_per_s']:.0f} tok/s"
            elif row.get("seconds"):
                notes = f"{row['seconds'] * 1e3:.1f} ms"
            lines.append(
                f"{ph:<16} "
                f"{(f'{mfu:.1%}' if mfu is not None else '-'):>8} "
                f"{(f'{bw:.1%}' if bw is not None else '-'):>8} "
                f"{notes:>14}"
            )
    else:
        lines.append("(no efficiency telemetry published yet)")
    hbm = report.get("hbm_bytes") or {}
    if hbm:
        occ = report.get("kv_occupancy_ratio") or 0.0
        lines.append(
            "hbm: "
            + "  ".join(
                f"{c}={v / (1 << 30):.3f}G" for c, v in sorted(hbm.items())
            )
            + (f"  (kv {occ:.1%} occupied)" if occ else "")
        )
    compiles = report.get("compiles") or {}
    if compiles:
        lines.append(
            "compiles: "
            + "  ".join(
                f"{p}×{int(c['count'])}"
                + (
                    f" ({c['total_s']:.2f}s)"
                    if c.get("total_s") is not None
                    else ""
                )
                for p, c in sorted(compiles.items())
            )
        )
    n = report.get("recompiles_after_warmup", 0)
    lines.append(
        f"recompiles after warmup: {int(n)}"
        + ("  <-- steady-state compile, investigate" if n else " (clean)")
    )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the CI dryrun lane


def run_dryrun(metrics_port: Optional[int] = None, steps: int = 4) -> dict:
    """Tiny self-contained efficiency exercise (CPU-safe): a short
    elastic-trainer window with the analytic per-example cost, then a
    warmed serving workload, then hard assertions over the process's
    own telemetry. Returns the report; raises AssertionError when any
    acceptance series is missing/zero or the steady-state loop
    recompiled."""
    import jax
    import numpy as np
    import optax

    from edl_tpu.models import llama
    from edl_tpu.obs import compilewatch
    from edl_tpu.obs import costmodel as cm
    from edl_tpu.obs import events as flight
    from edl_tpu.obs import memledger
    from edl_tpu.obs import metrics as om
    from edl_tpu.runtime.elastic import ElasticTrainer
    from edl_tpu.serving.engine import ContinuousBatchingEngine

    exporter = None
    if metrics_port is not None:
        from edl_tpu.obs.exporter import start_exporter

        exporter = start_exporter(port=metrics_port)
        print(f"# metrics endpoint {exporter.url}/metrics")

    cfg = llama.LlamaConfig.tiny(vocab=128)
    seq = 32

    # -- train window through the REAL elastic wiring ------------------
    trainer = ElasticTrainer(
        llama.make_loss_fn(cfg),
        optax.adam(1e-3),  # real moments: the ledger's "opt" category
        chips_per_worker=1,
        per_chip_batch=2,
        flops_per_example=seq * cm.train_flops_per_token(cfg, seq),
        hbm_bytes_per_example=cm.train_step_bytes(cfg, seq) / 2,
    )
    rng = np.random.RandomState(0)
    trainer.start(llama.init_params(jax.random.PRNGKey(0), cfg), 1)

    def data_fn(batch):
        return llama.synthetic_tokens(rng, batch, seq, cfg.vocab)

    trainer.train_steps(data_fn, steps)

    # -- serving: warm pass, then the steady-state loop ----------------
    def workload(eng):
        for i in range(4):
            eng.submit(f"p{i}", [1 + i, 2, 3], 10)
        eng.run()

    warm = ContinuousBatchingEngine(
        params=trainer.merged_state.params, cfg=cfg,
        max_slots=2, max_len=32, horizon=4,
    )
    workload(warm)
    del warm
    compilewatch.mark_warm()
    rec_before = sum(
        1
        for r in flight.default_recorder().records()
        if r.get("kind") == "obs.recompile"
    )
    eng = ContinuousBatchingEngine(
        params=trainer.merged_state.params, cfg=cfg,
        max_slots=2, max_len=32, horizon=4,
    )
    # hold a mid-flight view so kv occupancy is non-zero at scrape time
    for i in range(3):
        eng.submit(f"s{i}", [3 + i, 1], 12)
    for _ in range(3):
        eng.step()

    # -- self-scrape + hard assertions ---------------------------------
    if exporter is not None:
        from edl_tpu.obs.exporter import scrape

        text = scrape(exporter.url)
    else:
        text = om.default_registry().render()
    fams = parse_prometheus_text(text)
    report = report_from_fams(
        fams, source=exporter.url if exporter else "in-process"
    )

    def val(name, **match):
        return sum(
            v
            for labels, v in fams.get(name, ())
            if all(labels.get(k) == mv for k, mv in match.items())
        )

    for phase in ("train", "decode", "prefill"):
        assert val("edl_mfu", phase=phase) > 0, (
            f"edl_mfu{{phase={phase}}} is zero — the efficiency meter "
            "never fired"
        )
    assert val("edl_bw_util_ratio", phase="decode") > 0, (
        "edl_bw_util_ratio{phase=decode} is zero"
    )
    assert val("edl_hbm_bytes", category="kv") > 0, (
        "edl_hbm_bytes{category=kv} is zero — the KV cache never "
        "registered on the memory ledger"
    )
    for cat in ("params", "opt"):
        assert val("edl_hbm_bytes", category=cat) > 0, (
            f"edl_hbm_bytes{{category={cat}}} is zero"
        )
    assert val("edl_kv_occupancy_ratio") > 0, "kv occupancy gauge is zero"
    assert val("edl_compile_seconds_count") > 0, (
        "edl_compile_seconds has no observations"
    )
    # the acceptance contract: ZERO compiles on the steady-state
    # serving loop after warmup — every program was paid in the warm
    # pass, so a recompile here is the hazard class `edl check` flags
    # statically, observed at runtime
    rec_after = sum(
        1
        for r in flight.default_recorder().records()
        if r.get("kind") == "obs.recompile"
    )
    assert rec_after == rec_before == 0, (
        f"obs.recompile fired {rec_after} time(s) on the steady-state "
        "serving loop"
    )
    # finish the in-flight serving work and fold the ledger crosscheck
    eng.run()
    xc = memledger.default_ledger().crosscheck()
    if xc is not None:
        report["crosscheck"] = xc
    if exporter is not None:
        exporter.stop()
    print(
        f"profile dryrun OK: mfu train/decode/prefill non-zero, "
        f"kv={val('edl_hbm_bytes', category='kv'):.0f}B on ledger, "
        f"{int(val('edl_compiles_total'))} compiles, 0 recompiles "
        "after warmup"
    )
    return report


def is_bench_file(source: str) -> bool:
    return os.path.exists(source) and source.endswith(".json")


def report_for_source(source: str, timeout_s: float = 5.0) -> dict:
    if is_bench_file(source):
        return report_from_bench(source)
    return report_from_endpoint(source, timeout_s=timeout_s)
