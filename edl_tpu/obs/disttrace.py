"""Distributed tracing — cross-process trace propagation, clock
alignment, fleet trace merge, and critical-path/straggler analysis.

The control plane is inherently multi-process (master, pserver group,
elastic trainer group whose membership changes mid-job), but spans
(utils/tracing.py) and flight events (obs/events.py) were per-process:
each carried only a local clock and no identity linking a worker's
span to the coordinator decision that caused it. This module is the
Dapper-style layer that turns those per-process rings into ONE fleet
timeline:

* **trace context** — ``TraceContext(trace_id, span_id, parent_id)``
  carried in a contextvar. Roots are opened at request/step/reshard
  boundaries; ids for *agreed* roots (a step number, a reshard epoch,
  a rid) are DERIVED deterministically, so every process lands on the
  same ``trace_id`` without a network hop, while span ids stay random.
  Tracer spans and flight-recorder events both stamp the active
  context (the hooks installed below), so ``/trace`` and ``/events``
  agree on the same correlation keys.
* **propagation** — :func:`inject`/:func:`extract` move a context
  through any JSON payload (pushed KV windows), and
  :func:`publish_ctx`/:func:`fetch_ctx` ride a coordinator-KV side key
  next to a control verb (the rank-0 ``go`` decision), which is how a
  follower's span gets parented to the leader's publish span — the
  client→server pair the fleet merge links with flow events.
* **clock alignment** — :class:`ClockSync` samples RPC round trips
  against the coordinator's ``TIME`` op and estimates a per-worker
  wall-clock offset with the NTP midpoint estimator, keeping the
  minimum-RTT sample (the midpoint error is bounded by rtt/2, so the
  tightest round trip is the least-jittered estimate). Offsets are
  published to coordinator KV (obs/fleet.py ``clock_key``) and applied
  at merge time: ``t_coordinator ≈ t_worker + offset_s``.
* **fleet merge** — :func:`merge_fleet_trace` takes per-worker span
  windows (pushed on the MetricsPusher cadence) plus offsets and emits
  one Perfetto/chrome-trace document: one synthetic ``pid`` per
  worker (named via ``process_name`` metadata), every timestamp
  offset-corrected onto the coordinator axis, and chrome flow events
  (``ph:"s"``/``"f"``) linking each client span to the server span
  parented to it.
* **analysis** — :func:`critical_path` extracts the longest causal
  chain (per trace/step/reshard-epoch/rid) with per-hop durations and
  gaps; :func:`step_skew`/:func:`barrier_waits` are the straggler
  primitives obs/fleet.py turns into ``edl_step_skew_ratio`` /
  ``edl_barrier_wait_seconds{worker}`` and ``straggler.detected``.

THIS MODULE IS THE ONLY SANCTIONED ACCESSOR of the ``trace_id`` /
``span_id`` / ``parent_id`` keys — everything else goes through the
helpers here (enforced by the ``edl check`` telemetry-conventions
rule), so the wire format can evolve in one place.

jax-free, stdlib-only — the CLI and exporters import this.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "TraceContext",
    "TRACE_KEYS",
    "new_id",
    "derived_trace_id",
    "current",
    "root",
    "enter_root",
    "exit_root",
    "ctx_corr",
    "inject",
    "extract",
    "ids_of",
    "link_attrs",
    "publish_ctx",
    "fetch_ctx",
    "ClockSync",
    "ClockEstimate",
    "span_window_doc",
    "span_window_json",
    "load_span_window",
    "merge_fleet_trace",
    "critical_path",
    "render_critical_path",
    "step_skew",
    "barrier_waits",
]

# the one place these literals may appear (edl check telemetry rule)
TRACE_KEYS = ("trace_id", "span_id", "parent_id")


@dataclass(frozen=True)
class TraceContext:
    """One position in a distributed trace: which trace, which span,
    and which span caused it (None at a root)."""

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, new_id(), self.span_id)


def new_id() -> str:
    """Random 64-bit hex id (span ids, ad-hoc trace roots)."""
    return os.urandom(8).hex()


def derived_trace_id(*parts: Any) -> str:
    """Deterministic trace id from an agreed tuple — e.g.
    ``("step", job, epoch, i)`` or ``("rid", rid)`` — so every process
    opens the SAME trace for the same logical root without exchanging
    ids first."""
    h = hashlib.sha1(":".join(str(p) for p in parts).encode())
    return h.hexdigest()[:16]


_ctx: contextvars.ContextVar[Optional[TraceContext]] = contextvars.ContextVar(
    "edl_disttrace_ctx", default=None
)


def current() -> Optional[TraceContext]:
    return _ctx.get()


def enter_root(*parts: Any, trace_id: Optional[str] = None):
    """Token-based root entry (for loop bodies where a ``with`` is
    awkward). Deterministic id when ``parts`` are given, random
    otherwise. Pair with :func:`exit_root`."""
    tid = trace_id or (derived_trace_id(*parts) if parts else new_id())
    return _ctx.set(TraceContext(tid, new_id(), None))


def exit_root(token) -> None:
    _ctx.reset(token)


@contextlib.contextmanager
def root(*parts: Any, trace_id: Optional[str] = None):
    """Open a trace root for the duration of the block."""
    token = enter_root(*parts, trace_id=trace_id)
    try:
        yield _ctx.get()
    finally:
        exit_root(token)


@contextlib.contextmanager
def remote_child(ctx: Optional[TraceContext]):
    """Continue a trace received from another process: the block runs
    in a fresh span parented to the REMOTE span (the server half of a
    client→server pair). No-op when ``ctx`` is None."""
    if ctx is None:
        yield None
        return
    token = _ctx.set(ctx.child())
    try:
        yield _ctx.get()
    finally:
        _ctx.reset(token)


# ---------------------------------------------------------------------------
# dict propagation — the only sanctioned read/write of the trace keys


def inject(d: Dict[str, Any], ctx: Optional[TraceContext] = None) -> Dict[str, Any]:
    """Stamp ``d`` with the context's ids (the active one by default);
    returns ``d``. No-op when no context is active."""
    ctx = ctx or _ctx.get()
    if ctx is not None:
        d["trace_id"] = ctx.trace_id
        d["span_id"] = ctx.span_id
        if ctx.parent_id is not None:
            d["parent_id"] = ctx.parent_id
    return d


def extract(d: Dict[str, Any]) -> Optional[TraceContext]:
    """Read a context back out of a dict (``None`` when absent)."""
    tid = d.get("trace_id")
    sid = d.get("span_id")
    if not tid or not sid:
        return None
    return TraceContext(str(tid), str(sid), d.get("parent_id"))


def ids_of(d: Dict[str, Any]) -> Tuple[Optional[str], Optional[str], Optional[str]]:
    """(trace_id, span_id, parent_id) of a record's dict, Nones when
    unset — the read helper analysis/CLI code uses instead of
    hand-rolled key access."""
    return (d.get("trace_id"), d.get("span_id"), d.get("parent_id"))


def without_ids(d: Dict[str, Any]) -> Dict[str, Any]:
    """A copy of ``d`` with the trace keys removed — for renderers
    (postmortem timelines) that must not drown the human view in
    ids."""
    return {k: v for k, v in d.items() if k not in TRACE_KEYS}


def ctx_corr() -> Dict[str, str]:
    """The active context as correlation keys for a flight-recorder
    event (trace + span of the enclosing tracer span). Empty when no
    trace is active — events off any traced path cost one contextvar
    read."""
    ctx = _ctx.get()
    if ctx is None:
        return {}
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}


def link_attrs(remote: TraceContext) -> Dict[str, str]:
    """Span attrs for a LOCAL span caused by a remote one: fresh span
    id, parented to the remote span — the server half of a flow link."""
    return {
        "trace_id": remote.trace_id,
        "span_id": new_id(),
        "parent_id": remote.span_id,
    }


# ---------------------------------------------------------------------------
# coordinator-KV side-key propagation (values are newline-free strings)


def ctx_kv_key(key: str) -> str:
    """The side key carrying the trace context for a control-plane KV
    value at ``key`` (the value formats themselves — ``{i}:{verb}``
    etc. — stay untouched)."""
    return key + "#trace"


def publish_ctx(kv_put: Callable[[str, str], None], key: str,
                tag: str = "", ctx: Optional[TraceContext] = None) -> None:
    """Publish the active context next to the control value at
    ``key``. ``tag`` scopes the context to one decision (e.g. the step
    number) so a reader can reject a stale leftover."""
    ctx = ctx or _ctx.get()
    if ctx is None:
        return
    kv_put(ctx_kv_key(key), f"{tag}:{ctx.trace_id}:{ctx.span_id}")


def fetch_ctx(kv_get: Callable[[str], Optional[str]], key: str,
              tag: str = "") -> Optional[TraceContext]:
    """Read a published context back; None when absent, malformed, or
    tagged for a different decision."""
    try:
        v = kv_get(ctx_kv_key(key))
    # edl: no-lint[silent-failure] best-effort ctx fetch on the step hot path: a missed link costs one flow arrow, and logging per step would be noisier than the loss
    except Exception:
        return None
    if not v:
        return None
    parts = v.split(":")
    if len(parts) != 3 or parts[0] != tag:
        return None
    return TraceContext(parts[1], parts[2], None)


# ---------------------------------------------------------------------------
# clock alignment — NTP midpoint over coordinator round trips


@dataclass
class ClockEstimate:
    """``offset_s`` is what to ADD to this process's wall clock to
    land on the reference (coordinator) axis; ``rtt_s`` is the round
    trip of the winning sample (the estimator's error bound is
    rtt/2)."""

    offset_s: float
    rtt_s: float
    n: int

    def to_json(self) -> str:
        return json.dumps(
            {"offset_s": self.offset_s, "rtt_s": self.rtt_s, "n": self.n},
            separators=(",", ":"),
        )

    @staticmethod
    def from_json(raw: str) -> Optional["ClockEstimate"]:
        try:
            d = json.loads(raw)
            return ClockEstimate(
                float(d["offset_s"]), float(d["rtt_s"]), int(d.get("n", 1))
            )
        except (ValueError, TypeError, KeyError):
            return None


class ClockSync:
    """Per-process wall-clock offset estimator against a reference
    clock reachable only by RPC.

    Each sample brackets one ``remote_time()`` round trip with local
    wall-clock reads: ``offset = t_remote - (t0 + t1) / 2`` (the NTP
    midpoint — exact when the two legs are symmetric, wrong by at most
    rtt/2 otherwise). Jitter filter: keep the MINIMUM-RTT sample, the
    one with the tightest error bound; averaging would let one slow,
    asymmetric round trip poison the estimate.
    """

    def __init__(self, clock: Callable[[], float] = time.time):
        self.clock = clock
        self.last: Optional[ClockEstimate] = None
        self._t_sampled = 0.0

    def sample(self, remote_time: Callable[[], Optional[float]],
               n: int = 5) -> Optional[ClockEstimate]:
        """Take ``n`` round trips; returns (and retains) the best
        estimate, or None when the remote clock is unreachable or the
        op is unsupported (an old coordinator binary)."""
        best: Optional[ClockEstimate] = None
        got = 0
        for _ in range(max(1, n)):
            t0 = self.clock()
            try:
                ts = remote_time()
            # edl: no-lint[silent-failure] a failed round trip just shrinks the sample set; the caller surfaces a fully-failed burst as None
            except Exception:
                continue
            t1 = self.clock()
            if ts is None:
                continue
            got += 1
            est = ClockEstimate(ts - (t0 + t1) / 2.0, t1 - t0, 0)
            if best is None or est.rtt_s < best.rtt_s:
                best = est
        if best is not None:
            best.n = got
            self.last = best
            self._t_sampled = time.monotonic()
        return best

    def maybe_sample(self, remote_time, n: int = 5,
                     min_interval_s: float = 30.0) -> Optional[ClockEstimate]:
        """Throttled re-sample for periodic callers (the metrics-push
        cadence): at most one burst per ``min_interval_s``."""
        if self.last is not None and (
            time.monotonic() - self._t_sampled < min_interval_s
        ):
            return self.last
        return self.sample(remote_time, n=n)


# ---------------------------------------------------------------------------
# span windows — what a worker pushes through coordinator KV


def span_window_doc(tracer=None, last_n: int = 128) -> Dict[str, Any]:
    """The newest ``last_n`` tracer spans as a JSON-able doc with
    WALL-clock start times (``t_wall = tracer.t0_wall + start_s``), so
    windows from different processes can land on one axis once their
    clock offsets are known."""
    if tracer is None:
        from edl_tpu.utils import tracing

        tracer = tracing.tracer()
    spans, dropped = tracer._snapshot()
    spans = spans[-last_n:]
    return {
        "meta": {
            "pid": os.getpid(),
            "dropped": dropped,
            "retained": len(spans),
            "max_seq": max((s.seq for s in spans), default=0),
        },
        "spans": [
            {
                "name": s.name,
                "seq": s.seq,
                "t_wall": tracer.t0_wall + s.start_s,
                "dur_s": s.dur_s,
                "tid": s.thread % 2**31,
                "args": dict(s.attrs),
            }
            for s in spans
        ],
    }


def span_window_json(tracer=None, last_n: int = 128) -> str:
    """Single-line form of :func:`span_window_doc` (coordinator KV is
    a line protocol — the pushed value must not contain newlines)."""
    return json.dumps(span_window_doc(tracer, last_n), default=str,
                      separators=(",", ":"))


def load_span_window(raw: Any) -> Optional[Dict[str, Any]]:
    """Parse a pushed span window; None when undecodable. Torn or
    partial windows degrade to whatever parses: records missing their
    required fields are skipped, never fatal."""
    if isinstance(raw, dict):
        doc = raw
    else:
        try:
            doc = json.loads(raw)
        except (ValueError, TypeError):
            return None
    if not isinstance(doc, dict):
        return None
    spans = []
    for s in doc.get("spans") or []:
        if not isinstance(s, dict):
            continue
        if "name" not in s or "t_wall" not in s:
            continue  # torn record
        try:
            spans.append(
                {
                    "name": str(s["name"]),
                    "seq": int(s.get("seq", 0)),
                    "t_wall": float(s["t_wall"]),
                    "dur_s": float(s.get("dur_s", 0.0)),
                    "tid": int(s.get("tid", 0)),
                    "args": dict(s.get("args") or {}),
                }
            )
        except (ValueError, TypeError):
            continue
    return {"meta": dict(doc.get("meta") or {}), "spans": spans}


# ---------------------------------------------------------------------------
# fleet merge — one offset-corrected Perfetto document


def merge_fleet_trace(
    windows: Dict[str, Any],
    offsets: Optional[Dict[str, float]] = None,
    extra_meta: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Merge per-worker span windows into one chrome-trace document.

    ``windows`` maps worker name -> raw window JSON (or parsed doc);
    ``offsets`` maps worker name -> seconds to ADD to that worker's
    wall clock (ClockSync estimates; missing = 0). Each worker gets a
    synthetic ``pid`` named via ``process_name`` metadata; timestamps
    are offset-corrected and rebased so the earliest span starts at 0;
    chrome flow events (``ph:"s"`` on the client span, ``ph:"f"`` on
    the server span) link every parent→child span pair that crosses a
    process boundary. Undecodable windows are skipped and counted in
    the top-level ``skipped_windows``.
    """
    offsets = offsets or {}
    docs: Dict[str, Dict[str, Any]] = {}
    skipped = 0
    for worker, raw in sorted(windows.items()):
        doc = load_span_window(raw)
        if doc is None:
            skipped += 1
            continue
        docs[worker] = doc

    # corrected wall time per span, then rebase to the earliest
    corrected: List[Tuple[str, int, Dict[str, Any], float]] = []
    for pid, (worker, doc) in enumerate(sorted(docs.items()), start=1):
        off = float(offsets.get(worker, 0.0))
        for s in doc["spans"]:
            corrected.append((worker, pid, s, s["t_wall"] + off))
    base = min((t for *_x, t in corrected), default=0.0)

    events: List[Dict[str, Any]] = []
    for pid, (worker, _doc) in enumerate(sorted(docs.items()), start=1):
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": worker},
            }
        )
    by_span_id: Dict[str, Dict[str, Any]] = {}
    for worker, pid, s, t in corrected:
        ev = {
            "name": s["name"],
            "ph": "X",
            "ts": (t - base) * 1e6,
            "dur": s["dur_s"] * 1e6,
            "pid": pid,
            "tid": s["tid"],
            "seq": s["seq"],
            "args": {"worker": worker, **s["args"]},
        }
        events.append(ev)
        sid = ids_of(s["args"])[1]
        if sid:
            by_span_id[sid] = ev

    # flow events: client span -> the server span parented to it,
    # exactly one link per parent/child pair (dedup by child span id)
    flows = 0
    for ev in list(events):
        if ev.get("ph") != "X":
            continue
        _tid, sid, parent = ids_of(ev["args"])
        if not parent or parent not in by_span_id:
            continue
        src = by_span_id[parent]
        # only cross-PROCESS causality gets an arrow: intra-process
        # parent/child pairs are already visible as span nesting
        if src is ev or src["pid"] == ev["pid"]:
            continue
        fid = f"f{flows}"
        flows += 1
        events.append(
            {
                "name": "rpc", "cat": "disttrace", "ph": "s", "id": fid,
                "pid": src["pid"], "tid": src["tid"],
                # bind the arrow tail inside the client span
                "ts": src["ts"] + max(src["dur"] / 2, 0.0),
            }
        )
        events.append(
            {
                "name": "rpc", "cat": "disttrace", "ph": "f", "bp": "e",
                "id": fid, "pid": ev["pid"], "tid": ev["tid"], "ts": ev["ts"],
            }
        )
    doc = {
        "traceEvents": events,
        "base_t_wall": base,
        "workers": sorted(docs),
        "flow_links": flows,
        "skipped_windows": skipped,
    }
    if extra_meta:
        doc.update(extra_meta)
    return doc


# ---------------------------------------------------------------------------
# critical path — the longest causal chain


def _doc_spans(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Normalize a chrome-trace doc's X events (a merged fleet doc or
    a process-local /trace) into span records with seconds units."""
    pid_names = {}
    for e in doc.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e.get("pid")] = (e.get("args") or {}).get("name")
    out = []
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        out.append(
            {
                "name": e.get("name", "?"),
                "t_s": float(e.get("ts", 0.0)) / 1e6,
                "dur_s": float(e.get("dur", 0.0)) / 1e6,
                "worker": args.get("worker")
                or pid_names.get(e.get("pid"))
                or str(e.get("pid", "?")),
                "args": args,
            }
        )
    return out


def _matches(span: Dict[str, Any], rid, step, reshard_epoch, trace_id) -> bool:
    a = span["args"]
    if trace_id is not None and ids_of(a)[0] != trace_id:
        return False
    if rid is not None:
        rids = a.get("rids") or ()
        if a.get("rid") != rid and rid not in rids:
            return False
    if step is not None and a.get("step") != step:
        return False
    if reshard_epoch is not None and a.get("reshard_epoch") != reshard_epoch:
        return False
    return True


def critical_path(
    doc: Dict[str, Any],
    rid: Optional[str] = None,
    step: Optional[int] = None,
    reshard_epoch: Optional[int] = None,
    trace_id: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """The longest causal chain of spans matching the filter, as hops
    ``{name, worker, t_s, dur_s, gap_s}``.

    Selection: explicit ``trace_id``, or the deterministic root id for
    a ``reshard_epoch`` (every process derives the same one), or attr
    match on ``rid``/``rids``/``step``. Chain construction prefers the
    parent-link forest (maximum summed duration root→leaf, the Dapper
    critical path); spans without links fall back to the time-ordered
    sequence — for a single request's sequential hops the two
    coincide."""
    spans = _doc_spans(doc)
    if reshard_epoch is not None and trace_id is None:
        # accept either the derived reshard root id or an explicit attr
        want_tid = derived_trace_id("reshard", reshard_epoch)
        sel = [
            s for s in spans
            if ids_of(s["args"])[0] == want_tid
            or s["args"].get("reshard_epoch") == reshard_epoch
        ]
        if rid is not None or step is not None:
            sel = [s for s in sel if _matches(s, rid, step, None, None)]
    else:
        sel = [
            s for s in spans if _matches(s, rid, step, reshard_epoch, trace_id)
        ]
    if not sel:
        return []

    by_id: Dict[str, Dict[str, Any]] = {}
    children: Dict[str, List[Dict[str, Any]]] = {}
    linked = False
    for s in sel:
        _t, sid, parent = ids_of(s["args"])
        if sid:
            by_id[sid] = s
    for s in sel:
        _t, _sid, parent = ids_of(s["args"])
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
            linked = True

    if linked:
        # longest-total-duration root->leaf chain over the link forest
        memo: Dict[int, Tuple[float, List[Dict[str, Any]]]] = {}

        def best_chain(s) -> Tuple[float, List[Dict[str, Any]]]:
            key = id(s)
            if key in memo:
                return memo[key]
            memo[key] = (s["dur_s"], [s])  # cycle guard
            _t, sid, _p = ids_of(s["args"])
            best = (s["dur_s"], [s])
            for c in children.get(sid or "", ()):
                d, chain = best_chain(c)
                if s["dur_s"] + d > best[0]:
                    best = (s["dur_s"] + d, [s] + chain)
            memo[key] = best
            return best

        roots = [
            s for s in sel
            if not (ids_of(s["args"])[2] and ids_of(s["args"])[2] in by_id)
        ]
        chain = max((best_chain(r) for r in roots), key=lambda x: x[0])[1]
    else:
        chain = sorted(sel, key=lambda s: s["t_s"])

    hops: List[Dict[str, Any]] = []
    prev_end: Optional[float] = None
    for s in chain:
        hops.append(
            {
                "name": s["name"],
                "worker": s["worker"],
                "t_s": s["t_s"],
                "dur_s": s["dur_s"],
                "gap_s": max(s["t_s"] - prev_end, 0.0)
                if prev_end is not None else 0.0,
            }
        )
        prev_end = s["t_s"] + s["dur_s"]
    return hops


def render_critical_path(hops: List[Dict[str, Any]]) -> str:
    if not hops:
        return "(empty critical path: no spans matched the filter)"
    total = sum(h["dur_s"] for h in hops)

    def ms(v: float) -> str:
        return f"{v * 1e3:.1f}ms" if v < 1.0 else f"{v:.3f}s"

    lines = [f"critical path: {len(hops)} hops, {ms(total)} busy"]
    for i, h in enumerate(hops, 1):
        gap = f"  (+{ms(h['gap_s'])} gap)" if h["gap_s"] > 0 else ""
        lines.append(
            f"  {i:>2}. [{h['worker']}] {h['name']:<26} "
            f"t={ms(h['t_s']):>9}  dur={ms(h['dur_s']):>9}{gap}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# straggler analysis primitives


def step_skew(
    per_worker_p50: Dict[str, float]
) -> Tuple[float, Optional[str], float]:
    """(skew_ratio, slowest_worker, fleet_median) from per-worker step
    p50s: skew = slowest p50 / fleet median (1.0 = perfectly even).
    Needs >= 2 reporting workers to mean anything; returns (0, None,
    0) otherwise."""
    vals = {w: v for w, v in per_worker_p50.items() if v > 0}
    if len(vals) < 2:
        return 0.0, None, 0.0
    ordered = sorted(vals.values())
    n = len(ordered)
    median = (
        ordered[n // 2]
        if n % 2
        else (ordered[n // 2 - 1] + ordered[n // 2]) / 2.0
    )
    slow = max(vals, key=lambda w: vals[w])
    return (vals[slow] / median if median > 0 else 0.0), slow, median


def barrier_waits(arrivals: Dict[str, float]) -> Dict[str, float]:
    """Per-worker barrier wait attributed to the LAST arriver: each
    worker waits ``t_last - t_self`` (the straggler itself waits 0).
    ``arrivals`` are offset-corrected wall times of each worker's
    arrival at the same barrier (e.g. its ``worker.join`` for one
    membership epoch)."""
    if not arrivals:
        return {}
    t_last = max(arrivals.values())
    return {w: max(t_last - t, 0.0) for w, t in arrivals.items()}


def barrier_waits_from_events(
    events: Iterable[Dict[str, Any]], kind: str = "worker.join"
) -> Dict[str, float]:
    """Barrier waits for the LATEST epoch with >= 2 arrivals, from a
    (merged, offset-corrected) fleet event log. Arrival = the worker's
    ``worker.join`` for that epoch."""
    by_epoch: Dict[Any, Dict[str, float]] = {}
    for e in events:
        if e.get("kind") != kind:
            continue
        corr = e.get("corr") or {}
        attrs = e.get("attrs") or {}
        w = corr.get("worker")
        ep = attrs.get("epoch", corr.get("reshard_epoch"))
        if w is None or ep is None:
            continue
        # first join per (epoch, worker) wins: re-registration isn't
        # a barrier arrival
        by_epoch.setdefault(ep, {}).setdefault(str(w), float(e.get("t_wall", 0.0)))
    candidates = [
        (ep, arr) for ep, arr in by_epoch.items() if len(arr) >= 2
    ]
    if not candidates:
        return {}
    _ep, arrivals = max(candidates, key=lambda x: max(x[1].values()))
    return barrier_waits(arrivals)


# ---------------------------------------------------------------------------
# tracer integration — every span carries the active context, and a
# span body runs inside its own child context (so nested spans and the
# events emitted within parent correctly)


def _span_enter():
    cur = _ctx.get()
    if cur is None:
        return None, None
    child = cur.child()
    token = _ctx.set(child)
    return token, {
        "trace_id": child.trace_id,
        "span_id": child.span_id,
        "parent_id": child.parent_id,
    }


def _span_exit(token) -> None:
    if token is not None:
        _ctx.reset(token)


def _install() -> None:
    from edl_tpu.utils import tracing

    tracing.set_span_context_hooks(_span_enter, _span_exit)


_install()
