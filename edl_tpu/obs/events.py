"""Flight recorder — a bounded ring of typed, correlated events.

Metrics (obs/metrics.py) aggregate away causality and tracer spans
(utils/tracing.py) are process-local durations with no request/step
identity; neither can explain ONE incident after the fact. This module
is the causally-ordered event log production elastic systems treat as
the primary debugging surface: every autonomous control-plane decision
— admit, evict, reshard, retry, recover — lands here as one typed
event ``{seq, t_wall, kind, severity, correlation, attrs}`` with a
monotonically increasing sequence number, so "what happened to request
r17" or "what followed fault #3" is a filter, not a log grep.

Correlation keys are first-class (``rid`` for serving requests,
``step`` for checkpoints, ``reshard_epoch`` for elastic rescales,
``site`` for injected faults, ``worker`` for fleet identity), which is
what lets ``edl postmortem`` (obs/postmortem.py) rebuild per-request
timelines and fault→recovery chains across subsystems.

Design constraints, in order:

* **cheap, always-on** — one lock acquire + a deque append per event;
  sites sit on per-block / per-request / per-reshard paths, never
  per-token. The ring is bounded (drop-OLDEST, keeping the events
  closest to the incident) and evictions are counted
  (``dropped`` + ``edl_events_dropped_total``) so a truncated window
  is never mistaken for a complete one.
* **jax-free, stdlib-only** — the CLI and exporters import this.
* **a black box** — :func:`crash_dump` writes the ring as JSONL under
  ``$EDL_BLACKBOX_DIR`` (no-op when unset, never raises): recovery
  paths call it BEFORE rebuilding state, so the dump holds the events
  leading up to the incident.

Every emit also increments ``edl_events_total{kind}`` in the process
registry, which is what ``edl top``'s incident strip and fleet
dashboards consume without opening dumps. Warn/error KV-log lines
mirror in as ``log.warn`` / ``log.error`` events via the
``utils/logging.py`` sink (installed at import), so stray error logs
land on the same timeline as the decisions around them.

Usage::

    from edl_tpu.obs import events
    events.emit("serve.admit", rid="r3", slot=2, prompt_len=17)
    events.emit("serve.recover", severity="warn", error="...", rids=[...])
    events.default_recorder().dump("/tmp/flight.jsonl")
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Iterable, List, Optional

from edl_tpu.obs import disttrace
from edl_tpu.utils import logging as edl_logging

__all__ = [
    "Event",
    "FlightRecorder",
    "default_recorder",
    "reset_default_recorder",
    "emit",
    "crash_dump",
    "load_jsonl",
    "CORRELATION_KEYS",
]

# the first-class correlation schema: every key a timeline can be
# grouped by (postmortem filters on these, everything else is attrs)
CORRELATION_KEYS = ("rid", "step", "reshard_epoch", "site", "worker")

SEVERITIES = ("info", "warn", "error")


@dataclass
class Event:
    """One recorded decision/incident. ``t_wall`` is epoch seconds
    (human + cross-process ordering), ``t_mono`` is process
    ``perf_counter`` (merges onto the tracer's span timeline)."""

    seq: int
    t_wall: float
    t_mono: float
    kind: str
    severity: str = "info"
    corr: Dict[str, Any] = field(default_factory=dict)
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_record(self) -> Dict[str, Any]:
        return {
            "seq": self.seq,
            "t_wall": self.t_wall,
            "t_mono": self.t_mono,
            "kind": self.kind,
            "severity": self.severity,
            "corr": dict(self.corr),
            "attrs": dict(self.attrs),
        }


class FlightRecorder:
    """Thread-safe bounded ring of :class:`Event`.

    Appends are O(1); past ``max_events`` the OLDEST event is evicted
    and counted in ``dropped`` (the events nearest the incident are
    the ones worth keeping). ``counts()`` keeps monotonic per-kind
    totals that SURVIVE ring eviction — accounting never silently
    shrinks with the window.
    """

    def __init__(self, max_events: int = 8192, clock=time.time):
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        self.max_events = max_events
        self.clock = clock
        self._lock = threading.Lock()
        self._events: Deque[Event] = deque()
        self._seq = 0
        self.dropped = 0
        self._counts: Dict[str, int] = {}
        self._context: Dict[str, Any] = {}

    # -- recording ----------------------------------------------------------

    def set_context(self, **corr: Any) -> None:
        """Default correlation merged into every subsequent event —
        e.g. a worker process stamps ``worker=<id>`` once at bring-up
        so its whole timeline is fleet-attributable."""
        with self._lock:
            for k, v in corr.items():
                if v is None:
                    self._context.pop(k, None)
                else:
                    self._context[k] = v

    def emit(
        self,
        kind: str,
        severity: str = "info",
        *,
        rid: Optional[str] = None,
        step: Optional[int] = None,
        reshard_epoch: Optional[int] = None,
        site: Optional[str] = None,
        worker: Optional[str] = None,
        **attrs: Any,
    ) -> Event:
        """Record one event. Correlation keys are keyword-only and
        land in ``corr``; everything else is free-form ``attrs``."""
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}")
        t_wall = self.clock()
        t_mono = time.perf_counter()
        explicit = {
            "rid": rid, "step": step, "reshard_epoch": reshard_epoch,
            "site": site, "worker": worker,
        }
        # active distributed-trace context (obs/disttrace): events on a
        # traced path carry the enclosing span's trace/span ids, which
        # is how /events?rid= and /trace agree on one correlation key.
        # One contextvar read when no trace is active.
        tctx = disttrace.ctx_corr()
        with self._lock:
            corr = dict(self._context)
            if tctx:
                corr.update(tctx)
            corr.update((k, v) for k, v in explicit.items() if v is not None)
            self._seq += 1
            ev = Event(self._seq, t_wall, t_mono, kind, severity, corr, attrs)
            if len(self._events) >= self.max_events:
                self._events.popleft()
                self.dropped += 1
                dropped_now = True
            else:
                dropped_now = False
            self._events.append(ev)
            self._counts[kind] = self._counts.get(kind, 0) + 1
        _obs_count(kind, dropped_now)
        return ev

    # -- views --------------------------------------------------------------

    def events(
        self,
        kind: Optional[str] = None,
        rid: Optional[str] = None,
        severity: Optional[str] = None,
    ) -> List[Event]:
        with self._lock:
            out = list(self._events)
        return [
            e for e in out
            if (kind is None or e.kind == kind)
            and (rid is None or e.corr.get("rid") == rid)
            and (severity is None or e.severity == severity)
        ]

    def records(
        self,
        kind: Optional[str] = None,
        rid: Optional[str] = None,
        severity: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        """The JSON-able dict view — what the exporter's ``/events``
        serves, the fleet push publishes, and postmortem consumes."""
        return [e.to_record() for e in self.events(kind, rid, severity)]

    def counts(self) -> Dict[str, int]:
        """Monotonic per-kind totals (survive ring eviction)."""
        with self._lock:
            return dict(self._counts)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._counts.clear()

    # -- serialization ------------------------------------------------------

    def _meta_record(self, retained: int) -> Dict[str, Any]:
        # callers (to_jsonl/window_json) snapshot events BEFORE this,
        # so the lock is free to take here; the unguarded `dropped`
        # read was an `edl check` lockset-race finding
        with self._lock:
            dropped = self.dropped
        return {
            "meta": {
                "dropped": dropped,
                "max_events": self.max_events,
                "retained": retained,
                "pid": os.getpid(),
            }
        }

    def to_jsonl(self, last_n: Optional[int] = None) -> str:
        """JSONL dump: one meta line (ring accounting — a reader must
        see truncation) followed by one line per event, oldest first."""
        evs = self.events()
        if last_n is not None:
            evs = evs[-last_n:]
        lines = [json.dumps(self._meta_record(len(evs)), default=str)]
        lines.extend(
            json.dumps(e.to_record(), default=str, separators=(",", ":"))
            for e in evs
        )
        return "\n".join(lines) + "\n"

    def recent_jsonl(self, last_n: int = 256) -> str:
        """The newest ``last_n`` events as JSONL (dumps/debugging)."""
        return self.to_jsonl(last_n=last_n)

    def window_json(self, last_n: int = 256) -> str:
        """The fleet push window as ONE line — coordinator KV is a
        line protocol (``PUT k v\\n``), so the pushed value must not
        contain newlines. :func:`load_jsonl` accepts this doc form
        alongside plain JSONL."""
        evs = self.events()[-last_n:]
        return json.dumps(
            {
                **self._meta_record(len(evs)),
                "events": [e.to_record() for e in evs],
            },
            default=str,
            separators=(",", ":"),
        )

    def dump(self, path: str) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_jsonl())
        return path

    # -- Perfetto merge -----------------------------------------------------

    def to_chrome_events(self, tracer=None) -> List[Dict[str, Any]]:
        """Catapult instant events ("i"), aligned to the TRACER's
        timebase so they interleave with its duration spans in
        Perfetto/chrome://tracing."""
        if tracer is None:
            from edl_tpu.utils import tracing

            tracer = tracing.tracer()
        t0 = tracer.t0
        return [
            {
                "name": e.kind,
                "ph": "i",
                "s": "p",  # process-scoped instant marker
                "ts": (e.t_mono - t0) * 1e6,
                "pid": os.getpid(),
                "tid": 0,
                "args": {"severity": e.severity, **e.corr, **e.attrs},
            }
            for e in self.events()
        ]

    def to_chrome_doc(
        self, tracer=None, since_seq: int = 0, last_n=None
    ) -> Dict[str, Any]:
        """The tracer's chrome-trace document with this recorder's
        events merged in as instant events — one Perfetto load shows
        spans AND the decisions between them. Served by the exporter's
        ``/trace``. ``since_seq``/``last_n`` bound the SPAN window
        (tracer-side paging; instant markers are comparatively few and
        ride along whole)."""
        if tracer is None:
            from edl_tpu.utils import tracing

            tracer = tracing.tracer()
        doc = tracer.to_chrome_doc(since_seq=since_seq, last_n=last_n)
        doc["traceEvents"].extend(self.to_chrome_events(tracer))
        with self._lock:
            doc["eventsDropped"] = self.dropped
        return doc


def _obs_count(kind: str, dropped: bool) -> None:
    # resolved per emit so a registry swap in tests takes effect; the
    # get-or-create is one lock + dict hit (obs/metrics.py)
    from edl_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.default_registry()
    reg.counter(
        "edl_events_total", "flight-recorder events by kind", ("kind",)
    ).inc(kind=kind)
    if dropped:
        reg.counter(
            "edl_events_dropped_total",
            "flight-recorder events evicted from the bounded ring",
        ).inc()


# ---------------------------------------------------------------------------
# process-wide default recorder


def _ring_size() -> int:
    try:
        return max(1, int(os.environ.get("EDL_EVENTS_MAX", "8192")))
    except ValueError:
        return 8192


_default = FlightRecorder(max_events=_ring_size())
_default_lock = threading.Lock()


def default_recorder() -> FlightRecorder:
    return _default


def reset_default_recorder(max_events: Optional[int] = None) -> FlightRecorder:
    """Swap in a fresh default recorder (tests); returns the new one."""
    global _default
    with _default_lock:
        _default = FlightRecorder(max_events=max_events or _ring_size())
    return _default


def emit(kind: str, severity: str = "info", **kw: Any) -> Event:
    """Record into the process-wide default recorder."""
    return _default.emit(kind, severity, **kw)


# ---------------------------------------------------------------------------
# the black box: crash dumps


_dump_seq = 0


def crash_dump(tag: str, err: Optional[BaseException] = None) -> Optional[str]:
    """Dump the default recorder's ring to ``$EDL_BLACKBOX_DIR`` —
    called by recovery paths (serving ``_recover``, the elastic
    trainer's unhandled-exception path) BEFORE they rebuild state, so
    the file holds the timeline leading up to the incident. No-op
    (returns None) when the env var is unset; NEVER raises — the black
    box must not take the recovering process down with it."""
    global _dump_seq
    d = os.environ.get("EDL_BLACKBOX_DIR", "").strip()
    if not d:
        return None
    try:
        rec = default_recorder()
        if err is not None:
            # kind follows site.verb so the postmortem's chain matcher
            # can group it (was bare "crash"; edl check
            # telemetry-conventions)
            rec.emit(
                "blackbox.crash", severity="error",
                error=f"{type(err).__name__}: {err}", tag=tag,
            )
        with _default_lock:
            _dump_seq += 1
            n = _dump_seq
        path = os.path.join(d, f"blackbox-{tag}-{os.getpid()}-{n}.jsonl")
        return rec.dump(path)
    # edl: no-lint[silent-failure] the black box is best-effort BY CONTRACT: it runs inside recovery paths and must never take them down
    except Exception:  # pragma: no cover - the black box is best-effort
        return None


# ---------------------------------------------------------------------------
# loading dumps back


def load_jsonl(source: str) -> List[Dict[str, Any]]:
    """Parse a flight-recorder JSONL dump (a path or the raw text)
    into event records, skipping meta lines and tolerating truncated
    trailing lines (a crash dump may be cut short). Raises ValueError
    when nothing parseable is found."""
    if "\n" not in source and os.path.exists(source):
        with open(source) as f:
            text = f.read()
    else:
        text = source
    out: List[Dict[str, Any]] = []
    meta: Optional[Dict[str, Any]] = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail of a crash dump
        if not isinstance(rec, dict):
            continue
        if isinstance(rec.get("events"), list):
            # the single-line window-doc form (window_json)
            meta = rec.get("meta", meta)
            for e in rec["events"]:
                if isinstance(e, dict) and "kind" in e:
                    e.setdefault("corr", {})
                    e.setdefault("attrs", {})
                    out.append(e)
            continue
        if "meta" in rec and "kind" not in rec:
            meta = rec["meta"]
            continue
        if "kind" in rec:
            rec.setdefault("corr", {})
            rec.setdefault("attrs", {})
            out.append(rec)
    if not out and meta is None:
        raise ValueError("no flight-recorder events in input")
    if meta is not None and out:
        # surface ring truncation to the analyzer without a side channel
        out[0].setdefault("attrs", {})
        out[0]["attrs"].setdefault("_ring_dropped", meta.get("dropped", 0))
    return out


# ---------------------------------------------------------------------------
# log bridge: warn/error KV-log lines mirror onto the event timeline
# (the one-line hook lives in utils/logging.py; installing the sink
# here means the bridge is on exactly when a recorder exists)


def _log_event(level: str, logger: str, msg: str, kv: Dict[str, Any]) -> None:
    try:
        corr = {k: kv[k] for k in CORRELATION_KEYS if k in kv}
        attrs = {k: v for k, v in kv.items() if k not in CORRELATION_KEYS}
        _default.emit(
            f"log.{level}",
            severity=level if level in SEVERITIES else "warn",
            logger=logger,
            msg=msg,
            **corr,
            **attrs,
        )
    # edl: no-lint[silent-failure] the log->event sink itself: logging a sink failure would recurse into the sink
    except Exception:  # pragma: no cover - telemetry must never raise
        pass


edl_logging.set_event_sink(_log_event)
