"""Fleet-wide telemetry plumbing — worker push, coordinator
aggregation, tracer bridging, and monitor-sample exposition.

The reference collector polls the cluster every 10 s and the
autoscaler re-targets jobs from that census; here the equivalent data
plane is: every worker pushes a JSON snapshot of its process-local
:class:`~edl_tpu.obs.metrics.MetricsRegistry` into the job
coordinator's KV (``{job}/metrics/{worker}``) on a fixed cadence, and
the coordinator pod (runtime/coordinator_main.py ``--metrics-port``)
re-exposes the union on ``/metrics`` with every series labeled by
worker — one scrape shows the whole job.

Push for the worker->coordinator hop (workers may be NAT'd pods a
scraper can't reach; the KV plane already exists), pull for everything
facing operators/autoscalers (Prometheus model). Snapshots are
full-state, so a lost push costs staleness, never correctness, and
aggregation rebuilds from scratch each scrape — no delta protocol.
"""

from __future__ import annotations

import json
import random
import threading
from typing import Callable, Dict, Iterable, Optional

from edl_tpu.obs.metrics import MetricsRegistry, default_registry
from edl_tpu.utils import faults
from edl_tpu.utils.logging import kv_logger

log = kv_logger("obs")

METRICS_KV_PREFIX = "metrics"  # {job}/metrics/{worker} holds snapshot JSON
EVENTS_KV_PREFIX = "events"  # {job}/events/{worker} holds a JSONL window
TRACE_KV_PREFIX = "trace"  # {job}/trace/{worker} holds a span window
CLOCK_KV_PREFIX = "clock"  # {job}/clock/{worker} holds a ClockEstimate


def metrics_key(job: str, worker: str) -> str:
    return f"{job}/{METRICS_KV_PREFIX}/{worker}"


def events_key(job: str, worker: str) -> str:
    return f"{job}/{EVENTS_KV_PREFIX}/{worker}"


def trace_key(job: str, worker: str) -> str:
    return f"{job}/{TRACE_KV_PREFIX}/{worker}"


def clock_key(job: str, worker: str) -> str:
    return f"{job}/{CLOCK_KV_PREFIX}/{worker}"


class MetricsPusher:
    """Daemon thread publishing periodic registry snapshots.

    ``publish(json_str)`` is injected (the worker wires a coordinator
    ``kv_put`` with its own error handling) so this module stays free
    of coordinator imports. A failing publish is logged once per
    streak and retried — telemetry must never take the step loop down.

    Failed pushes back off with jittered exponential delay (reset on
    the first success) instead of retrying every interval at full rate:
    during a coordinator outage EVERY worker's pusher is failing at
    once, and a fixed cadence turns the recovering coordinator's first
    seconds into a synchronized retry stampede. The jitter (±50%)
    decorrelates the fleet; ``backoff_cap_s`` bounds how stale a
    recovered fleet's first snapshot can be. Each failure increments
    ``edl_metrics_push_failures_total``.
    """

    def __init__(
        self,
        publish: Callable[[str], None],
        interval_s: float = 10.0,
        registry: Optional[MetricsRegistry] = None,
        backoff_cap_s: float = 300.0,
        events_publish: Optional[Callable[[str], None]] = None,
        events_window: int = 256,
        recorder=None,
        trace_publish: Optional[Callable[[str], None]] = None,
        trace_window: int = 128,
        tracer=None,
        clock_refresh: Optional[Callable[[], None]] = None,
        tsdb=None,
        crosscheck: Optional[Callable[[], Optional[dict]]] = None,
    ):
        self._publish = publish
        self.interval_s = max(float(interval_s), 0.1)
        self.backoff_cap_s = max(float(backoff_cap_s), self.interval_s)
        self.registry = registry or default_registry()
        # flight-recorder window rides the same cadence/backoff as the
        # metric snapshot (same KV plane, same failure handling): the
        # coordinator's /events shows each worker's recent timeline
        self._events_publish = events_publish
        self.events_window = events_window
        self._recorder = recorder
        # recent tracer-span window on the same cadence: what the
        # coordinator's fleet /trace merges onto one clock axis
        # (obs/disttrace.span_window_json — wall-anchored spans)
        self._trace_publish = trace_publish
        self.trace_window = trace_window
        self._tracer = tracer
        # throttled clock re-sample (disttrace.ClockSync.maybe_sample
        # closure): offsets drift, so the estimate refreshes on the
        # push cadence without a dedicated thread
        self._clock_refresh = clock_refresh
        # local metric history (obs/tsdb.py): the SAME snapshot the
        # publish ships is appended on the same cadence — burn-rate
        # windows and `edl watch` come for free, zero new RPCs. A
        # string is taken as a directory path.
        if isinstance(tsdb, str):
            from edl_tpu.obs.tsdb import TSDB

            tsdb = TSDB(tsdb)
        self.tsdb = tsdb
        # ledger-vs-live-arrays crosscheck on the append cadence
        # (memledger satellite): default only when history is on —
        # the result lands in the snapshot as an alertable series
        if crosscheck is None and tsdb is not None:
            crosscheck = _default_crosscheck
        self._crosscheck = crosscheck
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # push_once runs on the pusher thread AND from stop()'s
        # last-gasp call on the owner's thread; the streak/backoff
        # state it shares with next_wait_s is lock-guarded (found by
        # `edl check` lockset-race; pinned by test_obs concurrency test)
        self._state_lock = threading.Lock()
        self._failing = False
        self._fail_streak = 0
        # private PRNG: jitter must not perturb anyone's seeded
        # random.random() stream (determinism elsewhere matters more
        # than jitter quality)
        self._rng = random.Random(id(self) ^ 0xED1)
        self.pushes = 0

    def push_once(self) -> bool:
        try:
            # chaos site: the paths a real outage exercises are the
            # registry serialize + the injected publish
            faults.fault_point("metrics.push")
            if self._crosscheck is not None:
                self._run_crosscheck()
            snap = self.registry.snapshot_json()
            self._publish(snap)
            # push_once can run on the pusher thread AND from stop()'s
            # last-gasp call; the store's open-bucket accumulators are
            # not reentrant, so appends serialize on the same lock as
            # the streak state
            with self._state_lock:
                if self.tsdb is not None:
                    self.tsdb.append(snap)
            if self._events_publish is not None:
                rec = self._recorder
                if rec is None:
                    from edl_tpu.obs import events as _events

                    rec = _events.default_recorder()
                # single-line doc: coordinator KV is a line protocol
                self._events_publish(rec.window_json(self.events_window))
            if self._trace_publish is not None:
                from edl_tpu.obs import disttrace

                self._trace_publish(
                    disttrace.span_window_json(self._tracer, self.trace_window)
                )
            if self._clock_refresh is not None:
                self._clock_refresh()
            with self._state_lock:
                self.pushes += 1
                self._failing = False
                self._fail_streak = 0
            return True
        except Exception as e:
            with self._state_lock:
                self._fail_streak += 1
                first_of_streak = not self._failing
                self._failing = True
            default_registry().counter(
                "edl_metrics_push_failures_total",
                "metrics snapshot pushes that raised",
            ).inc()
            if first_of_streak:
                log.warn("metrics push failed (will retry)", error=str(e))
            return False

    def _run_crosscheck(self) -> None:
        """Refresh ``edl_hbm_crosscheck_drift_bytes`` from the memory
        ledger on the push/append cadence, so ledger drift is a series
        an alert rule can watch instead of a manual call."""
        try:
            res = self._crosscheck()
        # edl: no-lint[silent-failure] the crosscheck needs live jax state; on a host without devices it degrades to "no reading", never to a failed push
        except Exception:
            res = None
        if res is None:
            return
        self.registry.gauge(
            "edl_hbm_crosscheck_drift_bytes",
            "ledger-vs-live-arrays drift from memledger.crosscheck(), "
            "refreshed on the metrics-push/tsdb-append cadence",
        ).set(abs(float(res.get("unaccounted_bytes", 0.0))))

    def next_wait_s(self) -> float:
        """Delay before the next push attempt: the fixed interval while
        healthy; doubling from the interval per consecutive failure,
        capped and jittered ±50%, while failing."""
        with self._state_lock:
            streak = self._fail_streak
        if streak == 0:
            return self.interval_s
        base = min(
            self.interval_s * (2 ** min(streak, 16)),
            self.backoff_cap_s,
        )
        return base * (0.5 + self._rng.random())

    def start(self) -> "MetricsPusher":
        def _run():
            while not self._stop.wait(self.next_wait_s()):
                self.push_once()

        self._thread = threading.Thread(
            target=_run, name="edl-metrics-push", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, final_push: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_push:
            self.push_once()  # last-gasp snapshot so a clean exit is visible
        with self._state_lock:
            if self.tsdb is not None:
                self.tsdb.flush()  # close open buckets for readers


def _default_crosscheck() -> Optional[dict]:
    """The pusher's default ledger probe: the process-wide ledger's
    ``crosscheck()`` (obs/memledger.py), which itself returns None on
    hosts without live jax state."""
    from edl_tpu.obs import memledger

    return memledger.default_ledger().crosscheck()


def aggregate_snapshots(
    snaps: Dict[str, str | dict], reg: Optional[MetricsRegistry] = None
) -> MetricsRegistry:
    """Merge per-source snapshot JSONs into one registry, labeling
    every series ``worker=<source>``. Undecodable snapshots are
    skipped (a half-written KV value must not kill the scrape)."""
    reg = reg or MetricsRegistry()
    for worker, raw in sorted(snaps.items()):
        try:
            snap = json.loads(raw) if isinstance(raw, str) else raw
            reg.merge_snapshot(snap, labels={"worker": worker})
        except (ValueError, TypeError) as e:
            log.warn("bad metrics snapshot", worker=worker, error=str(e))
    return reg


def collect_fleet(
    client, job: str, extra_sources: Iterable[str] = (),
    straggler: bool = True,
) -> MetricsRegistry:
    """Coordinator-side aggregation pass: read every live member's
    pushed snapshot (plus well-known non-member sources like the
    epoch's dist_service host) from KV and merge. Rebuilt per scrape —
    counters stay correct because each pass starts from an empty
    registry. With ``straggler`` (default) the merged per-worker
    step-time histograms additionally feed the skew gauges
    (:func:`straggler_pass`)."""
    names = [m.name for m in client.members()]
    names.extend(extra_sources)
    snaps: Dict[str, str] = {}
    for name in names:
        v = client.kv_get(metrics_key(job, name))
        if v:
            snaps[name] = v
    reg = aggregate_snapshots(snaps)
    g = reg.gauge("edl_fleet_reporting_workers", "workers with a pushed metrics snapshot")
    g.set(len(snaps))
    if straggler:
        try:
            straggler_pass(reg, client=client, job=job)
        except Exception as e:  # analysis must never kill the scrape
            log.warn("straggler pass failed", error=str(e))
    return reg


def load_clock_offsets(
    client, job: str, names: Iterable[str]
) -> Dict[str, float]:
    """Per-worker clock offsets (seconds to ADD to a worker's wall
    clock to land on the coordinator axis) from the estimates each
    worker published at its register/heartbeat handshake
    (obs/disttrace.ClockSync). Missing/undecodable -> omitted (treated
    as 0 downstream)."""
    from edl_tpu.obs.disttrace import ClockEstimate

    out: Dict[str, float] = {}
    for name in names:
        raw = client.kv_get(clock_key(job, name))
        if not raw:
            continue
        est = ClockEstimate.from_json(raw)
        if est is not None:
            out[name] = est.offset_s
    return out


def collect_fleet_events(
    client, job: str, extra_sources: Iterable[str] = (),
    apply_clock: bool = True,
) -> list:
    """Coordinator-side fleet log: read every live member's pushed
    flight-recorder window from KV, tag each record with its worker
    (unless the worker already stamped its context), correct each
    record's ``t_wall`` onto the coordinator's clock axis using the
    published per-worker offsets (``apply_clock``), and merge in
    causal order (corrected wall time, then per-process seq).
    Undecodable windows are skipped like bad metric snapshots — a
    half-written KV value must not kill the scrape."""
    from edl_tpu.obs.events import load_jsonl

    names = [m.name for m in client.members()]
    names.extend(extra_sources)
    offsets = load_clock_offsets(client, job, names) if apply_clock else {}
    merged: list = []
    for name in names:
        raw = client.kv_get(events_key(job, name))
        if not raw:
            continue
        try:
            recs = load_jsonl(raw)
        except ValueError:
            continue  # a window with no events yet
        off = offsets.get(name, 0.0)
        for r in recs:
            r.setdefault("corr", {}).setdefault("worker", name)
            if off and "t_wall" in r:
                try:
                    r["t_wall"] = float(r["t_wall"]) + off
                except (TypeError, ValueError):
                    pass
        merged.extend(recs)
    merged.sort(key=lambda r: (r.get("t_wall", 0.0), r.get("seq", 0)))
    return merged


def collect_fleet_trace(
    client, job: str, extra_sources: Iterable[str] = (),
    local_name: str = "coordinator", tracer=None,
) -> dict:
    """The fleet ``/trace`` document: every live member's pushed span
    window ({job}/trace/{worker}), offset-corrected onto the
    coordinator's clock axis and merged into ONE Perfetto doc with a
    per-worker ``pid``, ``process_name`` metadata, and chrome flow
    events linking RPC client→server span pairs
    (obs/disttrace.merge_fleet_trace). The coordinator process's own
    tracer rides along as ``local_name`` (offset 0 — it IS the
    reference clock)."""
    from edl_tpu.obs import disttrace

    names = [m.name for m in client.members()]
    names.extend(extra_sources)
    windows: Dict[str, str] = {}
    for name in names:
        raw = client.kv_get(trace_key(job, name))
        if raw:
            windows[name] = raw
    offsets = load_clock_offsets(client, job, names)
    if local_name:
        windows[local_name] = disttrace.span_window_doc(tracer)
        offsets[local_name] = 0.0
    return disttrace.merge_fleet_trace(windows, offsets)


# ---------------------------------------------------------------------------
# straggler analysis (obs/disttrace primitives -> scrapeable gauges)


# emit straggler.detected once per (worker, rounded skew) — a scrape
# cadence must not flood the flight ring with identical detections
_last_straggler: Optional[tuple] = None
_straggler_lock = threading.Lock()


def straggler_pass(
    reg: MetricsRegistry,
    client=None,
    job: Optional[str] = None,
    threshold: Optional[float] = None,
) -> None:
    """Derive straggler telemetry from a fleet-merged registry (and,
    when a KV client is given, the fleet event log):

    * ``edl_step_skew_ratio`` — slowest worker's step p50 over the
      fleet median (1.0 = even; needs >= 2 reporting workers);
    * ``edl_barrier_wait_seconds{worker}`` — rendezvous-barrier wait
      attributed to the LAST arriver (from offset-corrected
      ``worker.join`` arrivals of the latest epoch);
    * a ``straggler.detected`` flight event naming the slow worker
      when the skew crosses ``threshold`` (EDL_STRAGGLER_RATIO,
      default 1.5)."""
    import os as _os

    from edl_tpu.obs import disttrace

    if threshold is None:
        try:
            threshold = float(_os.environ.get("EDL_STRAGGLER_RATIO", "1.5"))
        except ValueError:
            threshold = 1.5
    fam = reg.get("edl_train_step_seconds")
    p50s: Dict[str, float] = {}
    if fam is not None and "worker" in fam.labelnames:
        wi = list(fam.labelnames).index("worker")
        for key, _s in fam.samples():
            w = key[wi]
            p50s[w] = fam.percentile(
                0.5, **dict(zip(fam.labelnames, key))
            )
    skew, slow, median = disttrace.step_skew(p50s)
    reg.gauge(
        "edl_step_skew_ratio",
        "slowest worker step p50 over the fleet median (1.0 = even)",
    ).set(skew)
    if slow is not None and skew >= threshold:
        global _last_straggler
        sig = (slow, round(skew, 1))
        with _straggler_lock:
            fire, _last_straggler = sig != _last_straggler, sig
        if fire:
            from edl_tpu.obs import events as _events

            _events.emit(
                "straggler.detected", severity="warn", worker=slow,
                skew_ratio=round(skew, 3), fleet_median_s=round(median, 6),
                p50_s=round(p50s[slow], 6),
            )
    if client is not None and job is not None:
        waits = disttrace.barrier_waits_from_events(
            collect_fleet_events(client, job)
        )
        if waits:
            g = reg.gauge(
                "edl_barrier_wait_seconds",
                "rendezvous-barrier wait charged to the last arriver",
                ("worker",),
            )
            for w, wait in sorted(waits.items()):
                g.set(wait, worker=w)


# ---------------------------------------------------------------------------
# tracer -> histogram bridge


def bridge_tracer(
    registry: Optional[MetricsRegistry] = None, tracer=None
) -> Callable:
    """Subscribe a registry to the process tracer: every recorded span
    becomes an ``edl_span_seconds{name=...}`` observation, so span
    timings (reshard phases, checkpoint I/O, serving blocks) are
    scrapeable as histograms, not just dumpable as a trace. Returns
    the installed listener (pass to ``Tracer.remove_listener`` to
    detach)."""
    from edl_tpu.utils import tracing

    reg = registry or default_registry()
    tr = tracer or tracing.tracer()
    hist = reg.histogram(
        "edl_span_seconds", "tracer span durations by name", ("name",)
    )

    def _on_span(span) -> None:
        hist.observe(span.dur_s, name=span.name)

    tr.add_listener(_on_span)
    return _on_span


# ---------------------------------------------------------------------------
# MonitorSample -> registry (the controller/StoreSource exposition path)


def registry_from_sample(sample, reg: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Map one :class:`~edl_tpu.monitor.collector.MonitorSample` (any
    source: ClusterSource, StoreSource, ServingSource) onto gauges —
    the controller daemon re-exposes its census this way, and the
    round trip (sample -> registry -> text -> parse) is pinned by
    tests/test_obs.py."""
    reg = reg or MetricsRegistry()
    g = reg.gauge
    g("edl_fleet_cpu_total_milli", "cluster CPU capacity (millicores)").set(
        sample.cpu_total_milli
    )
    g("edl_fleet_cpu_request_milli", "cluster CPU requested (millicores)").set(
        sample.cpu_request_milli
    )
    g("edl_fleet_chip_total", "cluster accelerator chips").set(sample.chip_total)
    g("edl_fleet_chip_request", "cluster chips requested").set(sample.chip_request)
    g("edl_fleet_cpu_util_pct", "CPU utilization percent").set(sample.cpu_util)
    g("edl_fleet_chip_util_pct", "chip utilization percent").set(sample.chip_util)
    g("edl_fleet_jobs", "job census", ("state",)).set(
        len(sample.submitted_jobs), state="submitted"
    )
    reg.get("edl_fleet_jobs").set(len(sample.pending_jobs), state="pending")
    workers = g("edl_job_workers", "running workers", ("job",))
    target = g("edl_job_parallelism", "autoscaler target parallelism", ("job",))
    reshards = g("edl_job_reshards", "reshard count (sampled)", ("job",))
    stall = g("edl_job_last_reshard_stall_seconds", "last reshard stall", ("job",))
    fallbacks = g("edl_job_reshard_fallbacks", "host-staged reshards (sampled)", ("job",))
    for name in sample.submitted_jobs:
        workers.set(sample.running_workers.get(name, 0), job=name)
        target.set(sample.parallelism.get(name, 0), job=name)
        reshards.set(sample.reshards.get(name, 0), job=name)
        stall.set(sample.last_stall_s.get(name, 0.0), job=name)
        fallbacks.set(sample.reshard_fallbacks.get(name, 0), job=name)
    if sample.serving:
        sv = g("edl_serving_snapshot", "serving engine snapshot values", ("key",))
        for k, v in sorted(sample.serving.items()):
            sv.set(float(v), key=k)
    return reg
