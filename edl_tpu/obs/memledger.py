"""Device memory ledger — who holds HBM, by category, live.

The serving/training processes hold a handful of LONG-LIVED device
allocations that between them decide what fits on a chip: the param
tree, optimizer moments, per-engine KV caches, device slot state.
Until now their sizes existed only as log lines at construction; the
ledger makes them a scrapeable balance sheet —

* ``edl_hbm_bytes{category}``   — bytes registered per category
  (``params`` / ``opt`` / ``kv`` / ``slot_state`` / …)
* ``edl_kv_occupancy_ratio``    — used KV tokens over capacity across
  registered engines: the number ROADMAP item 1 (paged KV) must move,
  measured before the paging exists.

Semantics that make it drift-proof:

* **keyed, replace-on-reregister** — entries are ``(owner, name)``
  keys; registering the same key REPLACES the old entry (delta applied
  to the category gauge). That is what makes the ledger donation- and
  recovery-aware: the engine's ``_recover`` → ``_alloc_device_state``
  re-registers its cache under the same key, so a crash/recover cycle
  cannot double-count (the exp_chaos lane asserts bytes are EXACTLY
  the single-cache figure after every chaos plan), and donated buffers
  — consumed and replaced by same-shaped outputs every dispatch — need
  no per-dispatch bookkeeping at all.
* **owner-scoped release** — ``release_owner(owner)`` drops every
  entry (and KV usage) an object registered; engines attach it via
  ``weakref.finalize`` so a garbage-collected engine cannot leave
  ghost bytes on the gauge.
* **cross-checkable** — :func:`MemoryLedger.crosscheck` compares the
  ledger total against ``jax.live_arrays()`` where the jax build
  offers it (lazy import; never required): ``live - ledger`` is the
  unaccounted transient pool.

jax-free at module scope (the obs/ contract); :func:`tree_nbytes`
walks any dict/list/tuple pytree of objects exposing ``.nbytes``
(device arrays, numpy arrays, int8 record dicts) without importing
anything.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from edl_tpu.obs import metrics as obs_metrics


def tree_nbytes(tree: Any) -> int:
    """Total ``.nbytes`` over a nested dict/list/tuple of array-likes.
    Non-array leaves (None, scalars, configs) count zero — the ledger
    measures device buffers, not bookkeeping."""
    if tree is None:
        return 0
    if isinstance(tree, dict):
        return sum(tree_nbytes(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(tree_nbytes(v) for v in tree)
    n = getattr(tree, "nbytes", None)
    return int(n) if isinstance(n, (int, float)) else 0


class MemoryLedger:
    """Thread-safe registry of long-lived device allocations."""

    def __init__(self, registry: Optional[obs_metrics.MetricsRegistry] = None):
        r = registry or obs_metrics.default_registry()
        self._lock = threading.Lock()
        # (owner, name) -> (category, nbytes)
        self._entries: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self._by_category: Dict[str, int] = {}
        # owner -> (used_tokens, capacity_tokens) for KV occupancy
        self._kv_usage: Dict[str, Tuple[int, int]] = {}
        self._g_bytes = r.gauge(
            "edl_hbm_bytes",
            "bytes of registered long-lived device allocations by "
            "category (obs/memledger.py)",
            ("category",),
        )
        self._g_kv_occ = r.gauge(
            "edl_kv_occupancy_ratio",
            "used KV-cache tokens (contiguous) or allocated blocks "
            "(paged) over capacity across registered engines",
        )
        # owner -> free block count (paged engines only)
        self._kv_blocks_free: Dict[str, int] = {}
        self._g_kv_free = r.gauge(
            "edl_kv_blocks_free",
            "free KV pool blocks across registered paged engines — the "
            "headroom admission gates on",
        )
        # owner -> (pool bytes incl. scales, capacity tokens): the
        # quantized-KV shrink, scrapeable as bytes per resident token
        self._kv_bpt: Dict[str, Tuple[int, int]] = {}
        self._g_kv_bpt = r.gauge(
            "edl_kv_bytes_per_token",
            "KV pool bytes (values + quantization scales) per token of "
            "pool capacity across registered paged engines — 2-4x lower "
            "under --kv-quant int8/int4",
        )
        self._c_prefix_hits = r.counter(
            "edl_kv_prefix_hit_total",
            "prefix-cache block hits: prompt blocks served from the "
            "shared KV pool instead of re-prefilled",
        )

    # -- allocations --------------------------------------------------------

    def register(
        self, owner: str, name: str, nbytes: float, category: str
    ) -> None:
        """Record (or REPLACE — same key never double-counts) one
        allocation."""
        nbytes = int(nbytes)
        key = (owner, name)
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                self._by_category[old[0]] = (
                    self._by_category.get(old[0], 0) - old[1]
                )
            self._entries[key] = (category, nbytes)
            self._by_category[category] = (
                self._by_category.get(category, 0) + nbytes
            )
            touched = {category} | ({old[0]} if old else set())
            totals = {c: self._by_category.get(c, 0) for c in touched}
        for c, v in totals.items():
            self._g_bytes.set(v, category=c)

    def register_tree(
        self, owner: str, name: str, tree: Any, category: str
    ) -> int:
        """Register a pytree's summed bytes; returns the figure."""
        n = tree_nbytes(tree)
        self.register(owner, name, n, category)
        return n

    def release(self, owner: str, name: str) -> int:
        """Drop one entry; returns the bytes released (0 if absent)."""
        key = (owner, name)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is None:
                return 0
            cat, n = old
            self._by_category[cat] = self._by_category.get(cat, 0) - n
            total = self._by_category[cat]
        self._g_bytes.set(total, category=cat)
        return n

    def release_owner(self, owner: str) -> int:
        """Drop every entry (and KV usage) registered under ``owner``
        — the engine's weakref.finalize hook. Returns bytes released."""
        with self._lock:
            keys = [k for k in self._entries if k[0] == owner]
            released = 0
            touched = set()
            for k in keys:
                cat, n = self._entries.pop(k)
                self._by_category[cat] = self._by_category.get(cat, 0) - n
                released += n
                touched.add(cat)
            self._kv_usage.pop(owner, None)
            self._kv_blocks_free.pop(owner, None)
            self._kv_bpt.pop(owner, None)
            totals = {c: self._by_category.get(c, 0) for c in touched}
            used = sum(u for u, _ in self._kv_usage.values())
            cap = sum(c for _, c in self._kv_usage.values())
            free = sum(self._kv_blocks_free.values())
            bpt_b = sum(b for b, _ in self._kv_bpt.values())
            bpt_t = sum(t for _, t in self._kv_bpt.values())
        for c, v in totals.items():
            self._g_bytes.set(v, category=c)
        self._g_kv_occ.set(used / cap if cap else 0.0)
        self._g_kv_free.set(free)
        self._g_kv_bpt.set(bpt_b / bpt_t if bpt_t else 0.0)
        return released

    # -- KV occupancy -------------------------------------------------------

    def set_kv_usage(self, owner: str, used_tokens: int, capacity_tokens: int):
        """One engine's live KV occupancy (prompt+generated tokens over
        slots×max_len); the gauge aggregates across engines. Called
        per engine step — one lock + two dict hits."""
        with self._lock:
            self._kv_usage[owner] = (int(used_tokens), int(capacity_tokens))
            used = sum(u for u, _ in self._kv_usage.values())
            cap = sum(c for _, c in self._kv_usage.values())
        self._g_kv_occ.set(used / cap if cap else 0.0)

    def set_kv_blocks_free(self, owner: str, free_blocks: int) -> None:
        """One paged engine's free-block headroom; the gauge aggregates
        across engines (contiguous engines never call this)."""
        with self._lock:
            self._kv_blocks_free[owner] = int(free_blocks)
            total = sum(self._kv_blocks_free.values())
        self._g_kv_free.set(total)

    def set_kv_bytes_per_token(
        self, owner: str, pool_bytes: int, capacity_tokens: int
    ) -> None:
        """One paged engine's pool bytes (values + scales) over its
        token capacity; the gauge publishes the byte-weighted average
        across engines — the figure ``--kv-quant`` shrinks 2-4x."""
        with self._lock:
            self._kv_bpt[owner] = (int(pool_bytes), int(capacity_tokens))
            b = sum(x for x, _ in self._kv_bpt.values())
            t = sum(y for _, y in self._kv_bpt.values())
        self._g_kv_bpt.set(b / t if t else 0.0)

    def count_prefix_hits(self, n: int = 1) -> None:
        """Count ``n`` prompt blocks served from the shared prefix
        cache (prefill skipped for those positions)."""
        self._c_prefix_hits.inc(n)

    # -- views --------------------------------------------------------------

    def total(self, category: Optional[str] = None) -> int:
        with self._lock:
            if category is not None:
                return self._by_category.get(category, 0)
            return sum(n for _, n in self._entries.values())

    def owner_total(self, owner: str, category: Optional[str] = None) -> int:
        """Bytes one owner has registered (optionally one category) —
        what the chaos lane pins across crash/recover cycles."""
        with self._lock:
            return sum(
                n
                for (o, _), (c, n) in self._entries.items()
                if o == owner and (category is None or c == category)
            )

    def categories(self) -> Dict[str, int]:
        with self._lock:
            return {c: n for c, n in self._by_category.items() if n}

    def kv_occupancy(self) -> float:
        with self._lock:
            used = sum(u for u, _ in self._kv_usage.values())
            cap = sum(c for _, c in self._kv_usage.values())
        return used / cap if cap else 0.0

    def crosscheck(self) -> Optional[Dict[str, float]]:
        """Compare the ledger against ``jax.live_arrays()`` when this
        jax build offers it. ``unaccounted`` (live − ledger) is the
        transient pool: batches in flight, jit temporaries, donated
        carries between dispatches. None when unavailable."""
        try:
            import jax

            live = sum(a.nbytes for a in jax.live_arrays())
        # edl: no-lint[silent-failure] capability probe: a build without live_arrays answers "unavailable", not an error
        except Exception:
            return None
        ledger = self.total()
        return {
            "ledger_bytes": float(ledger),
            "live_bytes": float(live),
            "unaccounted_bytes": float(live - ledger),
        }


# ---------------------------------------------------------------------------
# process-wide default (mirrors obs.metrics' default-registry pattern)

_default = MemoryLedger()
_default_lock = threading.Lock()


def default_ledger() -> MemoryLedger:
    return _default


def reset_default_ledger(
    registry: Optional[obs_metrics.MetricsRegistry] = None,
) -> MemoryLedger:
    """Swap in a fresh default ledger (tests); returns the new one.
    Pass the registry its gauges should publish into (tests that also
    reset the default metrics registry should pass the new one)."""
    global _default
    with _default_lock:
        _default = MemoryLedger(registry)
    return _default
