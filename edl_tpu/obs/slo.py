"""SLO & goodput — the serving metric that should drive scheduling.

Raw tokens/s rewards a server for finishing work nobody is waiting
for; what a multi-tenant deployment actually sells is **goodput under
SLO** — requests whose TTFT and per-token latency landed inside their
class's deadline (DistServe's argument, OSDI '24). This module turns a
run's per-request records (``ServingMetrics.requests`` — submit / pop
/ first-token / finish stamps plus tenant / SLO-class labels) into:

* a **goodput report**: per-class attained-vs-SLO fractions, goodput
  req/s, shed/timeout accounting, and exact per-phase
  (queue-wait / prefill / decode) p50/p95/p99 from the raw records
  (order statistics, not histogram interpolation — a run report can
  afford exactness);
* **live burn-rate gauges** for the exporter
  (``edl_slo_ttft_ok_ratio{class}``, ``edl_slo_itl_ok_ratio{class}``,
  ``edl_slo_goodput_rps``) so a scraper watches attainment decay in
  real time instead of discovering it in the postmortem;
* a text rendering for humans and a JSON-able dict for CI
  (``edl loadgen --json``).

jax-free and engine-free on purpose: the input is duck-typed (any
object with a ``requests`` dict of records carrying the stamp
attributes), so tests drive it with a fake clock and the analyzer can
replay stored runs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from edl_tpu.obs import metrics as obs_metrics

__all__ = [
    "SLOClass",
    "default_classes",
    "classes_by_name",
    "request_records",
    "percentiles",
    "compute_goodput",
    "update_gauges",
    "render_report",
]

# terminal outcomes that count as successfully served (an SLO can only
# be attained by work that finished; timeout/failed/rejected are the
# shed-accounting side of the report)
_SERVED = ("done", "eos")


@dataclass(frozen=True)
class SLOClass:
    """One latency class: a TTFT deadline (submit -> first token) and
    a per-token deadline (user-perceived TPOT — ``(finish - first
    token) / (tokens - 1)`` — so fused-block amortization cannot hide
    decode stalls)."""

    name: str
    ttft_slo_s: float
    itl_slo_s: float


def default_classes(
    ttft_slo_s: float = 1.0, itl_slo_s: float = 0.25
) -> Tuple[SLOClass, ...]:
    """The two-tier default mix: ``interactive`` at the given
    deadlines, ``batch`` at 8x TTFT / 4x ITL (throughput traffic cares
    about finishing, not about the first token)."""
    return (
        SLOClass("interactive", ttft_slo_s, itl_slo_s),
        SLOClass("batch", 8.0 * ttft_slo_s, 4.0 * itl_slo_s),
    )


def classes_by_name(
    classes: Iterable[SLOClass],
) -> Dict[str, SLOClass]:
    return {c.name: c for c in classes}


# ---------------------------------------------------------------------------
# record extraction


def _get(rec: Any, name: str, default=0.0):
    return getattr(rec, name, default)


def request_records(
    metrics: Any, since_s: Optional[float] = None
) -> List[Dict[str, Any]]:
    """Flatten ``ServingMetrics.requests`` into plain per-request
    dicts with the phase decomposition precomputed:

    ``queue_wait_s`` (submit -> pop), ``prefill_s`` (pop -> first
    token), ``decode_s`` (first token -> finish), ``total_s`` (submit
    -> finish), ``ttft_s``, ``tpot_s`` (0.0 when < 2 tokens), plus
    ``tenant`` / ``slo_class`` / ``outcome`` / ``tokens``. The three
    phases sum to ``total_s`` exactly for any finished request — the
    invariant tests/test_loadgen.py pins.

    ``since_s`` (same clock as the metrics object, ``time.monotonic``
    by default) keeps only requests that FINISHED at/after that
    instant — the trailing-window live view burn-rate gauges want,
    where attainment reflects what the system is doing NOW instead of
    averaging in the whole run's history."""
    out: List[Dict[str, Any]] = []
    for rid, rec in metrics.requests.items():
        if since_s is not None and float(_get(rec, "finish_s")) < since_s:
            continue
        has_submit = bool(_get(rec, "has_submit", False))
        has_pop = bool(_get(rec, "has_pop", False))
        submit = float(_get(rec, "submit_s"))
        pop = float(_get(rec, "pop_s"))
        first = float(_get(rec, "first_token_s"))
        finish = float(_get(rec, "finish_s"))
        tokens = int(_get(rec, "tokens", 0))
        r: Dict[str, Any] = {
            "rid": rid,
            "tenant": str(_get(rec, "tenant", "") or ""),
            "slo_class": str(_get(rec, "slo_class", "") or ""),
            "outcome": str(_get(rec, "outcome", "") or ""),
            "tokens": tokens,
            "queue_wait_s": (pop - submit) if (has_submit and has_pop) else 0.0,
            "prefill_s": (first - pop) if (has_pop and first) else 0.0,
            "decode_s": (finish - first) if (first and finish) else 0.0,
            "total_s": (finish - submit) if (has_submit and finish) else 0.0,
            "ttft_s": (first - submit) if (has_submit and first) else 0.0,
            "tpot_s": (
                (finish - first) / (tokens - 1)
                if tokens >= 2 and first and finish
                else 0.0
            ),
        }
        out.append(r)
    return out


def percentiles(
    values: Sequence[float], qs: Sequence[float] = (0.50, 0.95, 0.99)
) -> Dict[str, float]:
    """Exact order-statistic percentiles with linear interpolation
    between neighbors (numpy's default rule, stdlib-only). Empty input
    -> all zeros."""
    out = {f"p{int(q * 100)}": 0.0 for q in qs}
    if not values:
        return out
    vs = sorted(float(v) for v in values)
    n = len(vs)
    for q in qs:
        pos = q * (n - 1)
        lo = int(pos)
        hi = min(lo + 1, n - 1)
        frac = pos - lo
        out[f"p{int(q * 100)}"] = vs[lo] + frac * (vs[hi] - vs[lo])
    return out


# ---------------------------------------------------------------------------
# the goodput report


def compute_goodput(
    records: List[Dict[str, Any]],
    classes: Mapping[str, SLOClass],
    wall_s: float,
) -> Dict[str, Any]:
    """Goodput-under-SLO over one run's request records.

    A request is **good** when it finished (outcome done/eos), its
    TTFT met its class's ``ttft_slo_s``, and its user-perceived TPOT
    met ``itl_slo_s`` (single-token requests have no TPOT and pass
    that leg). Records whose ``slo_class`` is unknown/unset fall into
    the ``"unclassified"`` bucket with infinite deadlines — goodput
    degenerates to completion there, which is exactly what an
    SLO-less feed means. Attainment fractions are over FINISHED
    requests; ``goodput_fraction`` is over ALL requests (shed and
    timed-out work counts against you — that is the point)."""
    wall_s = max(float(wall_s), 0.0)
    inf = float("inf")

    def _cls(name: str) -> SLOClass:
        c = classes.get(name)
        if c is None:
            return SLOClass(name or "unclassified", inf, inf)
        return c

    per_class: Dict[str, Dict[str, float]] = {}
    per_tenant: Dict[str, Dict[str, float]] = {}
    n_good = n_served = 0
    shed = timeout = failed = 0
    for r in records:
        cname = r["slo_class"] or "unclassified"
        c = _cls(cname)
        cc = per_class.setdefault(
            cname,
            {
                "requests": 0, "served": 0, "good": 0,
                "ttft_ok": 0, "itl_ok": 0,
                "shed": 0, "timeout": 0, "failed": 0,
            },
        )
        tc = per_tenant.setdefault(
            r["tenant"] or "unattributed",
            {"requests": 0, "served": 0, "good": 0, "shed": 0, "timeout": 0},
        )
        cc["requests"] += 1
        tc["requests"] += 1
        outcome = r["outcome"]
        if outcome.startswith("rejected"):
            shed += 1
            cc["shed"] += 1
            tc["shed"] += 1
            continue
        if outcome == "timeout":
            timeout += 1
            cc["timeout"] += 1
            tc["timeout"] += 1
            continue
        if outcome == "failed":
            failed += 1
            cc["failed"] += 1
            continue
        if outcome not in _SERVED:
            continue  # still in flight when the run stopped
        n_served += 1
        cc["served"] += 1
        tc["served"] += 1
        ttft_ok = r["ttft_s"] <= c.ttft_slo_s
        itl_ok = r["tokens"] < 2 or r["tpot_s"] <= c.itl_slo_s
        cc["ttft_ok"] += ttft_ok
        cc["itl_ok"] += itl_ok
        if ttft_ok and itl_ok:
            n_good += 1
            cc["good"] += 1
            tc["good"] += 1

    served = [r for r in records if r["outcome"] in _SERVED]
    phases = {
        name: percentiles([r[name] for r in served])
        for name in ("queue_wait_s", "prefill_s", "decode_s", "ttft_s",
                     "tpot_s", "total_s")
    }
    n = len(records)
    for cname, cc in per_class.items():
        c = _cls(cname)
        srv = cc["served"]
        cc.update(
            ttft_slo_s=c.ttft_slo_s,
            itl_slo_s=c.itl_slo_s,
            ttft_slo_attainment=(cc["ttft_ok"] / srv) if srv else 0.0,
            itl_slo_attainment=(cc["itl_ok"] / srv) if srv else 0.0,
            goodput_rps=(cc["good"] / wall_s) if wall_s > 0 else 0.0,
        )
    tot_ttft_ok = sum(cc["ttft_ok"] for cc in per_class.values())
    tot_itl_ok = sum(cc["itl_ok"] for cc in per_class.values())
    return {
        "wall_s": round(wall_s, 6),
        "requests": n,
        "served": n_served,
        "good": n_good,
        "shed": shed,
        "timeout": timeout,
        "failed": failed,
        "throughput_rps": (n_served / wall_s) if wall_s > 0 else 0.0,
        "goodput_rps": (n_good / wall_s) if wall_s > 0 else 0.0,
        "goodput_fraction": (n_good / n) if n else 0.0,
        "ttft_slo_attainment": (tot_ttft_ok / n_served) if n_served else 0.0,
        "itl_slo_attainment": (tot_itl_ok / n_served) if n_served else 0.0,
        "phases": phases,
        "classes": per_class,
        "tenants": per_tenant,
    }


# ---------------------------------------------------------------------------
# live gauges (the exporter surface)


def update_gauges(
    report: Dict[str, Any],
    registry: Optional[obs_metrics.MetricsRegistry] = None,
) -> None:
    """Publish a report's attainment as live gauges — called on a
    cadence during a load run so ``/metrics`` shows SLO burn while it
    happens. Gauges overwrite, so repeated calls with cumulative
    reports are the natural burn-rate view (1 - ok_ratio is the error
    budget burned so far)."""
    r = registry or obs_metrics.default_registry()
    g_ttft = r.gauge(
        "edl_slo_ttft_ok_ratio",
        "fraction of served requests meeting their class TTFT SLO",
        ("slo_class",),
    )
    g_itl = r.gauge(
        "edl_slo_itl_ok_ratio",
        "fraction of served requests meeting their class per-token SLO",
        ("slo_class",),
    )
    for cname, cc in report.get("classes", {}).items():
        g_ttft.set(cc.get("ttft_slo_attainment", 0.0), slo_class=cname)
        g_itl.set(cc.get("itl_slo_attainment", 0.0), slo_class=cname)
    r.gauge(
        "edl_slo_goodput_rps",
        "requests/s finishing within their class SLOs",
    ).set(report.get("goodput_rps", 0.0))
    r.gauge(
        "edl_slo_goodput_fraction",
        "good requests / all requests (shed and timeouts count against)",
    ).set(report.get("goodput_fraction", 0.0))


# ---------------------------------------------------------------------------
# text rendering


def _pct(v: float) -> str:
    return f"{100.0 * v:.1f}%"


def render_report(report: Dict[str, Any]) -> str:
    """One human-readable block — the `edl loadgen` default output."""
    lines = [
        f"GOODPUT  {report['good']}/{report['requests']} good "
        f"({_pct(report['goodput_fraction'])}) "
        f"goodput={report['goodput_rps']:.2f} req/s "
        f"throughput={report['throughput_rps']:.2f} req/s "
        f"wall={report['wall_s']:.2f}s",
        f"         served={report['served']} shed={report['shed']} "
        f"timeout={report['timeout']} failed={report['failed']} "
        f"ttft_attainment={_pct(report['ttft_slo_attainment'])} "
        f"itl_attainment={_pct(report['itl_slo_attainment'])}",
    ]
    ph = report.get("phases", {})
    if ph:
        lines.append(
            f"{'phase':>12} {'p50':>10} {'p95':>10} {'p99':>10}"
        )
        for name in ("queue_wait_s", "prefill_s", "decode_s", "ttft_s",
                     "tpot_s", "total_s"):
            p = ph.get(name)
            if p is None:
                continue
            lines.append(
                f"{name:>12} {p['p50'] * 1e3:>8.1f}ms "
                f"{p['p95'] * 1e3:>8.1f}ms {p['p99'] * 1e3:>8.1f}ms"
            )
    for cname, cc in sorted(report.get("classes", {}).items()):
        lines.append(
            f"CLASS {cname}: {cc['good']:.0f}/{cc['requests']:.0f} good "
            f"ttft<= {cc.get('ttft_slo_s', 0):.3g}s: "
            f"{_pct(cc.get('ttft_slo_attainment', 0.0))}  "
            f"tpot<= {cc.get('itl_slo_s', 0):.3g}s: "
            f"{_pct(cc.get('itl_slo_attainment', 0.0))}  "
            f"goodput={cc.get('goodput_rps', 0.0):.2f}/s "
            f"shed={cc['shed']:.0f} timeout={cc['timeout']:.0f}"
        )
    for tname, tc in sorted(report.get("tenants", {}).items()):
        lines.append(
            f"TENANT {tname}: {tc['good']:.0f}/{tc['requests']:.0f} good "
            f"shed={tc['shed']:.0f} timeout={tc['timeout']:.0f}"
        )
    return "\n".join(lines)
