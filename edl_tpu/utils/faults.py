"""Deterministic fault injection — a seeded, process-wide registry of
named fault points.

The training plane earns the paper's "members come and go mid-job"
claim through leases, heartbeats, and p2p restore — but those paths
were only ever exercised by tests contriving ONE failure at a time.
This module is the chaos layer: code declares named sites on its real
failure paths (``fault_point("coord.rpc")`` inside the RPC loop, not a
mock), and a PLAN arms triggers at those sites so a harness
(scripts/exp_chaos.py) can drive many failures deterministically and
assert the recovery invariants.

Plan grammar (env ``EDL_FAULTS`` or :func:`arm`)::

    site:action@key=val[,key=val...][;site2:...]

    EDL_FAULTS="serve.dispatch:raise@n=3;coord.rpc:drop@p=0.05"

Actions
    ``raise``  raise :class:`InjectedFault` (a RuntimeError) at the site
    ``drop``   raise :class:`InjectedConnectionError` (a
               ConnectionError) — "the connection broke here", so
               reconnect/backoff paths run for real
    ``delay``  sleep ``s`` seconds (default 0.05) — stall, not fail

Triggers (exactly one per spec)
    ``n=K``      fire on the Kth call to the site (1-based), once
    ``every=K``  fire on every Kth call
    ``p=F``      fire with probability F per call, from a PRNG seeded
                 with ``(seed, site)`` — deterministic given the seed
                 and the per-site call sequence, independent of
                 interleaving across sites
    ``max=M``    (modifier) cap total firings of this spec at M

The site catalog lives in doc/robustness.md §2; the serving fleet adds
``router.forward`` (the router's forward-to-replica wire),
``replica.spawn`` (supervisor process launch), and ``replica.health``
(the supervisor's health probe) — armed drops there exercise the same
failover/respawn paths a SIGKILL exercises from outside.

``EDL_FAULTS`` may instead name a JSON file (path to an existing file,
or ``@path``): ``{"seed": 0, "faults": [{"site": "serve.dispatch",
"action": "raise", "n": 3}, ...]}``. ``EDL_FAULTS_SEED`` seeds the
inline-grammar form.

Every injection increments ``edl_faults_injected_total{site}`` in the
process obs registry, so a chaos run can PROVE its faults fired (a plan
that never triggers is a green run that tested nothing).

Unarmed cost is one module-attribute read and a falsy check per
``fault_point`` call — sites sit on per-block/per-RPC paths, never
per-token, so the serving dryrun numbers are unchanged with no plan
armed (the ISSUE-4 overhead acceptance).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Union

__all__ = [
    "InjectedFault",
    "InjectedConnectionError",
    "FaultSpec",
    "arm",
    "disarm",
    "armed",
    "fault_point",
    "counts",
    "parse_plan",
]

ACTIVE = False  # module-level fast flag: the unarmed no-op check

_ACTIONS = ("raise", "drop", "delay")

_lock = threading.RLock()
_armed_by_site: Dict[str, List["_ArmedFault"]] = {}


class InjectedFault(RuntimeError):
    """Raised by an armed ``raise`` action. ``site`` names the fault
    point, so recovery tests can assert WHERE the failure landed."""

    def __init__(self, site: str, nth: int):
        super().__init__(f"injected fault at {site} (call #{nth})")
        self.site = site
        self.nth = nth


class InjectedConnectionError(ConnectionError):
    """Raised by an armed ``drop`` action — a ConnectionError, so the
    real reconnect/backoff handling at the site runs, not a test mock."""

    def __init__(self, site: str, nth: int):
        super().__init__(f"injected connection drop at {site} (call #{nth})")
        self.site = site
        self.nth = nth


@dataclass(frozen=True)
class FaultSpec:
    """One parsed plan entry: a site, an action, and exactly one
    trigger (``n`` | ``every`` | ``p``) plus modifiers."""

    site: str
    action: str
    n: int = 0
    every: int = 0
    p: float = 0.0
    delay_s: float = 0.05
    max: int = 0  # 0 = unbounded

    def __post_init__(self):
        if self.action not in _ACTIONS:
            raise ValueError(
                f"{self.site}: unknown action {self.action!r} "
                f"(one of {_ACTIONS})"
            )
        triggers = sum((self.n > 0, self.every > 0, self.p > 0))
        if triggers != 1:
            raise ValueError(
                f"{self.site}: need exactly one trigger of n=/every=/p=, "
                f"got {triggers}"
            )
        if not 0 <= self.p <= 1:
            raise ValueError(f"{self.site}: p must be in [0, 1], got {self.p}")


class _ArmedFault:
    """Runtime state of one armed spec: its call counter and per-site
    seeded PRNG. Counting happens under the module lock; the action
    itself (sleep/raise) runs outside it."""

    def __init__(self, spec: FaultSpec, seed: int):
        self.spec = spec
        self.calls = 0
        self.fired = 0
        # (seed, site) keyed: deterministic per site regardless of how
        # calls to OTHER sites interleave
        self.rng = random.Random(f"{seed}/{spec.site}/{spec.action}")

    def should_fire(self) -> bool:
        self.calls += 1
        s = self.spec
        if s.max and self.fired >= s.max:
            return False
        if s.n:
            hit = self.calls == s.n
        elif s.every:
            hit = self.calls % s.every == 0
        else:
            hit = self.rng.random() < s.p
        if hit:
            self.fired += 1
        return hit


def parse_plan(plan: str) -> List[FaultSpec]:
    """Parse the ``site:action@params;...`` grammar into specs."""
    specs: List[FaultSpec] = []
    for part in plan.split(";"):
        part = part.strip()
        if not part:
            continue
        head, _, params = part.partition("@")
        site, sep, action = head.partition(":")
        if not sep or not site or not action:
            raise ValueError(
                f"bad fault spec {part!r}: want site:action@k=v[,k=v]"
            )
        kw: Dict[str, Union[int, float]] = {}
        for kv in params.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, sep, v = kv.partition("=")
            if not sep:
                raise ValueError(f"bad fault param {kv!r} in {part!r}")
            k = k.strip()
            if k in ("n", "every", "max"):
                kw[k] = int(v)
            elif k == "p":
                kw[k] = float(v)
            elif k == "s":
                kw["delay_s"] = float(v)
            else:
                raise ValueError(f"unknown fault param {k!r} in {part!r}")
        specs.append(FaultSpec(site=site.strip(), action=action.strip(), **kw))
    if not specs:
        raise ValueError(f"empty fault plan {plan!r}")
    return specs


def _specs_from_json(doc: dict) -> tuple:
    faults = doc.get("faults")
    if not isinstance(faults, list) or not faults:
        raise ValueError('fault plan JSON needs a non-empty "faults" list')
    specs = []
    for f in faults:
        f = dict(f)
        if "s" in f:
            f["delay_s"] = f.pop("s")
        specs.append(FaultSpec(**f))
    return specs, int(doc.get("seed", 0))


def arm(
    plan: Union[str, dict, Iterable[FaultSpec]], seed: int = 0
) -> List[FaultSpec]:
    """Replace the armed plan. ``plan`` is the string grammar, a JSON
    doc (``{"seed", "faults": [...]}`` — its seed wins), or FaultSpecs.
    Arming resets all call counters, so runs are reproducible."""
    global ACTIVE
    if isinstance(plan, str):
        specs = parse_plan(plan)
    elif isinstance(plan, dict):
        specs, seed = _specs_from_json(plan)
    else:
        specs = list(plan)
    with _lock:
        _armed_by_site.clear()
        for spec in specs:
            _armed_by_site.setdefault(spec.site, []).append(
                _ArmedFault(spec, seed)
            )
        ACTIVE = bool(_armed_by_site)
    return specs


def disarm() -> None:
    global ACTIVE
    with _lock:
        _armed_by_site.clear()
        ACTIVE = False


def armed() -> bool:
    return ACTIVE


def counts() -> Dict[str, int]:
    """{site: total injections} for the CURRENT plan (the process-wide
    ``edl_faults_injected_total`` counter survives re-arms; this view
    resets with each :func:`arm`)."""
    with _lock:
        return {
            site: sum(a.fired for a in armed_list)
            for site, armed_list in _armed_by_site.items()
        }


def _count_injection(site: str, nth: int, action: str) -> None:
    # resolved per injection so a registry swap in tests takes effect;
    # injections are rare by construction, so the lookup cost is noise
    from edl_tpu.obs import metrics as obs_metrics

    obs_metrics.default_registry().counter(
        "edl_faults_injected_total", "injected faults by site", ("site",)
    ).inc(site=site)
    # flight recorder: the injection lands on the SAME timeline as its
    # consequences, so `edl postmortem` can verify every fault is
    # followed by a recorded recovery (fault -> recover -> re-prefill
    # -> finish, the chaos lane's chain contract)
    from edl_tpu.obs import events

    events.emit(
        "fault.injected", severity="warn", site=site, nth=nth, action=action
    )


def fault_point(site: str) -> None:
    """Declare + check one named fault site. No-op (one attribute read)
    unless a plan armed this site; armed, it applies the first firing
    spec's action. Call it ON the real failure path — the point is that
    recovery code downstream runs against genuine control flow."""
    if not ACTIVE:
        return
    fire: Optional[_ArmedFault] = None
    with _lock:
        for a in _armed_by_site.get(site, ()):
            if a.should_fire():
                fire = a
                break
    if fire is None:
        return
    spec = fire.spec
    _count_injection(site, fire.calls, spec.action)
    if spec.action == "delay":
        time.sleep(spec.delay_s)
    elif spec.action == "drop":
        raise InjectedConnectionError(site, fire.calls)
    else:
        raise InjectedFault(site, fire.calls)


def _maybe_arm_from_env() -> None:
    raw = os.environ.get("EDL_FAULTS", "").strip()
    if not raw:
        return
    path = raw[1:] if raw.startswith("@") else raw
    if os.path.exists(path):
        with open(path) as f:
            arm(json.load(f))
    else:
        arm(raw, seed=int(os.environ.get("EDL_FAULTS_SEED", "0")))


_maybe_arm_from_env()
