"""Persistent-compilation-cache setup shared by every benchmark entry
point (bench.py, scripts/*.py) — ONE place for the cache policy, so no
probe silently runs with a cold or mismatched cache (the exact
cross-run-variance failure the probes exist to rule out).

Call :func:`configure` right after ``import jax`` and before any
compilation. Per-user path: a fixed /tmp name breaks (and is
poisonable) on shared hosts.
"""

from __future__ import annotations

import getpass
import os
import tempfile


def configure(min_compile_time_s: float = 2.0) -> str:
    import jax

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        tempfile.gettempdir(), f"edl_jax_cache_{getpass.getuser()}"
    )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_time_s
    )
    return cache_dir
