"""Tracing — span recording + JAX profiler hooks.

The reference has no tracing at all (SURVEY §5: closest is log15 caller
stacks); here the north-star metric is rescale-stall seconds, so the
elastic runtime emits timed spans (reshard phases, checkpoint I/O,
recompiles) into a process-wide tracer that can be dumped as
chrome://tracing / Perfetto JSON. ``jax_profile`` additionally wraps a
block in the XLA-level profiler (TensorBoard trace) when available.

Usage:
    from edl_tpu.utils import tracing
    with tracing.span("reshard", job="ctr", to=8):
        ...
    tracing.dump("/tmp/trace.json")
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, Iterator, List, Optional

from edl_tpu.utils.logging import kv_logger

log = kv_logger("tracing")


@dataclass
class Span:
    name: str
    start_s: float  # perf_counter-based, process-relative
    dur_s: float
    attrs: Dict[str, Any] = field(default_factory=dict)
    thread: int = 0
    seq: int = 0  # per-tracer monotonic id (survives ring eviction)


# distributed-trace context hooks (installed by edl_tpu.obs.disttrace
# at import): ``enter()`` runs at span open and returns (state, attrs)
# — the attrs carry the span's trace/span/parent ids when a trace is
# active — and ``exit(state)`` restores the enclosing context. Kept as
# injected callables so this low-level module stays free of obs
# imports and the hook costs one None-check when tracing alone.
_ctx_enter = None
_ctx_exit = None


def set_span_context_hooks(enter, exit) -> None:
    global _ctx_enter, _ctx_exit
    _ctx_enter, _ctx_exit = enter, exit


class Tracer:
    """Thread-safe in-memory span recorder.

    The buffer is a bounded RING: past ``max_spans`` the OLDEST span
    is evicted and ``dropped`` counts evictions — an always-on tracer
    must keep the spans closest to the incident, and the old
    drop-newest policy silently threw away exactly those (a reshard
    storm after a long soak recorded nothing). The eviction count
    surfaces in :meth:`summary` (the ``_tracer`` entry) and in the
    chrome-trace metadata, so a truncated trace is never mistaken for
    a complete one. ``add_listener`` subscribes observers (the obs
    bridge turns spans into scrapeable histograms) — listeners run
    outside the lock and must be cheap/non-throwing."""

    def __init__(self, max_spans: int = 100_000):
        self._lock = threading.Lock()
        self._spans: Deque[Span] = deque(maxlen=max_spans)
        # adjacent reads: t0_wall anchors start_s (perf_counter-
        # relative) on the wall clock, which is what lets span windows
        # from different processes merge onto one axis (obs/disttrace)
        self._t0 = time.perf_counter()
        self.t0_wall = time.time()
        self.t0 = self._t0  # public timebase (flight-recorder merge)
        self._seq = 0  # monotonic span id; never reset (paging cursor)
        self.max_spans = max_spans
        self.enabled = True
        self.dropped = 0  # spans evicted after the ring filled
        self._listeners: List[Callable[[Span], None]] = []

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        state = ctx_attrs = None
        if _ctx_enter is not None:
            # the span body runs inside its OWN child trace context:
            # nested spans parent here, and flight events emitted
            # within carry these ids (how /trace and /events agree)
            state, ctx_attrs = _ctx_enter()
        start = time.perf_counter()
        try:
            yield
        finally:
            if _ctx_exit is not None:
                _ctx_exit(state)
            if ctx_attrs:
                attrs = {**attrs, **ctx_attrs}
            self.record(name, start, time.perf_counter() - start, attrs)

    def record(self, name: str, start_s: float, dur_s: float,
               attrs: Optional[Dict[str, Any]] = None) -> None:
        """``start_s`` is absolute time.perf_counter(); stored relative to
        tracer start so chrome-trace timestamps line up across threads."""
        if not self.enabled:
            return
        span = Span(name, start_s - self._t0, dur_s, dict(attrs or {}),
                    threading.get_ident())
        with self._lock:
            self._seq += 1
            span.seq = self._seq
            if len(self._spans) >= self.max_spans:
                # ring semantics: evict the OLDEST, keep the new span
                if self.dropped == 0:
                    log.warn(
                        "span ring full; evicting oldest spans",
                        max_spans=self.max_spans,
                    )
                self.dropped += 1
            self._spans.append(span)
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(span)
            except Exception as e:  # telemetry must never take us down
                log.warn("span listener failed", error=str(e))

    def add_listener(self, fn: Callable[[Span], None]) -> None:
        """Subscribe ``fn(span)`` to every recorded span (called
        outside the tracer lock, after the span is stored)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[Span], None]) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        return [s for s in out if name is None or s.name == name]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name {count, total_s, max_s} rollup, plus a
        ``_tracer`` meta entry carrying the ring-buffer accounting
        (retained span count + evictions) so a truncated window is
        visible to every summary consumer."""
        spans, dropped = self._snapshot()
        out: Dict[str, Dict[str, float]] = {}
        for s in spans:
            agg = out.setdefault(s.name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += s.dur_s
            agg["max_s"] = max(agg["max_s"], s.dur_s)
        out["_tracer"] = {"spans": len(spans), "dropped": dropped}
        return out

    def to_chrome_trace(self) -> List[Dict[str, Any]]:
        """Catapult "X" (complete) events, microsecond units — loadable in
        chrome://tracing and Perfetto."""
        return [
            {
                "name": s.name,
                "ph": "X",
                "ts": s.start_s * 1e6,
                "dur": s.dur_s * 1e6,
                "pid": os.getpid(),
                "tid": s.thread % 2**31,
                "args": s.attrs,
            }
            for s in self.spans()
        ]

    def _snapshot(self):
        """(spans, dropped) under one lock acquire: readers must see a
        consistent pair (the unguarded ``self.dropped`` reads were an
        `edl check` lockset-race finding)."""
        with self._lock:
            return list(self._spans), self.dropped

    def to_chrome_doc(
        self, since_seq: int = 0, last_n: Optional[int] = None
    ) -> Dict[str, Any]:
        """Full chrome-trace JSON document: the events plus a metadata
        ("M") event and top-level ``dropped``, so a viewer AND a raw
        reader both see ring-buffer truncation. Served by the obs
        exporter's ``/trace`` and written by :meth:`dump`.

        ``since_seq``/``last_n`` bound the window (the ``/events``
        paging mirror): only spans with ``seq > since_seq`` ship,
        newest ``last_n`` kept. The metadata event carries ``max_seq``
        so an incremental puller knows its next cursor — a fleet
        cadence tick fetches the delta, not the whole ring."""
        spans, dropped = self._snapshot()
        if since_seq:
            spans = [s for s in spans if s.seq > since_seq]
        if last_n is not None:
            spans = spans[-max(int(last_n), 0):]
        events = [
            {
                "name": s.name,
                "ph": "X",
                "ts": s.start_s * 1e6,
                "dur": s.dur_s * 1e6,
                "pid": os.getpid(),
                "tid": s.thread % 2**31,
                "seq": s.seq,
                "args": s.attrs,
            }
            for s in spans
        ]
        events.append(
            {
                "name": "edl_tracer",
                "ph": "M",
                "pid": os.getpid(),
                "tid": 0,
                "args": {
                    "dropped": dropped,
                    "max_spans": self.max_spans,
                    "spans": len(events),
                    "max_seq": max((s.seq for s in spans), default=since_seq),
                },
            }
        )
        return {"traceEvents": events, "dropped": dropped}

    def dump(self, path: str) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        doc = self.to_chrome_doc()
        with open(path, "w") as f:
            json.dump(doc, f)
        log.info(
            "trace written",
            path=path,
            spans=max(len(doc["traceEvents"]) - 1, 0),
            dropped=doc["dropped"],
        )


_global = Tracer()


def tracer() -> Tracer:
    return _global


def span(name: str, **attrs: Any):
    return _global.span(name, **attrs)


def dump(path: str) -> None:
    _global.dump(path)


def summary() -> Dict[str, Dict[str, float]]:
    return _global.summary()


@contextlib.contextmanager
def jax_profile(logdir: str) -> Iterator[None]:
    """XLA-level profile of the block (TensorBoard trace viewer). No-op
    when jax.profiler is unavailable (e.g. stripped builds)."""
    try:
        import jax

        ctx = jax.profiler.trace(logdir)
        ctx.__enter__()  # may raise too (nested trace, unwritable logdir)
    except Exception as e:  # pragma: no cover
        log.warn("jax profiler unavailable", error=str(e))
        ctx = None
    try:
        yield
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
