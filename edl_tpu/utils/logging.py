"""Structured key-value logging (log15 analog, reference: cmd/edl/edl.go:26-28).

``kv_logger("autoscaler").info("scaling job", name=..., target=...)``
renders ``msg key=value ...`` lines with a level gate, matching the
reference's leveled KV style so operators get the same log surface.
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Any

_FORMAT = "%(asctime)s %(levelname)-5s [%(name)s] %(message)s"
_configured = False

# flight-recorder bridge: warn/error lines mirror into the process
# event timeline when a recorder is installed (edl_tpu/obs/events.py
# registers the sink at import; None = bridge off, zero overhead)
_event_sink = None


def set_event_sink(fn) -> None:
    """Install ``fn(level, logger_name, msg, kv)`` as the warn/error
    mirror, or None to detach."""
    global _event_sink
    _event_sink = fn


def configure(level: str = "info", stream=None) -> None:
    """Install the root handler (reference flag: -log_level, cmd/edl/edl.go:18)."""
    global _configured
    root = logging.getLogger("edl_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    if not _configured:
        h = logging.StreamHandler(stream or sys.stderr)
        h.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(h)
        root.propagate = False
        _configured = True


class KVLogger:
    def __init__(self, name: str):
        self._log = logging.getLogger(f"edl_tpu.{name}")

    @staticmethod
    def _render(msg: str, kv: dict) -> str:
        if not kv:
            return msg
        parts = " ".join(f"{k}={v!r}" for k, v in kv.items())
        return f"{msg} {parts}"

    def debug(self, msg: str, **kv: Any) -> None:
        self._log.debug(self._render(msg, kv))

    def info(self, msg: str, **kv: Any) -> None:
        self._log.info(self._render(msg, kv))

    def warn(self, msg: str, **kv: Any) -> None:
        self._log.warning(self._render(msg, kv))
        if _event_sink is not None:
            _event_sink("warn", self._log.name, msg, kv)

    def error(self, msg: str, **kv: Any) -> None:
        self._log.error(self._render(msg, kv))
        if _event_sink is not None:
            _event_sink("error", self._log.name, msg, kv)


def kv_logger(name: str) -> KVLogger:
    return KVLogger(name)


class Timer:
    """Context-manager stopwatch for reshard-stall accounting (the
    north-star metric; no reference analog — SURVEY §5 tracing gap)."""

    def __init__(self):
        self.elapsed = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        return False
