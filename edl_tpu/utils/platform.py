"""Virtual-CPU platform forcing for hardware-free multi-chip validation.

A TPU plugin registered at interpreter start (sitecustomize) outranks
``JAX_PLATFORMS=cpu`` set later, and backend choice is immutable once any
device query has run — so both the env vars *and* ``jax.config`` must be
set before the first query. Used by tests/conftest.py and
__graft_entry__.dryrun_multichip (SURVEY §4: multi-node testing without
a cluster).
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def prepare_virtual_cpu(n_devices: int = 8) -> None:
    """Arrange for an ``n_devices``-device virtual CPU platform WITHOUT
    touching the backend (no device query — callers that still need to
    run ``jax.distributed.initialize`` must not initialize XLA yet)."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(re.escape(_FLAG) + r"=(\d+)", flags)
    if m is None:
        flags = f"{flags} {_FLAG}={n_devices}".strip()
    elif int(m.group(1)) < n_devices:
        flags = flags.replace(m.group(0), f"{_FLAG}={n_devices}")
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_ENABLE_X64", "0")

    import jax

    jax.config.update("jax_platforms", "cpu")


def force_virtual_cpu(n_devices: int = 8) -> None:
    """Force an ``n_devices``-device virtual CPU platform.

    Must run before the first backend query in the process. Raises
    RuntimeError if a non-CPU backend already won or fewer devices than
    requested materialized.
    """
    prepare_virtual_cpu(n_devices)

    import jax

    devs = jax.devices()
    if devs[0].platform != "cpu" or len(devs) < n_devices:
        raise RuntimeError(
            f"force_virtual_cpu({n_devices}): got {len(devs)} "
            f"{devs[0].platform} device(s) — a non-CPU backend was already "
            "initialized in this process, or XLA_FLAGS was locked in"
        )
