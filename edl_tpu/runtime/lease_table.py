"""LeaseTable — the chip-lease state machine behind the coordinator.

The pure-Python twin of the native coordinator's chip-lease core
(native/coordinator/coordinator.cc): one shared pool, leases fenced by
a globally monotonic epoch, every mutation pushed through a persist
hook so a restarted broker resumes with exact accounting. Used two
ways:

* embedded by :class:`~edl_tpu.runtime.coordinator.PyCoordinator`
  (persisting the doc into its KV under ``lease/table``) so the
  toolchain-free fallback speaks the same lease API as the native
  server;
* directly by the ``dist-lease-broker`` schedcheck harness, which
  drives the RECOVERING window's confirm-vs-expire race under the
  deterministic scheduler.

Return values mirror the wire protocol, not exceptions: ``confirm``
answers ``"ok" | "stale_epoch" | "freed" | "unknown"`` exactly like
``LCONFIRM`` answers ``OK | FENCED <reason>``, so the client adapter
treats the native and Python backends identically.

Crash discipline: state mutates in memory, then the doc is persisted,
then the caller sees the reply. The ``lease.persist`` fault site sits
between persist and reply — the lost-reply window — so an injected
raise leaves a durably persisted grant whose caller never heard back;
the client-supplied idempotency token makes the retry return the same
lease instead of double-granting the chips.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

from edl_tpu.utils import faults

# state codes match the native ChipLease struct
GRANTED = 0
RECALLING = 1
FREED = 2


@dataclass
class LeaseRow:
    """One lease as the coordinator sees it (int id, int state — the
    broker-side :class:`~edl_tpu.elasticity.broker.Lease` is the
    human-facing view)."""

    id: int
    holder: str
    chips: int
    epoch: int
    state: int = GRANTED
    token: str = ""
    confirmed: bool = False


class LeaseTable:
    """Grant/recall/free/confirm over one shared pool, with epoch
    fencing and a RECOVERING re-confirmation window after restore."""

    def __init__(
        self,
        persist: Optional[Callable[[dict], None]] = None,
        recover_window_s: float = 5.0,
        clock=time.monotonic,
    ):
        self._lock = threading.Lock()
        self._leases: Dict[int, LeaseRow] = {}
        self._pool = 0
        self._free = 0
        self._epoch = 0  # globally monotonic; never reset
        self._next_id = 1
        self._recovering = False
        self._recover_started = 0.0
        self.recover_window_s = recover_window_s
        self._persist = persist
        self._clock = clock

    # -- persistence ---------------------------------------------------------

    def _doc_locked(self) -> dict:
        # FREED rows are history, not state: only live leases persist
        # (same policy as the native WAL snapshot's SLL lines)
        return {
            "pool": self._pool,
            "epoch": self._epoch,
            "next_id": self._next_id,
            "leases": [
                {
                    "id": l.id,
                    "holder": l.holder,
                    "chips": l.chips,
                    "epoch": l.epoch,
                    "state": l.state,
                    "token": l.token,
                }
                for l in self._leases.values()
                if l.state != FREED
            ],
        }

    def _persist_locked(self) -> None:
        if self._persist is not None:
            self._persist(self._doc_locked())
        # chaos site: the injected raise lands after the doc is durably
        # persisted but before the caller sees a reply — the lost-reply
        # window the idempotency token must absorb (conservation is
        # asserted across a restore from exactly this point)
        faults.fault_point("lease.persist")

    def restore(self, doc: dict) -> None:
        """Broker restart: rebuild from the last persisted doc. Live
        leases come back unconfirmed (confirms are session-local, like
        member TTLs) and the table enters RECOVERING: holders must
        re-confirm within the window or :meth:`expire` force-releases
        them. Free is recomputed from first principles so conservation
        holds no matter where in a mutation the old process died."""
        with self._lock:
            self._pool = int(doc.get("pool", 0))
            self._epoch = max(self._epoch, int(doc.get("epoch", 0)))
            self._next_id = max(self._next_id, int(doc.get("next_id", 1)))
            self._leases = {}
            live = 0
            for d in doc.get("leases", ()):
                row = LeaseRow(
                    id=int(d["id"]),
                    holder=d["holder"],
                    chips=int(d["chips"]),
                    epoch=int(d["epoch"]),
                    state=int(d.get("state", GRANTED)),
                    token=d.get("token", ""),
                    confirmed=False,
                )
                self._leases[row.id] = row
                if row.state != FREED:
                    live += row.chips
            self._free = self._pool - live
            if any(l.state != FREED for l in self._leases.values()):
                self._recovering = True
                self._recover_started = self._clock()

    # -- queries -------------------------------------------------------------

    @property
    def recovering(self) -> bool:
        with self._lock:
            return self._recovering

    def snap(self) -> dict:
        """Same shape as the parsed ``LSNAP`` reply."""
        with self._lock:
            return {
                "pool": self._pool,
                "free": self._free,
                "epoch": self._epoch,
                "recovering": self._recovering,
                "leases": [
                    {
                        "id": l.id,
                        "holder": l.holder,
                        "chips": l.chips,
                        "epoch": l.epoch,
                        "state": l.state,
                        "confirmed": l.confirmed,
                    }
                    for l in self._leases.values()
                ],
            }

    def check_conservation(self) -> bool:
        """live chips + free == pool — the invariant every transition
        preserves (and recovery restores)."""
        with self._lock:
            live = sum(
                l.chips for l in self._leases.values() if l.state != FREED
            )
            return live + self._free == self._pool

    # -- transitions ---------------------------------------------------------

    def init(self, total_chips: int) -> bool:
        """Pool init; idempotent on the same total, refused (False)
        while any lease is live. Epoch/next-id survive a re-init so
        fencing stays globally monotonic."""
        with self._lock:
            if self._pool == total_chips and self._pool > 0:
                return True
            if any(l.state != FREED for l in self._leases.values()):
                return False
            self._pool = total_chips
            self._free = total_chips
            self._leases = {}
            self._persist_locked()
            return True

    def grant(self, holder: str, chips: int, token: str = "") -> dict:
        """``{"ok": True, id, epoch, chips}`` or ``{"ok": False,
        reason: "nochips"|"nopool", free}``. Idempotent on ``token``
        among live leases: a retried grant (lost reply) returns the
        original lease unchanged — no chips move, no epoch bump."""
        with self._lock:
            if self._pool <= 0:
                return {"ok": False, "reason": "nopool", "free": 0}
            if token:
                for l in self._leases.values():
                    if l.state != FREED and l.token == token:
                        l.confirmed = True
                        self._maybe_recovered_locked()
                        return {
                            "ok": True, "id": l.id, "epoch": l.epoch,
                            "chips": l.chips,
                        }
            if chips <= 0 or chips > self._free:
                return {"ok": False, "reason": "nochips", "free": self._free}
            self._epoch += 1
            row = LeaseRow(
                id=self._next_id,
                holder=holder,
                chips=chips,
                epoch=self._epoch,
                token=token,
                confirmed=True,  # the live grantee just talked to us
            )
            self._next_id += 1
            self._leases[row.id] = row
            self._free -= chips
            self._persist_locked()
            return {
                "ok": True, "id": row.id, "epoch": row.epoch,
                "chips": row.chips,
            }

    def recall(self, lease_id: int) -> str:
        """GRANTED → RECALLING. ``"ok"`` (idempotent while RECALLING),
        ``"unknown"`` or ``"freed"``."""
        with self._lock:
            row = self._leases.get(lease_id)
            if row is None:
                return "unknown"
            if row.state == FREED:
                return "freed"
            if row.state == GRANTED:
                row.state = RECALLING
                self._persist_locked()
            return "ok"

    def free(self, lease_id: int) -> int:
        """Settle a lease: chips back to the pool. Returns the chips
        freed, ``-1`` unknown, ``-2`` already freed."""
        with self._lock:
            row = self._leases.get(lease_id)
            if row is None:
                return -1
            if row.state == FREED:
                return -2
            self._settle_locked(row)
            self._persist_locked()
            self._maybe_recovered_locked()
            return row.chips

    def confirm(self, lease_id: int, epoch: int) -> str:
        """The fencing check: ``"ok"``, or why the holder is fenced
        (``"stale_epoch"`` / ``"freed"`` / ``"unknown"``). Confirms are
        session-local — not persisted — like member TTLs."""
        with self._lock:
            row = self._leases.get(lease_id)
            if row is None:
                return "unknown"
            if row.state == FREED:
                return "freed"
            if self._stale_locked(row, epoch):
                return "stale_epoch"
            row.confirmed = True
            self._maybe_recovered_locked()
            return "ok"

    def crashed(self, holder: str) -> int:
        """Settle every live lease of a dead holder; returns chips
        returned to the pool."""
        with self._lock:
            chips = 0
            for row in self._leases.values():
                if row.state != FREED and row.holder == holder:
                    chips += row.chips
                    self._settle_locked(row)
            if chips:
                self._persist_locked()
                self._maybe_recovered_locked()
            return chips

    def expire(self) -> Tuple[int, int]:
        """Recovery reaper: once the window has passed, force-release
        every live lease that has not re-confirmed. Returns
        ``(force_released, still_recovering)``."""
        with self._lock:
            if not self._recovering:
                return (0, 0)
            if all(
                l.confirmed for l in self._leases.values() if l.state != FREED
            ):
                self._recovering = False
                return (0, 0)
            if self._clock() < self._recover_started + self.recover_window_s:
                return (0, 1)
            released = 0
            for row in self._leases.values():
                if row.state != FREED and not row.confirmed:
                    self._settle_locked(row)
                    released += 1
            self._recovering = False
            if released:
                self._persist_locked()
            return (released, 0)

    # -- locked helpers ------------------------------------------------------

    def _settle_locked(self, row: LeaseRow) -> None:
        if row.state == FREED:
            return  # settling is idempotent
        row.state = FREED
        self._free += row.chips

    def _stale_locked(self, row: LeaseRow, epoch: int) -> bool:
        """The epoch fence. The ``mut-dist-lease-broker`` schedcheck
        harness strips exactly this predicate to prove the fence is
        load-bearing."""
        return epoch != row.epoch

    def _maybe_recovered_locked(self) -> None:
        if self._recovering and all(
            l.confirmed for l in self._leases.values() if l.state != FREED
        ):
            self._recovering = False  # everyone re-confirmed: recovery over
