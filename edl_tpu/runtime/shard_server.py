"""Peer-to-peer shard transfer — RAM-to-RAM state redistribution.

The drain window of an elastic reshard has old and new worlds coexisting
as live processes; the state that must change owners already sits in the
old workers' host-RAM snapshots (checkpoint.LocalSnapshot). Moving it
worker-to-worker over TCP rides the data-plane network (DCN between TPU
hosts) instead of a shared-storage round trip — the reference's analog
is pserver state living in memory across trainer membership changes
(SURVEY §2.5 comm backend), which never touches disk either.

Each worker runs one :class:`ShardServer` thread serving its CURRENT
snapshot (the reference is swapped atomically at every reshard/commit
snapshot). Restorers probe peers with :func:`fetch_index` and feed
:class:`RemotePieces` handles into the checkpoint piece index —
``_PieceIndex.assemble`` already accepts any ``src[entry]``-indexable
source, so remote pieces participate in the same coverage-checked
assembly as RAM and disk pieces, fetched only for the slices this
process's devices actually need.

The transfer path is built for wire speed (VERDICT r4 #1):

- **batched + pipelined**: ``FETCHN`` requests K pieces in one verb and
  streams K length-prefixed payloads back-to-back, so per-piece RTTs
  collapse to one per batch;
- **parallel**: :meth:`RemotePieces.get_many` stripes a batch across a
  pool of connections (``EDL_P2P_CONNS``, default 4), each fetched by
  its own thread — and the checkpoint prefetch pass batches across
  peers too, so N servers are drained concurrently;
- **zero-copy**: the server ``sendall``s a memoryview of the piece (no
  ``tobytes`` staging), the client ``readinto``s a preallocated buffer
  that becomes the ndarray (no ``frombuffer().copy()``).

Line protocol (length-prefixed binary payloads):

    AUTH <token>\\n         -> OK\\n              (required iff the server
                                                 was given a token check)
    INDEX\\n                -> <len>\\n<json: {"step": S, "entries": {entry: dtype}}>
    FETCH <entry>\\n        -> <len>\\n<raw C-order bytes>   (-1\\n if unknown)
    FETCHN <n>\\n<e1>\\n...  -> n frames of <len>\\n<raw>      (-1\\n if unknown)

Entry keys are ``checkpoint._piece_key`` strings (leaf@offsets@shape),
so offset/extent geometry travels in the key and the index needs no
extra metadata round trips. The server binds the ``EDL_HOST_ADDR``
interface when set (pod IP in production — not every interface), and a
per-job token from coordinator KV gates access to the weights
(ADVICE r4): the trust boundary is "can read the job's KV", not "can
reach the port".
"""

from __future__ import annotations

import json
import os
import socket
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from edl_tpu.runtime.checkpoint import LocalSnapshot, _parse_piece_key, _piece_key
from edl_tpu.utils.logging import kv_logger

log = kv_logger("shardsrv")

_IO_TIMEOUT_S = 30.0
_MAX_BATCH = 4096  # FETCHN sanity cap (protocol abuse guard)


def _default_conns() -> int:
    try:
        return max(1, int(os.environ.get("EDL_P2P_CONNS", "4")))
    except ValueError:
        return 4


def _read_line(f) -> str:
    return f.readline().decode().rstrip("\n")


def _read_into(f, view: memoryview) -> None:
    """Fill the whole view via readinto (BufferedReader reads large
    remainders straight into the destination — no staging copies)."""
    filled, n = 0, len(view)
    while filled < n:
        k = f.readinto(view[filled:])
        if not k:
            raise OSError("short read")
        filled += k


def _read_exact(f, n: int) -> bytearray:
    """Read exactly n bytes into a fresh buffer via readinto — one
    allocation, no intermediate bytes objects."""
    buf = bytearray(n)
    _read_into(f, memoryview(buf))
    return buf


class ShardServer:
    """Serve this process's host-RAM snapshot pieces to peers.

    ``get_snapshot`` returns the snapshot to serve (or None before the
    first one exists); it is called per request, so the owner just keeps
    its ``_ram_snapshot`` attribute fresh and the server follows.
    ``check_token`` (optional) gates every connection: the first verb
    must then be a valid ``AUTH``. ``host`` defaults to the
    ``EDL_HOST_ADDR`` interface when set, else loopback — never every
    interface unless explicitly asked (``host="0.0.0.0"``)."""

    def __init__(
        self,
        get_snapshot: Callable[[], Optional[LocalSnapshot]],
        check_token: Optional[Callable[[str], bool]] = None,
        host: Optional[str] = None,
    ):
        self._get = get_snapshot
        self._check = check_token
        bind = host or os.environ.get("EDL_HOST_ADDR") or "127.0.0.1"
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._srv.bind((bind, 0))
        except OSError:
            # EDL_HOST_ADDR may be a name that is not a local interface
            # (NAT / service VIP): fall back to all interfaces so peers
            # can still reach us at the published address
            self._srv.bind(("0.0.0.0", 0))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._active = 0  # open peer connections (drain-linger signal)
        self._active_lock = threading.Lock()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    @property
    def active(self) -> int:
        with self._active_lock:
            return self._active

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:  # pragma: no cover - thread loop
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _send_piece(self, conn, f, arr) -> None:
        """One <len>\\n<raw> frame; payload bytes go straight from the
        snapshot array to the socket (no tobytes staging copy)."""
        if arr is None:
            f.write(b"-1\n")
            return
        a = np.ascontiguousarray(arr)  # no-op for snapshot pieces
        f.write(str(a.nbytes).encode() + b"\n")
        f.flush()
        conn.sendall(memoryview(a).cast("B", (a.nbytes,)))

    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(_IO_TIMEOUT_S)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        f = conn.makefile("rwb")
        with self._active_lock:
            self._active += 1
        authed = self._check is None
        try:
            while True:
                line = _read_line(f)
                if not line:
                    return
                if line.startswith("AUTH "):
                    if self._check is None or self._check(line[5:]):
                        authed = True
                        f.write(b"OK\n")
                        f.flush()
                        continue
                    return  # bad token: close without serving anything
                if not authed:
                    return  # first verb must be AUTH when gated
                snap = self._get()
                if line == "INDEX":
                    if snap is None:
                        payload = b'{"step": -1, "entries": {}}'
                    else:
                        entries = {
                            _piece_key(key, off, tuple(arr.shape)): str(
                                arr.dtype
                            )
                            for key, plist in snap.pieces.items()
                            for off, arr in plist
                        }
                        payload = json.dumps(
                            {"step": snap.step, "entries": entries}
                        ).encode()
                    f.write(str(len(payload)).encode() + b"\n" + payload)
                    f.flush()
                elif line.startswith("FETCHN "):
                    n = int(line[7:])
                    if not (0 <= n <= _MAX_BATCH):
                        return
                    wanted = [_read_line(f) for _ in range(n)]
                    for entry in wanted:
                        self._send_piece(conn, f, self._lookup(snap, entry))
                    f.flush()
                elif line.startswith("FETCH "):
                    self._send_piece(conn, f, self._lookup(snap, line[6:]))
                    f.flush()
                else:
                    return
        except (OSError, ValueError):
            pass  # peer went away mid-request: its restore retries elsewhere
        finally:
            with self._active_lock:
                self._active -= 1
            try:
                f.close()
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _lookup(snap: Optional[LocalSnapshot], entry: str):
        if snap is None:
            return None
        key, off, shape = _parse_piece_key(entry)
        for o, arr in snap.pieces.get(key, ()):
            if o == off and tuple(arr.shape) == shape:
                return arr
        return None


class _Conn:
    """One pooled client connection: connect-on-demand, AUTH handshake,
    pipelined FETCHN, reconnect-once retry."""

    def __init__(self, addr: str, token: Optional[str]):
        self.addr = addr
        self.token = token
        self.lock = threading.Lock()
        self.sock = None
        self.file = None

    def _connect_locked(self) -> None:
        """Open + AUTH the socket. ``self.lock`` must be held — the
        ``_locked`` suffix is the lock convention `edl check`'s
        lockset-race rule recognizes."""
        host, port = self.addr.rsplit(":", 1)
        self.sock = socket.create_connection(
            (host, int(port)), timeout=_IO_TIMEOUT_S
        )
        self.sock.settimeout(_IO_TIMEOUT_S)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.file = self.sock.makefile("rwb")
        if self.token is not None:
            self.file.write(b"AUTH " + self.token.encode() + b"\n")
            self.file.flush()
            if _read_line(self.file) != "OK":
                raise OSError(f"peer {self.addr} rejected auth")

    def _close_locked(self) -> None:
        try:
            if self.sock is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = self.file = None

    def close(self) -> None:
        """Public close takes the lock: a teardown racing an in-flight
        ``fetch_batch`` on another thread must not None the file out
        from under a read (waits for the current batch instead —
        `edl check` lockset-race found the unguarded variant)."""
        with self.lock:
            self._close_locked()

    def fetch_batch(
        self, entries: Sequence[str], dtypes: Dict[str, str]
    ) -> Dict[str, np.ndarray]:
        """Pipelined batch fetch: one FETCHN request, then K payloads
        read back-to-back into preallocated buffers (the arrays are
        views over those buffers — no copy)."""
        out: Dict[str, np.ndarray] = {}
        with self.lock:
            for attempt in (0, 1):  # one reconnect per batch
                try:
                    if self.sock is None:
                        self._connect_locked()
                    req = (f"FETCHN {len(entries)}\n" + "".join(
                        e + "\n" for e in entries
                    )).encode()
                    self.file.write(req)
                    self.file.flush()
                    missing = []
                    for entry in entries:
                        line = self.file.readline()
                        if not line:
                            # server idled out the connection between
                            # batches (30s I/O timeout): clean EOF —
                            # reconnect path, not a parse error
                            raise OSError("peer closed connection")
                        n = int(line)
                        if n < 0:
                            # keep READING the remaining frames: raising
                            # mid-stream would leave unread payloads on
                            # the wire, and the next batch on this
                            # pooled connection would read a stale frame
                            # as its own response
                            missing.append(entry)
                            continue
                        _, _, shape = _parse_piece_key(entry)
                        # receive straight into the final array —
                        # np.empty skips the zeroing pass a bytearray
                        # would pay on multi-MB pieces
                        arr = np.empty(shape, np.dtype(dtypes[entry]))
                        if arr.nbytes != n:
                            raise ValueError(
                                f"piece {entry}: {n} bytes vs "
                                f"expected {arr.nbytes}"
                            )
                        _read_into(
                            self.file,
                            memoryview(arr).cast("B", (n,))
                            if n
                            else memoryview(b""),
                        )
                        out[entry] = arr
                    if missing:
                        raise KeyError(
                            f"peer {self.addr} lost pieces {missing[:3]}"
                            + ("..." if len(missing) > 3 else "")
                        )
                    return out
                except (OSError, ValueError):
                    self._close_locked()  # self.lock already held here
                    out.clear()
                    if attempt:
                        raise
        raise OSError(f"unreachable peer {self.addr}")  # pragma: no cover


def fetch_index(
    addr: str, timeout_s: float = 2.0, token: Optional[str] = None
) -> Optional[Tuple[int, Dict[str, str]]]:
    """(step, {entry: dtype}) served by a peer, or None if unreachable —
    a dead/departed peer is an expected outcome, not an error."""
    host, port = addr.rsplit(":", 1)
    try:
        conn = socket.create_connection((host, int(port)), timeout=timeout_s)
    except OSError:
        return None
    try:
        conn.settimeout(_IO_TIMEOUT_S)
        f = conn.makefile("rwb")
        if token is not None:
            f.write(b"AUTH " + token.encode() + b"\n")
            f.flush()
            if _read_line(f) != "OK":
                return None
        f.write(b"INDEX\n")
        f.flush()
        n = int(_read_line(f))
        doc = json.loads(bytes(_read_exact(f, n)).decode())
        return int(doc["step"]), dict(doc["entries"])
    except (OSError, ValueError, KeyError):
        return None
    finally:
        try:
            conn.close()
        except OSError:
            pass


class RemotePieces:
    """Piece source over one peer's ShardServer, shaped for
    ``checkpoint._PieceIndex``: ``src[entry]`` returns that piece's
    ndarray, and :meth:`get_many` drains a batch through the connection
    pool — ``nconn`` sockets fetched by parallel threads, each request
    pipelined (``FETCHN``). The checkpoint prefetch pass calls
    ``get_many`` with everything a restore needs from this peer, so
    ``src[entry]`` during assembly is a cache hit. A fetch failure
    raises — the restore's coverage check then surfaces it instead of
    silently assembling a hole."""

    def __init__(
        self,
        addr: str,
        entries: Dict[str, str],
        token: Optional[str] = None,
        nconn: Optional[int] = None,
    ):
        self.addr = addr
        self._dtypes = entries
        self._conns = [
            _Conn(addr, token) for _ in range(nconn or _default_conns())
        ]
        self._cache: Dict[str, np.ndarray] = {}
        self._cache_lock = threading.Lock()

    def entries(self):
        return self._dtypes.keys()

    def close(self) -> None:
        for c in self._conns:
            c.close()
        with self._cache_lock:
            self._cache.clear()

    def get_many(self, entries: Iterable[str]) -> Dict[str, np.ndarray]:
        """Fetch a batch, striped round-robin across the connection
        pool and fetched concurrently; results land in the cache and
        are returned. Raises if any stripe ultimately fails."""
        entries = list(entries)  # may be a generator: iterated twice
        with self._cache_lock:
            want = [
                e for e in dict.fromkeys(entries) if e not in self._cache
            ]
        if want:
            nconn = min(len(self._conns), len(want))
            # greedy byte-balanced striping (largest first): piece sizes
            # are known from the entry geometry, and real snapshots mix
            # multi-MB matmul shards with KB-scale vectors — round-robin
            # would leave stripes idle while one drains the big pieces
            def nbytes(e: str) -> int:
                _, _, shape = _parse_piece_key(e)
                return int(
                    np.prod(shape, dtype=np.int64)
                    * np.dtype(self._dtypes[e]).itemsize
                    if shape
                    else np.dtype(self._dtypes[e]).itemsize
                )

            stripes: List[List[str]] = [[] for _ in range(nconn)]
            loads = [0] * nconn
            for e in sorted(want, key=nbytes, reverse=True):
                i = loads.index(min(loads))
                stripes[i].append(e)
                loads[i] += nbytes(e)
            errs: List[BaseException] = []
            results: List[Dict[str, np.ndarray]] = []

            def run(conn: _Conn, batch: List[str]) -> None:
                try:
                    results.append(conn.fetch_batch(batch, self._dtypes))
                except BaseException as e:  # surfaced to the caller
                    errs.append(e)

            if nconn == 1:
                run(self._conns[0], stripes[0])
            else:
                threads = [
                    threading.Thread(
                        target=run, args=(c, s), daemon=True
                    )
                    for c, s in zip(self._conns, stripes)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            # cache the stripes that DID land before surfacing any
            # failure: a retry (or lazy assembly) must not refetch
            # multi-MB pieces this call already transferred
            with self._cache_lock:
                for r in results:
                    self._cache.update(r)
            if errs:
                raise errs[0]
        with self._cache_lock:
            return {e: self._cache[e] for e in entries}

    def __getitem__(self, entry: str) -> np.ndarray:
        with self._cache_lock:
            hit = self._cache.get(entry)
        if hit is not None:
            return hit
        return self.get_many([entry])[entry]
