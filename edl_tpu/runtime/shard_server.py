"""Peer-to-peer shard transfer — RAM-to-RAM state redistribution.

The drain window of an elastic reshard has old and new worlds coexisting
as live processes; the state that must change owners already sits in the
old workers' host-RAM snapshots (checkpoint.LocalSnapshot). Moving it
worker-to-worker over TCP rides the data-plane network (DCN between TPU
hosts) instead of a shared-storage round trip — the reference's analog
is pserver state living in memory across trainer membership changes
(SURVEY §2.5 comm backend), which never touches disk either.

Each worker runs one :class:`ShardServer` thread serving its CURRENT
snapshot (the reference is swapped atomically at every reshard/commit
snapshot). Restorers probe peers with :func:`fetch_index` and feed
:class:`RemotePieces` handles into the checkpoint piece index —
``_PieceIndex.assemble`` already accepts any ``src[entry]``-indexable
source, so remote pieces participate in the same coverage-checked
assembly as RAM and disk pieces, fetched lazily and only for the slices
this process's devices actually need.

Line protocol (length-prefixed binary payloads):

    INDEX\n               -> <len>\n<json: {"step": S, "entries": {entry: dtype}}>
    FETCH <entry>\n        -> <len>\n<raw C-order bytes>   (-1\n if unknown)

Entry keys are ``checkpoint._piece_key`` strings (leaf@offsets@shape),
so offset/extent geometry travels in the key and the index needs no
extra metadata round trips.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from edl_tpu.runtime.checkpoint import LocalSnapshot, _parse_piece_key, _piece_key
from edl_tpu.utils.logging import kv_logger

log = kv_logger("shardsrv")

_IO_TIMEOUT_S = 30.0


def _read_line(f) -> str:
    return f.readline().decode().rstrip("\n")


class ShardServer:
    """Serve this process's host-RAM snapshot pieces to peers.

    ``get_snapshot`` returns the snapshot to serve (or None before the
    first one exists); it is called per request, so the owner just keeps
    its ``_ram_snapshot`` attribute fresh and the server follows."""

    def __init__(self, get_snapshot: Callable[[], Optional[LocalSnapshot]]):
        self._get = get_snapshot
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", 0))
        self._srv.listen(32)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._active = 0  # open peer connections (drain-linger signal)
        self._active_lock = threading.Lock()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    @property
    def active(self) -> int:
        with self._active_lock:
            return self._active

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:  # pragma: no cover - thread loop
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(_IO_TIMEOUT_S)
        f = conn.makefile("rwb")
        with self._active_lock:
            self._active += 1
        try:
            while True:
                line = _read_line(f)
                if not line:
                    return
                snap = self._get()
                if line == "INDEX":
                    if snap is None:
                        payload = b'{"step": -1, "entries": {}}'
                    else:
                        entries = {
                            _piece_key(key, off, tuple(arr.shape)): str(
                                arr.dtype
                            )
                            for key, plist in snap.pieces.items()
                            for off, arr in plist
                        }
                        payload = json.dumps(
                            {"step": snap.step, "entries": entries}
                        ).encode()
                    f.write(str(len(payload)).encode() + b"\n" + payload)
                    f.flush()
                elif line.startswith("FETCH "):
                    arr = self._lookup(snap, line[6:])
                    if arr is None:
                        f.write(b"-1\n")
                    else:
                        raw = np.ascontiguousarray(arr).tobytes()
                        f.write(str(len(raw)).encode() + b"\n" + raw)
                    f.flush()
                else:
                    return
        except (OSError, ValueError):
            pass  # peer went away mid-request: its restore retries elsewhere
        finally:
            with self._active_lock:
                self._active -= 1
            try:
                f.close()
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _lookup(snap: Optional[LocalSnapshot], entry: str):
        if snap is None:
            return None
        key, off, shape = _parse_piece_key(entry)
        for o, arr in snap.pieces.get(key, ()):
            if o == off and tuple(arr.shape) == shape:
                return arr
        return None


def fetch_index(
    addr: str, timeout_s: float = 2.0
) -> Optional[Tuple[int, Dict[str, str]]]:
    """(step, {entry: dtype}) served by a peer, or None if unreachable —
    a dead/departed peer is an expected outcome, not an error."""
    host, port = addr.rsplit(":", 1)
    try:
        conn = socket.create_connection((host, int(port)), timeout=timeout_s)
    except OSError:
        return None
    try:
        conn.settimeout(_IO_TIMEOUT_S)
        f = conn.makefile("rwb")
        f.write(b"INDEX\n")
        f.flush()
        n = int(_read_line(f))
        doc = json.loads(f.read(n).decode())
        return int(doc["step"]), dict(doc["entries"])
    except (OSError, ValueError, KeyError):
        return None
    finally:
        try:
            conn.close()
        except OSError:
            pass


class RemotePieces:
    """Lazy piece source over one peer's ShardServer, shaped for
    ``checkpoint._PieceIndex``: ``src[entry]`` fetches that piece's raw
    bytes over a persistent connection and returns the ndarray. A fetch
    failure raises — the restore's coverage check then surfaces it
    instead of silently assembling a hole."""

    def __init__(self, addr: str, entries: Dict[str, str]):
        self.addr = addr
        self._dtypes = entries
        self._lock = threading.Lock()
        self._conn = None
        self._file = None

    def entries(self):
        return self._dtypes.keys()

    def _connect(self):
        host, port = self.addr.rsplit(":", 1)
        self._conn = socket.create_connection(
            (host, int(port)), timeout=_IO_TIMEOUT_S
        )
        self._conn.settimeout(_IO_TIMEOUT_S)
        self._file = self._conn.makefile("rwb")

    def close(self) -> None:
        try:
            if self._conn is not None:
                self._conn.close()
        except OSError:
            pass
        self._conn = self._file = None

    def __getitem__(self, entry: str) -> np.ndarray:
        _, _, shape = _parse_piece_key(entry)
        dtype = np.dtype(self._dtypes[entry])
        with self._lock:
            for attempt in (0, 1):  # one reconnect per fetch
                try:
                    if self._conn is None:
                        self._connect()
                    self._file.write(b"FETCH " + entry.encode() + b"\n")
                    self._file.flush()
                    line = self._file.readline()
                    if not line:
                        # server idled out our connection between lazy
                        # fetches (its 30s I/O timeout): a clean EOF —
                        # take the reconnect path, not a parse error
                        raise OSError("peer closed connection")
                    n = int(line)
                    if n < 0:
                        raise KeyError(f"peer {self.addr} lost piece {entry}")
                    buf = self._file.read(n)
                    if len(buf) != n:
                        raise OSError("short read")
                    return np.frombuffer(buf, dtype).reshape(shape).copy()
                except (OSError, ValueError):
                    self.close()
                    if attempt:
                        raise
        raise OSError(f"unreachable peer {self.addr}")  # pragma: no cover
