"""LocalJobRunner — the minimum end-to-end slice, in one process.

Binds the control plane (controller/updater/autoscaler over a cluster
backend) to the elastic runtime (mesh + reshard) for a single
TrainingJob, playing the role of the reference's pod entrypoint + Paddle
runtime (reference: docker/paddle_k8s start_new_trainer:121-143 exec'ing
the user program against the master/etcd services). Scale retargets from
the autoscaler flow straight into an in-place reshard; reshard stalls
flow back into TrainingJobStatus.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import optax

from edl_tpu.api.job import JobPhase, TrainingJob
from edl_tpu.controller.controller import Controller
from edl_tpu.runtime.data import ElasticDataQueue
from edl_tpu.runtime.elastic import ElasticTrainer, ReshardEvent, TrainReport
from edl_tpu.utils.logging import kv_logger

log = kv_logger("localrun")


class LocalJobRunner:
    """Drive one submitted TrainingJob's training loop in-process."""

    def __init__(
        self,
        controller: Controller,
        job: TrainingJob,
        loss_fn: Callable,
        tx: optax.GradientTransformation,
        init_params: Any,
        chips_per_worker: Optional[int] = None,
        per_chip_batch: int = 32,
        param_pspecs=None,
        devices=None,
        sync_every: int = 1,
    ):
        self.controller = controller
        self.job = job
        cluster = controller.cluster
        group = cluster.get_worker_group(job)
        self.trainer = ElasticTrainer(
            loss_fn,
            tx,
            mesh_spec=job.spec.mesh,
            chips_per_worker=chips_per_worker
            if chips_per_worker is not None
            else max(job.chips_per_worker(), 1),
            per_chip_batch=per_chip_batch,
            param_pspecs=param_pspecs,
            devices=devices,
            on_reshard=self._reshard_done,
            sync_every=sync_every,
        )
        # autoscaler retarget -> in-place reshard at next step boundary
        self._attached = False
        if hasattr(cluster, "scale_listeners"):
            cluster.scale_listeners.append(self._on_scale)
            self._attached = True
        u = controller.updaters.get(job.qualified_name)
        if u is not None:
            u.runtime_attached = True  # this runner reports reshard stalls
        self.trainer.start(init_params, n_workers=group.parallelism)

    def detach(self) -> None:
        """Stop receiving scale events (called when the run completes, so
        a finished runner is neither retargeted nor kept alive)."""
        if self._attached:
            try:
                self.controller.cluster.scale_listeners.remove(self._on_scale)
            except ValueError:
                pass
            self._attached = False
        u = self.controller.updaters.get(self.job.qualified_name)
        if u is not None:
            u.runtime_attached = False

    def _on_scale(self, job_name: str, parallelism: int) -> None:
        if job_name == self.job.qualified_name:
            self.trainer.request_rescale(parallelism)

    def _reshard_done(self, ev: ReshardEvent) -> None:
        u = self.controller.updaters.get(self.job.qualified_name)
        if u is not None:
            u.on_reshard_done(ev.stall_s, fallback=ev.fallback)

    def sync_membership(self) -> None:
        """Reshard down to the live worker count when members die without
        a retarget (failure detection; the coordinator-heartbeat analog of
        Paddle's etcd membership — reference: train_ft.py:105-114
        use_etcd=True). The scheduler's target may still include a
        pending replacement; training proceeds with who's alive."""
        try:
            g = self.controller.cluster.get_worker_group(self.job)
        except KeyError:
            return
        live = g.active
        if 0 < live != self.trainer.n_workers:
            log.info(
                "membership change", live=live, workers=self.trainer.n_workers
            )
            self.trainer.request_rescale(live)

    def run(
        self,
        data_fn: Callable[[int], Any],
        n_steps: Optional[int] = None,
        queue: Optional[ElasticDataQueue] = None,
    ) -> TrainReport:
        """Train until ``n_steps`` or (with a queue) until the data queue
        drains; then mark the worker group complete so the updater's
        convert() lands the job in SUCCEEDED."""
        try:
            if n_steps is not None:
                report = self.trainer.train_steps(data_fn, n_steps)
            else:
                assert queue is not None, "need n_steps or a queue"
                report = self.trainer.report
                while not queue.done():
                    self.sync_membership()
                    report = self.trainer.train_steps(data_fn, 1)
            cluster = self.controller.cluster
            if hasattr(cluster, "finish_workers"):
                cluster.finish_workers(
                    self.job.namespace, f"{self.job.name}-worker"
                )
            self.controller.step()
        finally:
            self.detach()
        return report
