"""Coordinator bindings — native C++ service + client + Python fallback.

The coordination plane replacing the reference's etcd sidecar + Paddle
master Go binary (reference: pkg/jobparser.go:167-227,
docker/paddle_k8s:26-32). Three ways to get one, same duck-typed
interface:

- ``NativeCoordinator()``  — in-process C++ core via ctypes
  (libedl_coord.so, auto-built from native/coordinator).
- ``CoordinatorClient(host, port)`` — TCP client to a running
  ``edl-coordinator`` server (multi-host jobs).
- ``PyCoordinator()``      — pure-Python fallback when no toolchain.

Interface: kv_put/kv_get/kv_del · register/heartbeat/leave/expire/
epoch/members · barrier_arrive/barrier_count · queue_init/lease/ack/
nack/release_worker/queue_done/queue_stats.
"""

from __future__ import annotations

import ctypes
import json
import os
import random
import socket
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from edl_tpu.obs import disttrace
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.runtime.data import ElasticDataQueue, Task
from edl_tpu.runtime.lease_table import LeaseTable
from edl_tpu.utils import faults, tracing
from edl_tpu.utils.logging import kv_logger

log = kv_logger("coordinator")


def _rpc_counters():
    """RPC-volume telemetry for the coordination plane (a chatty
    rendezvous loop or a KV hot spot shows up as a per-op counter on
    /metrics, not just as mystery latency). Resolved per call so a
    registry swap in tests takes effect."""
    r = obs_metrics.default_registry()
    return (
        r.counter(
            "edl_coordinator_rpc_total",
            "coordinator client round trips", ("op",),
        ),
        r.counter(
            "edl_coordinator_reconnects_total",
            "coordinator client reconnect attempts",
        ),
    )


def _emit_rpc_error(op: str, err: Exception) -> None:
    """Flight-recorder entry for a failed coordinator round trip —
    error-path only (the happy path stays a counter inc), so RPC drops
    land on the same timeline as the reconnects and recoveries they
    cause."""
    from edl_tpu.obs import events

    events.emit(
        "coord.rpc_error", severity="warn", op=op,
        error=f"{type(err).__name__}: {err}",
    )

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "coordinator",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libedl_coord.so")
_BIN_PATH = os.path.join(_NATIVE_DIR, "build", "edl-coordinator")

_build_lock = threading.Lock()


_COORD_SOURCES = (
    "coordinator.h",
    "coordinator.cc",
    "capi.cc",
    "server_main.cc",
    "Makefile",
)


def _coord_fresh() -> bool:
    """Built artifacts newer than every source (incl. the Makefile, so
    flag changes rebuild) — same freshness policy as scheduler/native."""
    if not (os.path.exists(_LIB_PATH) and os.path.exists(_BIN_PATH)):
        return False
    built = min(os.path.getmtime(_LIB_PATH), os.path.getmtime(_BIN_PATH))
    for s in _COORD_SOURCES:
        p = os.path.join(_NATIVE_DIR, s)
        if os.path.exists(p) and os.path.getmtime(p) > built:
            return False
    return True


def ensure_native_built() -> bool:
    """Build the native lib/binary on demand; False if no toolchain."""
    if _coord_fresh():
        return True
    with _build_lock:
        if _coord_fresh():
            return True
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
            return True
        except Exception as e:  # no g++/make: fall back to PyCoordinator
            log.warn("native coordinator build failed", error=str(e))
            return False


@dataclass
class Member:
    name: str
    incarnation: int
    rank: int


def _parse_members(s: str) -> List[Member]:
    out = []
    if s:
        for part in s.split(","):
            name, inc, rank = part.rsplit(":", 2)
            out.append(Member(name, int(inc), int(rank)))
    return out


def _parse_lease_snap(s: str) -> Dict:
    """Parse ``pool free epoch recovering [id|holder|chips|epoch|state|
    confirmed,...]`` (the LSNAP payload; "|" because holders contain
    ":") into the same dict shape LeaseTable.snap() returns."""
    parts = s.split(" ", 4)
    out = {
        "pool": int(parts[0]),
        "free": int(parts[1]),
        "epoch": int(parts[2]),
        "recovering": bool(int(parts[3])),
        "leases": [],
    }
    if len(parts) > 4 and parts[4]:
        for ent in parts[4].split(","):
            lid, holder, chips, ep, st, conf = ent.split("|")
            out["leases"].append(
                {
                    "id": int(lid),
                    "holder": holder,
                    "chips": int(chips),
                    "epoch": int(ep),
                    "state": int(st),
                    "confirmed": bool(int(conf)),
                }
            )
    return out


class NativeCoordinator:
    """ctypes wrapper over the C++ core (in-process mode)."""

    def __init__(self, member_ttl_s: float = 10.0, wal_path: str = ""):
        if not ensure_native_built():
            raise RuntimeError("native coordinator unavailable")
        lib = ctypes.CDLL(_LIB_PATH)
        lib.edl_coord_new.restype = ctypes.c_void_p
        lib.edl_coord_new.argtypes = [ctypes.c_double]
        lib.edl_coord_new_wal.restype = ctypes.c_void_p
        lib.edl_coord_new_wal.argtypes = [ctypes.c_double, ctypes.c_char_p]
        lib.edl_coord_free.argtypes = [ctypes.c_void_p]
        lib.edl_kv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p]
        lib.edl_kv_get.restype = ctypes.c_longlong
        lib.edl_kv_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_longlong,
        ]
        lib.edl_kv_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.edl_member_register.restype = ctypes.c_longlong
        lib.edl_member_register.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_longlong,
        ]
        lib.edl_member_heartbeat.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.edl_member_leave.restype = ctypes.c_longlong
        lib.edl_member_leave.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.edl_member_expire.restype = ctypes.c_longlong
        lib.edl_member_expire.argtypes = [ctypes.c_void_p]
        lib.edl_epoch.restype = ctypes.c_longlong
        lib.edl_epoch.argtypes = [ctypes.c_void_p]
        lib.edl_members.restype = ctypes.c_longlong
        lib.edl_members.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_longlong,
        ]
        lib.edl_barrier_arrive.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.edl_barrier_count.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.edl_queue_init.argtypes = [
            ctypes.c_void_p,
            ctypes.c_longlong,
            ctypes.c_longlong,
            ctypes.c_int,
            ctypes.c_double,
            ctypes.c_int,
        ]
        lib.edl_queue_lease.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_longlong * 4,
        ]
        lib.edl_queue_ack.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.edl_queue_nack.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.edl_queue_release_worker.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.edl_queue_done.argtypes = [ctypes.c_void_p]
        lib.edl_queue_stats.argtypes = [ctypes.c_void_p, ctypes.c_longlong * 5]
        lib.edl_lease_init.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.edl_lease_grant.restype = ctypes.c_longlong
        lib.edl_lease_grant.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_char_p,
            ctypes.c_longlong * 2,
        ]
        lib.edl_lease_recall.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.edl_lease_free.restype = ctypes.c_longlong
        lib.edl_lease_free.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.edl_lease_confirm.argtypes = [
            ctypes.c_void_p,
            ctypes.c_longlong,
            ctypes.c_longlong,
        ]
        lib.edl_lease_crashed.restype = ctypes.c_longlong
        lib.edl_lease_crashed.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.edl_lease_expire.argtypes = [ctypes.c_void_p, ctypes.c_longlong * 2]
        lib.edl_lease_set_recover_window.argtypes = [
            ctypes.c_void_p,
            ctypes.c_double,
        ]
        lib.edl_lease_snap.restype = ctypes.c_longlong
        lib.edl_lease_snap.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_longlong,
        ]
        lib.edl_wal_compact.argtypes = [ctypes.c_void_p]
        lib.edl_wal_set_compact_bytes.argtypes = [
            ctypes.c_void_p,
            ctypes.c_longlong,
        ]
        lib.edl_wal_stats.argtypes = [ctypes.c_void_p, ctypes.c_longlong * 2]
        self._lib = lib
        # wal_path makes the coordinator durable: mutations append to a
        # write-ahead log; a new instance on the same path replays it
        if wal_path:
            # preflight the path so an unwritable WAL raises here
            # instead of running silently non-durable
            with open(wal_path, "a"):
                pass
            self._h = lib.edl_coord_new_wal(member_ttl_s, wal_path.encode())
        else:
            self._h = lib.edl_coord_new(member_ttl_s)

    def close(self):
        if self._h:
            self._lib.edl_coord_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        # edl: no-lint[silent-failure] __del__ during interpreter shutdown: nothing to report to, must never raise
        except Exception:
            pass

    # KV
    def kv_put(self, k: str, v: str) -> None:
        self._lib.edl_kv_put(self._h, k.encode(), v.encode())

    def kv_get(self, k: str) -> Optional[str]:
        buf = ctypes.create_string_buffer(65536)
        n = self._lib.edl_kv_get(self._h, k.encode(), buf, len(buf))
        return None if n < 0 else buf.value.decode()

    def kv_del(self, k: str) -> None:
        self._lib.edl_kv_del(self._h, k.encode())

    # membership
    def register(self, worker: str, incarnation: int) -> int:
        return self._lib.edl_member_register(self._h, worker.encode(), incarnation)

    def heartbeat(self, worker: str) -> bool:
        return bool(self._lib.edl_member_heartbeat(self._h, worker.encode()))

    def leave(self, worker: str) -> int:
        return self._lib.edl_member_leave(self._h, worker.encode())

    def expire(self) -> int:
        return self._lib.edl_member_expire(self._h)

    def epoch(self) -> int:
        return self._lib.edl_epoch(self._h)

    def members(self) -> List[Member]:
        buf = ctypes.create_string_buffer(65536)
        self._lib.edl_members(self._h, buf, len(buf))
        return _parse_members(buf.value.decode())

    def time(self) -> float:
        """In-process: the reference clock IS this process's clock."""
        return time.time()

    # barriers
    def barrier_arrive(self, name: str, worker: str) -> int:
        return self._lib.edl_barrier_arrive(self._h, name.encode(), worker.encode())

    def barrier_count(self, name: str) -> int:
        return self._lib.edl_barrier_count(self._h, name.encode())

    # queue
    def queue_init(
        self,
        n_samples: int,
        chunk: int,
        passes: int = 1,
        lease_timeout_s: float = 16.0,
        max_failures: int = 3,
    ) -> None:
        self._lib.edl_queue_init(
            self._h, n_samples, chunk, passes, lease_timeout_s, max_failures
        )

    def lease(self, worker: str) -> Optional[Task]:
        out = (ctypes.c_longlong * 4)()
        if not self._lib.edl_queue_lease(self._h, worker.encode(), out):
            return None
        return Task(task_id=out[0], start=out[1], end=out[2], epoch=out[3])

    def ack(self, task_id: int) -> bool:
        return bool(self._lib.edl_queue_ack(self._h, task_id))

    def nack(self, task_id: int) -> bool:
        return bool(self._lib.edl_queue_nack(self._h, task_id))

    def release_worker(self, worker: str) -> int:
        return self._lib.edl_queue_release_worker(self._h, worker.encode())

    def queue_done(self) -> bool:
        return bool(self._lib.edl_queue_done(self._h))

    def queue_stats(self) -> Dict[str, int]:
        out = (ctypes.c_longlong * 5)()
        self._lib.edl_queue_stats(self._h, out)
        return {
            "todo": out[0],
            "leased": out[1],
            "done": out[2],
            "dead": out[3],
            "epoch": out[4],
        }

    # chip leases (the distributed ChipLeaseBroker backend; WAL-logged,
    # so a SIGKILLed broker resumes with exact lease accounting)
    def lease_init(self, total_chips: int) -> bool:
        return bool(self._lib.edl_lease_init(self._h, total_chips))

    def lease_grant(self, holder: str, chips: int, token: str = "") -> Dict:
        token = token or uuid.uuid4().hex
        out = (ctypes.c_longlong * 2)()
        lid = self._lib.edl_lease_grant(
            self._h, holder.encode(), chips, token.encode(), out
        )
        if lid == -2:
            return {"ok": False, "reason": "nopool", "free": 0}
        if lid == -1:
            return {"ok": False, "reason": "nochips", "free": out[1]}
        return {"ok": True, "id": lid, "epoch": out[0], "chips": out[1]}

    def lease_recall(self, lease_id: int) -> str:
        rc = self._lib.edl_lease_recall(self._h, lease_id)
        return {0: "ok", -1: "unknown", -2: "freed"}[rc]

    def lease_free(self, lease_id: int) -> int:
        return self._lib.edl_lease_free(self._h, lease_id)

    def lease_confirm(self, lease_id: int, epoch: int) -> str:
        rc = self._lib.edl_lease_confirm(self._h, lease_id, epoch)
        return {0: "ok", 1: "stale_epoch", 2: "freed", 3: "unknown"}[rc]

    def lease_crashed(self, holder: str) -> int:
        return self._lib.edl_lease_crashed(self._h, holder.encode())

    def lease_expire(self) -> Tuple[int, int]:
        out = (ctypes.c_longlong * 2)()
        self._lib.edl_lease_expire(self._h, out)
        return (out[0], out[1])

    def lease_set_recover_window(self, seconds: float) -> None:
        self._lib.edl_lease_set_recover_window(self._h, seconds)

    def lease_snap(self) -> Dict:
        buf = ctypes.create_string_buffer(262144)
        self._lib.edl_lease_snap(self._h, buf, len(buf))
        return _parse_lease_snap(buf.value.decode())

    # WAL compaction (snapshot+truncate: replay cost O(state), not
    # O(history) — the compacted-etcd-durability analog)
    def wal_compact(self) -> None:
        self._lib.edl_wal_compact(self._h)

    def set_wal_compact_bytes(self, n: int) -> None:
        self._lib.edl_wal_set_compact_bytes(self._h, n)

    def wal_stats(self) -> Dict[str, int]:
        out = (ctypes.c_longlong * 2)()
        self._lib.edl_wal_stats(self._h, out)
        return {"appended_bytes": out[0], "compactions": out[1]}


class CoordinatorClient:
    """TCP client for the edl-coordinator line protocol.

    Survives coordinator restarts: a broken connection is re-dialed with
    exponential backoff for up to ``reconnect_window_s`` and the command
    re-issued (the WAL makes the restarted server resume with the same
    state, so retried commands are safe: PUT/DEL/REG/BARRIER are
    idempotent, a retried LEASE at worst leases a different task while
    the first lease times out and redelivers, and a retried ACK/NACK
    whose first attempt was applied returns False — callers already
    treat that as "lease gone"). Set ``reconnect_window_s=0`` to fail
    fast (the old behavior)."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = 10.0,
        reconnect_window_s: float = 30.0,
    ):
        self._host = host
        self._port = port
        self._timeout_s = timeout_s
        self._reconnect_window_s = reconnect_window_s
        self._lock = threading.Lock()
        self._sock = None
        self._file = None
        self._connect_locked()

    def _connect_locked(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout_s
        )
        self._file = self._sock.makefile("rwb")

    def _close_locked(self) -> None:
        try:
            if self._file is not None:
                self._file.close()
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = self._file = None

    def close(self) -> None:
        # public close must take the lock: _call holds it across a full
        # round trip, and tearing the socket down under an in-flight
        # RPC is exactly the _Conn.close race PR 7 fixed in shard_server
        with self._lock:
            self._close_locked()

    def _roundtrip_locked(self, line: str) -> str:
        if self._sock is None:
            self._connect_locked()
        self._file.write(line.encode() + b"\n")
        self._file.flush()
        resp = self._file.readline()
        if not resp:
            raise ConnectionError("coordinator closed connection")
        return resp.decode().rstrip("\n")

    def _call(self, line: str) -> str:
        rpcs, reconnects = _rpc_counters()
        with self._lock:
            deadline = time.monotonic() + self._reconnect_window_s
            backoff = 0.05
            while True:
                try:
                    # chaos site: an armed "drop" raises ConnectionError
                    # here, driving the REAL close/reconnect/backoff
                    # path below (scripts/exp_chaos.py soaks this at 5%)
                    faults.fault_point("coord.rpc")
                    if disttrace.current() is not None:
                        # on a traced path (a step/reshard/request
                        # root is active) the round trip becomes a
                        # client span carrying the trace context —
                        # the fleet merge's flow-link anchor. Untraced
                        # polling loops cost one contextvar read.
                        with tracing.span(
                            "coord.rpc", op=line.split(" ", 1)[0]
                        ):
                            out = self._roundtrip_locked(line)
                    else:
                        out = self._roundtrip_locked(line)
                    rpcs.inc(op=line.split(" ", 1)[0])
                    return out
                except (ConnectionError, OSError, socket.timeout) as e:
                    self._close_locked()
                    reconnects.inc()
                    _emit_rpc_error(line.split(" ", 1)[0], e)
                    if time.monotonic() >= deadline:
                        raise ConnectionError(
                            f"coordinator unreachable after "
                            f"{self._reconnect_window_s:.0f}s: {e}"
                        ) from e
                    time.sleep(backoff)
                    # decorrelated jitter, not plain doubling: after a
                    # broker restart every fenced holder re-confirms at
                    # once, and lockstep 0.05/0.1/0.2 waves would
                    # thundering-herd the accept loop — spreading each
                    # client's next attempt over [base, 3*prev) decoheres
                    # them while keeping the same 2 s ceiling
                    backoff = min(2.0, random.uniform(0.05, backoff * 3))

    def ping(self) -> bool:
        return self._call("PING") == "PONG"

    def time(self) -> Optional[float]:
        """The coordinator's wall clock (epoch seconds) — one round
        trip of the clock-alignment handshake (obs/disttrace.py
        ClockSync brackets this call with local reads). None against
        an old server binary without the TIME op, so callers degrade
        to offset 0 instead of failing bring-up."""
        r = self._call("TIME")
        if not r.startswith("TIME "):
            return None
        return int(r.split()[1]) / 1e6

    def kv_put(self, k: str, v: str) -> None:
        self._call(f"PUT {k} {v}")

    def kv_get(self, k: str) -> Optional[str]:
        r = self._call(f"GET {k}")
        return r[4:] if r.startswith("VAL ") else None

    def kv_del(self, k: str) -> None:
        self._call(f"DEL {k}")

    def register(self, worker: str, incarnation: int) -> int:
        return int(self._call(f"REG {worker} {incarnation}").split()[1])

    def heartbeat(self, worker: str) -> bool:
        return self._call(f"HB {worker}") == "OK"

    def leave(self, worker: str) -> int:
        return int(self._call(f"LEAVE {worker}").split()[1])

    def expire(self) -> int:
        return int(self._call("EXPIRE").split()[1])

    def epoch(self) -> int:
        return int(self._call("EPOCH").split()[1])

    def members(self) -> List[Member]:
        r = self._call("MEMBERS")
        return _parse_members(r[8:].strip())

    def barrier_arrive(self, name: str, worker: str) -> int:
        return int(self._call(f"BARRIER {name} {worker}").split()[1])

    def barrier_count(self, name: str) -> int:
        return int(self._call(f"BCOUNT {name}").split()[1])

    def queue_init(
        self,
        n_samples: int,
        chunk: int,
        passes: int = 1,
        lease_timeout_s: float = 16.0,
        max_failures: int = 3,
    ) -> None:
        self._call(f"QINIT {n_samples} {chunk} {passes} {lease_timeout_s}")

    def lease(self, worker: str) -> Optional[Task]:
        r = self._call(f"LEASE {worker}")
        if not r.startswith("TASK "):
            return None
        _, tid, start, end, epoch = r.split()
        return Task(
            task_id=int(tid), start=int(start), end=int(end), epoch=int(epoch)
        )

    def ack(self, task_id: int) -> bool:
        return self._call(f"ACK {task_id}") == "OK"

    def nack(self, task_id: int) -> bool:
        return self._call(f"NACK {task_id}") == "OK"

    def release_worker(self, worker: str) -> int:
        return int(self._call(f"RELEASE {worker}").split()[1])

    def queue_done(self) -> bool:
        return self._call("QDONE") == "DONE 1"

    def queue_stats(self) -> Dict[str, int]:
        parts = self._call("QSTATS").split()[1:]
        keys = ("todo", "leased", "done", "dead", "epoch")
        return dict(zip(keys, map(int, parts)))

    def wal_compact(self) -> None:
        self._call("COMPACT")

    def wal_stats(self) -> Dict[str, int]:
        parts = self._call("WALSTATS").split()[1:]
        return {"appended_bytes": int(parts[0]), "compactions": int(parts[1])}

    # chip leases. Same graceful degradation as time(): an old server
    # binary without the lease ops answers "ERR unknown command" and
    # every method returns None, so callers can fall back to the
    # in-process broker instead of failing bring-up. Holders and
    # tokens must be space-free (":" is fine — "train:job0").

    def lease_init(self, total_chips: int) -> Optional[bool]:
        r = self._call(f"LINIT {total_chips}")
        if r.startswith("OK"):
            return True
        if r == "ERR busy":
            return False
        return None

    def lease_grant(
        self, holder: str, chips: int, token: str = ""
    ) -> Optional[Dict]:
        # the token makes a retried grant (reconnect window re-issuing
        # after a lost reply) return the original lease, not a second
        # one — the WAL-replayed server still knows the token
        token = token or uuid.uuid4().hex
        r = self._call(f"LGRANT {holder} {chips} {token}")
        if r.startswith("LEASE "):
            _, lid, ep, ch = r.split()
            return {
                "ok": True, "id": int(lid), "epoch": int(ep),
                "chips": int(ch), "token": token,
            }
        if r.startswith("ERR nochips"):
            return {"ok": False, "reason": "nochips", "free": int(r.split()[2])}
        if r == "ERR nopool":
            return {"ok": False, "reason": "nopool", "free": 0}
        return None

    def lease_recall(self, lease_id: int) -> Optional[str]:
        r = self._call(f"LRECALL {lease_id}")
        if r == "OK":
            return "ok"
        if r.startswith("ERR unknown c"):  # old server: no lease ops
            return None
        if r.startswith("ERR "):
            return r.split()[1]  # "unknown" | "freed"
        return None

    def lease_free(self, lease_id: int) -> Optional[int]:
        r = self._call(f"LFREE {lease_id}")
        if r.startswith("OK "):
            return int(r.split()[1])
        if r == "ERR unknown":
            return -1
        if r == "ERR freed":
            return -2
        return None

    def lease_confirm(self, lease_id: int, epoch: int) -> Optional[str]:
        r = self._call(f"LCONFIRM {lease_id} {epoch}")
        if r.startswith("OK"):
            return "ok"
        if r.startswith("FENCED "):
            return r.split()[1]  # "stale_epoch" | "freed" | "unknown"
        return None

    def lease_crashed(self, holder: str) -> Optional[int]:
        r = self._call(f"LCRASH {holder}")
        return int(r.split()[1]) if r.startswith("OK ") else None

    def lease_expire(self) -> Optional[Tuple[int, int]]:
        r = self._call("LEXPIRE")
        if not r.startswith("OK "):
            return None
        _, released, recovering = r.split()
        return (int(released), int(recovering))

    def lease_snap(self) -> Optional[Dict]:
        r = self._call("LSNAP")
        if not r.startswith("LEASES "):
            return None
        return _parse_lease_snap(r[7:])


class CoordinatorServer:
    """Spawn/own an edl-coordinator process (per-job coordinator pod
    analog). With ``wal_path`` the server is durable: :meth:`restart`
    (or a crash + external respawn) resumes from the write-ahead log
    with exact KV/membership/queue accounting — the etcd-durability
    analog (reference: pkg/jobparser.go:167-184 runs etcd in the
    master pod; docker/paddle_k8s:28-31 restarts the master against
    it)."""

    def __init__(
        self,
        port: int = 0,
        member_ttl_s: float = 10.0,
        wal_path: str = "",
        wal_compact_bytes: int = 0,  # 0 = server default (1 MiB)
        lease_recover_s: float = -1.0,  # <0 = server default (5 s)
    ):
        if not ensure_native_built():
            raise RuntimeError("native coordinator unavailable")
        if port == 0:
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
        self.port = port
        self.member_ttl_s = member_ttl_s
        self.wal_path = wal_path
        self.wal_compact_bytes = wal_compact_bytes
        self.lease_recover_s = lease_recover_s
        self._spawn()

    def _spawn(self) -> None:
        cmd = [
            _BIN_PATH,
            "--port", str(self.port),
            "--member-ttl", str(self.member_ttl_s),
        ]
        if self.wal_path:
            cmd += ["--wal", self.wal_path]
        if self.wal_compact_bytes > 0:
            cmd += ["--wal-compact-bytes", str(self.wal_compact_bytes)]
        if self.lease_recover_s >= 0:
            # chip-lease recovery window: how long a restarted broker
            # waits for holders to re-confirm before force-releasing
            cmd += ["--lease-recover", str(self.lease_recover_s)]
        self._proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT
        )
        line = self._proc.stdout.readline().decode()
        if "listening" not in line:
            raise RuntimeError(f"coordinator failed to start: {line!r}")

    def client(self) -> CoordinatorClient:
        return CoordinatorClient("127.0.0.1", self.port)

    def kill(self) -> None:
        """Fault injection: SIGKILL the coordinator process (no
        graceful shutdown, no flush beyond the per-mutation WAL
        append)."""
        if self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait(timeout=5)

    def restart(self) -> None:
        """Respawn on the same port, recovering from the WAL (no-op
        state without one). Clients built by :meth:`client` reconnect
        automatically."""
        self.kill()
        self._spawn()

    def stop(self) -> None:
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:  # pragma: no cover
                self._proc.kill()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class PyCoordinator:
    """Pure-Python fallback with the same interface (no toolchain needed)."""

    def __init__(self, member_ttl_s: float = 10.0):
        self._ttl = member_ttl_s
        self._lock = threading.Lock()
        self._kv: Dict[str, str] = {}
        self._members: Dict[str, Tuple[int, float]] = {}
        self._epoch = 0
        self._barriers: Dict[str, set] = {}
        self._queue: Optional[ElasticDataQueue] = None
        # chip leases: the shared state machine, persisting its doc
        # into this KV (the memory-only analog of the native WAL)
        self._lease_table = LeaseTable(persist=self._lease_persist)

    def kv_put(self, k, v):
        with self._lock:
            self._kv[k] = v

    def kv_get(self, k):
        with self._lock:
            return self._kv.get(k)

    def kv_del(self, k):
        with self._lock:
            self._kv.pop(k, None)

    def register(self, worker, incarnation):
        with self._lock:
            cur = self._members.get(worker)
            if cur and cur[0] > incarnation:
                return self._epoch  # zombie with stale incarnation
            if cur is None or cur[0] != incarnation:
                self._epoch += 1
            self._members[worker] = (incarnation, time.monotonic() + self._ttl)
            return self._epoch

    def heartbeat(self, worker):
        with self._lock:
            if worker not in self._members:
                return False
            inc, _ = self._members[worker]
            self._members[worker] = (inc, time.monotonic() + self._ttl)
            return True

    def leave(self, worker):
        with self._lock:
            if self._members.pop(worker, None) is not None:
                self._epoch += 1
            return self._epoch

    def expire(self):
        with self._lock:
            now = time.monotonic()
            dead = [w for w, (_, exp) in self._members.items() if exp <= now]
            for w in dead:
                del self._members[w]
            if dead:
                self._epoch += 1
            return self._epoch

    def epoch(self):
        with self._lock:
            return self._epoch

    def members(self):
        with self._lock:
            return [
                Member(name, inc, rank)
                for rank, (name, (inc, _)) in enumerate(
                    sorted(self._members.items())
                )
            ]

    def time(self):
        """Duck-typed clock-sync parity: in-process fallback, so the
        reference clock is the local one."""
        return time.time()

    def barrier_arrive(self, name, worker):
        with self._lock:
            self._barriers.setdefault(name, set()).add(worker)
            return len(self._barriers[name])

    def barrier_count(self, name):
        with self._lock:
            return len(self._barriers.get(name, ()))

    def queue_init(self, n_samples, chunk, passes=1, lease_timeout_s=16.0,
                   max_failures=3):
        self._queue = ElasticDataQueue(
            n_samples, chunk, passes=passes, lease_timeout_s=lease_timeout_s
        )

    def lease(self, worker):
        return self._queue.get_task(worker) if self._queue else None

    def ack(self, task_id):
        self._queue.ack(task_id)
        return True

    def nack(self, task_id):
        self._queue.nack(task_id)
        return True

    def release_worker(self, worker):
        return self._queue.release_worker(worker) if self._queue else 0

    def queue_done(self):
        return self._queue.done() if self._queue else False

    def queue_stats(self):
        return self._queue.progress() if self._queue else {}

    # chip leases: delegate to the shared LeaseTable (same return
    # values as the native bindings, so the client adapter can't tell
    # the backends apart)
    def _lease_persist(self, doc):
        self.kv_put("lease/table", json.dumps(doc, sort_keys=True))

    def lease_restore(self):
        """Simulate a broker restart: rebuild the lease table from the
        persisted KV doc. Live leases come back unconfirmed and the
        table enters RECOVERING — the WAL-replay analog for the
        memory-only fallback (tests crash the table, then restore)."""
        doc = self.kv_get("lease/table")
        window = self._lease_table.recover_window_s
        self._lease_table = LeaseTable(
            persist=self._lease_persist, recover_window_s=window
        )
        if doc:
            self._lease_table.restore(json.loads(doc))

    def lease_init(self, total_chips):
        return self._lease_table.init(total_chips)

    def lease_grant(self, holder, chips, token=""):
        return self._lease_table.grant(holder, chips, token or uuid.uuid4().hex)

    def lease_recall(self, lease_id):
        return self._lease_table.recall(lease_id)

    def lease_free(self, lease_id):
        return self._lease_table.free(lease_id)

    def lease_confirm(self, lease_id, epoch):
        return self._lease_table.confirm(lease_id, epoch)

    def lease_crashed(self, holder):
        return self._lease_table.crashed(holder)

    def lease_expire(self):
        return self._lease_table.expire()

    def lease_set_recover_window(self, seconds):
        self._lease_table.recover_window_s = seconds

    def lease_snap(self):
        return self._lease_table.snap()

    # WAL interface parity (duck-typed with NativeCoordinator): the
    # Python fallback is memory-only, so these are honest no-ops
    def wal_compact(self):
        pass

    def set_wal_compact_bytes(self, n):
        pass

    def wal_stats(self):
        return {"appended_bytes": 0, "compactions": 0}


def make_coordinator(member_ttl_s: float = 10.0):
    """Best available in-process coordinator: native, else Python."""
    try:
        return NativeCoordinator(member_ttl_s)
    except RuntimeError:
        return PyCoordinator(member_ttl_s)
