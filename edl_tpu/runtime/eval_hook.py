"""Held-out export evaluation — the commit-leader's eval hook.

Reference parity: AUC fetched in the train loop
(/root/reference/example/ctr/ctr/train.py:161-167). Here the commit
leader evaluates every PUBLISHED export (the servable artifact, not the
live device state) against a held-out shards-dir split and publishes
``eval_metric`` = "<step>:<value>" in coordinator KV for the
monitor/CLI. Extracted from worker_main (VERDICT r4 #4).

Resource bounds (ADVICE r4): the split is CAPPED (``eval_max_rows``),
never the whole dir into leader RAM; ``eval_device="cpu"`` moves the
forward passes off the accelerator so eval cannot contend with the
training step loop for HBM; failures are best-effort but NOT silent —
a consecutive-failure count surfaces in KV (``eval_failures``)."""

from __future__ import annotations

from typing import Callable, Optional

from edl_tpu.utils.logging import kv_logger

log = kv_logger("eval")


class ExportEvaluator:
    """One per worker; only the commit leader calls :meth:`evaluate`.
    ``eval_fn(params, rows) -> float`` comes from the workload."""

    def __init__(self, cfg, key_fn: Callable[..., str]):
        self.cfg = cfg
        self._k = key_fn
        self.eval_fn: Optional[Callable] = None  # set by run()
        self._rows = None  # held-out split, loaded once (capped)
        self._failures = 0  # consecutive failures (KV-surfaced)

    def evaluate(self, client, step: int) -> None:
        cfg = self.cfg
        if not cfg.eval_dir or self.eval_fn is None:
            return
        try:
            import contextlib

            from edl_tpu.runtime.export import load_export
            from edl_tpu.runtime.shards import FileShardSource

            if self._rows is None:
                src = FileShardSource(cfg.eval_dir)
                # cap, don't slurp: the split lives in leader host RAM
                # for the job's lifetime (ADVICE r4)
                self._rows = src.fetch_range(
                    0, min(src.n_samples, cfg.eval_max_rows)
                )
            params, _ = load_export(cfg.export_dir)
            ctx = contextlib.nullcontext()
            if cfg.eval_device == "cpu":
                # off the accelerator: eval forwards must not contend
                # with the training step loop for HBM
                import jax

                ctx = jax.default_device(jax.devices("cpu")[0])
            with ctx:
                metric = float(self.eval_fn(params, self._rows))
            client.kv_put(self._k("eval_metric"), f"{step}:{metric:.6f}")
            log.info("eval", step=step, metric=round(metric, 6))
            self._failures = 0
        except Exception as e:  # pragma: no cover - eval is best-effort
            # best-effort, but NOT silent: repeated failures (e.g. the
            # eval OOMing the leader every commit) surface in KV where
            # the monitor/CLI can see them, not just a local log line
            self._failures += 1
            try:
                client.kv_put(self._k("eval_failures"), str(self._failures))
            # edl: no-lint[silent-failure] failure-counter publish is best-effort; the eval failure itself is log.warn'd on the next line
            except Exception:
                pass
            log.warn("export eval failed", error=str(e))
