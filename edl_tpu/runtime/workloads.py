"""Workload registry — each built-in model family as an elastic-worker
workload (extracted from worker_main per VERDICT r4 #4).

Each entry builds a :class:`Workload`: ``batch_fn(start, end)``
synthesizes the samples of index range [start, end) deterministically,
so any worker can materialize any leased task (the RecordIO-shard
analog); ``pspecs(plan)`` returns model-specific parameter
PartitionSpecs (None = the generic fsdp rule of parallel/sharding.py);
``eval_fn(params, rows)`` is the held-out metric the commit leader
publishes (runtime/eval_hook.py); ``model_meta`` is the architecture
record exports carry for serving consumers (runtime/predict.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import numpy as np

from edl_tpu.obs import costmodel
from edl_tpu.runtime.worker_config import WorkerConfig

# --------------------------------------------------------------------------
# model registry — each entry builds a Workload: batch_fn(start, end)
# synthesizes the samples of index range [start, end) deterministically,
# so any worker can materialize any leased task (the RecordIO-shard
# analog); pspecs(plan) returns model-specific parameter PartitionSpecs
# (None = the generic fsdp rule of parallel/sharding.py).


@dataclass
class Workload:
    init_params: Callable[[], Any]
    loss_fn: Callable
    batch_fn: Callable[[int, int], Dict[str, np.ndarray]]
    pspecs: Optional[Callable[[Any], Any]] = None
    # mesh-aware loss factory (plan, mesh) -> loss_fn. Models whose
    # program depends on the mesh layout (llama's sp ring attention /
    # pp pipeline schedule) provide this; it is re-invoked after every
    # rendezvous so the compiled step matches the current elastic mesh.
    # When absent, the static loss_fn is used as-is.
    make_loss: Optional[Callable[[Any, Any], Callable]] = None
    # JSON-safe architecture record (e.g. LlamaConfig.to_meta()) that
    # rides export manifests so a serving consumer can rebuild the
    # model (CLI: `edl generate`)
    model_meta: Optional[Dict[str, Any]] = None
    # held-out evaluation ``f(params, rows) -> float`` run by the
    # commit leader on every published export (cfg.eval_dir)
    eval_fn: Optional[Callable[[Any, Dict[str, np.ndarray]], float]] = None
    # analytic model FLOPs per training example (obs/costmodel.py) —
    # when declared, the worker step loop publishes the live roofline
    # gauges edl_mfu{phase="train"} from measured examples/s
    flops_per_example: Optional[float] = None

    def loss_for(self, plan, mesh) -> Callable:
        return self.make_loss(plan, mesh) if self.make_loss else self.loss_fn


def _linreg_workload(cfg: WorkerConfig) -> Workload:
    import jax

    from edl_tpu.models import linreg

    rng = np.random.RandomState(cfg.seed)
    w_true = rng.randn(linreg.N_FEATURES, 1).astype(np.float32)

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        x = r.randn(end - start, linreg.N_FEATURES).astype(np.float32)
        y = x @ w_true + 0.1 * r.randn(end - start, 1).astype(np.float32)
        return {"x": x, "y": y}

    def eval_rmse(params, rows):
        pred = np.asarray(linreg.predict(params, rows["x"]))
        return float(np.sqrt(np.mean((pred - rows["y"]) ** 2)))

    return Workload(
        lambda: linreg.init_params(jax.random.PRNGKey(cfg.seed)),
        linreg.loss_fn,
        batch_fn,
        eval_fn=eval_rmse,
    )


def _ctr_workload(cfg: WorkerConfig) -> Workload:
    import jax

    from edl_tpu.models import ctr

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        return ctr.synthetic_batch(r, end - start, vocab=cfg.vocab)

    def eval_auc(params, rows):
        import jax.numpy as jnp

        logits = ctr.forward(
            params, jnp.asarray(rows["dense"]), jnp.asarray(rows["sparse"])
        )
        # the reference's in-train-loop metric (example/ctr/ctr/
        # train.py:161-167): AUC over the held-out split
        return float(
            ctr.batch_auc(logits, jnp.asarray(rows["label"], jnp.float32))
        )

    emb_kw = {"emb": cfg.emb} if cfg.emb else {}
    return Workload(
        lambda: ctr.init_params(
            jax.random.PRNGKey(cfg.seed), vocab=cfg.vocab, **emb_kw
        ),
        ctr.make_loss_fn(),
        batch_fn,
        eval_fn=eval_auc,
        flops_per_example=costmodel.ctr_train_flops_per_example(
            **({"emb": cfg.emb} if cfg.emb else {})
        ),
        # architecture record so `edl predict` can score a CTR export
        # offline — THE reference serving artifact
        # (example/ctr/ctr/train.py:169-180). ctr.forward reads its
        # architecture from the params themselves; the record is the
        # family dispatch + provenance.
        model_meta={
            "family": "ctr",
            "vocab": cfg.vocab,
            "emb": cfg.emb or ctr.DEFAULT_EMBEDDING,
            "mlp_dims": list(ctr.MLP_DIMS),
        },
    )


_EVAL_CHUNK = 64  # rows per forward in held-out evals: LM heads emit
# [rows, T, vocab] f32 logits — one unchunked call over a real split
# would OOM the commit leader


def _lm_ppl_eval(logits_fn):
    """Chunked next-token perplexity over {tokens [N, T+1]} — shared by
    the llama/moe workloads (only the forward differs). The chunking/CE
    math itself lives in models/evals.py, the SAME implementation
    `edl predict` scores with — in-job eval_metric and an offline
    re-score of one export cannot diverge."""

    def eval_ppl(params, rows):
        from edl_tpu.models.evals import lm_ppl

        return lm_ppl(logits_fn, params, rows["tokens"], chunk=_EVAL_CHUNK)

    return eval_ppl


def _llama_workload(cfg: WorkerConfig) -> Workload:
    """The flagship: Llama decoder under elastic FSDP(×TP) — BASELINE
    config #5 ("Llama-3-8B elastic FSDP across growing TPU slice") at
    the configured scale (tests: LlamaConfig.tiny)."""
    import dataclasses

    import jax

    from edl_tpu.models import llama

    mcfg = dataclasses.replace(
        llama.LlamaConfig.tiny(vocab=cfg.vocab),
        int8_mxu=cfg.int8_mxu,
        int8_wgrad_bf16=cfg.int8_wgrad_bf16,
    )

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        return llama.synthetic_tokens(r, end - start, cfg.seq_len, cfg.vocab)

    return Workload(
        lambda: llama.init_params(jax.random.PRNGKey(cfg.seed), mcfg),
        llama.make_loss_fn(mcfg),
        batch_fn,
        pspecs=lambda plan: llama.param_pspecs(mcfg, plan),
        # sp/pp are mesh-layout-dependent (ring attention shard_map /
        # GPipe schedule) — rebuild the loss per rendezvous
        make_loss=lambda plan, mesh: llama.make_loss_fn(mcfg, plan, mesh),
        model_meta=mcfg.to_meta(),
        eval_fn=_lm_ppl_eval(lambda p, t: llama.forward(p, t, mcfg)),
        flops_per_example=cfg.seq_len
        * costmodel.train_flops_per_token(mcfg, cfg.seq_len),
    )


def _bert_workload(cfg: WorkerConfig) -> Workload:
    """BERT-class MLM pretraining under elastic DP with checkpoint
    reshard (BASELINE config #4: "ERNIE / BERT-base pretraining")."""
    import jax

    from edl_tpu.models import bert

    mcfg = bert.BertConfig.tiny(vocab=cfg.vocab)

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        return bert.synthetic_mlm_batch(r, end - start, cfg.seq_len, cfg.vocab)

    def eval_mlm_acc(params, rows):
        # masked-token top-1 accuracy — the shared chunked
        # implementation `edl predict` also scores with (models/evals)
        from edl_tpu.models.evals import masked_top1

        acc, _ = masked_top1(
            lambda p, t: bert.forward(p, t, mcfg), params, rows,
            chunk=_EVAL_CHUNK,
        )
        return acc

    return Workload(
        lambda: bert.init_params(jax.random.PRNGKey(cfg.seed), mcfg),
        bert.make_loss_fn(mcfg),
        batch_fn,
        pspecs=lambda plan: bert.param_pspecs(mcfg, plan),
        model_meta=mcfg.to_meta(),
        eval_fn=eval_mlm_acc,
    )


def _resnet_workload(cfg: WorkerConfig) -> Workload:
    """ResNet-class image classification under elastic all-reduce DP
    (BASELINE config #3: "ResNet-50 ImageNet, elastic all-reduce DP")."""
    import jax

    from edl_tpu.models import resnet

    mcfg = resnet.ResNetConfig.tiny()

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        return resnet.synthetic_batch(r, end - start)

    def eval_top1(params, rows):
        import jax.numpy as jnp

        logits = resnet.forward(params, jnp.asarray(rows["images"]), mcfg)
        pred = np.asarray(jnp.argmax(logits, -1))
        return float((pred == rows["label"]).mean())

    return Workload(
        lambda: resnet.init_params(jax.random.PRNGKey(cfg.seed), mcfg),
        resnet.make_loss_fn(mcfg),
        batch_fn,
        pspecs=lambda plan: resnet.param_pspecs(mcfg, plan),
        model_meta=mcfg.to_meta(),
        eval_fn=eval_top1,
    )


def _moe_workload(cfg: WorkerConfig) -> Workload:
    """Mixture-of-Experts decoder under elastic DPxEP (no reference
    analog — SURVEY §2.5 "Expert parallelism: NO"; mesh "ep=2,dp"
    pins the expert axis while dp absorbs membership change)."""
    import dataclasses

    import jax

    from edl_tpu.models import moe

    mcfg = dataclasses.replace(
        moe.MoEConfig.tiny(vocab=cfg.vocab),
        int8_mxu=cfg.int8_mxu,
        int8_wgrad_bf16=cfg.int8_wgrad_bf16,
    )

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        return moe.synthetic_tokens(r, end - start, cfg.seq_len, cfg.vocab)

    return Workload(
        lambda: moe.init_params(jax.random.PRNGKey(cfg.seed), mcfg),
        moe.make_loss_fn(mcfg),
        batch_fn,
        pspecs=lambda plan: moe.param_pspecs(mcfg, plan),
        model_meta=mcfg.to_meta(),
        eval_fn=_lm_ppl_eval(lambda p, t: moe.forward(p, t, mcfg)[0]),
        # MoE: the cost model prices the ACTIVATED (top_k) expert width
        flops_per_example=cfg.seq_len
        * costmodel.train_flops_per_token(mcfg, cfg.seq_len),
    )


WORKLOADS: Dict[str, Callable[[WorkerConfig], Workload]] = {
    "linreg": _linreg_workload,
    "ctr": _ctr_workload,
    "llama": _llama_workload,
    "bert": _bert_workload,
    "resnet": _resnet_workload,
    "moe": _moe_workload,
}
