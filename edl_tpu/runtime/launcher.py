"""ProcessJobLauncher — run an elastic job as real worker processes.

The local-machine realization of the reference's pod lifecycle: the
controller "creates pods" by spawning worker processes running
``edl_tpu.runtime.worker_main`` (reference: trainer batch Job pods
exec'ing docker/paddle_k8s), scales up by spawning more, and scales
down by SIGTERM-ing the highest-numbered workers (reference: the k8s
Job controller shrinking ``Parallelism``,  pkg/autoscaler.go:361).
A per-job coordinator process (runtime/coordinator.py, the etcd/master
analog) provides membership, rendezvous KV, and the data task queue.

This is also the multi-host template: on a TPU pod slice each "worker"
is one host process and ``EDL_LOCAL_DEVICES`` is unset so the real
backend is used.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from edl_tpu.runtime.coordinator import CoordinatorClient, CoordinatorServer
from edl_tpu.utils.logging import kv_logger

log = kv_logger("launcher")


@dataclass
class WorkerProc:
    worker_id: str
    proc: subprocess.Popen
    log_path: str


@dataclass
class ProcessJobLauncher:
    job: str = "job"
    model: str = "linreg"
    mesh: str = "dp"  # MeshPlan.parse grammar: "dp" | "fsdp" | "fsdp,tp=2" …
    min_workers: int = 1
    max_workers: int = 8
    n_samples: int = 2048
    passes: int = 1
    per_device_batch: int = 32
    local_devices: int = 1  # 0 = use the real backend
    work_dir: str = "."
    member_ttl_s: float = 3.0
    # must comfortably exceed a worker's first-step XLA compile (~2-5 s
    # on a cold process): a lease that times out mid-compile is
    # redelivered and the job trains those rows twice (at-least-once)
    lease_timeout_s: float = 10.0
    fault_tolerant: bool = True
    ckpt_every: int = 0  # periodic sharded-commit cadence (steps)
    seed: int = 0
    seq_len: int = 32  # llama workload sequence length
    data_dir: str = ""  # on-disk dataset (runtime/shards.py layout)
    export: bool = False  # publish servable params exports (export_dir)
    step_sleep_s: float = 0.0
    sync_every: int = 1  # delayed-sync DP: K local steps between averages
    # virtual multi-slice topology: group every K consecutive workers
    # into one TPU slice (0 = single-slice / undeclared). Worker wNNN
    # gets EDL_SLICE = NNN // K, so a scale-up past one slice's hosts
    # lands the new workers on the next slice — the BASELINE north-star
    # shape (v5e-4 -> v5e-64 crosses slice boundaries). slice_map
    # overrides per worker id for irregular layouts (tests).
    workers_per_slice: int = 0
    slice_map: Dict[str, int] = field(default_factory=dict)
    # coordinator WAL auto-compaction threshold (bytes appended since
    # the last snapshot; 0 = server default 1 MiB). The WAL stays
    # O(state) regardless of job length.
    wal_compact_bytes: int = 0
    extra_env: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        os.makedirs(self.ckpt_dir, exist_ok=True)
        os.makedirs(self.log_dir, exist_ok=True)
        # durable coordinator: the WAL lives in the job work dir, so a
        # killed coordinator can be restarted with exact accounting.
        # A launcher always starts a NEW job — drop any previous job's
        # log (a stale WAL would replay its queue_inited/phase KV and
        # the fresh job would "complete" without training).
        wal_path = os.path.join(self.work_dir, "coordinator.wal")
        if os.path.exists(wal_path):
            os.remove(wal_path)
        self.server = CoordinatorServer(
            member_ttl_s=self.member_ttl_s,
            wal_path=wal_path,
            wal_compact_bytes=self.wal_compact_bytes,
        )
        self.client: CoordinatorClient = self.server.client()
        self.workers: List[WorkerProc] = []
        self._next_id = 0

    # -- coordinator fault injection ----------------------------------------

    def kill_coordinator(self) -> None:
        """SIGKILL the coordinator process mid-job (the SPOF fault the
        reference tolerates via etcd durability)."""
        self.server.kill()

    def restart_coordinator(self) -> None:
        """Respawn the coordinator on the same port; it recovers from
        the WAL and the workers' reconnecting clients resume."""
        self.server.restart()

    @property
    def ckpt_dir(self) -> str:
        return os.path.join(self.work_dir, "ckpt")

    @property
    def log_dir(self) -> str:
        return os.path.join(self.work_dir, "logs")

    @property
    def export_dir(self) -> str:
        return os.path.join(self.work_dir, "export")

    # -- pod lifecycle -------------------------------------------------------

    def _slice_of(self, worker_id: str) -> int:
        if worker_id in self.slice_map:
            return self.slice_map[worker_id]
        if self.workers_per_slice > 0:
            return int(worker_id.lstrip("w")) // self.workers_per_slice
        return -1

    def slice_workers(self, slice_id: int) -> List[WorkerProc]:
        """Live workers placed on one slice (fault injection: a slice
        outage kills all of them at once)."""
        return [
            w for w in self.live_workers() if self._slice_of(w.worker_id) == slice_id
        ]

    def kill_slice(self, slice_id: int) -> List[str]:
        """SIGKILL every live worker of a slice — the multi-slice fault
        the north-star scenario must survive (a whole v5e slice
        preempted at once). Tolerates workers exiting underfoot."""
        victims = []
        for w in self.slice_workers(slice_id):
            try:
                self.kill(w.worker_id)
                victims.append(w.worker_id)
            except KeyError:  # exited between listing and signal
                pass
        return victims

    def _env(self, worker_id: str) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(
            {
                "EDL_JOB_NAME": self.job,
                "EDL_WORKER_ID": worker_id,
                "EDL_COORDINATOR": f"127.0.0.1:{self.server.port}",
                "EDL_WORKERS": str(self.min_workers),
                "EDL_WORKERS_MIN": str(self.min_workers),
                "EDL_WORKERS_MAX": str(self.max_workers),
                "EDL_FAULT_TOLERANT": "1" if self.fault_tolerant else "0",
                "EDL_MODEL": self.model,
                "EDL_MESH": self.mesh,
                "EDL_CKPT_EVERY": str(self.ckpt_every),
                "EDL_SEQ_LEN": str(self.seq_len),
                "EDL_DATA_DIR": self.data_dir,
                "EDL_LOCAL_DEVICES": str(self.local_devices),
                "EDL_PER_DEVICE_BATCH": str(self.per_device_batch),
                "EDL_NUM_SAMPLES": str(self.n_samples),
                "EDL_NUM_PASSES": str(self.passes),
                "EDL_LEASE_TIMEOUT_S": str(self.lease_timeout_s),
                "EDL_MEMBER_TTL_S": str(self.member_ttl_s),
                "EDL_CKPT_DIR": self.ckpt_dir,
                "EDL_EXPORT_DIR": self.export_dir if self.export else "",
                "EDL_LOG_DIR": self.log_dir,
                "EDL_SEED": str(self.seed),
                "EDL_STEP_SLEEP_S": str(self.step_sleep_s),
                "EDL_SYNC_EVERY": str(self.sync_every),
                "EDL_SLICE": str(self._slice_of(worker_id)),
                "PYTHONPATH": os.pathsep.join(
                    [
                        os.path.dirname(
                            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
                        )
                    ]
                    + os.environ.get("PYTHONPATH", "").split(os.pathsep)
                ).rstrip(os.pathsep),
            }
        )
        if self.local_devices > 0:
            # override anything inherited from a test parent so the
            # worker gets exactly the requested virtual chip count
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count={self.local_devices}"
            )
        env.update(self.extra_env)
        return env

    def spawn(self) -> WorkerProc:
        worker_id = f"w{self._next_id:03d}"
        self._next_id += 1
        log_path = os.path.join(self.log_dir, f"{worker_id}.log")
        f = open(log_path, "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "edl_tpu.runtime.worker_main"],
            env=self._env(worker_id),
            stdout=f,
            stderr=subprocess.STDOUT,
        )
        f.close()  # child holds the fd
        wp = WorkerProc(worker_id, proc, log_path)
        self.workers.append(wp)
        log.info("spawned worker", worker=worker_id, pid=proc.pid)
        return wp

    def start(self, n_workers: Optional[int] = None) -> None:
        for _ in range(n_workers if n_workers is not None else self.min_workers):
            self.spawn()

    def live_workers(self) -> List[WorkerProc]:
        return [w for w in self.workers if w.proc.poll() is None]

    def scale_to(self, n: int) -> List[str]:
        """Reference semantics: retargeting Parallelism adds pods or
        removes the newest ones (graceful SIGTERM drain). Returns the
        worker ids that were sent SIGTERM (empty on scale-up)."""
        live = self.live_workers()
        terminated: List[str] = []
        if n > len(live):
            for _ in range(n - len(live)):
                self.spawn()
        else:
            for w in sorted(live, key=lambda w: w.worker_id)[n:]:
                log.info("terminating worker", worker=w.worker_id)
                w.proc.send_signal(signal.SIGTERM)
                terminated.append(w.worker_id)
        return terminated

    def kill(self, worker_id: str, sig: int = signal.SIGKILL) -> None:
        """Fault injection: hard-kill a worker (no graceful drain)."""
        for w in self.live_workers():
            if w.worker_id == worker_id:
                w.proc.send_signal(sig)
                return
        raise KeyError(worker_id)

    # -- observation ---------------------------------------------------------

    def kv(self, key: str) -> Optional[str]:
        return self.client.kv_get(f"{self.job}/{key}")

    def progress(self) -> int:
        return int(self.kv("progress") or "0")

    def wait_progress(self, at_least: int, timeout_s: float = 120.0) -> int:
        deadline = time.monotonic() + timeout_s
        while True:
            p = self.progress()
            if p >= at_least:
                return p
            if time.monotonic() > deadline:
                raise TimeoutError(f"progress {p} < {at_least}")
            if all(w.proc.poll() is not None for w in self.workers):
                raise RuntimeError(f"all workers exited at progress {p}")
            time.sleep(0.05)

    def wait(self, timeout_s: float = 300.0) -> Dict[str, int]:
        """Wait for every worker process to exit; {worker_id: returncode}."""
        deadline = time.monotonic() + timeout_s
        for w in self.workers:
            remain = max(0.1, deadline - time.monotonic())
            try:
                w.proc.wait(timeout=remain)
            except subprocess.TimeoutExpired:
                raise TimeoutError(
                    f"worker {w.worker_id} still running; "
                    f"log tail: {self.log_tail(w.worker_id)}"
                )
        return {w.worker_id: w.proc.returncode for w in self.workers}

    def log_tail(self, worker_id: str, n_bytes: int = 2000) -> str:
        for w in self.workers:
            if w.worker_id == worker_id:
                with open(w.log_path, "rb") as f:
                    data = f.read()
                return data[-n_bytes:].decode(errors="replace")
        return ""

    def stop(self) -> None:
        for w in self.live_workers():
            w.proc.kill()
        for w in self.workers:
            if w.proc.poll() is None:
                try:
                    w.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        self.client.close()
        self.server.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
