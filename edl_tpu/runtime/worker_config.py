"""Worker configuration — the EDL_* environment contract.

Extracted from worker_main (VERDICT r4 #4); the contract itself is the
TPU analog of the reference's PADDLE_INIT_* env injection
(pkg/jobparser.go:263-311), documented field by field below.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

# --------------------------------------------------------------------------
# config


@dataclass
class WorkerConfig:
    job: str
    worker_id: str
    coord_host: str
    coord_port: int
    min_workers: int
    max_workers: int
    fault_tolerant: bool
    model: str = "linreg"
    # elastic mesh string (MeshPlan.parse): "dp" | "fsdp" | "fsdp,tp=2" …
    # — one growth axis absorbs membership change, fixed axes survive it
    mesh: str = "dp"
    local_devices: int = 0  # >0: force an n-device virtual CPU platform
    per_device_batch: int = 32
    n_samples: int = 4096
    passes: int = 1
    lease_timeout_s: float = 16.0
    member_ttl_s: float = 10.0
    ckpt_dir: str = ""
    # periodic sharded-checkpoint cadence in steps (0 = only at
    # reshard/stop). REQUIRED for crash recovery on state no single
    # process can snapshot (fsdp): a SIGKILL'd peer takes its primary
    # shards with it, so survivors roll back to the last commit.
    ckpt_every: int = 0
    # how long the commit leader waits for every member's shard write
    # before abandoning the manifest (size with shard bytes / storage
    # bandwidth: multi-GB FSDP shards on shared storage need minutes)
    ckpt_commit_timeout_s: float = 300.0
    seed: int = 0
    vocab: int = 4096  # ctr/llama hash/token space (small for tests)
    emb: int = 0  # ctr embedding dim override (0 = model default)
    seq_len: int = 64  # llama sequence length
    # on-disk dataset (runtime/shards.py manifest dir, usually a mounted
    # volume). When set, leased tasks read REAL rows from shard files
    # instead of synthesizing them, and n_samples comes from the
    # manifest (reference: pre-baked RecordIO shards,
    # example/fit_a_line/Dockerfile:1-8).
    data_dir: str = ""
    rendezvous_timeout_s: float = 120.0
    step_sleep_s: float = 0.0  # throttle (tests: keeps jobs scalable mid-run)
    # servable export root: the commit leader writes a params-only,
    # dtype-cast artifact at every checkpoint commit and at stop
    # (reference save_inference_model, example/ctr/ctr/train.py:169-180)
    export_dir: str = ""
    export_dtype: str = "bfloat16"
    # delayed-sync DP: K local steps per dp group between cross-group
    # averages (trainer.LocalSyncStepper; the --async_mode analog,
    # reference example/ctr/ctr/train.py:75-79). 1 = fully synchronous.
    # Requires a dp-only mesh. Crash semantics: grouped state cannot be
    # snapshotted across a membership change, so a SIGKILL'd peer rolls
    # the job back to the last committed checkpoint (cadence:
    # ckpt_every) — graceful reshards/stops merge first and lose nothing.
    sync_every: int = 1
    # peer-to-peer state redistribution (shard_server.py): workers serve
    # their host-RAM snapshots over TCP; a reshard restores owner-
    # changing shards worker-to-worker across the drain window instead
    # of round-tripping through shared storage, and departing workers
    # linger (bounded) until the new world confirms restore. The data
    # plane for a migration to a DISJOINT worker set.
    p2p: bool = True
    p2p_linger_s: float = 20.0
    # held-out eval split (runtime/shards.py dataset dir): the commit
    # leader evaluates every published export against it and publishes
    # eval_metric in KV — the AUC-in-the-train-loop analog (reference:
    # example/ctr/ctr/train.py:161-167). Requires export_dir and a
    # workload that defines eval_fn.
    eval_dir: str = ""
    # eval resource bounds (ADVICE r4): the held-out split is CAPPED
    # (not the whole dir into leader RAM), and EDL_EVAL_DEVICE=cpu
    # moves the forward passes off the accelerator so eval never
    # contends with the training step loop for HBM.
    eval_max_rows: int = 4096
    eval_device: str = ""
    # llama/moe workloads: run the projection (and MoE expert) matmuls
    # on the MXU's double-rate int8 path (ops/int8_matmul.py — dynamic
    # absmax both operands, STE gradients; +12% flagship throughput,
    # loss tracks bf16 within noise, doc/design.md "Int8 MXU
    # training"). Exports and checkpoints are unaffected: weights at
    # rest stay dense.
    int8_mxu: bool = False
    # with int8_mxu: keep wgrad on the bf16 MXU path while fwd/dgrad
    # stay int8 (ADVICE r6 — gradients are heavy-tailed; one outlier
    # crushes a whole contraction slice's absmax resolution, and the
    # weight-update noise compounds over runs far longer than the
    # measured loss-parity window). ~1/6 of the 2x rate win for an
    # update path whose error is bf16 rounding, not quantization.
    int8_wgrad_bf16: bool = False
    # telemetry (edl_tpu/obs): EDL_METRICS_PORT >= 0 starts the HTTP
    # exporter (/metrics Prometheus text, /trace chrome-trace JSON,
    # /healthz) on that port (0 = ephemeral; the bound port is
    # published in coordinator KV at {job}/metrics_addr/{worker} so
    # `edl top` can find it). -1 = no exporter.
    metrics_port: int = -1
    # cadence of metric-snapshot pushes into coordinator KV
    # ({job}/metrics/{worker}) for the coordinator's fleet-aggregated
    # /metrics (runtime/coordinator_main.py --metrics-port). 0 = no
    # pushes. Matches the reference collector's 10 s census period.
    metrics_push_s: float = 10.0
    # EDL_TSDB_DIR: record this worker's registry snapshot into an
    # on-disk metric history (obs/tsdb.py) on the push cadence — zero
    # new RPCs, the pusher already holds the snapshot. Served on the
    # exporter's /history and replayable with `edl watch DIR`. Setting
    # it also arms the memledger crosscheck on the same cadence
    # (edl_hbm_crosscheck_drift_bytes). "" = off.
    tsdb_dir: str = ""
    # TPU slice this host belongs to (multi-slice topology). -1 =
    # unknown: the mesh build falls back to the hardware's own
    # ``device.slice_index`` (real multislice TPU exposes it). When set
    # (launcher/controller placement, or GKE's MEGASCALE_SLICE_ID), the
    # worker publishes it in coordinator KV so EVERY peer can order the
    # global device list slice-major at reshard — dp/pp cross slices
    # over DCN, fsdp/sp/ep/tp stay inside one slice's ICI
    # (parallel/mesh.py MeshPlan.build slices=...).
    slice_id: int = -1

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "WorkerConfig":
        e = dict(env if env is not None else os.environ)
        host, port = (e.get("EDL_COORDINATOR") or "127.0.0.1:7164").rsplit(":", 1)
        return cls(
            job=e.get("EDL_JOB_NAME", "job"),
            worker_id=e.get("EDL_WORKER_ID")
            or e.get("HOSTNAME")
            or f"w{os.getpid()}",
            coord_host=host,
            coord_port=int(port),
            min_workers=int(e.get("EDL_WORKERS_MIN", e.get("EDL_WORKERS", "1"))),
            max_workers=int(e.get("EDL_WORKERS_MAX", e.get("EDL_WORKERS", "1"))),
            fault_tolerant=e.get("EDL_FAULT_TOLERANT", "0") == "1",
            model=e.get("EDL_MODEL", "linreg"),
            mesh=e.get("EDL_MESH", "dp"),
            local_devices=int(e.get("EDL_LOCAL_DEVICES", "0")),
            per_device_batch=int(e.get("EDL_PER_DEVICE_BATCH", "32")),
            n_samples=int(e.get("EDL_NUM_SAMPLES", "4096")),
            passes=int(e.get("EDL_NUM_PASSES", "1")),
            lease_timeout_s=float(e.get("EDL_LEASE_TIMEOUT_S", "16")),
            member_ttl_s=float(e.get("EDL_MEMBER_TTL_S", "10")),
            ckpt_dir=e.get("EDL_CKPT_DIR", ""),
            ckpt_every=int(e.get("EDL_CKPT_EVERY", "0")),
            ckpt_commit_timeout_s=float(
                e.get("EDL_CKPT_COMMIT_TIMEOUT_S", "300")
            ),
            seed=int(e.get("EDL_SEED", "0")),
            vocab=int(e.get("EDL_VOCAB", "4096")),
            emb=int(e.get("EDL_EMB", "0")),
            seq_len=int(e.get("EDL_SEQ_LEN", "64")),
            data_dir=e.get("EDL_DATA_DIR", ""),
            rendezvous_timeout_s=float(e.get("EDL_RENDEZVOUS_TIMEOUT_S", "120")),
            step_sleep_s=float(e.get("EDL_STEP_SLEEP_S", "0")),
            sync_every=int(e.get("EDL_SYNC_EVERY", "1")),
            export_dir=e.get("EDL_EXPORT_DIR", ""),
            export_dtype=e.get("EDL_EXPORT_DTYPE", "bfloat16"),
            p2p=e.get("EDL_P2P", "1") != "0",
            p2p_linger_s=float(e.get("EDL_P2P_LINGER_S", "20")),
            eval_dir=e.get("EDL_EVAL_DIR", ""),
            eval_max_rows=int(e.get("EDL_EVAL_MAX_ROWS", "4096")),
            eval_device=e.get("EDL_EVAL_DEVICE", ""),
            int8_mxu=e.get("EDL_INT8_MXU", "0") == "1",
            int8_wgrad_bf16=e.get("EDL_INT8_WGRAD_BF16", "0") == "1",
            metrics_port=int(e.get("EDL_METRICS_PORT", "-1")),
            metrics_push_s=float(e.get("EDL_METRICS_PUSH_S", "10")),
            tsdb_dir=e.get("EDL_TSDB_DIR", ""),
            # MEGASCALE_SLICE_ID is what GKE injects into multislice
            # TPU pods — honoring it makes the kube path slice-aware
            # with no manifest change
            slice_id=int(
                e.get("EDL_SLICE", e.get("MEGASCALE_SLICE_ID", "-1"))
            ),
        )
