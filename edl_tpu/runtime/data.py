"""ElasticDataQueue — task-queue data dispatch that survives membership change.

Port of the Paddle master's task queue semantics the reference leans on
for elasticity (reference: docker/paddle_k8s:26-32 runs the master with
``-chunk-per-task=1 -task-timout-dur=16s``; trainers pull tasks via
``cloud_reader``, example/fit_a_line/train_ft.py:105-114): data is cut
into chunk tasks; workers lease tasks; a lease that times out or whose
worker leaves is redelivered, so sample coverage is exactly-once-ish
across membership change. Passes (epochs) mirror the reference's
``passes`` spec field.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from edl_tpu.utils import faults

DEFAULT_LEASE_TIMEOUT_S = 16.0  # reference: -task-timout-dur=16s
MAX_TASK_FAILURES = 3  # reference master's task failure cap analog


@dataclass
class Task:
    """One chunk of work: a half-open range [start, end) of sample
    indices (the RecordIO-chunk analog)."""

    task_id: int
    start: int
    end: int
    epoch: int
    failures: int = 0


@dataclass
class _Lease:
    task: Task
    worker: str
    expires: float


class ElasticDataQueue:
    """Thread-safe lease/ack task queue over ``n_samples`` split into
    ``chunk_size`` tasks, replayed for ``passes`` epochs."""

    def __init__(
        self,
        n_samples: int,
        chunk_size: int,
        passes: int = 1,
        lease_timeout_s: float = DEFAULT_LEASE_TIMEOUT_S,
    ):
        if n_samples <= 0 or chunk_size <= 0:
            raise ValueError("n_samples and chunk_size must be positive")
        self.n_samples = n_samples
        self.chunk_size = chunk_size
        self.passes = passes
        self.lease_timeout_s = lease_timeout_s
        self._lock = threading.Lock()
        self._epoch = 0
        self._todo: List[Task] = []
        self._leases: Dict[int, _Lease] = {}
        self._done_count = 0
        self._dead: List[Task] = []  # tasks that exceeded MAX_TASK_FAILURES
        self._next_id = 0
        self._fill_epoch_locked(0)

    def _fill_epoch_locked(self, epoch: int) -> None:
        for start in range(0, self.n_samples, self.chunk_size):
            self._todo.append(
                Task(
                    task_id=self._next_id,
                    start=start,
                    end=min(start + self.chunk_size, self.n_samples),
                    epoch=epoch,
                )
            )
            self._next_id += 1

    @property
    def tasks_per_epoch(self) -> int:
        return -(-self.n_samples // self.chunk_size)

    # -- worker surface ----------------------------------------------------

    def get_task(self, worker: str) -> Optional[Task]:
        """Lease the next task (reference: cloud_reader's master fetch).
        None when the epoch's tasks are all leased/done — the caller
        retries or finishes."""
        # chaos site: a lost/late lease is redelivered by the timeout,
        # the redelivery invariant exp_chaos.py soaks
        faults.fault_point("data.lease")
        with self._lock:
            self._reap_expired_locked()
            if not self._todo and not self._leases:
                self._advance_epoch_locked()
            if not self._todo:
                return None
            task = self._todo.pop(0)
            self._leases[task.task_id] = _Lease(
                task=task, worker=worker, expires=time.monotonic() + self.lease_timeout_s
            )
            return task

    def ack(self, task_id: int) -> None:
        """Mark a leased task complete."""
        with self._lock:
            lease = self._leases.pop(task_id, None)
            if lease is not None:
                self._done_count += 1
                if not self._todo and not self._leases:
                    self._advance_epoch_locked()

    def nack(self, task_id: int) -> None:
        """Return a task to the queue (worker failed mid-chunk)."""
        with self._lock:
            lease = self._leases.pop(task_id, None)
            if lease is not None:
                self._requeue_locked(lease.task)

    def release_worker(self, worker: str) -> int:
        """Requeue every task leased by a departed worker (membership
        change; reference: master redelivers on trainer death). Returns
        the number requeued."""
        with self._lock:
            gone = [tid for tid, l in self._leases.items() if l.worker == worker]
            for tid in gone:
                self._requeue_locked(self._leases.pop(tid).task)
            return len(gone)

    # -- state -------------------------------------------------------------

    def done(self) -> bool:
        with self._lock:
            self._reap_expired_locked()
            return not self._todo and not self._leases and self._epoch >= self.passes - 1

    def progress(self) -> Dict[str, int]:
        with self._lock:
            return {
                "epoch": self._epoch,
                "todo": len(self._todo),
                "leased": len(self._leases),
                "done": self._done_count,
                "dead": len(self._dead),
            }

    # -- internals (lock held) ---------------------------------------------

    def _requeue_locked(self, task: Task) -> None:
        task.failures += 1
        if task.failures > MAX_TASK_FAILURES:
            self._dead.append(task)
        else:
            self._todo.append(task)

    def _reap_expired_locked(self) -> None:
        now = time.monotonic()
        expired = [tid for tid, l in self._leases.items() if l.expires <= now]
        for tid in expired:
            self._requeue_locked(self._leases.pop(tid).task)

    def _advance_epoch_locked(self) -> bool:
        if self._epoch < self.passes - 1:
            self._epoch += 1
            self._fill_epoch_locked(self._epoch)
            return True
        return False


class QueueBatcher:
    """Fixed-size batches from chunked tasks, with correct at-least-once
    accounting: a task is acked only when every one of its samples has
    been handed out, so batch size and chunk size need not align (the
    cloud_reader's buffered-read analog,
    reference: example/fit_a_line/train_ft.py:111-114).

    ``fetch(task) -> dict[str, np.ndarray]`` loads one chunk's arrays.
    """

    def __init__(self, queue: ElasticDataQueue, fetch, worker: str = "w0"):
        self.queue = queue
        self.fetch = fetch
        self.worker = worker
        self._buffer: List = []  # (task_id, arrays, offset)

    def _buffered(self) -> int:
        total = 0
        for _, arrays, offset in self._buffer:
            total += next(iter(arrays.values())).shape[0] - offset
        return total

    def next_batch(self, batch_size: int, rollover: bool = False):
        """Next batch dict, or None when the queue is drained. The final
        batch may be short (callers pad or drop). With ``rollover`` a
        short batch at a pass boundary is topped up from the next pass
        (leases advance epochs), so batches stay full-size until the
        true end of the queue — the streaming mode long-running trainers
        want."""
        import numpy as _np

        while self._buffered() < batch_size:
            task = self.queue.get_task(self.worker)
            if task is None:
                break
            self._buffer.append((task.task_id, self.fetch(task), 0))
        if not self._buffer:
            return None
        if rollover and self._buffered() < batch_size:
            head = self.next_batch(self._buffered())  # drain the tail...
            rest = self.next_batch(batch_size - next(
                iter(head.values())
            ).shape[0])  # ...then pull from the next pass
            if rest is None:
                return head
            return {
                k: _np.concatenate([head[k], rest[k]], axis=0) for k in head
            }
        need = batch_size
        pieces: List = []
        new_buffer = []
        for task_id, arrays, offset in self._buffer:
            n = next(iter(arrays.values())).shape[0]
            if need > 0:
                take = min(need, n - offset)
                pieces.append({k: v[offset : offset + take] for k, v in arrays.items()})
                offset += take
                need -= take
            if offset >= n:
                self.queue.ack(task_id)  # fully consumed
            else:
                new_buffer.append((task_id, arrays, offset))
        self._buffer = new_buffer
        return {
            k: _np.concatenate([p[k] for p in pieces], axis=0)
            for k in pieces[0]
        }


class StaticShardReader:
    """Classic non-elastic data sharding: chunk ``i`` belongs to worker
    ``i % n_workers`` (reference: example/fit_a_line/fluid/common.py:24-40
    ``cluster_reader`` shards files by ``idx % trainers == trainer_id``).
    No leases, no redelivery — membership is fixed for the life of the
    job, the DistributeTranspiler-era mode (W3). Complements
    :class:`ElasticDataQueue`, which is the elastic/fault-tolerant mode.
    """

    def __init__(
        self,
        n_samples: int,
        chunk_size: int,
        n_workers: int,
        worker_id: int,
    ):
        if not 0 <= worker_id < n_workers:
            raise ValueError(f"worker_id {worker_id} not in [0, {n_workers})")
        if n_samples <= 0 or chunk_size <= 0:
            raise ValueError("n_samples and chunk_size must be positive")
        self.n_samples = n_samples
        self.chunk_size = chunk_size
        self.n_workers = n_workers
        self.worker_id = worker_id

    def chunks(self) -> List[Task]:
        """This worker's chunk tasks, in deterministic order."""
        out: List[Task] = []
        n_chunks = -(-self.n_samples // self.chunk_size)
        for i in range(self.worker_id, n_chunks, self.n_workers):
            start = i * self.chunk_size
            out.append(
                Task(
                    task_id=i,
                    start=start,
                    end=min(start + self.chunk_size, self.n_samples),
                    epoch=0,
                )
            )
        return out

    def epoch_indices(self) -> List[int]:
        """Flat sample indices this worker owns, one epoch."""
        idx: List[int] = []
        for t in self.chunks():
            idx.extend(range(t.start, t.end))
        return idx
