"""File-backed dataset shards — real data through the elastic queue.

The reference trains from pre-baked on-disk shards: RecordIO files baked
into the job image (reference: example/fit_a_line/Dockerfile:1-8) or
downloaded per trainer (reference: example/ctr/ctr/train.py:222-227,
``hash(file) % 10 == trainer_id``). The TPU design replaces RecordIO
with npz shard files + a JSON manifest; the *assignment* of data to
workers stays with the coordinator's lease queue (runtime/data.py), so
any worker can materialize any leased [start, end) range regardless of
which files hold it — the property that makes the data plane elastic.

Layout of a dataset directory::

    manifest.json                {"n_samples": N, "keys": [...], "files":
                                  [{"file": ..., "start": s, "end": e}]}
    shard-00000.npz              arrays for samples [start, end)
    shard-00001.npz              ...

``write_shards`` builds one (the Dockerfile-prebake analog);
``FileShardSource`` reads ranges lazily — only the files overlapping a
requested range are opened, so a worker's I/O is proportional to the
data it actually leases.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, List, Optional

import numpy as np

MANIFEST = "manifest.json"


def write_shards(
    data_dir: str,
    arrays: Dict[str, np.ndarray],
    shard_size: int = 4096,
) -> dict:
    """Cut column arrays (equal leading dims) into npz shard files +
    manifest. Atomic per file; the manifest is written LAST so a
    partially-written dataset is never readable."""
    if not arrays:
        raise ValueError("no arrays to shard")
    n = next(iter(arrays.values())).shape[0]
    for k, v in arrays.items():
        if v.shape[0] != n:
            raise ValueError(
                f"array {k!r} has {v.shape[0]} samples, expected {n}"
            )
    if shard_size <= 0:
        raise ValueError("shard_size must be positive")
    os.makedirs(data_dir, exist_ok=True)
    files: List[dict] = []
    for i, start in enumerate(range(0, n, shard_size)):
        end = min(start + shard_size, n)
        fname = f"shard-{i:05d}.npz"
        fd, tmp = tempfile.mkstemp(dir=data_dir, suffix=".npz.tmp")
        os.close(fd)
        with open(tmp, "wb") as f:
            np.savez(f, **{k: v[start:end] for k, v in arrays.items()})
        os.replace(tmp, os.path.join(data_dir, fname))
        files.append({"file": fname, "start": start, "end": end})
    manifest = {
        "n_samples": n,
        "keys": sorted(arrays.keys()),
        "files": files,
    }
    fd, tmp = tempfile.mkstemp(dir=data_dir, suffix=".json.tmp")
    os.close(fd)
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(data_dir, MANIFEST))
    return manifest


class FileShardSource:
    """Random-range access over a shard directory.

    ``fetch_range(start, end)`` assembles the rows [start, end) from
    whichever files overlap — the ``QueueBatcher.fetch`` /
    worker ``batch_fn`` adapter for real on-disk data.
    """

    def __init__(self, data_dir: str):
        self.data_dir = data_dir
        path = os.path.join(data_dir, MANIFEST)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no dataset manifest at {path}; run write_shards first"
            )
        with open(path) as f:
            self.manifest = json.load(f)
        self.n_samples: int = int(self.manifest["n_samples"])
        self.keys: List[str] = list(self.manifest["keys"])
        self._files = self.manifest["files"]
        self._cache: Dict[str, dict] = {}  # one decoded shard kept hot

    def _load(self, entry: dict) -> dict:
        fname = entry["file"]
        if fname not in self._cache:
            self._cache.clear()  # LRU of size 1: sequential reads hit it
            with np.load(
                os.path.join(self.data_dir, fname), allow_pickle=False
            ) as z:
                self._cache[fname] = {k: z[k] for k in z.files}
        return self._cache[fname]

    def fetch_range(self, start: int, end: int) -> Dict[str, np.ndarray]:
        if not 0 <= start < end <= self.n_samples:
            raise IndexError(
                f"range [{start}, {end}) outside dataset of {self.n_samples}"
            )
        pieces: List[dict] = []
        for entry in self._files:
            lo, hi = max(start, entry["start"]), min(end, entry["end"])
            if lo >= hi:
                continue
            data = self._load(entry)
            s = lo - entry["start"]
            pieces.append({k: data[k][s : s + (hi - lo)] for k in self.keys})
        if len(pieces) == 1:
            return pieces[0]
        return {
            k: np.concatenate([p[k] for p in pieces], axis=0)
            for k in self.keys
        }

    def fetch(self, task) -> Dict[str, np.ndarray]:
        """QueueBatcher-compatible: task carries [start, end)."""
        return self.fetch_range(task.start, task.end)
