"""Coordinator pod entrypoint — run the per-job coordinator service.

The coordinator pod is the master-pod analog (reference: master
ReplicaSet + etcd sidecar, pkg/jobparser.go:167-227): one per job,
owning membership, KV, barriers, and the elastic task queue. This
wrapper resolves/builds the native server (native/coordinator) and
execs it, so the container's PID-1 signal handling applies to the
server itself.

Used by the KubeCluster coordinator Deployment
(edl_tpu/cluster/kube.py) and handy for manual bring-up:

    python -m edl_tpu.runtime.coordinator_main --port 7164
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="edl-coordinator")
    ap.add_argument("--port", type=int, default=7164)
    ap.add_argument(
        "--member-ttl", type=float, default=10.0,
        help="seconds without heartbeat before a member is reaped",
    )
    a = ap.parse_args(argv)

    from edl_tpu.runtime.coordinator import _BIN_PATH, ensure_native_built

    if not ensure_native_built():
        print("native coordinator unavailable (no toolchain?)", file=sys.stderr)
        return 1
    os.execv(
        _BIN_PATH,
        [_BIN_PATH, "--port", str(a.port), "--member-ttl", str(a.member_ttl)],
    )
    return 0  # unreachable


if __name__ == "__main__":
    sys.exit(main())
