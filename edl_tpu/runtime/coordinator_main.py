"""Coordinator pod entrypoint — run the per-job coordinator service.

The coordinator pod is the master-pod analog (reference: master
ReplicaSet + etcd sidecar, pkg/jobparser.go:167-227): one per job,
owning membership, KV, barriers, and the elastic task queue. This
wrapper resolves/builds the native server (native/coordinator) and
execs it, so the container's PID-1 signal handling applies to the
server itself.

With ``--metrics-port`` the wrapper instead SUPERVISES the server as a
child and runs the job's fleet telemetry endpoint alongside it: every
worker pushes metric snapshots into this coordinator's KV
(``{job}/metrics/{worker}``, obs/fleet.py), and each scrape of
``/metrics`` here re-exposes the aggregated union with every series
labeled by worker — the one-stop Prometheus target for the whole job.

Used by the KubeCluster coordinator Deployment
(edl_tpu/cluster/kube.py) and handy for manual bring-up:

    python -m edl_tpu.runtime.coordinator_main --port 7164 \
        [--metrics-port 9100 --job myjob]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

# non-member sources whose snapshots the aggregation also reads (the
# epoch's dist_service host pushes under this reserved name)
EXTRA_METRIC_SOURCES = ("dist_service",)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="edl-coordinator")
    ap.add_argument("--port", type=int, default=7164)
    ap.add_argument(
        "--member-ttl", type=float, default=10.0,
        help="seconds without heartbeat before a member is reaped",
    )
    ap.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve the fleet-aggregated telemetry endpoint on this "
        "port (0 = ephemeral; prints the bound URL). Aggregates the "
        "metric snapshots workers push into this coordinator's KV.",
    )
    ap.add_argument(
        "--job", default="job",
        help="job name for the metrics KV prefix ({job}/metrics/*); "
        "only used with --metrics-port",
    )
    ap.add_argument(
        "--tsdb-dir", default=None,
        help="record the fleet-aggregated snapshot into this metric-"
        "history directory (obs/tsdb.py) every --watch-interval and "
        "evaluate the alert rules over it; served on /history and "
        "replayable with `edl watch DIR`. Only with --metrics-port.",
    )
    ap.add_argument(
        "--rules", default=None,
        help="alert-rules JSON for the fleet watchdog (default: the "
        "built-in obs/alerts.py DEFAULT_RULES); only with --tsdb-dir",
    )
    ap.add_argument(
        "--watch-interval", type=float, default=10.0,
        help="seconds between fleet snapshot/alert-evaluation passes "
        "when --tsdb-dir is set",
    )
    a = ap.parse_args(argv)

    from edl_tpu.runtime.coordinator import (
        _BIN_PATH,
        CoordinatorServer,
        ensure_native_built,
    )

    if not ensure_native_built():
        print("native coordinator unavailable (no toolchain?)", file=sys.stderr)
        return 1

    if a.metrics_port is None:
        os.execv(
            _BIN_PATH,
            [_BIN_PATH, "--port", str(a.port), "--member-ttl", str(a.member_ttl)],
        )
        return 0  # unreachable

    # supervised mode: server child + aggregation exporter in this
    # process (telemetry rides the same pod, same lifecycle)
    from edl_tpu import obs

    server = CoordinatorServer(port=a.port, member_ttl_s=a.member_ttl)
    client = server.client()

    # optional fleet watchdog: append the aggregated snapshot to an
    # on-disk history and run the alert rules over it — the coordinator
    # is the one process that already sees every worker's series, so
    # fleet-level burn rates evaluate here (doc/observability.md
    # "History, alerting & burn rates")
    db = engine = None
    if a.tsdb_dir:
        db = obs.TSDB(a.tsdb_dir)
        engine = obs.engine_from_doc(obs.load_rules_doc(a.rules))

    exporter = obs.start_exporter(
        lambda: obs.collect_fleet(client, a.job, EXTRA_METRIC_SOURCES),
        port=a.metrics_port,
        # /events here is the worker-labeled FLEET log: the union of
        # every member's pushed flight-recorder window ({job}/events/*)
        # on ONE clock axis (per-worker offsets applied)
        events_source=lambda: obs.collect_fleet_events(
            client, a.job, EXTRA_METRIC_SOURCES
        ),
        # /trace here is the FLEET merge: every member's pushed span
        # window, offset-corrected, worker-labeled, with RPC flow
        # links (obs/fleet.collect_fleet_trace -> disttrace)
        trace_source=lambda: obs.collect_fleet_trace(
            client, a.job, EXTRA_METRIC_SOURCES
        ),
        history=db,
    )
    print(
        f"coordinator on :{a.port}; fleet metrics at {exporter.url}/metrics "
        f"(fleet event log at /events, merged fleet trace at /trace)",
        flush=True,
    )
    try:
        next_watch = time.time()
        while server._proc.poll() is None:
            time.sleep(0.5)
            if db is not None and time.time() >= next_watch:
                next_watch = time.time() + a.watch_interval
                try:
                    reg = obs.collect_fleet(
                        client, a.job, EXTRA_METRIC_SOURCES
                    )
                    now = time.time()
                    db.append(reg.snapshot(), t=now)
                    for tr in engine.evaluate(db, now):
                        print(
                            f"ALERT {tr['transition']} {tr['rule']} "
                            f"[{tr['severity']}]",
                            flush=True,
                        )
                except Exception:  # edl: no-lint[silent-failure] the watchdog must never take down the coordinator it watches; next pass retries
                    pass
        return server._proc.returncode or 0
    except KeyboardInterrupt:
        return 0
    finally:
        if db is not None:
            db.flush()
        exporter.stop()
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
