"""Worker entrypoint — discovery, barrier, rank, failure gate.

Port of the reference's pod runtime glue (reference: docker/paddle_k8s
start_new_trainer:121-143 + docker/k8s_tools.py fetch_pod_id:127-151):
a starting worker

  1. reads the EDL_* env contract (api/parser.py pod_env),
  2. connects to the job coordinator and registers with a fresh
     incarnation number,
  3. checks the failure gate (fault-tolerant jobs tolerate up to
     EDL_WORKERS failures; non-FT tolerate 0 —
     reference: check_failed_cnt docker/paddle_k8s:34-42),
  4. waits at the start barrier for min_replicas peers
     (reference: wait_pods_running barriers, paddle_k8s:128-130),
  5. takes its deterministic rank (index of its name in the sorted
     live-member list — reference: k8s_tools.py fetch_pod_id),
  6. initializes jax.distributed when spanning hosts, and
  7. hands control to the training program; exit codes are classified
     into a termination reason (reference: check_trainer_ret
     paddle_k8s:44-60).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from edl_tpu.utils.logging import kv_logger

log = kv_logger("entrypoint")

# exit-code classification (reference: docker/paddle_k8s:44-60)
EXIT_REASONS = {
    0: "success",
    136: "floating point exception",
    139: "segmentation fault",
    134: "aborted",
}


class FailureGateError(RuntimeError):
    pass


@dataclass
class WorkerContext:
    job_name: str
    worker_id: str
    rank: int
    world_size: int
    incarnation: int
    coordinator: object
    membership_epoch: int


def classify_exit(code: int) -> str:
    return EXIT_REASONS.get(code, f"exit code {code}")


def check_failure_gate(coordinator, job_name: str, fault_tolerant: bool,
                       budget: int) -> None:
    """reference: check_failed_cnt docker/paddle_k8s:34-42 — FT jobs
    tolerate up to ``budget`` failures, non-FT tolerate 0. The failure
    count lives in coordinator KV (termination-log analog)."""
    raw = coordinator.kv_get(f"{job_name}/failed_count") or "0"
    failed = int(raw)
    limit = budget if fault_tolerant else 0
    if failed > limit:
        raise FailureGateError(
            f"job {job_name} exceeded failure budget: {failed} > {limit}"
        )


def record_failure(coordinator, job_name: str, reason: str) -> int:
    failed = int(coordinator.kv_get(f"{job_name}/failed_count") or "0") + 1
    coordinator.kv_put(f"{job_name}/failed_count", str(failed))
    coordinator.kv_put(f"{job_name}/last_failure", reason)
    return failed


def bootstrap(
    coordinator,
    env: Optional[Dict[str, str]] = None,
    barrier_timeout_s: float = 300.0,
    poll_s: float = 0.05,
) -> WorkerContext:
    """Steps 1-6. ``coordinator`` is any coordinator-interface object
    (runtime/coordinator.py); env defaults to os.environ."""
    env = dict(env if env is not None else os.environ)
    job = env.get("EDL_JOB_NAME", "job")
    worker_id = env.get("EDL_WORKER_ID") or env.get("HOSTNAME") or f"w{os.getpid()}"
    min_workers = int(env.get("EDL_WORKERS_MIN", env.get("EDL_WORKERS", "1")))
    fault_tolerant = env.get("EDL_FAULT_TOLERANT", "0") == "1"

    check_failure_gate(
        coordinator, job, fault_tolerant, budget=int(env.get("EDL_WORKERS", "1"))
    )

    # incarnation: monotonic per worker name, owned by the coordinator KV
    inc_key = f"{job}/incarnation/{worker_id}"
    incarnation = int(coordinator.kv_get(inc_key) or "0") + 1
    coordinator.kv_put(inc_key, str(incarnation))
    epoch = coordinator.register(worker_id, incarnation)

    # start barrier: wait for min_replicas live members
    # (reference: paddle_k8s:128-130 waits pservers+master Running)
    coordinator.barrier_arrive(f"{job}/start", worker_id)
    deadline = time.monotonic() + barrier_timeout_s
    while coordinator.barrier_count(f"{job}/start") < min_workers:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"start barrier: {coordinator.barrier_count(f'{job}/start')}"
                f"/{min_workers} workers after {barrier_timeout_s}s"
            )
        time.sleep(poll_s)

    members = coordinator.members()
    rank = next((m.rank for m in members if m.name == worker_id), -1)
    if rank < 0:
        raise RuntimeError(f"worker {worker_id} missing from membership")
    ctx = WorkerContext(
        job_name=job,
        worker_id=worker_id,
        rank=rank,
        world_size=len(members),
        incarnation=incarnation,
        coordinator=coordinator,
        membership_epoch=epoch,
    )
    log.info(
        "worker bootstrapped",
        job=job,
        worker=worker_id,
        rank=rank,
        world=ctx.world_size,
        incarnation=incarnation,
    )
    return ctx


def init_jax_distributed(ctx: WorkerContext, coordinator_address: str) -> None:
    """Multi-host only: bind this process into the JAX runtime
    (replaces the pserver endpoint fan-out,
    reference: docker/paddle_k8s:4-11). Single-host callers skip this."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=ctx.world_size,
        process_id=ctx.rank,
    )


def run_worker(
    ctx: WorkerContext,
    main: Callable[[WorkerContext], int],
) -> int:
    """Step 7: run the training program, classify the outcome, maintain
    the failure count (reference: check_trainer_ret paddle_k8s:44-60)."""
    try:
        code = int(main(ctx) or 0)
    except Exception as e:  # program crash
        record_failure(ctx.coordinator, ctx.job_name, f"exception: {e}")
        ctx.coordinator.leave(ctx.worker_id)
        ctx.coordinator.release_worker(ctx.worker_id)
        raise
    reason = classify_exit(code)
    if code != 0:
        record_failure(ctx.coordinator, ctx.job_name, reason)
        ctx.coordinator.release_worker(ctx.worker_id)
    ctx.coordinator.leave(ctx.worker_id)
    log.info("worker exited", worker=ctx.worker_id, code=code, reason=reason)
    return code
