"""Checkpointing — the reshard mechanism, and disk persistence.

The reference delegates persistence to workload code
(reference: example/ctr/ctr/train.py:169-180 save_inference_model every
1000 batches) and pserver state to Paddle's etcd runtime. Here
checkpointing is first-class (SURVEY §5: "it is the reshard mechanism"):

- ``snapshot``/``restore``: device state ⇄ host RAM — the fast path an
  elastic rescale rides (no disk in the loop). Valid only when every
  array is fully addressable from this process (single-process meshes,
  or dp-replicated state).
- ``save``/``load``: host snapshot ⇄ disk, flattened-keypath npz — the
  single-file crash-recovery path for small states.
- ``snapshot_local``/``save_shards``/``write_manifest``/``load_sharded``:
  the multi-process sharded format. Each process snapshots ONLY its
  addressable shards (host RAM bounded by local shard bytes), writes
  one ``shards-r<rank>-of-<world>.npz``, a leader commits
  ``manifest.json`` last (manifest presence = checkpoint valid), and a
  later epoch at ANY world size restores by assembling exactly the
  pieces its local devices need — RAM pieces when the step matches,
  disk pieces otherwise. This replaces the reference's trainer-0
  full-state save (example/ctr/ctr/train.py:169-180), which cannot
  scale to FSDP state that no single host can materialize.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.parallel import sharding as shd
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.train.trainer import TrainState, shard_state
from edl_tpu.utils import faults, tracing


def _obs_io(direction: str, kind: str, dt_s: float, nbytes: int) -> None:
    """Checkpoint I/O telemetry: duration histograms by format kind
    (dense single-file vs multi-process shards) + a bytes counter —
    scrapeable alongside the checkpoint.* tracer spans."""
    r = obs_metrics.default_registry()
    name = (
        "edl_checkpoint_save_seconds"
        if direction == "write"
        else "edl_checkpoint_restore_seconds"
    )
    help = (
        "checkpoint write time" if direction == "write"
        else "checkpoint read/restore time"
    )
    r.histogram(name, help, ("kind",)).observe(dt_s, kind=kind)
    if nbytes:
        r.counter(
            "edl_checkpoint_bytes_total", "checkpoint bytes moved", ("op",)
        ).inc(nbytes, op=direction)


def _emit_ckpt(event: str, step: int, **attrs) -> None:
    """Flight-recorder entry (ckpt.save / ckpt.commit / ckpt.load)
    keyed by step — the restore-point decisions a postmortem needs to
    know when explaining which state a recovery rolled back to.
    ``attrs`` carries the format (``fmt`` = dense | shards)."""
    from edl_tpu.obs import events

    events.emit(event, step=step, **attrs)


def snapshot(state: TrainState) -> TrainState:
    """Device → host RAM (step one of the reshard protocol)."""
    return TrainState(
        step=np.asarray(jax.device_get(state.step)),
        params=shd.to_host(state.params),
        opt_state=shd.to_host(state.opt_state),
    )


def restore(
    host_state: TrainState, plan: MeshPlan, mesh, param_pspecs=None
) -> TrainState:
    """Host RAM → device, sharded for the (possibly new) mesh (step
    three of the reshard protocol)."""
    return shard_state(host_state, plan, mesh, param_pspecs)


def staged_reshard(
    state: TrainState, plan: MeshPlan, mesh, param_pspecs=None,
    stage: Optional[str] = None,
) -> TrainState:
    """Device → host → device as ONE overlapped pipeline — the host
    fallback of the reshard protocol when ``snapshot`` + ``restore``
    would run the two transfer directions back to back. Delegates to
    :func:`edl_tpu.parallel.sharding.stream_reshard` (shared window and
    piece policies with ``to_host``); the sum-form snapshot/restore
    pair remains for disk checkpoints.

    ``stage`` compresses the OPTIMIZER-MOMENT leaves (never params — the
    f32 master weights move exactly) for the host round trip:

    - ``"int8"`` (default, env ``EDL_RESHARD_STAGE``): blockwise-absmax
      int8 (ops/quant.py, the 8-bit-Adam staging recipe) — Adam state
      bytes 3P → ~1.5P, halving the fallback stall. Moments perturb by
      ≤ 1/254 of their block absmax, once per rescale.
    - ``"bf16"``: device-side cast, 3P → 2P, exponent-exact.
    - ``"f32"``: no compression (bit-identical staging).
    """
    from edl_tpu.train.trainer import state_pspecs

    stage = stage or os.environ.get("EDL_RESHARD_STAGE", "int8")
    if stage not in ("int8", "bf16", "f32"):
        raise ValueError(f"unknown reshard staging mode {stage!r}")
    if stage != "f32":
        # lossy staging is the default for the stall win — make every
        # activation of it visible so operators know the optimizer
        # moments were perturbed (ADVICE r3; exactness callers pin
        # stage="f32")
        from edl_tpu.utils.logging import kv_logger

        kv_logger("checkpoint").info(
            "staged reshard with lossy moment compression",
            stage=stage,
            override="EDL_RESHARD_STAGE=f32 for exact staging",
        )
    sharding_tree = shd.named(state_pspecs(state, plan, param_pspecs), mesh)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    sh_leaves = treedef.flatten_up_to(sharding_tree)

    # moment leaves = exactly the opt_state subtree, identified by
    # object identity (NOT flatten position — a future TrainState field
    # must never silently fall into the lossy-compression set)
    opt_ids = {id(x) for x in jax.tree_util.tree_leaves(state.opt_state)}

    def _compressible(i, x) -> bool:
        return (
            stage != "f32"
            and id(x) in opt_ids
            and getattr(x, "dtype", None) == jnp.float32
            and getattr(x, "ndim", 0) >= 1
            and getattr(x, "size", 0) >= 4096
        )

    if stage == "f32" or not any(
        _compressible(i, x) for i, x in enumerate(leaves)
    ):
        return jax.tree_util.tree_unflatten(
            treedef, shd.stream_reshard(leaves, sh_leaves)
        )

    from jax.sharding import NamedSharding, PartitionSpec as P

    from edl_tpu.ops import quant

    moved, moved_sh, plan_ops = [], [], []
    for i, (x, sh) in enumerate(zip(leaves, sh_leaves)):
        if not _compressible(i, x):
            plan_ops.append(("raw", len(moved)))
            moved.append(x)
            moved_sh.append(sh)
        elif stage == "bf16":
            plan_ops.append(("bf16", len(moved)))
            moved.append(quant.cast_to(x, jnp.bfloat16))
            moved_sh.append(sh)
        else:  # int8
            q, s = quant.quantize_on_device(x)
            plan_ops.append(("int8", len(moved), sh))
            moved.append(q)
            moved_sh.append(sh)
            # scales are shape[:-1] f32 (1/last_dim of the leaf bytes):
            # replicated placement is cheap and always divides
            moved.append(s)
            moved_sh.append(NamedSharding(mesh, P()))
    placed = shd.stream_reshard(moved, moved_sh)

    out = []
    for op in plan_ops:
        if op[0] == "raw":
            out.append(placed[op[1]])
        elif op[0] == "bf16":
            out.append(quant.cast_to(placed[op[1]], jnp.float32))
        else:
            j, sh = op[1], op[2]
            out.append(quant.dequantize_to(placed[j], placed[j + 1], sh))
    return jax.tree_util.tree_unflatten(treedef, out)


def state_nbytes(state) -> int:
    """Total bytes of a TrainState (params + optimizer + step)."""
    return sum(
        int(getattr(x, "nbytes", 0)) for x in jax.tree_util.tree_leaves(state)
    )


STAGE_MOMENT_FACTOR = {"f32": 1.0, "bf16": 0.5, "int8": 0.26}


def host_fallback_stall_model(
    state_bytes: int,
    hosts_after: int,
    host_bw_bytes_s: float,
    moment_bytes: int = 0,
    stage: str = "f32",
) -> float:
    """Worst-case host-staged reshard stall, in seconds.

    The fallback moves state through host RAM when no device path
    exists (disjoint device sets — e.g. a slice swap). Each surviving
    host must ingest its share of the FULL post-reshard state through
    its own host<->device link; with the overlapped down/up pipeline
    (sharding.stream_reshard) the stall is ~max(d2h, h2d) ≈ one
    direction's bytes over the link bandwidth. Shrinks are the worst
    case: fewer hosts absorb the same total state (the v5e-64 → v5e-4
    shrink in BASELINE.md concentrates 16x the per-host bytes).

    ``moment_bytes``/``stage`` model the optimizer-moment staging
    compression of :func:`staged_reshard`: wire bytes =
    (state - moments) + moments·factor, where the int8 factor 0.26 is
    1/4 payload + ~1/D scale overhead. Params always move at full
    fidelity — an Adam state (moments = 2/3 of bytes) halves its stall
    under int8 staging, while an adafactor state (factored moments,
    params-dominated) barely moves, and the model says so honestly.
    ``host_bw_bytes_s`` must be the RAW link bandwidth (derived from an
    UNCOMPRESSED staging measurement — bench.py's f32 run); the model
    is evaluated as ``stall_model_8b_1host_s``; doc/reshard_stall.md
    carries the derivation and the <30 s budget check.
    """
    if hosts_after <= 0 or host_bw_bytes_s <= 0:
        raise ValueError("hosts_after and host_bw_bytes_s must be positive")
    if stage not in STAGE_MOMENT_FACTOR:
        raise ValueError(f"unknown reshard staging mode {stage!r}")
    if not 0 <= moment_bytes <= state_bytes:
        raise ValueError(
            f"moment_bytes {moment_bytes} outside [0, {state_bytes}]"
        )
    factor = STAGE_MOMENT_FACTOR[stage]
    wire = (state_bytes - moment_bytes) + moment_bytes * factor
    return (wire / hosts_after) / host_bw_bytes_s


def p2p_migrate_stall_model(
    state_bytes: int, hosts_after: int, link_bw_bytes_s: float
) -> float:
    """Worst-case stall of a DISJOINT-set migration over the P2P shard
    plane (runtime/shard_server.py): each of the ``hosts_after`` new
    hosts ingests its 1/H share of the full state concurrently over its
    data-plane network link — no storage round trip (the old path paid
    a write AND a read of the full state through shared storage).
    ``link_bw_bytes_s`` is per-host network bandwidth (DCN-class in
    production; bench.py measures the shard-plane software stack as
    ``p2p_bw_gbs``). Derivation + budget table: doc/reshard_stall.md."""
    if hosts_after <= 0 or link_bw_bytes_s <= 0:
        raise ValueError("hosts_after and link_bw_bytes_s must be positive")
    return (state_bytes / hosts_after) / link_bw_bytes_s


# -- disk format -------------------------------------------------------------


def _leaf_keys(tree):
    """[(key, leaf)] with stable string keys — the single source of the
    key-derivation rule for both save and load."""
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(jax.tree_util.keystr((p,)).strip("[]'.") for p in path)
        out.append((key, leaf))
    return out


def _flatten(tree) -> Dict[str, np.ndarray]:
    return {k: np.asarray(v) for k, v in _leaf_keys(tree)}


def save(path: str, state: TrainState, metadata: Dict[str, Any] = None) -> None:
    """Atomic npz checkpoint: params + opt_state + step + metadata in ONE
    file, published by a single rename (no torn meta/state pair)."""
    # chaos site: the dense save IS its own commit (single rename)
    faults.fault_point("ckpt.commit")
    t0 = time.perf_counter()
    os.makedirs(path, exist_ok=True)
    host = snapshot(state) if not isinstance(state.step, np.ndarray) else state
    payload = {
        "step": np.asarray(host.step),
        "meta": np.frombuffer(
            json.dumps(metadata or {}).encode(), dtype=np.uint8
        ),
    }
    payload.update({f"p:{k}": v for k, v in _flatten(host.params).items()})
    payload.update({f"o:{k}": v for k, v in _flatten(host.opt_state).items()})
    fd, tmp = tempfile.mkstemp(dir=path, suffix=".npz.tmp")
    os.close(fd)
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, os.path.join(path, "state.npz"))
    _obs_io(
        "write", "dense", time.perf_counter() - t0,
        sum(int(v.nbytes) for v in payload.values()),
    )
    # the dense save IS the commit (single rename): one timeline entry
    _emit_ckpt("ckpt.commit", int(np.asarray(host.step)), fmt="dense")


def load(path: str, like: TrainState) -> TrainState:
    """Load into the structure of ``like`` (a template state — freshly
    initialized params/opt_state define the tree)."""
    t0 = time.perf_counter()
    with np.load(os.path.join(path, "state.npz")) as z:
        data = {k: z[k] for k in z.files}
    _obs_io(
        "read", "dense", time.perf_counter() - t0,
        sum(int(v.nbytes) for v in data.values()),
    )
    _emit_ckpt("ckpt.load", int(np.asarray(data["step"])), fmt="dense")

    def _fill(tree, prefix):
        treedef = jax.tree_util.tree_structure(tree)
        new_leaves = []
        for key, leaf in _leaf_keys(tree):
            stored = data[f"{prefix}:{key}"]
            if stored.shape != np.shape(leaf):
                raise ValueError(
                    f"checkpoint shape mismatch at {key}: "
                    f"{stored.shape} vs {np.shape(leaf)}"
                )
            new_leaves.append(stored)
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    return TrainState(
        step=data["step"],
        params=_fill(like.params, "p"),
        opt_state=_fill(like.opt_state, "o"),
    )


def load_metadata(path: str) -> Dict[str, Any]:
    with np.load(os.path.join(path, "state.npz")) as z:
        if "meta" in z.files:
            return json.loads(bytes(z["meta"]).decode())
    # legacy layout: meta.json sidecar next to the npz
    sidecar = os.path.join(path, "meta.json")
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            return json.load(f)
    return {}


# ---------------------------------------------------------------------------
# sharded multi-process format
#
# Layout under a checkpoint root:
#   <root>/step-00000042/shards-r0003-of-0004.npz   (one per writing rank)
#   <root>/step-00000042/manifest.json              (committed LAST, atomic)
#
# npz entry key: "<p|o>:<leaf path>@<comma-joined offsets>" — the piece's
# position in the global array. The manifest carries global shapes/dtypes,
# the step, metadata, and the exact file list; a loader trusts only
# manifest-listed files (stale/partial writer files are ignored).


@dataclass
class LocalSnapshot:
    """One process's addressable fraction of a TrainState, on host.

    ``pieces[key]`` maps a flattened leaf key to ``[(offset, array)]`` —
    every distinct shard this process holds (deduped across local
    replica devices). ``primary[key]`` lists the offsets for which this
    process owns replica 0 — the disk-write set: across all processes
    the primary pieces tile every global array exactly once.
    """

    step: int
    pieces: Dict[str, List[Tuple[Tuple[int, ...], np.ndarray]]]
    primary: Dict[str, List[Tuple[int, ...]]]
    shapes: Dict[str, Tuple[int, ...]]
    dtypes: Dict[str, str]
    # leaves that were plain host arrays (no replica ownership): every
    # process claims them, so only the writer leader puts them on disk
    host_only: Dict[str, bool] = field(default_factory=dict)

    def is_complete(self) -> bool:
        """True when this process alone holds every byte of the state
        (dp-replicated or single-process meshes) — the condition for a
        solo crash-checkpoint write."""
        for key, shape in self.shapes.items():
            total = int(np.prod(shape)) if shape else 1
            have = sum(
                int(np.prod(p.shape)) if p.shape else 1
                for _, p in self.pieces.get(key, [])
            )
            if have < total:
                return False
        return True


def _state_leaf_items(state: TrainState):
    """Flattened (key, leaf) pairs with the p:/o: prefixes shared with
    the single-file format."""
    items = [(f"p:{k}", v) for k, v in _leaf_keys(state.params)]
    items += [(f"o:{k}", v) for k, v in _leaf_keys(state.opt_state)]
    return items


def snapshot_local(state: TrainState) -> LocalSnapshot:
    """Device → host for THIS process's addressable shards only.

    Works on any multi-process sharded state (where ``snapshot``'s
    whole-tree ``jax.device_get`` would fail on non-addressable
    arrays); host RAM is bounded by the process-local shard bytes.
    Transfers are issued async first, then landed.
    """
    items = _state_leaf_items(state)
    # issue all D2H copies before blocking on any
    for _, leaf in items:
        if isinstance(leaf, jax.Array):
            for sh in leaf.addressable_shards:
                try:
                    sh.data.copy_to_host_async()
                # edl: no-lint[silent-failure] capability probe: backends without async D2H just fall through to the synchronous copy below
                except Exception:  # pragma: no cover - backend-dependent
                    pass
    pieces: Dict[str, List[Tuple[Tuple[int, ...], np.ndarray]]] = {}
    primary: Dict[str, List[Tuple[int, ...]]] = {}
    shapes: Dict[str, Tuple[int, ...]] = {}
    dtypes: Dict[str, str] = {}
    host_only: Dict[str, bool] = {}
    for key, leaf in items:
        shapes[key] = tuple(getattr(leaf, "shape", ()))
        dtypes[key] = np.dtype(getattr(leaf, "dtype", np.float32)).name
        if isinstance(leaf, jax.Array):
            by_off: Dict[Tuple[int, ...], np.ndarray] = {}
            prim: set = set()
            for sh in leaf.addressable_shards:
                off = tuple(int(s.start or 0) for s in sh.index)
                if off not in by_off:
                    by_off[off] = np.asarray(sh.data)
                if sh.replica_id == 0:
                    prim.add(off)
            pieces[key] = sorted(by_off.items())
            primary[key] = sorted(prim)
        else:  # host leaf: whole array, claimed by every process
            arr = np.asarray(leaf)
            off = tuple(0 for _ in arr.shape)
            pieces[key] = [(off, arr)]
            primary[key] = [off]
            host_only[key] = True
    return LocalSnapshot(
        step=int(jax.device_get(state.step)),
        pieces=pieces,
        primary=primary,
        shapes=shapes,
        dtypes=dtypes,
        host_only=host_only,
    )


def _piece_key(key: str, off: Tuple[int, ...], shape: Tuple[int, ...]) -> str:
    """Entry name carries position AND extent so a loader can test
    overlap against a target slice without touching the bytes."""
    return (
        f"{key}@{','.join(map(str, off))}@{','.join(map(str, shape))}"
    )


def _parse_piece_key(k: str) -> Tuple[str, Tuple[int, ...], Tuple[int, ...]]:
    key, _, shape_s = k.rpartition("@")
    key, _, off_s = key.rpartition("@")
    off = tuple(int(x) for x in off_s.split(",")) if off_s else ()
    shape = tuple(int(x) for x in shape_s.split(",")) if shape_s else ()
    return key, off, shape


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step-{step:08d}")


def shard_filename(rank: int, world: int) -> str:
    return f"shards-r{rank:04d}-of-{world:04d}.npz"


def save_shards(
    root: str,
    snap: LocalSnapshot,
    rank: int,
    world: int,
    host_leaves: bool = False,
    all_pieces: bool = False,
) -> str:
    """Write this process's primary pieces into the step directory
    (atomic tmp+rename). Replica-0 ownership already makes jax-array
    pieces unique across processes — including fully-replicated leaves,
    whose replica 0 lives on exactly one process. Host (numpy) leaves
    have no replica notion, so every snapshot claims them; only the
    rank passed ``host_leaves=True`` (the writer leader) includes them.
    ``all_pieces=True`` writes every local piece regardless of replica
    ownership — the solo crash-write path, where a surviving process
    with a complete (dp-replicated) snapshot must persist leaves whose
    replica 0 lived on the dead peer. Returns the shard filename (for
    the leader's manifest)."""
    faults.fault_point("ckpt.save")
    t0 = time.perf_counter()
    d = step_dir(root, snap.step)
    os.makedirs(d, exist_ok=True)
    payload: Dict[str, np.ndarray] = {}
    for key, plist in snap.pieces.items():
        if all_pieces:
            chosen = plist
        else:
            if snap.host_only.get(key) and not host_leaves:
                continue
            prim = set(snap.primary.get(key, ()))
            chosen = [(o, a) for o, a in plist if o in prim]
        for off, arr in chosen:
            payload[_piece_key(key, off, tuple(arr.shape))] = arr
    fname = shard_filename(rank, world)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    os.close(fd)
    with tracing.span("checkpoint.save_shards", step=snap.step, rank=rank):
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, os.path.join(d, fname))
    _obs_io(
        "write", "shards", time.perf_counter() - t0,
        sum(int(a.nbytes) for a in payload.values()),
    )
    _emit_ckpt("ckpt.save", snap.step, fmt="shards", rank=rank, world=world)
    return fname


def write_manifest(
    root: str,
    snap: LocalSnapshot,
    files: List[str],
    metadata: Optional[Dict[str, Any]] = None,
) -> None:
    """Commit the checkpoint: manifest.json names the step, the leaf
    schema, and the exact shard files. Written atomically, LAST — a
    step dir without a manifest is an aborted write and is ignored by
    loaders and reaped by :func:`gc_step_dirs`."""
    # chaos site: a commit that fails here leaves an aborted (manifest-
    # less) step dir, which loaders ignore and gc_step_dirs reaps — the
    # crash-consistency property exp_chaos.py soaks
    faults.fault_point("ckpt.commit")
    d = step_dir(root, snap.step)
    doc = {
        "step": snap.step,
        "files": sorted(set(files)),
        "shapes": {k: list(v) for k, v in snap.shapes.items()},
        "dtypes": snap.dtypes,
        "meta": metadata or {},
    }
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
    os.close(fd)
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, os.path.join(d, "manifest.json"))
    _emit_ckpt("ckpt.commit", snap.step, fmt="shards", files=len(doc["files"]))


def latest_manifest(root: str) -> Optional[Dict[str, Any]]:
    """Newest committed checkpoint's manifest (with its directory under
    key ``_dir``), or None."""
    if not os.path.isdir(root):
        return None
    best = None
    for name in sorted(os.listdir(root), reverse=True):
        if not name.startswith("step-"):
            continue
        mpath = os.path.join(root, name, "manifest.json")
        if os.path.exists(mpath):
            with open(mpath) as f:
                best = json.load(f)
            best["_dir"] = os.path.join(root, name)
            break
    return best


def gc_step_dirs(root: str, keep: int = 2) -> None:
    """Drop all but the newest ``keep`` committed checkpoints, plus any
    aborted (manifest-less) dirs older than the newest committed one."""
    import shutil

    if not os.path.isdir(root):
        return
    dirs = sorted(d for d in os.listdir(root) if d.startswith("step-"))
    committed = [
        d for d in dirs if os.path.exists(os.path.join(root, d, "manifest.json"))
    ]
    victims = set(committed[:-keep] if keep else committed)
    if committed:
        newest = committed[-1]
        victims |= {
            d
            for d in dirs
            if d < newest and d not in committed
        }
    for d in victims:
        shutil.rmtree(os.path.join(root, d), ignore_errors=True)


class _PieceIndex:
    """Piece lookup across RAM snapshot + manifest-listed shard files +
    remote peers. Entry keys carry (offset, shape), so overlap against a
    target slice is decided without I/O; disk pieces load lazily (npz
    members decompress on access) and remote pieces fetch lazily
    (shard_server.RemotePieces) — a process reads only the bytes its
    local devices need. Priority at equal offsets: disk < remote peer <
    local RAM (same bytes everywhere; cheaper source wins)."""

    def __init__(
        self,
        manifest: Optional[Dict[str, Any]],
        ram: Optional[LocalSnapshot],
        remotes: Sequence[Any] = (),
        shapes: Optional[Dict[str, Tuple[int, ...]]] = None,
    ):
        # expected leaf ranks (when known): geometry of a different rank
        # — a stale/version-skewed peer — is dropped HERE, so a skewed
        # entry can neither crash assemble's box math nor be silently
        # zip-truncated into the overlap test; the same filter
        # peer_coverage_ok applies at decision time
        ranks = (
            {k: len(tuple(s)) for k, s in shapes.items()}
            if shapes is not None
            else None
        )

        def ok(key: str, off, shape) -> bool:
            return (
                ranks is None
                or key not in ranks
                or (len(off) == ranks[key] and len(shape) == ranks[key])
            )

        # {leaf key: {(offset, shape): source}} where source is a host
        # array or an (indexable, entry) lazy handle — NpzFile or a
        # shard_server.RemotePieces, both fetched as src[entry]. Keyed
        # by full (offset, extent) geometry so same-offset pieces of
        # DIFFERENT extents (mixed world layouts in a P2P restore) both
        # survive; replicas (same geometry) collapse, cheaper source
        # winning by insertion order.
        self._index: Dict[
            str, Dict[Tuple[Tuple[int, ...], Tuple[int, ...]], Any]
        ] = {}
        self._files: List[Any] = []
        if manifest is not None:
            for fname in manifest["files"]:
                z = np.load(
                    os.path.join(manifest["_dir"], fname), allow_pickle=False
                )
                self._files.append(z)
                for entry in z.files:
                    key, off, shape = _parse_piece_key(entry)
                    if ok(key, off, shape):
                        self._index.setdefault(key, {})[(off, shape)] = (z, entry)
        for src in remotes:
            for entry in src.entries():
                key, off, shape = _parse_piece_key(entry)
                if ok(key, off, shape):
                    self._index.setdefault(key, {})[(off, shape)] = (src, entry)
        if ram is not None:
            for key, plist in ram.pieces.items():
                for off, arr in plist:
                    self._index.setdefault(key, {})[
                        (off, tuple(arr.shape))
                    ] = arr

    def close(self) -> None:
        for z in self._files:
            z.close()

    def prefetch(self, wants: Sequence[Tuple[str, Tuple[int, ...], Tuple[int, ...]]]) -> None:
        """Batch-fetch every REMOTE piece overlapping the wanted boxes
        (``(leaf key, starts, stops)``) before assembly: entries are
        grouped per peer and drained through each peer's ``get_many``
        (parallel pooled connections), with the peers themselves
        drained concurrently — so a restore moves at aggregate network
        speed instead of one piece per RTT. Purely an optimization:
        pieces it misses are fetched lazily by ``assemble``."""
        by_src: Dict[int, Tuple[Any, set]] = {}
        for key, starts, stops in wants:
            for (off, pshape), src in self._index.get(key, {}).items():
                if isinstance(src, np.ndarray) or not isinstance(src, tuple):
                    continue
                holder, entry = src
                if not hasattr(holder, "get_many"):
                    continue
                if pshape and starts:
                    lo = [max(b, o) for b, o in zip(starts, off)]
                    hi = [
                        min(e, o + s)
                        for e, o, s in zip(stops, off, pshape)
                    ]
                    if any(l >= h for l, h in zip(lo, hi)):
                        continue
                by_src.setdefault(id(holder), (holder, set()))[1].add(entry)
        if not by_src:
            return
        errs: List[BaseException] = []

        def drain(holder, entries) -> None:
            try:
                holder.get_many(sorted(entries))
            except BaseException as e:
                # a dead peer surfaces at assembly (coverage check), not
                # here — prefetch must not turn a survivable layout into
                # a hard failure
                errs.append(e)

        if len(by_src) == 1:
            ((holder, entries),) = by_src.values()
            drain(holder, entries)
        else:
            threads = [
                threading.Thread(target=drain, args=(h, es), daemon=True)
                for h, es in by_src.values()
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if errs:
            from edl_tpu.utils.logging import kv_logger

            kv_logger("checkpoint").warn(
                "p2p prefetch incomplete", err=str(errs[0])
            )

    def assemble(
        self, key: str, idx: Tuple, shape: Tuple[int, ...], dtype
    ) -> np.ndarray:
        """Materialize the slice ``idx`` of leaf ``key`` from stored
        pieces. Coverage is proved geometrically (:func:`_boxes_tile`
        over the clipped piece boxes), so overlapping pieces from mixed
        world layouts (P2P restores) are handled correctly — overlap
        regions carry identical same-step bytes, and a genuine hole is
        surfaced even when clipped volumes sum past the target."""
        starts = [
            (s.start or 0) if isinstance(s, slice) else 0 for s in idx
        ]
        stops = [
            (s.stop if s.stop is not None else shape[i])
            if isinstance(s, slice)
            else shape[i]
            for i, s in enumerate(idx)
        ]
        out_shape = tuple(e - b for b, e in zip(starts, stops))
        out = np.empty(out_shape, dtype)
        boxes: List[Tuple[Tuple[int, ...], Tuple[int, ...]]] = []
        for (off, pshape), src in self._index.get(key, {}).items():
            if not shape:  # scalar leaf
                out[...] = src if isinstance(src, np.ndarray) else src[0][src[1]]
                boxes.append(((), ()))
                break
            lo = [max(b, o) for b, o in zip(starts, off)]
            hi = [min(e, o + s) for e, o, s in zip(stops, off, pshape)]
            if any(l >= h for l, h in zip(lo, hi)):
                continue  # no overlap: piece bytes never touched
            arr = src if isinstance(src, np.ndarray) else src[0][src[1]]
            out[
                tuple(slice(l - b, h - b) for l, b, h in zip(lo, starts, hi))
            ] = arr[
                tuple(slice(l - o, h - o) for l, o, h in zip(lo, off, hi))
            ]
            boxes.append((
                tuple(l - b for l, b in zip(lo, starts)),
                tuple(h - l for l, h in zip(lo, hi)),
            ))
        if not _boxes_tile(out_shape, boxes):
            raise ValueError(
                f"checkpoint piece coverage incomplete for {key}{idx}: "
                f"{len(boxes)} pieces leave a hole in {out_shape}"
            )
        return out


def _materialize(
    index: _PieceIndex,
    step: int,
    like: TrainState,
    state_shardings: TrainState,
    shapes: Dict[str, Tuple[int, ...]],
    dtypes: Dict[str, str],
) -> TrainState:
    def _wants(prefix: str, tmpl, shardings):
        """Every (leaf, starts, stops) box this process's devices will
        assemble — known up front from the target sharding, so remote
        pieces can be prefetched in one parallel pass across peers
        instead of one lazy fetch per piece during assembly."""
        keys = [k for k, _ in _leaf_keys(tmpl)]
        leaves = jax.tree_util.tree_leaves(tmpl)
        sh_leaves = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: hasattr(x, "device_set")
        )
        out = []
        for key, leaf, sh in zip(keys, leaves, sh_leaves):
            shape = tuple(getattr(leaf, "shape", ()))
            try:
                idxs = set(
                    sh.addressable_devices_indices_map(shape).values()
                )
            # edl: no-lint[silent-failure] sharding-flavor probe: lazy per-piece fetches cover anything the bulk path can't classify
            except Exception:
                continue  # unknown sharding flavor: lazy fetches cover it
            for idx in idxs:
                starts = tuple(
                    (s.start or 0) if isinstance(s, slice) else 0
                    for s in idx
                )
                stops = tuple(
                    (s.stop if s.stop is not None else shape[i])
                    if isinstance(s, slice)
                    else shape[i]
                    for i, s in enumerate(idx)
                )
                out.append((f"{prefix}:{key}", starts, stops))
        return out

    index.prefetch(
        _wants("p", like.params, state_shardings.params)
        + _wants("o", like.opt_state, state_shardings.opt_state)
    )

    def _build(prefix: str, tmpl, shardings):
        keys = [k for k, _ in _leaf_keys(tmpl)]
        leaves = jax.tree_util.tree_leaves(tmpl)
        sh_leaves = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: hasattr(x, "device_set")
        )
        out = []
        for key, leaf, sh in zip(keys, leaves, sh_leaves):
            fq = f"{prefix}:{key}"
            if fq not in shapes:
                raise KeyError(f"checkpoint missing leaf {fq}")
            shape = tuple(shapes[fq])
            want = tuple(getattr(leaf, "shape", ()))
            if shape != want:
                raise ValueError(
                    f"checkpoint shape mismatch at {fq}: {shape} vs {want}"
                )
            dt = np.dtype(dtypes[fq])
            out.append(
                jax.make_array_from_callback(
                    shape,
                    sh,
                    lambda i, k=fq, s=shape, d=dt: index.assemble(k, i, s, d),
                )
            )
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(tmpl), out
        )

    step_sh = jax.tree_util.tree_leaves(
        state_shardings.step, is_leaf=lambda x: hasattr(x, "device_set")
    )[0]
    step_val = np.asarray(step, np.int32)
    return TrainState(
        step=jax.make_array_from_callback((), step_sh, lambda i: step_val),
        params=_build("p", like.params, state_shardings.params),
        opt_state=_build("o", like.opt_state, state_shardings.opt_state),
    )


def load_sharded(
    root: str,
    like: TrainState,
    state_shardings: TrainState,
    ram: Optional[LocalSnapshot] = None,
    manifest: Optional[Dict[str, Any]] = None,
) -> TrainState:
    """Assemble a TrainState onto a (possibly different-world) mesh from
    the newest committed sharded checkpoint, preferring RAM pieces when
    ``ram`` matches the checkpoint step. Each process materializes only
    its local shards (``jax.make_array_from_callback``), so host RAM
    stays bounded by local shard bytes at every world size.

    ``like`` is a structure template (ShapeDtypeStructs are fine);
    ``state_shardings`` a TrainState of NamedShardings for the target
    mesh. Pass ``manifest`` (from :func:`latest_manifest`) to pin the
    exact checkpoint — otherwise the newest committed one is re-scanned
    here, which can race a concurrent commit.
    """
    if manifest is None:
        manifest = latest_manifest(root)
    if manifest is None:
        raise FileNotFoundError(f"no committed sharded checkpoint under {root}")
    if ram is not None and ram.step != manifest["step"]:
        ram = None  # stale/ahead RAM: disk manifest is the agreed truth
    shapes = {k: tuple(v) for k, v in manifest["shapes"].items()}
    index = _PieceIndex(manifest, ram, shapes=shapes)
    t0 = time.perf_counter()
    try:
        with tracing.span("checkpoint.load_sharded", step=manifest["step"]):
            return _materialize(
                index,
                manifest["step"],
                like,
                state_shardings,
                shapes,
                manifest["dtypes"],
            )
    finally:
        index.close()
        _obs_io("read", "shards", time.perf_counter() - t0, 0)
        _emit_ckpt("ckpt.load", int(manifest["step"]), fmt="shards")


def template_schema(like: TrainState) -> Tuple[Dict[str, Tuple[int, ...]], Dict[str, str]]:
    """(shapes, dtypes) keyed like the sharded format, derived from a
    structure template — what a PEER-only restore uses in place of a
    manifest (shard_server P2P migration: no disk artifact exists)."""
    shapes: Dict[str, Tuple[int, ...]] = {}
    dtypes: Dict[str, str] = {}
    for key, leaf in _state_leaf_items(like):
        shapes[key] = tuple(getattr(leaf, "shape", ()))
        dtypes[key] = np.dtype(getattr(leaf, "dtype", np.float32)).name
    return shapes, dtypes


def _boxes_tile(shape: Tuple[int, ...], boxes: Sequence[Tuple[Tuple[int, ...], Tuple[int, ...]]]) -> bool:
    """Whether axis-aligned boxes ``(offset, extent)`` cover every element
    of ``shape`` — a true geometric union, not an element-count sum, so
    partially overlapping pieces at misaligned offsets (e.g. same-step
    snapshots taken under two different world layouts) cannot sum past
    the total while leaving a hole. Coordinate-compress each axis on the
    box boundaries, then mark covered cells on a boolean grid: correct
    for any overlap pattern, and cheap for real shard layouts (pieces
    cut along at most a couple of axes, so the grid stays tiny)."""
    if not shape:
        return bool(boxes)
    if any(s == 0 for s in shape):
        return True
    cuts: List[List[int]] = []
    for d, size in enumerate(shape):
        c = {0, size}
        for off, ext in boxes:
            c.add(min(max(off[d], 0), size))
            c.add(min(max(off[d] + ext[d], 0), size))
        cuts.append(sorted(c))
    grid_shape = tuple(len(c) - 1 for c in cuts)
    if int(np.prod(grid_shape)) > (1 << 24):  # pathological offsets only:
        # fall back to the conservative answer — an uncommitted P2P
        # restore degrades to the disk manifest, never to a hole.
        return False
    grid = np.zeros(grid_shape, dtype=bool)
    for off, ext in boxes:
        sel = tuple(
            slice(
                int(np.searchsorted(cuts[d], min(max(off[d], 0), size))),
                int(np.searchsorted(cuts[d], min(max(off[d] + ext[d], 0), size))),
            )
            for d, size in enumerate(shape)
        )
        grid[sel] = True
    return bool(grid.all())


def peer_coverage_ok(
    like: TrainState, piece_entries: Sequence[str]
) -> bool:
    """Whether a set of piece entry keys (from peers' shard-server
    indexes, deduped by (leaf, offset) — replicas collapse) tiles every
    leaf of ``like`` completely — deduped by full (leaf, offset, extent)
    geometry, so replicas collapse while same-offset pieces of DIFFERENT
    extents (mixed world layouts) all contribute. Pure key geometry, no
    byte transfer: the go/no-go check before committing a membership to
    a P2P restore. Coverage is decided by per-leaf box union
    (:func:`_boxes_tile`), so the decision agrees with what assembly
    will actually find."""
    shapes, _ = template_schema(like)
    boxes: Dict[str, List[Tuple[Tuple[int, ...], Tuple[int, ...]]]] = {}
    seen = set()
    for entry in piece_entries:
        key, off, shape = _parse_piece_key(entry)
        if (key, off, shape) in seen:
            continue
        seen.add((key, off, shape))
        boxes.setdefault(key, []).append((off, shape))
    for key, shape in shapes.items():
        want = tuple(shape)
        # a stale/version-skewed peer can advertise geometry of a
        # different rank than the current template — non-contributing,
        # never a crash (the decision degrades to disk, same as any
        # other coverage miss)
        usable = [
            (o, e)
            for o, e in boxes.get(key, ())
            if len(o) == len(want) and len(e) == len(want)
        ]
        if not _boxes_tile(want, usable):
            return False
    return True


def load_from_pieces(
    step: int,
    like: TrainState,
    state_shardings: TrainState,
    ram: Optional[LocalSnapshot] = None,
    manifest: Optional[Dict[str, Any]] = None,
    remotes: Sequence[Any] = (),
) -> TrainState:
    """Assemble a TrainState at ``step`` from any mix of sources: local
    RAM snapshot, a committed manifest AT THE SAME STEP, and remote
    peers (shard_server.RemotePieces) — the P2P migration restore. The
    leaf schema comes from the template, so a pure-peer restore needs
    no disk artifact at all. Assembly is coverage-checked per slice; a
    vanished peer surfaces as an error, never a silent hole."""
    if ram is not None and ram.step != step:
        ram = None
    if manifest is not None and manifest["step"] != step:
        manifest = None
    shapes, dtypes = template_schema(like)
    index = _PieceIndex(manifest, ram, remotes=remotes, shapes=shapes)
    try:
        return _materialize(index, step, like, state_shardings, shapes, dtypes)
    finally:
        index.close()


def restore_local(
    like: TrainState,
    state_shardings: TrainState,
    ram: LocalSnapshot,
) -> TrainState:
    """RAM-only restore for states this process holds completely (dp
    meshes / single process) when no checkpoint dir is configured — the
    in-RAM reshard fast path without any disk in the loop."""
    if not ram.is_complete():
        raise ValueError(
            "RAM snapshot does not cover the full state; a shared "
            "checkpoint dir is required to reshard this mesh"
        )
    return _materialize(
        _PieceIndex(None, ram), ram.step, like, state_shardings, ram.shapes, ram.dtypes
    )
