"""Checkpointing — the reshard mechanism, and disk persistence.

The reference delegates persistence to workload code
(reference: example/ctr/ctr/train.py:169-180 save_inference_model every
1000 batches) and pserver state to Paddle's etcd runtime. Here
checkpointing is first-class (SURVEY §5: "it is the reshard mechanism"):

- ``snapshot``/``restore``: device state ⇄ host RAM — the fast path an
  elastic rescale rides (no disk in the loop).
- ``save``/``load``: host snapshot ⇄ disk, flattened-keypath npz — the
  crash-recovery path.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Tuple

import jax
import numpy as np

from edl_tpu.parallel import sharding as shd
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.train.trainer import TrainState, shard_state


def snapshot(state: TrainState) -> TrainState:
    """Device → host RAM (step one of the reshard protocol)."""
    return TrainState(
        step=np.asarray(jax.device_get(state.step)),
        params=shd.to_host(state.params),
        opt_state=shd.to_host(state.opt_state),
    )


def restore(
    host_state: TrainState, plan: MeshPlan, mesh, param_pspecs=None
) -> TrainState:
    """Host RAM → device, sharded for the (possibly new) mesh (step
    three of the reshard protocol)."""
    return shard_state(host_state, plan, mesh, param_pspecs)


# -- disk format -------------------------------------------------------------


def _flatten(tree) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(jax.tree_util.keystr((p,)).strip("[]'.") for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def save(path: str, state: TrainState, metadata: Dict[str, Any] = None) -> None:
    """Atomic npz checkpoint: params + opt_state + step (+ JSON sidecar)."""
    os.makedirs(path, exist_ok=True)
    host = snapshot(state) if not isinstance(state.step, np.ndarray) else state
    payload = {"step": np.asarray(host.step)}
    payload.update({f"p:{k}": v for k, v in _flatten(host.params).items()})
    payload.update({f"o:{k}": v for k, v in _flatten(host.opt_state).items()})
    fd, tmp = tempfile.mkstemp(dir=path, suffix=".npz.tmp")
    os.close(fd)
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, os.path.join(path, "state.npz"))
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(metadata or {}, f)


def load(path: str, like: TrainState) -> TrainState:
    """Load into the structure of ``like`` (a template state — freshly
    initialized params/opt_state define the tree)."""
    with np.load(os.path.join(path, "state.npz")) as z:
        data = {k: z[k] for k in z.files}

    def _fill(tree, prefix):
        flat_keys = _flatten(tree).keys()
        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
        new_leaves = []
        for path_entries, leaf in leaves_with_path:
            key = "/".join(
                jax.tree_util.keystr((p,)).strip("[]'.") for p in path_entries
            )
            stored = data[f"{prefix}:{key}"]
            if stored.shape != np.shape(leaf):
                raise ValueError(
                    f"checkpoint shape mismatch at {key}: "
                    f"{stored.shape} vs {np.shape(leaf)}"
                )
            new_leaves.append(stored)
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    return TrainState(
        step=data["step"],
        params=_fill(like.params, "p"),
        opt_state=_fill(like.opt_state, "o"),
    )


def load_metadata(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, "meta.json")) as f:
        return json.load(f)
