"""Checkpointing — the reshard mechanism, and disk persistence.

The reference delegates persistence to workload code
(reference: example/ctr/ctr/train.py:169-180 save_inference_model every
1000 batches) and pserver state to Paddle's etcd runtime. Here
checkpointing is first-class (SURVEY §5: "it is the reshard mechanism"):

- ``snapshot``/``restore``: device state ⇄ host RAM — the fast path an
  elastic rescale rides (no disk in the loop).
- ``save``/``load``: host snapshot ⇄ disk, flattened-keypath npz — the
  crash-recovery path.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Tuple

import jax
import numpy as np

from edl_tpu.parallel import sharding as shd
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.train.trainer import TrainState, shard_state


def snapshot(state: TrainState) -> TrainState:
    """Device → host RAM (step one of the reshard protocol)."""
    return TrainState(
        step=np.asarray(jax.device_get(state.step)),
        params=shd.to_host(state.params),
        opt_state=shd.to_host(state.opt_state),
    )


def restore(
    host_state: TrainState, plan: MeshPlan, mesh, param_pspecs=None
) -> TrainState:
    """Host RAM → device, sharded for the (possibly new) mesh (step
    three of the reshard protocol)."""
    return shard_state(host_state, plan, mesh, param_pspecs)


def staged_reshard(
    state: TrainState, plan: MeshPlan, mesh, param_pspecs=None
) -> TrainState:
    """Device → host → device as ONE overlapped pipeline — the host
    fallback of the reshard protocol when ``snapshot`` + ``restore``
    would run the two transfer directions back to back. Delegates to
    :func:`edl_tpu.parallel.sharding.stream_reshard` (shared window and
    piece policies with ``to_host``); the sum-form snapshot/restore
    pair remains for disk checkpoints."""
    from edl_tpu.train.trainer import state_pspecs

    sharding_tree = shd.named(state_pspecs(state, plan, param_pspecs), mesh)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    sh_leaves = treedef.flatten_up_to(sharding_tree)
    return jax.tree_util.tree_unflatten(
        treedef, shd.stream_reshard(leaves, sh_leaves)
    )


# -- disk format -------------------------------------------------------------


def _leaf_keys(tree):
    """[(key, leaf)] with stable string keys — the single source of the
    key-derivation rule for both save and load."""
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(jax.tree_util.keystr((p,)).strip("[]'.") for p in path)
        out.append((key, leaf))
    return out


def _flatten(tree) -> Dict[str, np.ndarray]:
    return {k: np.asarray(v) for k, v in _leaf_keys(tree)}


def save(path: str, state: TrainState, metadata: Dict[str, Any] = None) -> None:
    """Atomic npz checkpoint: params + opt_state + step + metadata in ONE
    file, published by a single rename (no torn meta/state pair)."""
    os.makedirs(path, exist_ok=True)
    host = snapshot(state) if not isinstance(state.step, np.ndarray) else state
    payload = {
        "step": np.asarray(host.step),
        "meta": np.frombuffer(
            json.dumps(metadata or {}).encode(), dtype=np.uint8
        ),
    }
    payload.update({f"p:{k}": v for k, v in _flatten(host.params).items()})
    payload.update({f"o:{k}": v for k, v in _flatten(host.opt_state).items()})
    fd, tmp = tempfile.mkstemp(dir=path, suffix=".npz.tmp")
    os.close(fd)
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, os.path.join(path, "state.npz"))


def load(path: str, like: TrainState) -> TrainState:
    """Load into the structure of ``like`` (a template state — freshly
    initialized params/opt_state define the tree)."""
    with np.load(os.path.join(path, "state.npz")) as z:
        data = {k: z[k] for k in z.files}

    def _fill(tree, prefix):
        treedef = jax.tree_util.tree_structure(tree)
        new_leaves = []
        for key, leaf in _leaf_keys(tree):
            stored = data[f"{prefix}:{key}"]
            if stored.shape != np.shape(leaf):
                raise ValueError(
                    f"checkpoint shape mismatch at {key}: "
                    f"{stored.shape} vs {np.shape(leaf)}"
                )
            new_leaves.append(stored)
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    return TrainState(
        step=data["step"],
        params=_fill(like.params, "p"),
        opt_state=_fill(like.opt_state, "o"),
    )


def load_metadata(path: str) -> Dict[str, Any]:
    with np.load(os.path.join(path, "state.npz")) as z:
        if "meta" in z.files:
            return json.loads(bytes(z["meta"]).decode())
    # legacy layout: meta.json sidecar next to the npz
    sidecar = os.path.join(path, "meta.json")
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            return json.load(f)
    return {}
