"""Elastic multi-process worker program — real worker processes, zero restarts.

This is the process-level realization of the elastic protocol that
`runtime/elastic.py` implements in-process: each worker is a separate OS
process (one per TPU host in production; virtual-CPU JAX processes in
tests), peers are discovered through the job coordinator
(runtime/coordinator.py — the etcd/master analog, reference:
docker/paddle_k8s:14-32), and data comes from the coordinator's task
queue (reference: cloud_reader + master task queue,
example/fit_a_line/train_ft.py:105-114).

Lifecycle, per membership epoch ("incarnation" of the collective):

  1. rendezvous: wait until the coordinator's member list is stable,
     take the deterministic rank (reference: k8s_tools.py fetch_pod_id);
  2. the rank-0 member spawns the epoch's EXTERNAL coordination-service
     host (runtime/dist_service.py — outside the workers so leader death
     is survivable), which publishes the endpoint in coordinator KV;
     every worker connects as a pure client (world = live members);
  3. restore train state — from the in-RAM host snapshot if this worker
     survived the previous epoch, else from the job checkpoint
     (joiners), else fresh init (job start);
  4. lockstep training: every step the rank-0 worker publishes ONE
     decision — ``step`` / ``reshard`` / ``stop`` — in KV and all
     workers obey it. This is what keeps SPMD collectives aligned
     across membership change: a worker may only stop stepping after a
     published ``reshard``/``stop``, so nobody leaves a peer stranded
     inside an all-reduce. Data tasks are leased per step and acked
     after the optimizer update (lease timeout redelivers lost work —
     reference: -task-timout-dur=16s, docker/paddle_k8s:28-31).
  5. on ``reshard``: snapshot state to host RAM, write the job
     checkpoint (lowest-rank live worker), ``jax.distributed.shutdown``,
     clear XLA backends, and loop back to (1) — the process itself
     never restarts, which is the BASELINE north star ("zero job
     restarts", <30 s stall).

Scale-up: the controller just starts another worker process; its
registration bumps the membership epoch, rank 0 notices and publishes
``reshard``. Scale-down: the controller sends SIGTERM; the worker sets
a leaving flag but KEEPS stepping until rank 0 publishes ``reshard``
(graceful drain), then deregisters and exits 0. Crash: lease timeout +
member TTL expiry bump the epoch; survivors recover from the last
completed step (the train step does not donate its inputs, so state is
still live after a failed collective).

Env contract (EDL_*, reference: pkg/jobparser.go:263-311 PADDLE_INIT_*):
see ``WorkerConfig.from_env``.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
import dataclasses
from typing import Any, Callable, Dict, Optional

import numpy as np

from edl_tpu.obs import disttrace
from edl_tpu.runtime.coordinator import CoordinatorClient
from edl_tpu.runtime import entrypoint
from edl_tpu.utils import tracing
from edl_tpu.utils.logging import kv_logger

log = kv_logger("worker")

_POLL_S = 0.02


def _emit_worker_event(kind: str, worker: str, severity: str = "info", **attrs):
    """Flight-recorder emit keyed by worker (join/leave/heartbeat —
    the membership decisions a fleet postmortem reconstructs).
    Telemetry must never take the worker down."""
    try:
        from edl_tpu.obs import events

        events.emit(kind, severity, worker=worker, **attrs)
    # edl: no-lint[silent-failure] the event-emit wrapper itself: telemetry must never take the worker down, and logging from here could recurse into the sink
    except Exception:  # pragma: no cover - defensive
        pass


# --------------------------------------------------------------------------
# config: runtime/worker_config.py (re-exported: the EDL_* env contract)

from edl_tpu.runtime.worker_config import WorkerConfig  # noqa: E402



# --------------------------------------------------------------------------
# model registry: runtime/workloads.py (re-exported for existing
# consumers of the env contract)

from edl_tpu.runtime.workloads import WORKLOADS, Workload  # noqa: E402



# --------------------------------------------------------------------------
# platform / jax.distributed plumbing


def _setup_platform(cfg: WorkerConfig) -> None:
    """Platform/env setup only — must NOT query devices: the XLA backend
    may only initialize after jax.distributed.initialize."""
    import jax

    if cfg.local_devices > 0:
        from edl_tpu.utils.platform import prepare_virtual_cpu

        prepare_virtual_cpu(cfg.local_devices)
        # cross-process CPU collectives need gloo
        jax.config.update("jax_cpu_collectives_implementation", "gloo")


def _initialize_distributed(
    addr: str, world: int, rank: int, timeout_s: int = 60
) -> None:
    """Client-only jax.distributed bring-up against an EXTERNAL
    coordination service (runtime/dist_service.py). Stock
    ``jax.distributed.initialize`` would make rank 0 host the service
    in-process, turning rank-0 death into an unrecoverable loss of the
    rendezvous plane. ``recoverable=True`` keeps a peer's death from
    being broadcast as a fatal job error to the survivors."""
    from jax._src import distributed as _dist
    from jax._src.lib import _jax

    state = _dist.global_state
    if state.client is not None:  # pragma: no cover - defensive
        raise RuntimeError("distributed state already initialized")
    state.client = _jax.get_distributed_runtime_client(
        addr,
        rank,
        init_timeout=timeout_s,
        heartbeat_timeout=10,
        shutdown_timeout=10,
        use_compression=True,
        recoverable=True,
    )
    state.client.connect()
    state.process_id = rank
    state.num_processes = world
    state.coordinator_address = addr


def _reset_distributed_state() -> None:
    """Drop jax.distributed's global state without a disconnect RPC, so
    a later initialize() starts clean (and jax's atexit shutdown
    becomes a no-op)."""
    from jax._src import distributed as _dist

    if _dist.global_state.client is not None:
        _dist.global_state.client = None
        _dist.global_state.service = None
        _dist.global_state.process_id = 0
        _dist.global_state.num_processes = 0


def _shutdown_distributed() -> None:
    """Tear down jax.distributed, tolerating a dead coordinator (the
    rank-0 peer may be the one that crashed)."""
    import jax

    done = threading.Event()

    def _go():
        try:
            jax.distributed.shutdown()
        except Exception as e:  # pragma: no cover - error-path logging
            log.warn("distributed shutdown error", error=str(e))
        finally:
            done.set()

    t = threading.Thread(target=_go, daemon=True)
    t.start()
    if not done.wait(timeout=15):  # pragma: no cover
        log.warn("distributed shutdown timed out; forcing state reset")
    _reset_distributed_state()


def _clear_backends() -> None:
    import jax

    jax.clear_caches()
    try:
        from jax._src import xla_bridge

        xla_bridge._clear_backends()
    # edl: no-lint[silent-failure] version probe: the handler body IS the handling (the newer-jax fallback path)
    except Exception:  # pragma: no cover - jax-version fallback
        import jax.extend.backend

        jax.extend.backend.clear_backends()


# --------------------------------------------------------------------------
# the worker


class ElasticWorker:
    def __init__(self, cfg: WorkerConfig):
        self.cfg = cfg
        self.client = CoordinatorClient(cfg.coord_host, cfg.coord_port, 30.0)
        self._leaving = False
        # last snapshot of THIS process's addressable shards (the RAM
        # half of the reshard protocol; disk holds the committed union)
        self._ram_snapshot = None  # checkpoint.LocalSnapshot
        self._pending_commit: Optional[threading.Thread] = None
        self._last_local: Optional[Dict[str, np.ndarray]] = None
        self._resharded = 0
        self._local_rows = 0  # batch rows this process feeds per step
        self._model_meta = None  # architecture record for exports
        # epoch-scoped KV (go/dist/disc keys) retired by past epochs,
        # GC'd one epoch later — keeps the coordinator KV (and its WAL
        # snapshots) O(live state), not O(job epochs). The two-phase
        # deferral semantics (and which keys MUST take the late lane)
        # live in runtime/epoch_gc.py.
        from edl_tpu.runtime.epoch_gc import EpochKeyGC
        from edl_tpu.runtime.eval_hook import ExportEvaluator
        from edl_tpu.runtime.p2p_restore import P2PRestorePlane

        self._gc = EpochKeyGC()
        # p2p shard plane brokering (server lifecycle, roster, restore
        # decision, veto, drain-window linger): runtime/p2p_restore.py
        self._p2p = P2PRestorePlane(
            cfg, self._k, self._gc, lambda: self._ram_snapshot
        )
        # commit-leader held-out eval: runtime/eval_hook.py
        self._eval = ExportEvaluator(cfg, self._k)
        self._incarnation = 0  # set at bootstrap; bumped to force regroup
        self._restore_failures = 0
        self._exporter = None  # obs.MetricsExporter when EDL_METRICS_PORT set
        self._pusher = None  # obs.MetricsPusher when metrics_push_s > 0
        self._hb_degraded = False  # heartbeat loop cut off from coordinator

    # -- keys ----------------------------------------------------------------
    def _k(self, *parts: str) -> str:
        return "/".join((self.cfg.job,) + parts)

    # -- telemetry (edl_tpu/obs) ---------------------------------------------
    def _telemetry_start(self) -> None:
        """Bring up this worker's observability surface: the process
        registry (full core catalog + tracer bridge, so reshard/
        checkpoint spans are scrapeable as histograms), the optional
        HTTP exporter, and the periodic snapshot push into coordinator
        KV that feeds the coordinator's fleet-aggregated /metrics.
        Telemetry failures degrade to warnings — never the job."""
        from edl_tpu import obs

        cfg = self.cfg
        obs.ensure_core_series()
        obs.bridge_tracer()
        # every flight-recorder event this process emits from here on
        # carries worker identity — the fleet log's correlation key
        obs.events.default_recorder().set_context(worker=cfg.worker_id)
        # clock alignment (obs/disttrace): bracket coordinator TIME
        # round trips to estimate this process's wall-clock offset
        # (NTP midpoint, min-RTT sample) and publish it so the fleet
        # merge lands every worker's spans/events on ONE axis. Refresh
        # rides the metrics-push cadence below, throttled.
        self._clock = obs.disttrace.ClockSync()
        clock_kv = obs.clock_key(cfg.job, cfg.worker_id)

        def _clock_publish():
            try:
                est = self._clock.maybe_sample(self.client.time)
                if est is not None:
                    self.client.kv_put(clock_kv, est.to_json())
            except Exception as e:  # telemetry must never take the job
                log.warn("clock sync failed", error=str(e))

        _clock_publish()
        # EDL_TSDB_DIR: one shared on-disk history (obs/tsdb.py) — the
        # pusher appends snapshots into it on its cadence, the exporter
        # serves it on /history, `edl watch DIR` replays it offline
        tsdb = obs.TSDB(cfg.tsdb_dir) if cfg.tsdb_dir else None
        if cfg.metrics_port >= 0:
            try:
                self._exporter = obs.start_exporter(
                    port=cfg.metrics_port, history=tsdb
                )
                # advertise the bound (possibly ephemeral) port so
                # `edl top` / scrapers can discover it through KV
                self.client.kv_put(
                    self._k("metrics_addr", cfg.worker_id),
                    f"127.0.0.1:{self._exporter.port}",
                )
            except OSError as e:
                log.warn("metrics exporter failed to bind", error=str(e))
        if cfg.metrics_push_s > 0:
            key = obs.metrics_key(cfg.job, cfg.worker_id)
            ekey = obs.events_key(cfg.job, cfg.worker_id)
            tkey = obs.trace_key(cfg.job, cfg.worker_id)
            # the main client is lock-serialized per roundtrip, so the
            # pusher thread can share it (same pattern would hold for a
            # dedicated connection; sharing avoids a third socket).
            # The flight-recorder window AND the recent tracer-span
            # window ride the same cadence so the coordinator's
            # /events shows the worker-labeled fleet log and /trace
            # merges every worker onto the coordinator's clock axis.
            self._pusher = obs.MetricsPusher(
                lambda payload: self.client.kv_put(key, payload),
                interval_s=cfg.metrics_push_s,
                events_publish=lambda payload: self.client.kv_put(
                    ekey, payload
                ),
                trace_publish=lambda payload: self.client.kv_put(
                    tkey, payload
                ),
                clock_refresh=_clock_publish,
                # the same snapshot also lands in the on-disk history
                # — and arms the memledger crosscheck gauge — at zero
                # extra RPCs
                tsdb=tsdb,
            ).start()

    def _telemetry_stop(self) -> None:
        if self._pusher is not None:
            try:
                self._pusher.stop(final_push=True)
            # edl: no-lint[silent-failure] teardown best-effort; a failing final push is already counted by the pusher's failure counter
            except Exception:  # pragma: no cover - teardown best-effort
                pass
            self._pusher = None
        if self._exporter is not None:
            try:
                self._exporter.stop()
            # edl: no-lint[silent-failure] teardown best-effort exporter stop
            except Exception:  # pragma: no cover
                pass
            self._exporter = None

    # -- SIGTERM: graceful drain --------------------------------------------
    def _on_sigterm(self, signum, frame):  # pragma: no cover - signal path
        # Python delivers signals on the main thread (same thread as
        # run()), and _leaving is a monotonic bool the beat thread only
        # polls — a stale read costs one extra heartbeat, never
        # correctness
        # edl: no-lint[lockset-race]
        self._leaving = True
        try:
            # separate connection: the main client may be mid-call
            c = CoordinatorClient(self.cfg.coord_host, self.cfg.coord_port, 5.0)
            c.kv_put(self._k("leaving", self.cfg.worker_id), "1")
            c.close()
        except Exception as e:
            # an unpublished leaving-mark downgrades the graceful drain
            # to a lease-expiry eviction — loud, not silent (edl check
            # silent-failure)
            log.warn("could not publish leaving mark", error=str(e))

    # -- rendezvous ----------------------------------------------------------
    def _stable_members(self):
        """Wait until membership is stable (same epoch + members across
        two reads, no pending leavers among them), then return
        (epoch, members)."""
        cl = self.client
        deadline = time.monotonic() + self.cfg.rendezvous_timeout_s
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError("rendezvous: membership never stabilized")
            cl.expire()
            e1 = cl.epoch()
            ms = cl.members()
            names = [m.name for m in ms]
            if self.cfg.worker_id not in names or not names:
                time.sleep(_POLL_S)
                continue
            if any(cl.kv_get(self._k("leaving", n)) for n in names):
                time.sleep(_POLL_S)  # leaver still deregistering
                continue
            time.sleep(0.1)
            if cl.epoch() == e1 and [m.name for m in cl.members()] == names:
                return e1, ms

    def _spawn_dist_service(self, epoch: int, world: int) -> None:
        """Launch the external coordination-service host for this epoch
        (runtime/dist_service.py). Detached: it must outlive this worker
        so that rank-0 death cannot take the rendezvous plane with it."""
        import subprocess

        log_dir = os.environ.get("EDL_LOG_DIR", "")
        if log_dir:
            out = open(
                os.path.join(log_dir, f"dist_service_e{epoch}.log"), "ab"
            )
        else:
            out = subprocess.DEVNULL
        subprocess.Popen(
            [
                sys.executable,
                "-m",
                "edl_tpu.runtime.dist_service",
                "--job", self.cfg.job,
                "--epoch", str(epoch),
                "--world", str(world),
                "--coordinator",
                f"{self.cfg.coord_host}:{self.cfg.coord_port}",
            ],
            stdout=out,
            stderr=subprocess.STDOUT if log_dir else subprocess.DEVNULL,
            start_new_session=True,
        )
        if log_dir:
            out.close()  # child holds the fd

    def _rendezvous(self):
        """Agree on (epoch, rank, world, dist endpoint) with all live
        peers. The rank-0 member spawns the epoch's external service
        host, which publishes the endpoint; everyone polls for it.
        Restarts automatically if membership shifts underfoot."""
        cl = self.client
        while True:
            epoch, members = self._stable_members()
            me = next(m for m in members if m.name == self.cfg.worker_id)
            world = len(members)
            key = self._k("dist", str(epoch))
            if me.rank == 0 and cl.kv_get(key) is None:
                self._spawn_dist_service(epoch, world)
            addr = None
            deadline = time.monotonic() + self.cfg.rendezvous_timeout_s
            while addr is None:
                addr = cl.kv_get(key)
                if addr is None:
                    if cl.epoch() != epoch:
                        break  # membership moved: restart rendezvous
                    # (an orphan service host self-dismisses after its
                    # epoch goes stale — dist_service.py --orphan-grace)
                    if time.monotonic() > deadline:
                        raise TimeoutError("rendezvous: no dist endpoint")
                    time.sleep(_POLL_S)
            if addr is None:
                continue
            return epoch, me.rank, world, addr, members

    # -- state placement -----------------------------------------------------
    def _restore_state(self, wl, tx, plan, mesh, cl=None, epoch=0, rank=0,
                       members=()):
        """P2P peer pieces (rank-0-brokered decision; newest covered
        step) > committed sharded checkpoint (+RAM pieces when the step
        matches) > RAM-only (dp/single-process, no ckpt dir) > fresh
        sharded init. All processes restore the same step: the P2P
        decision key / the manifest is the agreed truth, so survivors
        whose RAM ran ahead of the last commit (fsdp crash) roll back
        with everyone else.

        Never materializes the full state on any host: restore builds
        only local shards (make_array_from_callback), fresh init runs
        jit-sharded (VERDICT r1 weak #2/#3).
        """
        import jax

        from edl_tpu.parallel import sharding as shd
        from edl_tpu.runtime import checkpoint as ckpt
        from edl_tpu.train.trainer import TrainState, state_pspecs

        pspecs = wl.pspecs(plan) if wl.pspecs is not None else None
        like = jax.eval_shape(lambda: TrainState.create(wl.init_params(), tx))
        state_sh = shd.named(state_pspecs(like, plan, pspecs), mesh)
        manifest = (
            ckpt.latest_manifest(self.cfg.ckpt_dir) if self.cfg.ckpt_dir else None
        )
        if self.cfg.p2p and cl is not None:
            state = self._p2p.restore(
                cl, epoch, rank, members, like, state_sh, manifest,
                self._ram_snapshot,
            )
            if state is not None:
                return state, pspecs
        if manifest is not None:
            state = ckpt.load_sharded(
                self.cfg.ckpt_dir,
                like,
                state_sh,
                ram=self._ram_snapshot,
                manifest=manifest,
            )
            log.info("restored", step=int(manifest["step"]))
        elif (
            self._ram_snapshot is not None and self._ram_snapshot.is_complete()
        ):
            state = ckpt.restore_local(like, state_sh, self._ram_snapshot)
        else:
            # job start — or an fsdp crash before ANY commit existed
            # (nothing restorable: the dead peer's shards are gone and
            # no manifest was written); restart the job's math from
            # step 0 rather than killing every survivor
            if self._ram_snapshot is not None:
                log.warn(
                    "no committed checkpoint and local snapshot is "
                    "partial; reinitializing from step 0"
                )
            state = jax.jit(
                lambda: TrainState.create(wl.init_params(), tx),
                out_shardings=state_sh,
            )()
        return state, pspecs


    def _join_pending_commit(self) -> None:
        """At most ONE background commit is in flight; the next commit,
        a crash rescue, or an epoch teardown serializes behind it."""
        t = self._pending_commit
        if t is None:
            return
        t.join(self.cfg.ckpt_commit_timeout_s + 30)
        if t.is_alive():  # pragma: no cover - hung storage
            log.error("background checkpoint commit did not finish in time")
        self._pending_commit = None

    def _coordinated_checkpoint(
        self, cl, epoch, state, rank, members, background=False
    ):
        """Commit the state as a sharded checkpoint: every member writes
        its primary shards, the leader (lowest live rank) awaits all
        marks and commits manifest.json last. A member dying mid-write
        aborts the commit (its primary shards are unrecoverable), and
        the previous committed step remains the restore point.

        ``background=True`` (the periodic "ckpt" verb): the host-RAM
        snapshot is taken synchronously — the device state mutates next
        step — but the disk write, mark posting, and the leader's
        mark-wait + manifest commit run on a writer thread with its own
        coordinator connection, so multi-GB shard writes overlap
        training instead of stalling it. Stop/reshard commits stay
        synchronous: teardown must not outrun the manifest."""
        from edl_tpu.runtime import checkpoint as ckpt

        cfg = self.cfg
        self._join_pending_commit()
        snap = ckpt.snapshot_local(state)
        self._ram_snapshot = snap
        if not cfg.ckpt_dir:
            return
        # A reshard/stop at the same step a background "ckpt" commit
        # just finished would re-commit an identical state — and the
        # finished commit's mark-cleanup can race the re-commit's fresh
        # marks (same (epoch, step, worker) keys), stranding the leader
        # in its mark wait. The leader's view of ckpt_step is
        # authoritative here: it joined the very thread that wrote it.
        if int(cl.kv_get(self._k("ckpt_step")) or "-1") >= snap.step:
            return
        world = len(members)

        def _write(client, own_client: bool) -> None:
            try:
                alive = {m.name for m in client.members()}
                leader = min(
                    (m.rank for m in members if m.name in alive), default=rank
                )
                own = os.path.join(
                    ckpt.step_dir(cfg.ckpt_dir, snap.step),
                    ckpt.shard_filename(rank, world),
                )
                if rank != leader and os.path.exists(own):
                    # a background commit of this exact step already
                    # wrote this rank's shards (atomic rename => the
                    # file is complete) but its manifest aborted; a
                    # non-leader's stale read of ckpt_step cannot see
                    # that — reuse the file, only re-post the mark
                    fname = os.path.basename(own)
                else:
                    fname = ckpt.save_shards(
                        cfg.ckpt_dir, snap, rank, world,
                        host_leaves=(rank == leader),
                    )
                mark = lambda n: self._k(  # noqa: E731
                    "ckmark", str(epoch), str(snap.step), n
                )
                client.kv_put(mark(cfg.worker_id), fname)
                if rank != leader:
                    # leak guard (ADVICE r2): the leader skips a commit
                    # when ITS ckpt_step read shows the step already
                    # committed — and since the skip is decided on this
                    # same shared KV, one fresh read here sees it too.
                    # In that case nobody will collect this mark:
                    # reclaim it now. The healthy path (leader waiting
                    # on marks) stays fire-and-forget.
                    if (
                        int(client.kv_get(self._k("ckpt_step")) or "-1")
                        >= snap.step
                    ):
                        client.kv_del(mark(cfg.worker_id))
                    return
                # scale the commit deadline with shard size is the
                # caller's job (EDL_CKPT_COMMIT_TIMEOUT_S); the default
                # must accommodate multi-GB writes to shared storage
                deadline = time.monotonic() + cfg.ckpt_commit_timeout_s
                files = None
                while time.monotonic() < deadline:
                    client.expire()
                    alive = {m.name for m in client.members()}
                    got, waiting, dead_unwritten = [], [], []
                    for m in members:
                        v = client.kv_get(mark(m.name))
                        if v:
                            got.append(v)
                        elif m.name in alive:
                            waiting.append(m.name)
                        else:
                            dead_unwritten.append(m.name)
                    if not waiting:
                        files = got if not dead_unwritten else None
                        break
                    time.sleep(_POLL_S)
                for m in members:  # marks served their purpose either way
                    client.kv_del(mark(m.name))
                if files:
                    ckpt.write_manifest(
                        cfg.ckpt_dir, snap, files, {"job": cfg.job}
                    )
                    # monotonic max-write: a commit thread that stalled
                    # past its join timeout must not regress the
                    # pointer a LATER commit already advanced
                    cur = int(client.kv_get(self._k("ckpt_step")) or "-1")
                    if snap.step > cur:
                        client.kv_put(self._k("ckpt_step"), str(snap.step))
                    ckpt.gc_step_dirs(cfg.ckpt_dir, keep=2)
                    if cfg.export_dir:
                        # servable params-only artifact on every commit
                        # (the save_inference_model cadence, reference
                        # example/ctr/ctr/train.py:169-180) — assembled
                        # from the shards just committed, so it works
                        # for fsdp states no single process holds
                        try:
                            from edl_tpu.runtime import export as exp

                            d = exp.export_from_checkpoint(
                                cfg.ckpt_dir,
                                cfg.export_dir,
                                dtype=cfg.export_dtype,
                                ram=snap,  # skip re-reading own shards
                                model_meta=self._model_meta,
                            )
                            if d:
                                log.info(
                                    "export published",
                                    dir=d,
                                    step=snap.step,
                                )
                                self._eval.evaluate(client, snap.step)
                        except Exception as e:  # pragma: no cover
                            log.error("export failed", error=str(e))
                else:  # pragma: no cover - crash-timing path
                    # surfaced as a counter so monitors can alarm on
                    # repeated aborts (a job silently training without
                    # restore points)
                    aborts = int(
                        client.kv_get(self._k("ckpt_aborts")) or "0"
                    ) + 1
                    client.kv_put(self._k("ckpt_aborts"), str(aborts))
                    log.error(
                        "checkpoint commit aborted "
                        "(peer died or write timed out)",
                        step=snap.step,
                        aborts=aborts,
                    )
            except Exception as e:  # pragma: no cover - storage faults
                log.error("checkpoint commit failed", error=str(e))
                try:
                    aborts = int(
                        client.kv_get(self._k("ckpt_aborts")) or "0"
                    ) + 1
                    client.kv_put(self._k("ckpt_aborts"), str(aborts))
                # edl: no-lint[silent-failure] abort-counter publish is best-effort; the commit failure itself was log.error'd just above
                except Exception:
                    pass
                if not own_client:
                    # synchronous (stop/reshard) commits must not be
                    # silently lost: the job would report success with
                    # a stale restore point
                    raise
            finally:
                if own_client:
                    try:
                        client.close()
                    # edl: no-lint[silent-failure] closing a one-shot client at teardown
                    except Exception:
                        pass

        if not background:
            _write(cl, own_client=False)
            return

        def _bg():
            try:
                client = CoordinatorClient(
                    cfg.coord_host, cfg.coord_port, 10.0
                )
            except Exception as e:  # pragma: no cover - coord hiccup
                log.error(
                    "background commit could not reach coordinator",
                    error=str(e),
                )
                return
            _write(client, own_client=True)

        t = threading.Thread(
            target=_bg, name="edl-ckpt-commit", daemon=True
        )
        t.start()
        self._pending_commit = t

    def _crash_checkpoint(self, cl, snap, rank, world) -> None:
        """After a failed collective any survivor may be the only one
        left. A survivor holding the COMPLETE state (dp-replicated)
        persists it solo if newer than the last commit (atomic manifest
        rename; content identical among lockstep peers, so racing
        writers are harmless). FSDP survivors cannot — the dead peer's
        primary shards died with it — so the job rolls back to the last
        committed step (cadence: cfg.ckpt_every)."""
        from edl_tpu.runtime import checkpoint as ckpt

        if not self.cfg.ckpt_dir:
            return
        self._join_pending_commit()  # serialize behind an in-flight commit
        known = int(cl.kv_get(self._k("ckpt_step")) or "-1")
        if snap.step <= known or not snap.is_complete():
            return
        fname = ckpt.save_shards(
            self.cfg.ckpt_dir, snap, rank, world,
            host_leaves=True, all_pieces=True,
        )
        ckpt.write_manifest(self.cfg.ckpt_dir, snap, [fname], {"job": self.cfg.job})
        cl.kv_put(self._k("ckpt_step"), str(snap.step))

    # -- the run -------------------------------------------------------------
    def run(self) -> int:
        cfg = self.cfg
        _setup_platform(cfg)
        import jax

        import optax

        from edl_tpu.parallel.mesh import MeshPlan

        wl = WORKLOADS[cfg.model](cfg)
        self._model_meta = wl.model_meta
        self._eval.eval_fn = wl.eval_fn
        # workload-declared analytic cost: lets the step loop publish
        # the live roofline gauge edl_mfu{phase="train"} (obs/costmodel)
        self._flops_per_example = wl.flops_per_example
        if cfg.eval_dir and wl.eval_fn is None:
            # surface the misconfiguration once: otherwise EDL_EVAL_DIR
            # on a workload without an eval hook is a silent no-op
            log.warn(
                "EDL_EVAL_DIR set but workload defines no eval_fn; "
                "no eval_metric will be published",
                model=cfg.model,
            )
        if cfg.data_dir:
            # real on-disk data: leased [start, end) ranges read shard
            # files instead of the workload's synthetic generator
            from edl_tpu.runtime.shards import FileShardSource

            source = FileShardSource(cfg.data_dir)
            wl = dataclasses.replace(wl, batch_fn=source.fetch_range)
            cfg.n_samples = source.n_samples
            log.info(
                "dataset attached", dir=cfg.data_dir, n_samples=cfg.n_samples
            )
        tx = optax.adam(1e-2 if cfg.model == "linreg" else 1e-3)

        if self._leaving:  # SIGTERM during startup: never joined
            return 0
        if cfg.slice_id >= 0:
            # published BEFORE registration so any peer that sees us in
            # membership can already read our slice id at rendezvous
            self.client.kv_put(
                self._k("slice", cfg.worker_id), str(cfg.slice_id)
            )
        # serve our host-RAM snapshot to peers (P2P reshard data plane);
        # published before registration like the slice id. Server
        # lifecycle, token, roster, and restore brokering:
        # runtime/p2p_restore.py.
        self._p2p.start(self.client)
        ctx = entrypoint.bootstrap(self.client)
        self._incarnation = ctx.incarnation
        heartbeat_stop = self._start_heartbeat(ctx.incarnation)
        self._telemetry_start()
        try:
            return self._epochs(cfg, jax, MeshPlan, wl, tx)
        except Exception as e:
            entrypoint.record_failure(self.client, cfg.job, f"exception: {e}")
            raise
        finally:
            heartbeat_stop.set()
            self._telemetry_stop()

    def _start_heartbeat(self, incarnation: int) -> threading.Event:
        """TTL keep-alive on its own connection (steps may outlast the
        member TTL). Survives transient coordinator hiccups by
        reconnecting, and re-registers if a missed TTL already evicted
        us — the re-registration bumps the epoch, which correctly shows
        up to the group as a membership change."""
        stop = threading.Event()
        interval = min(0.5, max(0.1, self.cfg.member_ttl_s / 4))

        def _beat():  # pragma: no cover - timing-dependent
            c = None
            while not stop.wait(interval):
                c = self._beat_tick(c, incarnation)
            if c is not None:
                try:
                    c.close()
                # edl: no-lint[silent-failure] closing the beat client at thread exit
                except Exception:
                    pass

        threading.Thread(target=_beat, daemon=True).start()
        return stop

    def _beat_tick(self, c, incarnation: int):
        """One heartbeat attempt; returns the (re)usable client or None
        after a failure. NEVER raises — a ConnectionError here (the
        client's reconnect window exhausted during a long coordinator
        outage) used to kill the beat thread, leaving the worker running
        but silently TTL-expiring out of membership. Instead the worker
        flips a degraded flag + gauge (``edl_worker_heartbeat_degraded``,
        scrapeable so the fleet view shows WHO is beating blind) and
        keeps retrying every tick until it departs; the first successful
        beat clears the flag (and re-registers if the TTL already
        evicted us)."""
        from edl_tpu.obs import metrics as obs_metrics

        cfg = self.cfg
        gauge = obs_metrics.default_registry().gauge(
            "edl_worker_heartbeat_degraded",
            "1 while the heartbeat loop cannot reach the coordinator",
        )
        try:
            if c is None:
                c = CoordinatorClient(cfg.coord_host, cfg.coord_port, 5.0)
            if not c.heartbeat(cfg.worker_id) and not self._leaving:
                log.warn("TTL-evicted while alive; re-registering")
                _emit_worker_event(
                    "worker.re_register", cfg.worker_id, severity="warn",
                )
                c.register(cfg.worker_id, incarnation)
            if self._hb_degraded:
                self._hb_degraded = False
                gauge.set(0)
                log.info("heartbeat recovered")
                _emit_worker_event(
                    "worker.heartbeat_recovered", cfg.worker_id
                )
            return c
        except Exception as e:
            if not self._hb_degraded:
                self._hb_degraded = True
                gauge.set(1)
                log.warn(
                    "heartbeat degraded; retrying until departure",
                    error=f"{type(e).__name__}: {e}",
                )
                _emit_worker_event(
                    "worker.heartbeat_degraded", cfg.worker_id,
                    severity="warn", error=f"{type(e).__name__}: {e}",
                )
            try:
                if c is not None:
                    c.close()
            # edl: no-lint[silent-failure] discarding the broken beat connection; the degraded heartbeat was already emitted above
            except Exception:
                pass
            return None

    def _epochs(self, cfg, jax, MeshPlan, wl, tx) -> int:
        from edl_tpu.train.trainer import make_train_step

        cl = self.client
        init_failures = 0
        while True:
            if self._leaving:
                return self._depart(code=0)
            epoch, rank, world, addr, members = self._rendezvous()
            log.info(
                "epoch up", epoch=epoch, rank=rank, world=world, dist=addr
            )
            _emit_worker_event(
                "worker.join", self.cfg.worker_id,
                epoch=epoch, rank=rank, world=world,
            )
            try:
                _initialize_distributed(addr, world, rank)
                init_failures = 0
            except Exception as e:
                # a peer died between rendezvous and connect (its TTL
                # expiry will bump the epoch) — or the service host
                # itself died with membership unchanged. Retract the
                # endpoint we failed against (guarded: only if still
                # current) so the next rendezvous respawns a fresh host
                # instead of spinning on the corpse.
                log.warn("distributed init failed; regrouping", error=str(e))
                _shutdown_distributed()
                if cl.kv_get(self._k("dist", str(epoch))) == addr:
                    cl.kv_del(self._k("dist", str(epoch)))
                    cl.kv_put(self._dist_done_key(epoch, addr), "1")
                    # a live host deletes its own mark; sweep up after a
                    # dead one so failed inits don't leak KV forever
                    self._gc.defer_late(self._dist_done_key(epoch, addr))
                init_failures += 1
                if init_failures >= 5:
                    raise RuntimeError(
                        f"distributed init failed {init_failures}x; giving up"
                    ) from e
                continue
            # jax.distributed installs a C++ SIGTERM preemption notifier
            # that would swallow our graceful-drain handler — take it back
            signal.signal(signal.SIGTERM, self._on_sigterm)
            devs = jax.devices()
            plan = MeshPlan.parse(cfg.mesh, len(devs))
            slices = self._device_slices(cl, members, devs)
            mesh = plan.build(devs, slices=slices)
            if rank == 0:
                # observability: the CURRENT epoch's mesh device order
                # by slice (slice-major by construction when multi —
                # inner axes intact, or build would have raised).
                # Re-published every epoch so a reshard back to one
                # slice doesn't leave a defunct layout advertised.
                # Consumed by tests/monitor.
                if slices is not None:
                    sl_of = {id(d): s for d, s in zip(devs, slices)}
                    val = ",".join(
                        str(sl_of[id(d)]) for d in mesh.devices.flatten()
                    )
                else:
                    val = ""  # slice-blind epoch
                cl.kv_put(self._k("mesh_slices"), val)
            rows = cfg.per_device_batch * plan.batch_shards()
            if rows % world:
                raise ValueError(
                    f"batch rows {rows} (per_device_batch×batch_shards) do "
                    f"not divide across {world} processes — align tp/pp "
                    f"axes with chips per worker"
                )
            self._local_rows = rows // world
            try:
                state, pspecs = self._restore_state(
                    wl, tx, plan, mesh, cl=cl, epoch=epoch, rank=rank,
                    members=members,
                )
            except Exception as e:
                # a P2P source died between decision and fetch (or the
                # decision timed out). Peers who DID restore may already
                # be in the step loop with a world-size program that
                # includes us — quietly retrying would strand them in a
                # collective. Bump our incarnation: the epoch change
                # sends everyone back through reshard (their fresh
                # snapshots re-seed the next decision), and we regroup.
                restore_failures = getattr(self, "_restore_failures", 0) + 1
                self._restore_failures = restore_failures
                log.warn(
                    "state restore failed; regrouping",
                    error=str(e), failures=restore_failures,
                )
                _shutdown_distributed()
                _clear_backends()
                if restore_failures >= 3:
                    raise
                if cl.epoch() == epoch:
                    # membership hasn't moved on its own (e.g. a peer's
                    # server vanished without its TTL expiring yet):
                    # force the bump so nobody strands in a collective.
                    # The incarnation KV is the monotonic owner
                    # (entrypoint.bootstrap): write through it so a
                    # later process restart cannot reuse this value and
                    # silently fail to bump the epoch.
                    inc_key = self._k("incarnation", self.cfg.worker_id)
                    self._incarnation = (
                        max(self._incarnation, int(cl.kv_get(inc_key) or "0"))
                        + 1
                    )
                    cl.kv_put(inc_key, str(self._incarnation))
                    cl.register(self.cfg.worker_id, self._incarnation)
                continue
            self._restore_failures = 0
            # confirm the restore to any lingering leavers (they serve
            # P2P pieces until the new world is safely up). EVERY member
            # marks its own restore; rank 0 collects the marks before
            # advancing restored_step — publishing after only its own
            # restore would release the leavers while a slower peer is
            # still mid-fetch (connection reset, failed epoch).
            rmark = lambda n: self._k("restored", str(epoch), n)  # noqa: E731
            cl.kv_put(rmark(cfg.worker_id), "1")
            # the LATE lane, not defer(): this epoch's own GC drain runs
            # before rank 0 finishes collecting the marks (epoch_gc.py)
            self._gc.defer_late(rmark(cfg.worker_id))
            if rank == 0:
                deadline = time.monotonic() + cfg.rendezvous_timeout_s
                confirmed = False
                while time.monotonic() < deadline:
                    cl.expire()
                    alive = {m.name for m in cl.members()}
                    if all(
                        cl.kv_get(rmark(m.name)) or m.name not in alive
                        for m in members
                    ):
                        confirmed = True
                        break
                    if cl.epoch() != epoch:
                        break  # a peer died mid-restore: regrouping anyway
                    time.sleep(_POLL_S)
                if confirmed:
                    # leavers' linger is bounded by p2p_linger_s, so an
                    # unconfirmed epoch cannot strand them — but only a
                    # CONFIRMED restore may release them early
                    s = int(jax.device_get(state.step))
                    if s > int(cl.kv_get(self._k("restored_step")) or "-1"):
                        cl.kv_put(self._k("restored_step"), str(s))
            loss_fn = wl.loss_for(plan, mesh)
            # donate=False: after a failed collective (peer crash) the
            # pre-step buffers must still be alive to recover from.
            step = make_train_step(
                loss_fn, tx, plan, mesh, param_pspecs=pspecs, donate=False
            )
            stepper = None
            if cfg.sync_every > 1:
                from edl_tpu.train.trainer import LocalSyncStepper

                stepper = LocalSyncStepper(
                    loss_fn, tx, plan, mesh, donate=False
                )
                state = stepper.localize(state)

            # GC the epoch-scoped keys recorded at our own past
            # teardowns. Safe HERE (after _initialize_distributed):
            # every member has connected to this epoch's service, which
            # it only does after finishing the previous epoch's
            # teardown — nobody still reads those keys. EVERY worker
            # drains its own ledger (deletes are idempotent across
            # peers), so the keys go away even when rank 0 is a
            # freshly restarted process with no history. The two-lane
            # deferral semantics: runtime/epoch_gc.py.
            self._gc.drain(cl.kv_del)
            if rank == 0:
                self._ensure_queue(cl)
            outcome = self._train_epoch(
                cfg, jax, cl, epoch, rank, world, plan, mesh, state, step,
                wl.batch_fn, members, stepper=stepper,
            )
            self._teardown_epoch(cl, epoch, rank, members, addr)
            if outcome == "stop":
                return self._finish(rank)
            # outcome == "reshard": state already snapshotted
            self._resharded += 1
            # monotonic max-write: a late joiner's small private count
            # must not clobber the job-wide one
            if self._resharded > int(cl.kv_get(self._k("reshards")) or "0"):
                cl.kv_put(self._k("reshards"), str(self._resharded))
            _clear_backends()
            if self._leaving:
                return self._depart(code=0)

    def _device_slices(self, cl, members, devs):
        """Per-device slice ids for this epoch's global device list,
        from each member's published slice KV (runtime/worker_main.py
        run()). Returns None — mesh build falls back to the hardware's
        own ``device.slice_index`` — when this worker has no declared
        slice or any peer's is missing: a half-declared topology must
        not silently build a wrong slice-major order."""
        if self.cfg.slice_id < 0:
            return None
        by_rank = {}
        for m in members:
            v = cl.kv_get(self._k("slice", m.name))
            if v is None or int(v) < 0:
                log.warn(
                    "member without slice id; building slice-blind mesh",
                    member=m.name,
                )
                return None
            # member rank == jax.distributed process id (rendezvous
            # passes me.rank to _initialize_distributed)
            by_rank[m.rank] = int(v)
        if any(d.process_index not in by_rank for d in devs):
            return None
        return [by_rank[d.process_index] for d in devs]

    def _ensure_queue(self, cl) -> None:
        cfg = self.cfg
        if not cl.kv_get(self._k("queue_inited")):
            # one task = one process's per-step rows; constant across
            # rescales because the growth axis scales with world
            cl.queue_init(
                cfg.n_samples,
                self._local_rows,
                passes=cfg.passes,
                lease_timeout_s=cfg.lease_timeout_s,
            )
            cl.kv_put(self._k("queue_inited"), "1")

    def _chunk(self) -> int:
        return self._local_rows

    @staticmethod
    def _pad_to(batch: Dict[str, np.ndarray], n: int) -> Dict[str, np.ndarray]:
        """Wrap-pad every leaf's leading dim to exactly ``n`` samples.
        SPMD peers must contribute identical local shapes every step, so
        a ragged tail task (n_samples % chunk) is padded by repeating
        its own samples — coverage accounting stays exact via acks; the
        repeats only even out the tensor shape."""
        have = next(iter(batch.values())).shape[0]
        if have == n:
            return batch
        idx = np.resize(np.arange(have), n)
        return {k: v[idx] for k, v in batch.items()}

    def _local_batch(self, cl, batch_fn):
        """Lease one task; fall back to replaying the previous local
        batch when the queue has no task for us this step (tail rounds —
        coverage still exactly-once via acks; replay only pads the SPMD
        shape). Returns (local_np_batch, task_id_or_None).

        Every batch carries real-row weights ``_w`` (1 = leased row,
        0 = wrap-padding / replay / zero filler), consumed by the model
        losses (models/losses.py row_mean): filler rows keep the SPMD
        shapes aligned but contribute ZERO gradient, so the update at a
        ragged tail equals the sequential gradient over real rows."""
        chunk = self._chunk()
        task = cl.lease(self.cfg.worker_id)
        if task is not None:
            have = task.end - task.start
            local = self._pad_to(batch_fn(task.start, task.end), chunk)
            w = np.zeros(chunk, np.float32)
            w[:have] = 1.0
            local["_w"] = w
            self._last_local = local
            return local, task.task_id
        if self._last_local is not None:
            replay = dict(self._last_local)
            replay["_w"] = np.zeros(chunk, np.float32)
            return replay, None
        # first-ever step with no task: zero batch of chunk shape (probe
        # only what the dataset has — a file-backed source bounds-checks,
        # and the dataset may be smaller than one process's rows)
        probe = self._pad_to(
            batch_fn(0, min(chunk, self.cfg.n_samples)), chunk
        )
        zero = {k: np.zeros_like(v) for k, v in probe.items()}
        zero["_w"] = np.zeros(chunk, np.float32)
        return zero, None

    def _train_epoch(
        self, cfg, jax, cl, epoch, rank, world, plan, mesh, state, step,
        batch_fn, members, stepper=None,
    ):
        """Lockstep loop. Returns "stop" | "reshard" with
        self._ram_snapshot holding this process's shards of the last
        completed (or last committed, after a crash) step.

        With ``stepper`` (delayed-sync DP) the live state is grouped
        (leading dp axis); every peer syncs at the same K boundary
        (derived from the shared step counter), and commit points merge
        to the consensus average first — both are collectives, which is
        safe exactly where they run: on a healthy mesh under a rank-0
        verb. The crash path cannot merge (the mesh just failed), so it
        skips the RAM snapshot and rolls back to the last commit."""
        from edl_tpu.runtime import checkpoint as ckpt
        from edl_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.default_registry()
        h_step = reg.histogram(
            "edl_train_step_seconds",
            "full step wall time (data + dispatch + sync)",
        )
        h_data = reg.histogram(
            "edl_train_data_wait_seconds",
            "host wait for the next batch (data stall)",
        )
        h_block = reg.histogram(
            "edl_train_host_block_seconds",
            "host blocked on device results (sync stall)",
        )
        c_examples = reg.counter(
            "edl_train_examples_total", "training rows consumed"
        )
        g_loss = reg.gauge("edl_train_loss", "most recent training loss")
        eff = n_local = None
        if getattr(self, "_flops_per_example", None):
            from edl_tpu.obs import costmodel as _cm

            # per-CHIP roofline: this process's rows over its local
            # devices; the fleet view sums per-worker gauges
            eff = _cm.EfficiencyMeter(registry=reg)
            n_local = max(jax.local_device_count(), 1)

        go_key = self._k("go", str(epoch))
        sharding = plan.batch_sharding(mesh)
        first_loss_key = self._k("loss_first")
        while True:
            i = int(jax.device_get(state.step))
            # one DERIVED trace per lockstep decision: every process
            # independently opens trace ("step", job, epoch, i) — no
            # id exchange needed — so rank 0's publish span and each
            # follower's recv span land in one trace. The recv span
            # parents to the publish span through the go key's trace
            # side key, which is the cross-process client→server pair
            # the fleet merge links with a flow arrow.
            if rank == 0:
                step_tok = disttrace.enter_root("step", cfg.job, epoch, i)
                verb = self._decide(cl, epoch, i)
                with tracing.span("coord.go", step=i, verb=verb):
                    # ctx side key FIRST: a follower that can read the
                    # verb must already be able to fetch its context
                    disttrace.publish_ctx(cl.kv_put, go_key, tag=str(i))
                    cl.kv_put(go_key, f"{i}:{verb}")
            else:
                # the await poll runs OUTSIDE the trace root: polling
                # RPCs must not flood the span ring while rank 0 is
                # inside a long step
                verb = self._await_go(cl, go_key, i, members)
                step_tok = disttrace.enter_root("step", cfg.job, epoch, i)
                rctx = disttrace.fetch_ctx(cl.kv_get, go_key, tag=str(i))
                if rctx is not None:
                    tracing.tracer().record(
                        "coord.go.recv", time.perf_counter(), 0.0,
                        {"step": i, "verb": verb,
                         **disttrace.link_attrs(rctx)},
                    )
            try:
                verb = self._step_verb(
                    cfg, jax, cl, epoch, rank, world, members, state,
                    step, stepper, verb, i, go_key, first_loss_key,
                    sharding, batch_fn, h_step, h_data, h_block,
                    c_examples, g_loss, eff, n_local,
                )
            finally:
                disttrace.exit_root(step_tok)
            if isinstance(verb, tuple):  # (new state, keep looping)
                state = verb[0]
                continue
            return verb

    def _step_verb(
        self, cfg, jax, cl, epoch, rank, world, members, state, step,
        stepper, verb, i, go_key, first_loss_key, sharding, batch_fn,
        h_step, h_data, h_block, c_examples, g_loss, eff, n_local,
    ):
        """One published verb's work, inside the step's trace root.
        Returns ``(new_state,)`` to continue the lockstep loop or the
        epoch outcome string ("stop" | "reshard"). The whole verb runs
        under a ``train.step`` span so the fleet trace shows each
        worker's step duration beside the go decision that caused it
        (per-worker step skew is visible on one axis)."""
        from edl_tpu.runtime import checkpoint as ckpt

        with tracing.span(
            "train.step", step=i, verb=verb, worker=self.cfg.worker_id
        ):
            if verb in ("step", "ckpt"):
                t_iter = time.perf_counter()
                local, task_id = self._local_batch(cl, batch_fn)
                gbatch = jax.tree_util.tree_map(
                    lambda x: jax.make_array_from_process_local_data(
                        sharding, x
                    ),
                    local,
                )
                h_data.observe(time.perf_counter() - t_iter)
                try:
                    if stepper is not None:
                        new_state, metrics = stepper.step(state, gbatch)
                        if (i + 1) % cfg.sync_every == 0:
                            new_state = stepper.sync(new_state)
                    else:
                        new_state, metrics = step(state, gbatch)
                    t_sync = time.perf_counter()
                    loss = float(jax.device_get(metrics["loss"]))
                    h_block.observe(time.perf_counter() - t_sync)
                except Exception as e:
                    # peer died mid-collective: recover from last
                    # completed state (crash path; epoch will bump once
                    # the member TTL reaps the dead peer)
                    log.warn("step failed; recovering", step=i, error=str(e))
                    if task_id is not None:
                        cl.nack(task_id)
                    if stepper is None:
                        snap = ckpt.snapshot_local(state)
                        self._ram_snapshot = snap
                        self._crash_checkpoint(cl, snap, rank, world)
                    else:
                        # grouped state cannot move across a dp-width
                        # change and merging needs the (dead) mesh —
                        # keep the existing RAM snapshot untouched: it
                        # already holds the last MERGED commit
                        # (_coordinated_checkpoint), which is exactly
                        # the rollback point
                        log.warn(
                            "delayed-sync crash: rolling back to last commit"
                        )
                    self._await_peer_reaped(cl, epoch)
                    return "reshard"
                state = new_state
                c_examples.inc(self._local_rows)
                g_loss.set(loss)
                step_wall = time.perf_counter() - t_iter
                h_step.observe(step_wall)
                if eff is not None:
                    from edl_tpu.obs.costmodel import Cost

                    eff.observe(
                        "train",
                        Cost(
                            self._local_rows * self._flops_per_example
                            / n_local,
                            0.0,
                        ),
                        step_wall,
                    )
                if task_id is not None:
                    cl.ack(task_id)
                if cfg.step_sleep_s:
                    time.sleep(cfg.step_sleep_s)
                if rank == 0:
                    if not cl.kv_get(first_loss_key):
                        cl.kv_put(first_loss_key, repr(loss))
                    cl.kv_put(self._k("loss_last"), repr(loss))
                    cl.kv_put(self._k("progress"), str(i + 1))
                if verb == "ckpt":  # periodic commit of the NEW state,
                    # written behind the continuing step loop
                    self._coordinated_checkpoint(
                        cl, epoch,
                        stepper.merge(state) if stepper is not None else state,
                        rank, members, background=True,
                    )
            else:  # stop | reshard — commit the completed state
                self._coordinated_checkpoint(
                    cl, epoch,
                    stepper.merge(state) if stepper is not None else state,
                    rank, members,
                )
                return verb
        return (state,)

    def _await_peer_reaped(self, cl, failed_epoch: int) -> None:
        """A collective just failed, so some peer is dead but may not
        have TTL-expired yet. Re-rendezvousing before the coordinator
        reaps it would rebuild the world WITH the corpse — and a
        jax.distributed connect timeout is fatal. Wait for the epoch to
        move, then one extra TTL for any other silent deaths."""
        deadline = time.monotonic() + self.cfg.rendezvous_timeout_s
        while cl.epoch() == failed_epoch:
            cl.expire()
            if time.monotonic() > deadline:  # pragma: no cover
                raise TimeoutError("dead peer never reaped")
            time.sleep(0.1)
        time.sleep(self.cfg.member_ttl_s)
        cl.expire()

    def _decide(self, cl, epoch: int, i: int) -> str:
        cl.expire()
        if self._leaving or cl.epoch() != epoch:
            return "reshard"
        ms = cl.members()
        if any(cl.kv_get(self._k("leaving", m.name)) for m in ms):
            return "reshard"
        if cl.queue_done():
            return "stop"
        if (
            self.cfg.ckpt_every
            and self.cfg.ckpt_dir
            and (i + 1) % self.cfg.ckpt_every == 0
        ):
            return "ckpt"  # step, then commit the resulting state
        return "step"

    def _await_go(self, cl, go_key: str, i: int, members) -> str:
        """Wait for rank 0's decision for step ``i``. A published
        decision always wins (rank 0 may already be inside the step's
        collective). Only when there is NO decision yet AND rank 0 has
        left membership (crashed + TTL-reaped, or departed) can it
        never publish again — treat that as a reshard. Note: a mere
        epoch bump is NOT a bail-out signal; rank 0 may be alive and
        about to publish ``step``, and abandoning it then would strand
        it inside the collective."""
        deadline = time.monotonic() + self.cfg.rendezvous_timeout_s
        prefix = f"{i}:"
        rank0 = next(m.name for m in members if m.rank == 0)
        while True:
            v = cl.kv_get(go_key)
            if v and v.startswith(prefix):
                return v.split(":", 1)[1]
            cl.expire()
            if rank0 not in {m.name for m in cl.members()}:
                log.warn("rank-0 worker gone; resharding", step=i)
                return "reshard"
            if time.monotonic() > deadline:
                raise TimeoutError(f"no go decision for step {i}")
            time.sleep(_POLL_S)

    def _dist_done_key(self, epoch: int, addr: str) -> str:
        """Dismissal key scoped to one service instance's address, so
        dismissing a dead host cannot kill its respawn at the same
        epoch."""
        return self._k("dist_done", str(epoch), addr.rsplit(":", 1)[1])

    def _teardown_epoch(self, cl, epoch: int, rank: int, members, addr: str) -> None:
        """Ordered disconnect from this epoch's (external) coordination
        service. A live leader — the lowest-rank surviving member, since
        rank 0 itself may be the casualty — waits for every other live
        member's disconnect mark, disconnects last, and dismisses the
        service host via ``dist_done``. Dismissing it earlier would
        abort still-connected peers (their error pollers treat a dead
        service as fatal)."""
        me = self.cfg.worker_id
        disc = lambda name: self._k("disc", str(epoch), name)  # noqa: E731
        # retire this epoch's coordination keys at the NEXT rendezvous
        # (they must survive until every peer has left the epoch; the
        # dist_done mark must outlive the service host's dismissal poll)
        self._gc.defer(
            self._k("go", str(epoch)),
            self._k("dist", str(epoch)),
            *[disc(m.name) for m in members],
        )
        self._gc.defer_late(self._dist_done_key(epoch, addr))
        cl.expire()
        alive = {m.name for m in cl.members()}
        leader = min(
            (m.rank for m in members if m.name in alive), default=rank
        )
        if rank == leader:
            peers = [m.name for m in members if m.name != me]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                cl.expire()
                live = {m.name for m in cl.members()}
                if all(cl.kv_get(disc(p)) or p not in live for p in peers):
                    break
                time.sleep(_POLL_S)
            _shutdown_distributed()
            cl.kv_put(self._dist_done_key(epoch, addr), "1")
            return
        _shutdown_distributed()
        cl.kv_put(disc(me), "1")

    def _finish(self, rank: int) -> int:
        cl = self.client
        if rank == 0:
            cl.kv_put(self._k("phase"), "succeeded")
        log.info("job complete", worker=self.cfg.worker_id)
        _emit_worker_event(
            "worker.leave", self.cfg.worker_id, reason="complete"
        )
        cl.leave(self.cfg.worker_id)
        cl.release_worker(self.cfg.worker_id)
        return 0

    def _depart(self, code: int) -> int:
        cl = self.client
        log.info("departing (scale-down)", worker=self.cfg.worker_id)
        _emit_worker_event(
            "worker.leave", self.cfg.worker_id, reason="scale-down"
        )
        cl.release_worker(self.cfg.worker_id)
        cl.leave(self.cfg.worker_id)
        cl.kv_del(self._k("leaving", self.cfg.worker_id))
        self._p2p.linger(cl)
        return code


def main(argv=None) -> int:
    import argparse

    # provisional shield: a scale-down SIGTERM that lands before the
    # worker has joined the job (registration happens inside run()) is
    # a clean no-op departure — exit 0 without touching membership.
    # The drain handler replaces this below. The only remaining window
    # is interpreter startup itself (same exposure as a pod deleted
    # during container start in the reference).
    signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))

    # configuration comes from the EDL_* env contract injected by the
    # controller (api/parser.py pod_env); argv exists for --help only
    argparse.ArgumentParser(
        prog="edl-worker",
        description="elastic worker entrypoint; configured via the EDL_* "
        "environment contract (EDL_JOB_NAME, EDL_COORDINATOR, EDL_WORKER_ID, "
        "EDL_WORKERS_MIN/MAX, EDL_FAULT_TOLERANT, EDL_ENTRY, ...)",
    ).parse_args(argv)
    from edl_tpu.utils.logging import configure

    configure(os.environ.get("EDL_LOG_LEVEL", "info"))
    cfg = WorkerConfig.from_env()
    worker = ElasticWorker(cfg)
    # install BEFORE the heavy jax import: a scale-down SIGTERM can land
    # while the worker is still starting up
    signal.signal(signal.SIGTERM, worker._on_sigterm)
    try:
        return worker.run()
    except entrypoint.FailureGateError as e:
        log.error("failure gate", error=str(e))
        return 2


if __name__ == "__main__":
    sys.exit(main())
