"""Elastic multi-process worker program — real worker processes, zero restarts.

This is the process-level realization of the elastic protocol that
`runtime/elastic.py` implements in-process: each worker is a separate OS
process (one per TPU host in production; virtual-CPU JAX processes in
tests), peers are discovered through the job coordinator
(runtime/coordinator.py — the etcd/master analog, reference:
docker/paddle_k8s:14-32), and data comes from the coordinator's task
queue (reference: cloud_reader + master task queue,
example/fit_a_line/train_ft.py:105-114).

Lifecycle, per membership epoch ("incarnation" of the collective):

  1. rendezvous: wait until the coordinator's member list is stable,
     take the deterministic rank (reference: k8s_tools.py fetch_pod_id);
  2. the rank-0 member spawns the epoch's EXTERNAL coordination-service
     host (runtime/dist_service.py — outside the workers so leader death
     is survivable), which publishes the endpoint in coordinator KV;
     every worker connects as a pure client (world = live members);
  3. restore train state — from the in-RAM host snapshot if this worker
     survived the previous epoch, else from the job checkpoint
     (joiners), else fresh init (job start);
  4. lockstep training: every step the rank-0 worker publishes ONE
     decision — ``step`` / ``reshard`` / ``stop`` — in KV and all
     workers obey it. This is what keeps SPMD collectives aligned
     across membership change: a worker may only stop stepping after a
     published ``reshard``/``stop``, so nobody leaves a peer stranded
     inside an all-reduce. Data tasks are leased per step and acked
     after the optimizer update (lease timeout redelivers lost work —
     reference: -task-timout-dur=16s, docker/paddle_k8s:28-31).
  5. on ``reshard``: snapshot state to host RAM, write the job
     checkpoint (lowest-rank live worker), ``jax.distributed.shutdown``,
     clear XLA backends, and loop back to (1) — the process itself
     never restarts, which is the BASELINE north star ("zero job
     restarts", <30 s stall).

Scale-up: the controller just starts another worker process; its
registration bumps the membership epoch, rank 0 notices and publishes
``reshard``. Scale-down: the controller sends SIGTERM; the worker sets
a leaving flag but KEEPS stepping until rank 0 publishes ``reshard``
(graceful drain), then deregisters and exits 0. Crash: lease timeout +
member TTL expiry bump the epoch; survivors recover from the last
completed step (the train step does not donate its inputs, so state is
still live after a failed collective).

Env contract (EDL_*, reference: pkg/jobparser.go:263-311 PADDLE_INIT_*):
see ``WorkerConfig.from_env``.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import numpy as np

from edl_tpu.runtime.coordinator import CoordinatorClient
from edl_tpu.runtime import entrypoint
from edl_tpu.utils.logging import kv_logger

log = kv_logger("worker")

_POLL_S = 0.02


# --------------------------------------------------------------------------
# config


@dataclass
class WorkerConfig:
    job: str
    worker_id: str
    coord_host: str
    coord_port: int
    min_workers: int
    max_workers: int
    fault_tolerant: bool
    model: str = "linreg"
    # elastic mesh string (MeshPlan.parse): "dp" | "fsdp" | "fsdp,tp=2" …
    # — one growth axis absorbs membership change, fixed axes survive it
    mesh: str = "dp"
    local_devices: int = 0  # >0: force an n-device virtual CPU platform
    per_device_batch: int = 32
    n_samples: int = 4096
    passes: int = 1
    lease_timeout_s: float = 16.0
    member_ttl_s: float = 10.0
    ckpt_dir: str = ""
    # periodic sharded-checkpoint cadence in steps (0 = only at
    # reshard/stop). REQUIRED for crash recovery on state no single
    # process can snapshot (fsdp): a SIGKILL'd peer takes its primary
    # shards with it, so survivors roll back to the last commit.
    ckpt_every: int = 0
    # how long the commit leader waits for every member's shard write
    # before abandoning the manifest (size with shard bytes / storage
    # bandwidth: multi-GB FSDP shards on shared storage need minutes)
    ckpt_commit_timeout_s: float = 300.0
    seed: int = 0
    vocab: int = 4096  # ctr/llama hash/token space (small for tests)
    emb: int = 0  # ctr embedding dim override (0 = model default)
    seq_len: int = 64  # llama sequence length
    # on-disk dataset (runtime/shards.py manifest dir, usually a mounted
    # volume). When set, leased tasks read REAL rows from shard files
    # instead of synthesizing them, and n_samples comes from the
    # manifest (reference: pre-baked RecordIO shards,
    # example/fit_a_line/Dockerfile:1-8).
    data_dir: str = ""
    rendezvous_timeout_s: float = 120.0
    step_sleep_s: float = 0.0  # throttle (tests: keeps jobs scalable mid-run)
    # servable export root: the commit leader writes a params-only,
    # dtype-cast artifact at every checkpoint commit and at stop
    # (reference save_inference_model, example/ctr/ctr/train.py:169-180)
    export_dir: str = ""
    export_dtype: str = "bfloat16"
    # delayed-sync DP: K local steps per dp group between cross-group
    # averages (trainer.LocalSyncStepper; the --async_mode analog,
    # reference example/ctr/ctr/train.py:75-79). 1 = fully synchronous.
    # Requires a dp-only mesh. Crash semantics: grouped state cannot be
    # snapshotted across a membership change, so a SIGKILL'd peer rolls
    # the job back to the last committed checkpoint (cadence:
    # ckpt_every) — graceful reshards/stops merge first and lose nothing.
    sync_every: int = 1
    # peer-to-peer state redistribution (shard_server.py): workers serve
    # their host-RAM snapshots over TCP; a reshard restores owner-
    # changing shards worker-to-worker across the drain window instead
    # of round-tripping through shared storage, and departing workers
    # linger (bounded) until the new world confirms restore. The data
    # plane for a migration to a DISJOINT worker set.
    p2p: bool = True
    p2p_linger_s: float = 20.0
    # held-out eval split (runtime/shards.py dataset dir): the commit
    # leader evaluates every published export against it and publishes
    # eval_metric in KV — the AUC-in-the-train-loop analog (reference:
    # example/ctr/ctr/train.py:161-167). Requires export_dir and a
    # workload that defines eval_fn.
    eval_dir: str = ""
    # eval resource bounds (ADVICE r4): the held-out split is CAPPED
    # (not the whole dir into leader RAM), and EDL_EVAL_DEVICE=cpu
    # moves the forward passes off the accelerator so eval never
    # contends with the training step loop for HBM.
    eval_max_rows: int = 4096
    eval_device: str = ""
    # TPU slice this host belongs to (multi-slice topology). -1 =
    # unknown: the mesh build falls back to the hardware's own
    # ``device.slice_index`` (real multislice TPU exposes it). When set
    # (launcher/controller placement, or GKE's MEGASCALE_SLICE_ID), the
    # worker publishes it in coordinator KV so EVERY peer can order the
    # global device list slice-major at reshard — dp/pp cross slices
    # over DCN, fsdp/sp/ep/tp stay inside one slice's ICI
    # (parallel/mesh.py MeshPlan.build slices=...).
    slice_id: int = -1

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "WorkerConfig":
        e = dict(env if env is not None else os.environ)
        host, port = (e.get("EDL_COORDINATOR") or "127.0.0.1:7164").rsplit(":", 1)
        return cls(
            job=e.get("EDL_JOB_NAME", "job"),
            worker_id=e.get("EDL_WORKER_ID")
            or e.get("HOSTNAME")
            or f"w{os.getpid()}",
            coord_host=host,
            coord_port=int(port),
            min_workers=int(e.get("EDL_WORKERS_MIN", e.get("EDL_WORKERS", "1"))),
            max_workers=int(e.get("EDL_WORKERS_MAX", e.get("EDL_WORKERS", "1"))),
            fault_tolerant=e.get("EDL_FAULT_TOLERANT", "0") == "1",
            model=e.get("EDL_MODEL", "linreg"),
            mesh=e.get("EDL_MESH", "dp"),
            local_devices=int(e.get("EDL_LOCAL_DEVICES", "0")),
            per_device_batch=int(e.get("EDL_PER_DEVICE_BATCH", "32")),
            n_samples=int(e.get("EDL_NUM_SAMPLES", "4096")),
            passes=int(e.get("EDL_NUM_PASSES", "1")),
            lease_timeout_s=float(e.get("EDL_LEASE_TIMEOUT_S", "16")),
            member_ttl_s=float(e.get("EDL_MEMBER_TTL_S", "10")),
            ckpt_dir=e.get("EDL_CKPT_DIR", ""),
            ckpt_every=int(e.get("EDL_CKPT_EVERY", "0")),
            ckpt_commit_timeout_s=float(
                e.get("EDL_CKPT_COMMIT_TIMEOUT_S", "300")
            ),
            seed=int(e.get("EDL_SEED", "0")),
            vocab=int(e.get("EDL_VOCAB", "4096")),
            emb=int(e.get("EDL_EMB", "0")),
            seq_len=int(e.get("EDL_SEQ_LEN", "64")),
            data_dir=e.get("EDL_DATA_DIR", ""),
            rendezvous_timeout_s=float(e.get("EDL_RENDEZVOUS_TIMEOUT_S", "120")),
            step_sleep_s=float(e.get("EDL_STEP_SLEEP_S", "0")),
            sync_every=int(e.get("EDL_SYNC_EVERY", "1")),
            export_dir=e.get("EDL_EXPORT_DIR", ""),
            export_dtype=e.get("EDL_EXPORT_DTYPE", "bfloat16"),
            p2p=e.get("EDL_P2P", "1") != "0",
            p2p_linger_s=float(e.get("EDL_P2P_LINGER_S", "20")),
            eval_dir=e.get("EDL_EVAL_DIR", ""),
            eval_max_rows=int(e.get("EDL_EVAL_MAX_ROWS", "4096")),
            eval_device=e.get("EDL_EVAL_DEVICE", ""),
            # MEGASCALE_SLICE_ID is what GKE injects into multislice
            # TPU pods — honoring it makes the kube path slice-aware
            # with no manifest change
            slice_id=int(
                e.get("EDL_SLICE", e.get("MEGASCALE_SLICE_ID", "-1"))
            ),
        )


# --------------------------------------------------------------------------
# model registry — each entry builds a Workload: batch_fn(start, end)
# synthesizes the samples of index range [start, end) deterministically,
# so any worker can materialize any leased task (the RecordIO-shard
# analog); pspecs(plan) returns model-specific parameter PartitionSpecs
# (None = the generic fsdp rule of parallel/sharding.py).


@dataclass
class Workload:
    init_params: Callable[[], Any]
    loss_fn: Callable
    batch_fn: Callable[[int, int], Dict[str, np.ndarray]]
    pspecs: Optional[Callable[[Any], Any]] = None
    # mesh-aware loss factory (plan, mesh) -> loss_fn. Models whose
    # program depends on the mesh layout (llama's sp ring attention /
    # pp pipeline schedule) provide this; it is re-invoked after every
    # rendezvous so the compiled step matches the current elastic mesh.
    # When absent, the static loss_fn is used as-is.
    make_loss: Optional[Callable[[Any, Any], Callable]] = None
    # JSON-safe architecture record (e.g. LlamaConfig.to_meta()) that
    # rides export manifests so a serving consumer can rebuild the
    # model (CLI: `edl generate`)
    model_meta: Optional[Dict[str, Any]] = None
    # held-out evaluation ``f(params, rows) -> float`` run by the
    # commit leader on every published export (cfg.eval_dir)
    eval_fn: Optional[Callable[[Any, Dict[str, np.ndarray]], float]] = None

    def loss_for(self, plan, mesh) -> Callable:
        return self.make_loss(plan, mesh) if self.make_loss else self.loss_fn


def _linreg_workload(cfg: WorkerConfig) -> Workload:
    import jax

    from edl_tpu.models import linreg

    rng = np.random.RandomState(cfg.seed)
    w_true = rng.randn(linreg.N_FEATURES, 1).astype(np.float32)

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        x = r.randn(end - start, linreg.N_FEATURES).astype(np.float32)
        y = x @ w_true + 0.1 * r.randn(end - start, 1).astype(np.float32)
        return {"x": x, "y": y}

    def eval_rmse(params, rows):
        pred = np.asarray(linreg.predict(params, rows["x"]))
        return float(np.sqrt(np.mean((pred - rows["y"]) ** 2)))

    return Workload(
        lambda: linreg.init_params(jax.random.PRNGKey(cfg.seed)),
        linreg.loss_fn,
        batch_fn,
        eval_fn=eval_rmse,
    )


def _ctr_workload(cfg: WorkerConfig) -> Workload:
    import jax

    from edl_tpu.models import ctr

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        return ctr.synthetic_batch(r, end - start, vocab=cfg.vocab)

    def eval_auc(params, rows):
        import jax.numpy as jnp

        logits = ctr.forward(
            params, jnp.asarray(rows["dense"]), jnp.asarray(rows["sparse"])
        )
        # the reference's in-train-loop metric (example/ctr/ctr/
        # train.py:161-167): AUC over the held-out split
        return float(
            ctr.batch_auc(logits, jnp.asarray(rows["label"], jnp.float32))
        )

    emb_kw = {"emb": cfg.emb} if cfg.emb else {}
    return Workload(
        lambda: ctr.init_params(
            jax.random.PRNGKey(cfg.seed), vocab=cfg.vocab, **emb_kw
        ),
        ctr.make_loss_fn(),
        batch_fn,
        eval_fn=eval_auc,
        # architecture record so `edl predict` can score a CTR export
        # offline — THE reference serving artifact
        # (example/ctr/ctr/train.py:169-180). ctr.forward reads its
        # architecture from the params themselves; the record is the
        # family dispatch + provenance.
        model_meta={
            "family": "ctr",
            "vocab": cfg.vocab,
            "emb": cfg.emb or ctr.DEFAULT_EMBEDDING,
            "mlp_dims": list(ctr.MLP_DIMS),
        },
    )


_EVAL_CHUNK = 64  # rows per forward in held-out evals: LM heads emit
# [rows, T, vocab] f32 logits — one unchunked call over a real split
# would OOM the commit leader


def _lm_ppl_eval(logits_fn):
    """Chunked next-token perplexity over {tokens [N, T+1]} — shared by
    the llama/moe workloads (only the forward differs); CE accumulates
    per row slice so no [N, T, vocab] tensor ever materializes."""

    def eval_ppl(params, rows):
        import jax.numpy as jnp
        import optax

        toks = np.asarray(rows["tokens"])
        total, count = 0.0, 0
        for s in range(0, len(toks), _EVAL_CHUNK):
            t = jnp.asarray(toks[s : s + _EVAL_CHUNK])
            logits = logits_fn(params, t[:, :-1])
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, t[:, 1:]
            )
            total += float(jnp.sum(ce))
            count += ce.size
        return float(np.exp(total / max(count, 1)))

    return eval_ppl


def _llama_workload(cfg: WorkerConfig) -> Workload:
    """The flagship: Llama decoder under elastic FSDP(×TP) — BASELINE
    config #5 ("Llama-3-8B elastic FSDP across growing TPU slice") at
    the configured scale (tests: LlamaConfig.tiny)."""
    import jax

    from edl_tpu.models import llama

    mcfg = llama.LlamaConfig.tiny(vocab=cfg.vocab)

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        return llama.synthetic_tokens(r, end - start, cfg.seq_len, cfg.vocab)

    return Workload(
        lambda: llama.init_params(jax.random.PRNGKey(cfg.seed), mcfg),
        llama.make_loss_fn(mcfg),
        batch_fn,
        pspecs=lambda plan: llama.param_pspecs(mcfg, plan),
        # sp/pp are mesh-layout-dependent (ring attention shard_map /
        # GPipe schedule) — rebuild the loss per rendezvous
        make_loss=lambda plan, mesh: llama.make_loss_fn(mcfg, plan, mesh),
        model_meta=mcfg.to_meta(),
        eval_fn=_lm_ppl_eval(lambda p, t: llama.forward(p, t, mcfg)),
    )


def _bert_workload(cfg: WorkerConfig) -> Workload:
    """BERT-class MLM pretraining under elastic DP with checkpoint
    reshard (BASELINE config #4: "ERNIE / BERT-base pretraining")."""
    import jax

    from edl_tpu.models import bert

    mcfg = bert.BertConfig.tiny(vocab=cfg.vocab)

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        return bert.synthetic_mlm_batch(r, end - start, cfg.seq_len, cfg.vocab)

    def eval_mlm_acc(params, rows):
        import jax.numpy as jnp

        # masked-token top-1 accuracy, chunked (vocab-sized logits)
        correct = total = 0
        toks = np.asarray(rows["tokens"])
        for s in range(0, len(toks), _EVAL_CHUNK):
            sl = slice(s, s + _EVAL_CHUNK)
            logits = bert.forward(params, jnp.asarray(toks[sl]), mcfg)
            pred = np.asarray(jnp.argmax(logits, -1))
            mask = rows["mask"][sl] > 0
            correct += int((pred[mask] == rows["targets"][sl][mask]).sum())
            total += int(mask.sum())
        return correct / max(total, 1)

    return Workload(
        lambda: bert.init_params(jax.random.PRNGKey(cfg.seed), mcfg),
        bert.make_loss_fn(mcfg),
        batch_fn,
        pspecs=lambda plan: bert.param_pspecs(mcfg, plan),
        model_meta=mcfg.to_meta(),
        eval_fn=eval_mlm_acc,
    )


def _resnet_workload(cfg: WorkerConfig) -> Workload:
    """ResNet-class image classification under elastic all-reduce DP
    (BASELINE config #3: "ResNet-50 ImageNet, elastic all-reduce DP")."""
    import jax

    from edl_tpu.models import resnet

    mcfg = resnet.ResNetConfig.tiny()

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        return resnet.synthetic_batch(r, end - start)

    def eval_top1(params, rows):
        import jax.numpy as jnp

        logits = resnet.forward(params, jnp.asarray(rows["images"]), mcfg)
        pred = np.asarray(jnp.argmax(logits, -1))
        return float((pred == rows["label"]).mean())

    return Workload(
        lambda: resnet.init_params(jax.random.PRNGKey(cfg.seed), mcfg),
        resnet.make_loss_fn(mcfg),
        batch_fn,
        pspecs=lambda plan: resnet.param_pspecs(mcfg, plan),
        model_meta=mcfg.to_meta(),
        eval_fn=eval_top1,
    )


def _moe_workload(cfg: WorkerConfig) -> Workload:
    """Mixture-of-Experts decoder under elastic DPxEP (no reference
    analog — SURVEY §2.5 "Expert parallelism: NO"; mesh "ep=2,dp"
    pins the expert axis while dp absorbs membership change)."""
    import jax

    from edl_tpu.models import moe

    mcfg = moe.MoEConfig.tiny(vocab=cfg.vocab)

    def batch_fn(start: int, end: int) -> Dict[str, np.ndarray]:
        r = np.random.RandomState(cfg.seed * 1_000_003 + start + 1)
        return moe.synthetic_tokens(r, end - start, cfg.seq_len, cfg.vocab)

    return Workload(
        lambda: moe.init_params(jax.random.PRNGKey(cfg.seed), mcfg),
        moe.make_loss_fn(mcfg),
        batch_fn,
        pspecs=lambda plan: moe.param_pspecs(mcfg, plan),
        model_meta=mcfg.to_meta(),
        eval_fn=_lm_ppl_eval(lambda p, t: moe.forward(p, t, mcfg)[0]),
    )


WORKLOADS: Dict[str, Callable[[WorkerConfig], Workload]] = {
    "linreg": _linreg_workload,
    "ctr": _ctr_workload,
    "llama": _llama_workload,
    "bert": _bert_workload,
    "resnet": _resnet_workload,
    "moe": _moe_workload,
}


# --------------------------------------------------------------------------
# platform / jax.distributed plumbing


def _setup_platform(cfg: WorkerConfig) -> None:
    """Platform/env setup only — must NOT query devices: the XLA backend
    may only initialize after jax.distributed.initialize."""
    import jax

    if cfg.local_devices > 0:
        from edl_tpu.utils.platform import prepare_virtual_cpu

        prepare_virtual_cpu(cfg.local_devices)
        # cross-process CPU collectives need gloo
        jax.config.update("jax_cpu_collectives_implementation", "gloo")


def _initialize_distributed(
    addr: str, world: int, rank: int, timeout_s: int = 60
) -> None:
    """Client-only jax.distributed bring-up against an EXTERNAL
    coordination service (runtime/dist_service.py). Stock
    ``jax.distributed.initialize`` would make rank 0 host the service
    in-process, turning rank-0 death into an unrecoverable loss of the
    rendezvous plane. ``recoverable=True`` keeps a peer's death from
    being broadcast as a fatal job error to the survivors."""
    from jax._src import distributed as _dist
    from jax._src.lib import _jax

    state = _dist.global_state
    if state.client is not None:  # pragma: no cover - defensive
        raise RuntimeError("distributed state already initialized")
    state.client = _jax.get_distributed_runtime_client(
        addr,
        rank,
        init_timeout=timeout_s,
        heartbeat_timeout=10,
        shutdown_timeout=10,
        use_compression=True,
        recoverable=True,
    )
    state.client.connect()
    state.process_id = rank
    state.num_processes = world
    state.coordinator_address = addr


def _reset_distributed_state() -> None:
    """Drop jax.distributed's global state without a disconnect RPC, so
    a later initialize() starts clean (and jax's atexit shutdown
    becomes a no-op)."""
    from jax._src import distributed as _dist

    if _dist.global_state.client is not None:
        _dist.global_state.client = None
        _dist.global_state.service = None
        _dist.global_state.process_id = 0
        _dist.global_state.num_processes = 0


def _shutdown_distributed() -> None:
    """Tear down jax.distributed, tolerating a dead coordinator (the
    rank-0 peer may be the one that crashed)."""
    import jax

    done = threading.Event()

    def _go():
        try:
            jax.distributed.shutdown()
        except Exception as e:  # pragma: no cover - error-path logging
            log.warn("distributed shutdown error", error=str(e))
        finally:
            done.set()

    t = threading.Thread(target=_go, daemon=True)
    t.start()
    if not done.wait(timeout=15):  # pragma: no cover
        log.warn("distributed shutdown timed out; forcing state reset")
    _reset_distributed_state()


def _clear_backends() -> None:
    import jax

    jax.clear_caches()
    try:
        from jax._src import xla_bridge

        xla_bridge._clear_backends()
    except Exception:  # pragma: no cover - jax-version fallback
        import jax.extend.backend

        jax.extend.backend.clear_backends()


_VETO_TTL_EPOCHS = 4


def _veto_active(raw: Optional[str], epoch: int) -> bool:
    """Whether a per-step p2p veto KV value (the epoch it was written)
    is still in force. One key PER STEP, written blindly on failure:
    writes for different steps never race each other, so no veto can be
    lost to a read-modify-write interleaving (a single set-valued key
    would let a straggler's stale write resurrect a doomed step).
    Malformed values read as expired rather than wedging the decision."""
    if not raw:
        return False
    try:
        return epoch - int(raw) <= _VETO_TTL_EPOCHS
    except ValueError:
        return False


# --------------------------------------------------------------------------
# the worker


class ElasticWorker:
    def __init__(self, cfg: WorkerConfig):
        self.cfg = cfg
        self.client = CoordinatorClient(cfg.coord_host, cfg.coord_port, 30.0)
        self._leaving = False
        # last snapshot of THIS process's addressable shards (the RAM
        # half of the reshard protocol; disk holds the committed union)
        self._ram_snapshot = None  # checkpoint.LocalSnapshot
        self._pending_commit: Optional[threading.Thread] = None
        self._last_local: Optional[Dict[str, np.ndarray]] = None
        self._resharded = 0
        self._local_rows = 0  # batch rows this process feeds per step
        self._model_meta = None  # architecture record for exports
        # epoch-scoped KV (go/dist/disc keys) retired by past epochs,
        # GC'd one epoch later — keeps the coordinator KV (and its WAL
        # snapshots) O(live state), not O(job epochs). dist_done marks
        # go through _gc_later (an extra epoch of delay): the detached
        # service host polls them every 0.5 s and normally deletes its
        # own, so the worker only sweeps up after a crashed host — and
        # must not win a race against a live host's dismissal poll.
        self._gc_keys: list = []
        self._gc_later: list = []
        self._shard_server = None  # p2p shard service (run())
        self._p2p_token = None  # per-job shard-plane auth (run())
        self._incarnation = 0  # set at bootstrap; bumped to force regroup
        self._restore_failures = 0
        self._eval_fn = None  # workload eval hook (run(), cfg.eval_dir)
        self._eval_rows = None  # held-out split, loaded once (capped)
        self._eval_failures = 0  # consecutive eval failures (KV-surfaced)

    # -- keys ----------------------------------------------------------------
    def _k(self, *parts: str) -> str:
        return "/".join((self.cfg.job,) + parts)

    # -- SIGTERM: graceful drain --------------------------------------------
    def _on_sigterm(self, signum, frame):  # pragma: no cover - signal path
        self._leaving = True
        try:
            # separate connection: the main client may be mid-call
            c = CoordinatorClient(self.cfg.coord_host, self.cfg.coord_port, 5.0)
            c.kv_put(self._k("leaving", self.cfg.worker_id), "1")
            c.close()
        except Exception:
            pass

    # -- rendezvous ----------------------------------------------------------
    def _stable_members(self):
        """Wait until membership is stable (same epoch + members across
        two reads, no pending leavers among them), then return
        (epoch, members)."""
        cl = self.client
        deadline = time.monotonic() + self.cfg.rendezvous_timeout_s
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError("rendezvous: membership never stabilized")
            cl.expire()
            e1 = cl.epoch()
            ms = cl.members()
            names = [m.name for m in ms]
            if self.cfg.worker_id not in names or not names:
                time.sleep(_POLL_S)
                continue
            if any(cl.kv_get(self._k("leaving", n)) for n in names):
                time.sleep(_POLL_S)  # leaver still deregistering
                continue
            time.sleep(0.1)
            if cl.epoch() == e1 and [m.name for m in cl.members()] == names:
                return e1, ms

    def _spawn_dist_service(self, epoch: int, world: int) -> None:
        """Launch the external coordination-service host for this epoch
        (runtime/dist_service.py). Detached: it must outlive this worker
        so that rank-0 death cannot take the rendezvous plane with it."""
        import subprocess

        log_dir = os.environ.get("EDL_LOG_DIR", "")
        if log_dir:
            out = open(
                os.path.join(log_dir, f"dist_service_e{epoch}.log"), "ab"
            )
        else:
            out = subprocess.DEVNULL
        subprocess.Popen(
            [
                sys.executable,
                "-m",
                "edl_tpu.runtime.dist_service",
                "--job", self.cfg.job,
                "--epoch", str(epoch),
                "--world", str(world),
                "--coordinator",
                f"{self.cfg.coord_host}:{self.cfg.coord_port}",
            ],
            stdout=out,
            stderr=subprocess.STDOUT if log_dir else subprocess.DEVNULL,
            start_new_session=True,
        )
        if log_dir:
            out.close()  # child holds the fd

    def _rendezvous(self):
        """Agree on (epoch, rank, world, dist endpoint) with all live
        peers. The rank-0 member spawns the epoch's external service
        host, which publishes the endpoint; everyone polls for it.
        Restarts automatically if membership shifts underfoot."""
        cl = self.client
        while True:
            epoch, members = self._stable_members()
            me = next(m for m in members if m.name == self.cfg.worker_id)
            world = len(members)
            key = self._k("dist", str(epoch))
            if me.rank == 0 and cl.kv_get(key) is None:
                self._spawn_dist_service(epoch, world)
            addr = None
            deadline = time.monotonic() + self.cfg.rendezvous_timeout_s
            while addr is None:
                addr = cl.kv_get(key)
                if addr is None:
                    if cl.epoch() != epoch:
                        break  # membership moved: restart rendezvous
                    # (an orphan service host self-dismisses after its
                    # epoch goes stale — dist_service.py --orphan-grace)
                    if time.monotonic() > deadline:
                        raise TimeoutError("rendezvous: no dist endpoint")
                    time.sleep(_POLL_S)
            if addr is None:
                continue
            return epoch, me.rank, world, addr, members

    # -- state placement -----------------------------------------------------
    def _restore_state(self, wl, tx, plan, mesh, cl=None, epoch=0, rank=0,
                       members=()):
        """P2P peer pieces (rank-0-brokered decision; newest covered
        step) > committed sharded checkpoint (+RAM pieces when the step
        matches) > RAM-only (dp/single-process, no ckpt dir) > fresh
        sharded init. All processes restore the same step: the P2P
        decision key / the manifest is the agreed truth, so survivors
        whose RAM ran ahead of the last commit (fsdp crash) roll back
        with everyone else.

        Never materializes the full state on any host: restore builds
        only local shards (make_array_from_callback), fresh init runs
        jit-sharded (VERDICT r1 weak #2/#3).
        """
        import jax

        from edl_tpu.parallel import sharding as shd
        from edl_tpu.runtime import checkpoint as ckpt
        from edl_tpu.train.trainer import TrainState, state_pspecs

        pspecs = wl.pspecs(plan) if wl.pspecs is not None else None
        like = jax.eval_shape(lambda: TrainState.create(wl.init_params(), tx))
        state_sh = shd.named(state_pspecs(like, plan, pspecs), mesh)
        manifest = (
            ckpt.latest_manifest(self.cfg.ckpt_dir) if self.cfg.ckpt_dir else None
        )
        if self.cfg.p2p and cl is not None:
            state = self._p2p_restore(
                cl, epoch, rank, members, like, state_sh, manifest
            )
            if state is not None:
                return state, pspecs
        if manifest is not None:
            state = ckpt.load_sharded(
                self.cfg.ckpt_dir,
                like,
                state_sh,
                ram=self._ram_snapshot,
                manifest=manifest,
            )
            log.info("restored", step=int(manifest["step"]))
        elif (
            self._ram_snapshot is not None and self._ram_snapshot.is_complete()
        ):
            state = ckpt.restore_local(like, state_sh, self._ram_snapshot)
        else:
            # job start — or an fsdp crash before ANY commit existed
            # (nothing restorable: the dead peer's shards are gone and
            # no manifest was written); restart the job's math from
            # step 0 rather than killing every survivor
            if self._ram_snapshot is not None:
                log.warn(
                    "no committed checkpoint and local snapshot is "
                    "partial; reinitializing from step 0"
                )
            state = jax.jit(
                lambda: TrainState.create(wl.init_params(), tx),
                out_shardings=state_sh,
            )()
        return state, pspecs

    # -- P2P reshard data plane ----------------------------------------------

    def _merge_shardsrv_roster(self, cl, members) -> list:
        """Rank 0 unions the current members into the job's shard-server
        roster (single writer per epoch: no read-modify-write races).
        Departed workers stay listed while recent — exactly the window
        in which a migration needs to find their lingering servers —
        and age out of the 16-name cap."""
        import json as _json

        names = _json.loads(cl.kv_get(self._k("shardsrv_names")) or "[]")
        for m in members:
            if m.name in names:
                names.remove(m.name)  # refresh recency
            names.append(m.name)
        # cap covers every CURRENT member (they sit at the tail, so the
        # cap can never age out a live worker's only addr publication)
        cap = max(16, len(members))
        for dropped in names[:-cap]:  # GC aged-out workers' addr keys
            cl.kv_del(self._k("shardsrv", dropped))
        names = names[-cap:]
        cl.kv_put(self._k("shardsrv_names"), _json.dumps(names))
        return names

    def _probe_peers(self, cl):
        """{name: (addr, step, entries)} for every reachable shard
        server on the roster except our own. Probes run in parallel —
        dead entries cost one bounded connect timeout, not a serial
        scan."""
        import json as _json

        from edl_tpu.runtime.shard_server import fetch_index

        names = _json.loads(cl.kv_get(self._k("shardsrv_names")) or "[]")
        out: Dict[str, Any] = {}
        lock = threading.Lock()

        def probe(name, addr):
            got = fetch_index(addr, timeout_s=1.0, token=self._p2p_token)
            if got is not None and got[0] >= 0:
                with lock:
                    out[name] = (addr, got[0], got[1])

        threads = []
        for name in names:
            if name == self.cfg.worker_id:
                continue
            addr = cl.kv_get(self._k("shardsrv", name))
            if not addr:
                continue
            t = threading.Thread(target=probe, args=(name, addr), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(5.0)
        with lock:
            # a straggler thread (slow peer past the bounded join) must
            # not mutate the dict the caller is iterating
            return dict(out)

    def _p2p_restore(self, cl, epoch, rank, members, like, state_sh, manifest):
        """Restore from peers' RAM snapshots over the drain window
        (VERDICT r3 #5). Rank 0 probes the roster, picks the NEWEST
        step whose pieces (peers + its own RAM) tile the full state and
        is at least as new as the committed manifest, and publishes the
        decision; everyone assembles that step from own-RAM + manifest
        (same step) + lazily-fetched peer pieces. Returns None when the
        decision is to use disk/fresh (callers fall through)."""
        from edl_tpu.runtime import checkpoint as ckpt
        from edl_tpu.runtime.shard_server import RemotePieces

        # converge on the job token (a cold-start write race can leave
        # an early worker holding the losing value; KV is the truth)
        self._p2p_token = cl.kv_get(self._k("p2p_token")) or self._p2p_token
        dkey = self._k("restore", str(epoch))
        peers = None
        if rank == 0:
            self._merge_shardsrv_roster(cl, members)
            peers = self._probe_peers(cl)
            own = self._ram_snapshot
            m_step = int(manifest["step"]) if manifest is not None else -1
            cand = sorted(
                {s for (_, s, _) in peers.values()}
                | ({own.step} if own is not None else set()),
                reverse=True,
            )
            # a worker that failed ASSEMBLING a p2p step (peer advertised
            # pieces but fetches failed) vetoes that step for a few
            # epochs — otherwise a deterministic decision re-picks the
            # doomed step every regroup until the failure abort, even
            # though the manifest fallback was available (ADVICE r4).
            # One KV key per vetoed step (see _veto_active): vetoes for
            # different steps can neither ping-pong a shared slot nor
            # lose each other to concurrent read-modify-writes.
            decision = "none"
            for s in cand:
                if s < m_step:
                    break  # never restore older than the committed truth
                # NO GC delete of expired veto keys here: a read-then-
                # delete could race a straggler's fresh blind write and
                # erase an ACTIVE veto. The keys are a few bytes each
                # and only exist for steps whose restore actually
                # failed — boundedness comes from rarity, not reaping.
                if _veto_active(cl.kv_get(self._k("p2p_veto", str(s))), epoch):
                    continue
                entries = [
                    e
                    for (_, ps, es) in peers.values()
                    if ps == s
                    for e in es
                ]
                if own is not None and own.step == s:
                    entries += [
                        ckpt._piece_key(k, o, tuple(a.shape))
                        for k, plist in own.pieces.items()
                        for o, a in plist
                    ]
                if ckpt.peer_coverage_ok(like, entries):
                    decision = f"p2p:{s}"
                    break
            cl.kv_put(dkey, decision)
        else:
            deadline = time.monotonic() + self.cfg.rendezvous_timeout_s
            rank0 = next((m.name for m in members if m.rank == 0), None)
            decision = cl.kv_get(dkey)
            while decision is None:
                # bail fast instead of burning the whole rendezvous
                # timeout: a DEAD rank 0 can never publish (same rule
                # as _await_go), and an epoch bump means the group is
                # regrouping anyway — unlike a step verb, an unpublished
                # RESTORE decision cannot have a collective in flight,
                # so abandoning it strands nobody
                cl.expire()
                if rank0 not in {m.name for m in cl.members()}:
                    raise RuntimeError(
                        "rank-0 worker died before the restore decision"
                    )
                if cl.epoch() != epoch:
                    raise RuntimeError(
                        "membership moved before the restore decision"
                    )
                if time.monotonic() > deadline:
                    raise TimeoutError("no restore decision from rank 0")
                time.sleep(_POLL_S)
                decision = cl.kv_get(dkey)
        # GC one epoch LATE (_gc_later): rank 0 reaches the next GC
        # point while same-epoch peers may still be polling this key —
        # deleting it now would strand them for the full timeout
        self._gc_later.append(dkey)
        # observability (tests/monitor): how the LAST restore happened
        if rank == 0:
            cl.kv_put(self._k("restore_last"), decision)
        if not decision.startswith("p2p:"):
            return None
        step = int(decision[4:])
        if peers is None:
            peers = self._probe_peers(cl)
        remotes = [
            RemotePieces(addr, entries, token=self._p2p_token)
            for (addr, s, entries) in peers.values()
            if s == step
        ]
        try:
            state = ckpt.load_from_pieces(
                step, like, state_sh,
                ram=self._ram_snapshot,
                manifest=manifest,
                remotes=remotes,
            )
        except Exception:
            # veto this step so the regroup's next decision falls
            # through to the manifest instead of re-picking it (the
            # veto key is NOT epoch-scoped: it must outlive this epoch;
            # one key per step — a blind, raceless write)
            try:
                cl.kv_put(self._k("p2p_veto", str(step)), str(epoch))
            except Exception:
                pass
            raise
        finally:
            for r in remotes:
                r.close()
        log.info("restored via p2p", step=step, peers=len(remotes))
        return state

    def _eval_export(self, client, step: int) -> None:
        """Held-out evaluation on every published export (the leader,
        host-side, behind the step loop): reference parity for AUC
        fetched in the train loop (example/ctr/ctr/train.py:161-167).
        Needs cfg.eval_dir (a runtime/shards.py dataset) and a workload
        eval_fn; publishes ``eval_metric`` = "<step>:<value>" in KV for
        the monitor/CLI and logs it."""
        cfg = self.cfg
        if not cfg.eval_dir or self._eval_fn is None:
            return
        try:
            import contextlib

            from edl_tpu.runtime.export import load_export
            from edl_tpu.runtime.shards import FileShardSource

            if self._eval_rows is None:
                src = FileShardSource(cfg.eval_dir)
                # cap, don't slurp: the split lives in leader host RAM
                # for the job's lifetime (ADVICE r4)
                self._eval_rows = src.fetch_range(
                    0, min(src.n_samples, cfg.eval_max_rows)
                )
            params, _ = load_export(cfg.export_dir)
            ctx = contextlib.nullcontext()
            if cfg.eval_device == "cpu":
                # off the accelerator: eval forwards must not contend
                # with the training step loop for HBM
                import jax

                ctx = jax.default_device(jax.devices("cpu")[0])
            with ctx:
                metric = float(self._eval_fn(params, self._eval_rows))
            client.kv_put(self._k("eval_metric"), f"{step}:{metric:.6f}")
            log.info("eval", step=step, metric=round(metric, 6))
            self._eval_failures = 0
        except Exception as e:  # pragma: no cover - eval is best-effort
            # best-effort, but NOT silent: repeated failures (e.g. the
            # eval OOMing the leader every commit) surface in KV where
            # the monitor/CLI can see them, not just a local log line
            self._eval_failures += 1
            try:
                client.kv_put(
                    self._k("eval_failures"), str(self._eval_failures)
                )
            except Exception:
                pass
            log.warn("export eval failed", error=str(e))

    def _join_pending_commit(self) -> None:
        """At most ONE background commit is in flight; the next commit,
        a crash rescue, or an epoch teardown serializes behind it."""
        t = self._pending_commit
        if t is None:
            return
        t.join(self.cfg.ckpt_commit_timeout_s + 30)
        if t.is_alive():  # pragma: no cover - hung storage
            log.error("background checkpoint commit did not finish in time")
        self._pending_commit = None

    def _coordinated_checkpoint(
        self, cl, epoch, state, rank, members, background=False
    ):
        """Commit the state as a sharded checkpoint: every member writes
        its primary shards, the leader (lowest live rank) awaits all
        marks and commits manifest.json last. A member dying mid-write
        aborts the commit (its primary shards are unrecoverable), and
        the previous committed step remains the restore point.

        ``background=True`` (the periodic "ckpt" verb): the host-RAM
        snapshot is taken synchronously — the device state mutates next
        step — but the disk write, mark posting, and the leader's
        mark-wait + manifest commit run on a writer thread with its own
        coordinator connection, so multi-GB shard writes overlap
        training instead of stalling it. Stop/reshard commits stay
        synchronous: teardown must not outrun the manifest."""
        from edl_tpu.runtime import checkpoint as ckpt

        cfg = self.cfg
        self._join_pending_commit()
        snap = ckpt.snapshot_local(state)
        self._ram_snapshot = snap
        if not cfg.ckpt_dir:
            return
        # A reshard/stop at the same step a background "ckpt" commit
        # just finished would re-commit an identical state — and the
        # finished commit's mark-cleanup can race the re-commit's fresh
        # marks (same (epoch, step, worker) keys), stranding the leader
        # in its mark wait. The leader's view of ckpt_step is
        # authoritative here: it joined the very thread that wrote it.
        if int(cl.kv_get(self._k("ckpt_step")) or "-1") >= snap.step:
            return
        world = len(members)

        def _write(client, own_client: bool) -> None:
            try:
                alive = {m.name for m in client.members()}
                leader = min(
                    (m.rank for m in members if m.name in alive), default=rank
                )
                own = os.path.join(
                    ckpt.step_dir(cfg.ckpt_dir, snap.step),
                    ckpt.shard_filename(rank, world),
                )
                if rank != leader and os.path.exists(own):
                    # a background commit of this exact step already
                    # wrote this rank's shards (atomic rename => the
                    # file is complete) but its manifest aborted; a
                    # non-leader's stale read of ckpt_step cannot see
                    # that — reuse the file, only re-post the mark
                    fname = os.path.basename(own)
                else:
                    fname = ckpt.save_shards(
                        cfg.ckpt_dir, snap, rank, world,
                        host_leaves=(rank == leader),
                    )
                mark = lambda n: self._k(  # noqa: E731
                    "ckmark", str(epoch), str(snap.step), n
                )
                client.kv_put(mark(cfg.worker_id), fname)
                if rank != leader:
                    # leak guard (ADVICE r2): the leader skips a commit
                    # when ITS ckpt_step read shows the step already
                    # committed — and since the skip is decided on this
                    # same shared KV, one fresh read here sees it too.
                    # In that case nobody will collect this mark:
                    # reclaim it now. The healthy path (leader waiting
                    # on marks) stays fire-and-forget.
                    if (
                        int(client.kv_get(self._k("ckpt_step")) or "-1")
                        >= snap.step
                    ):
                        client.kv_del(mark(cfg.worker_id))
                    return
                # scale the commit deadline with shard size is the
                # caller's job (EDL_CKPT_COMMIT_TIMEOUT_S); the default
                # must accommodate multi-GB writes to shared storage
                deadline = time.monotonic() + cfg.ckpt_commit_timeout_s
                files = None
                while time.monotonic() < deadline:
                    client.expire()
                    alive = {m.name for m in client.members()}
                    got, waiting, dead_unwritten = [], [], []
                    for m in members:
                        v = client.kv_get(mark(m.name))
                        if v:
                            got.append(v)
                        elif m.name in alive:
                            waiting.append(m.name)
                        else:
                            dead_unwritten.append(m.name)
                    if not waiting:
                        files = got if not dead_unwritten else None
                        break
                    time.sleep(_POLL_S)
                for m in members:  # marks served their purpose either way
                    client.kv_del(mark(m.name))
                if files:
                    ckpt.write_manifest(
                        cfg.ckpt_dir, snap, files, {"job": cfg.job}
                    )
                    # monotonic max-write: a commit thread that stalled
                    # past its join timeout must not regress the
                    # pointer a LATER commit already advanced
                    cur = int(client.kv_get(self._k("ckpt_step")) or "-1")
                    if snap.step > cur:
                        client.kv_put(self._k("ckpt_step"), str(snap.step))
                    ckpt.gc_step_dirs(cfg.ckpt_dir, keep=2)
                    if cfg.export_dir:
                        # servable params-only artifact on every commit
                        # (the save_inference_model cadence, reference
                        # example/ctr/ctr/train.py:169-180) — assembled
                        # from the shards just committed, so it works
                        # for fsdp states no single process holds
                        try:
                            from edl_tpu.runtime import export as exp

                            d = exp.export_from_checkpoint(
                                cfg.ckpt_dir,
                                cfg.export_dir,
                                dtype=cfg.export_dtype,
                                ram=snap,  # skip re-reading own shards
                                model_meta=self._model_meta,
                            )
                            if d:
                                log.info(
                                    "export published",
                                    dir=d,
                                    step=snap.step,
                                )
                                self._eval_export(client, snap.step)
                        except Exception as e:  # pragma: no cover
                            log.error("export failed", error=str(e))
                else:  # pragma: no cover - crash-timing path
                    # surfaced as a counter so monitors can alarm on
                    # repeated aborts (a job silently training without
                    # restore points)
                    aborts = int(
                        client.kv_get(self._k("ckpt_aborts")) or "0"
                    ) + 1
                    client.kv_put(self._k("ckpt_aborts"), str(aborts))
                    log.error(
                        "checkpoint commit aborted "
                        "(peer died or write timed out)",
                        step=snap.step,
                        aborts=aborts,
                    )
            except Exception as e:  # pragma: no cover - storage faults
                log.error("checkpoint commit failed", error=str(e))
                try:
                    aborts = int(
                        client.kv_get(self._k("ckpt_aborts")) or "0"
                    ) + 1
                    client.kv_put(self._k("ckpt_aborts"), str(aborts))
                except Exception:
                    pass
                if not own_client:
                    # synchronous (stop/reshard) commits must not be
                    # silently lost: the job would report success with
                    # a stale restore point
                    raise
            finally:
                if own_client:
                    try:
                        client.close()
                    except Exception:
                        pass

        if not background:
            _write(cl, own_client=False)
            return

        def _bg():
            try:
                client = CoordinatorClient(
                    cfg.coord_host, cfg.coord_port, 10.0
                )
            except Exception as e:  # pragma: no cover - coord hiccup
                log.error(
                    "background commit could not reach coordinator",
                    error=str(e),
                )
                return
            _write(client, own_client=True)

        t = threading.Thread(
            target=_bg, name="edl-ckpt-commit", daemon=True
        )
        t.start()
        self._pending_commit = t

    def _crash_checkpoint(self, cl, snap, rank, world) -> None:
        """After a failed collective any survivor may be the only one
        left. A survivor holding the COMPLETE state (dp-replicated)
        persists it solo if newer than the last commit (atomic manifest
        rename; content identical among lockstep peers, so racing
        writers are harmless). FSDP survivors cannot — the dead peer's
        primary shards died with it — so the job rolls back to the last
        committed step (cadence: cfg.ckpt_every)."""
        from edl_tpu.runtime import checkpoint as ckpt

        if not self.cfg.ckpt_dir:
            return
        self._join_pending_commit()  # serialize behind an in-flight commit
        known = int(cl.kv_get(self._k("ckpt_step")) or "-1")
        if snap.step <= known or not snap.is_complete():
            return
        fname = ckpt.save_shards(
            self.cfg.ckpt_dir, snap, rank, world,
            host_leaves=True, all_pieces=True,
        )
        ckpt.write_manifest(self.cfg.ckpt_dir, snap, [fname], {"job": self.cfg.job})
        cl.kv_put(self._k("ckpt_step"), str(snap.step))

    # -- the run -------------------------------------------------------------
    def run(self) -> int:
        cfg = self.cfg
        _setup_platform(cfg)
        import jax

        import optax

        from edl_tpu.parallel.mesh import MeshPlan

        wl = WORKLOADS[cfg.model](cfg)
        self._model_meta = wl.model_meta
        self._eval_fn = wl.eval_fn
        if cfg.eval_dir and self._eval_fn is None:
            # surface the misconfiguration once: otherwise EDL_EVAL_DIR
            # on a workload without an eval hook is a silent no-op
            log.warn(
                "EDL_EVAL_DIR set but workload defines no eval_fn; "
                "no eval_metric will be published",
                model=cfg.model,
            )
        if cfg.data_dir:
            # real on-disk data: leased [start, end) ranges read shard
            # files instead of the workload's synthetic generator
            from edl_tpu.runtime.shards import FileShardSource

            source = FileShardSource(cfg.data_dir)
            wl = dataclasses.replace(wl, batch_fn=source.fetch_range)
            cfg.n_samples = source.n_samples
            log.info(
                "dataset attached", dir=cfg.data_dir, n_samples=cfg.n_samples
            )
        tx = optax.adam(1e-2 if cfg.model == "linreg" else 1e-3)

        if self._leaving:  # SIGTERM during startup: never joined
            return 0
        if cfg.slice_id >= 0:
            # published BEFORE registration so any peer that sees us in
            # membership can already read our slice id at rendezvous
            self.client.kv_put(
                self._k("slice", cfg.worker_id), str(cfg.slice_id)
            )
        if cfg.p2p:
            # serve our host-RAM snapshot to peers (P2P reshard data
            # plane); published before registration like the slice id.
            # EDL_HOST_ADDR is the reachable address of this host
            # (pod IP in production; loopback for local jobs).
            from edl_tpu.runtime.shard_server import ShardServer

            # per-job token gates the weight plane (ADVICE r4): first
            # worker to look writes one; everyone converges on the KV
            # value (re-read after write — last write wins for all)
            tok = self.client.kv_get(self._k("p2p_token"))
            if not tok:
                import secrets

                self.client.kv_put(
                    self._k("p2p_token"), secrets.token_hex(16)
                )
                tok = self.client.kv_get(self._k("p2p_token"))
            self._p2p_token = tok
            self._shard_server = ShardServer(
                lambda: self._ram_snapshot,
                check_token=lambda t: bool(t) and t == self._p2p_token,
            )
            self.client.kv_put(
                self._k("shardsrv", cfg.worker_id),
                f"{os.environ.get('EDL_HOST_ADDR', '127.0.0.1')}:"
                f"{self._shard_server.port}",
            )
        ctx = entrypoint.bootstrap(self.client)
        self._incarnation = ctx.incarnation
        heartbeat_stop = self._start_heartbeat(ctx.incarnation)
        try:
            return self._epochs(cfg, jax, MeshPlan, wl, tx)
        except Exception as e:
            entrypoint.record_failure(self.client, cfg.job, f"exception: {e}")
            raise
        finally:
            heartbeat_stop.set()

    def _start_heartbeat(self, incarnation: int) -> threading.Event:
        """TTL keep-alive on its own connection (steps may outlast the
        member TTL). Survives transient coordinator hiccups by
        reconnecting, and re-registers if a missed TTL already evicted
        us — the re-registration bumps the epoch, which correctly shows
        up to the group as a membership change."""
        stop = threading.Event()
        cfg = self.cfg
        interval = min(0.5, max(0.1, cfg.member_ttl_s / 4))

        def _beat():  # pragma: no cover - timing-dependent
            c = None
            while not stop.wait(interval):
                try:
                    if c is None:
                        c = CoordinatorClient(cfg.coord_host, cfg.coord_port, 5.0)
                    if not c.heartbeat(cfg.worker_id) and not self._leaving:
                        log.warn("TTL-evicted while alive; re-registering")
                        c.register(cfg.worker_id, incarnation)
                except Exception:
                    try:
                        if c is not None:
                            c.close()
                    except Exception:
                        pass
                    c = None
            if c is not None:
                try:
                    c.close()
                except Exception:
                    pass

        threading.Thread(target=_beat, daemon=True).start()
        return stop

    def _epochs(self, cfg, jax, MeshPlan, wl, tx) -> int:
        from edl_tpu.train.trainer import make_train_step

        cl = self.client
        init_failures = 0
        while True:
            if self._leaving:
                return self._depart(code=0)
            epoch, rank, world, addr, members = self._rendezvous()
            log.info(
                "epoch up", epoch=epoch, rank=rank, world=world, dist=addr
            )
            try:
                _initialize_distributed(addr, world, rank)
                init_failures = 0
            except Exception as e:
                # a peer died between rendezvous and connect (its TTL
                # expiry will bump the epoch) — or the service host
                # itself died with membership unchanged. Retract the
                # endpoint we failed against (guarded: only if still
                # current) so the next rendezvous respawns a fresh host
                # instead of spinning on the corpse.
                log.warn("distributed init failed; regrouping", error=str(e))
                _shutdown_distributed()
                if cl.kv_get(self._k("dist", str(epoch))) == addr:
                    cl.kv_del(self._k("dist", str(epoch)))
                    cl.kv_put(self._dist_done_key(epoch, addr), "1")
                    # a live host deletes its own mark; sweep up after a
                    # dead one so failed inits don't leak KV forever
                    self._gc_later.append(self._dist_done_key(epoch, addr))
                init_failures += 1
                if init_failures >= 5:
                    raise RuntimeError(
                        f"distributed init failed {init_failures}x; giving up"
                    ) from e
                continue
            # jax.distributed installs a C++ SIGTERM preemption notifier
            # that would swallow our graceful-drain handler — take it back
            signal.signal(signal.SIGTERM, self._on_sigterm)
            devs = jax.devices()
            plan = MeshPlan.parse(cfg.mesh, len(devs))
            slices = self._device_slices(cl, members, devs)
            mesh = plan.build(devs, slices=slices)
            if rank == 0:
                # observability: the CURRENT epoch's mesh device order
                # by slice (slice-major by construction when multi —
                # inner axes intact, or build would have raised).
                # Re-published every epoch so a reshard back to one
                # slice doesn't leave a defunct layout advertised.
                # Consumed by tests/monitor.
                if slices is not None:
                    sl_of = {id(d): s for d, s in zip(devs, slices)}
                    val = ",".join(
                        str(sl_of[id(d)]) for d in mesh.devices.flatten()
                    )
                else:
                    val = ""  # slice-blind epoch
                cl.kv_put(self._k("mesh_slices"), val)
            rows = cfg.per_device_batch * plan.batch_shards()
            if rows % world:
                raise ValueError(
                    f"batch rows {rows} (per_device_batch×batch_shards) do "
                    f"not divide across {world} processes — align tp/pp "
                    f"axes with chips per worker"
                )
            self._local_rows = rows // world
            try:
                state, pspecs = self._restore_state(
                    wl, tx, plan, mesh, cl=cl, epoch=epoch, rank=rank,
                    members=members,
                )
            except Exception as e:
                # a P2P source died between decision and fetch (or the
                # decision timed out). Peers who DID restore may already
                # be in the step loop with a world-size program that
                # includes us — quietly retrying would strand them in a
                # collective. Bump our incarnation: the epoch change
                # sends everyone back through reshard (their fresh
                # snapshots re-seed the next decision), and we regroup.
                restore_failures = getattr(self, "_restore_failures", 0) + 1
                self._restore_failures = restore_failures
                log.warn(
                    "state restore failed; regrouping",
                    error=str(e), failures=restore_failures,
                )
                _shutdown_distributed()
                _clear_backends()
                if restore_failures >= 3:
                    raise
                if cl.epoch() == epoch:
                    # membership hasn't moved on its own (e.g. a peer's
                    # server vanished without its TTL expiring yet):
                    # force the bump so nobody strands in a collective.
                    # The incarnation KV is the monotonic owner
                    # (entrypoint.bootstrap): write through it so a
                    # later process restart cannot reuse this value and
                    # silently fail to bump the epoch.
                    inc_key = self._k("incarnation", self.cfg.worker_id)
                    self._incarnation = (
                        max(self._incarnation, int(cl.kv_get(inc_key) or "0"))
                        + 1
                    )
                    cl.kv_put(inc_key, str(self._incarnation))
                    cl.register(self.cfg.worker_id, self._incarnation)
                continue
            self._restore_failures = 0
            # confirm the restore to any lingering leavers (they serve
            # P2P pieces until the new world is safely up). EVERY member
            # marks its own restore; rank 0 collects the marks before
            # advancing restored_step — publishing after only its own
            # restore would release the leavers while a slower peer is
            # still mid-fetch (connection reset, failed epoch).
            rmark = lambda n: self._k("restored", str(epoch), n)  # noqa: E731
            cl.kv_put(rmark(cfg.worker_id), "1")
            # _gc_later, NOT _gc_keys: this epoch's own GC drain runs
            # before rank 0 finishes collecting the marks
            self._gc_later.append(rmark(cfg.worker_id))
            if rank == 0:
                deadline = time.monotonic() + cfg.rendezvous_timeout_s
                confirmed = False
                while time.monotonic() < deadline:
                    cl.expire()
                    alive = {m.name for m in cl.members()}
                    if all(
                        cl.kv_get(rmark(m.name)) or m.name not in alive
                        for m in members
                    ):
                        confirmed = True
                        break
                    if cl.epoch() != epoch:
                        break  # a peer died mid-restore: regrouping anyway
                    time.sleep(_POLL_S)
                if confirmed:
                    # leavers' linger is bounded by p2p_linger_s, so an
                    # unconfirmed epoch cannot strand them — but only a
                    # CONFIRMED restore may release them early
                    s = int(jax.device_get(state.step))
                    if s > int(cl.kv_get(self._k("restored_step")) or "-1"):
                        cl.kv_put(self._k("restored_step"), str(s))
            loss_fn = wl.loss_for(plan, mesh)
            # donate=False: after a failed collective (peer crash) the
            # pre-step buffers must still be alive to recover from.
            step = make_train_step(
                loss_fn, tx, plan, mesh, param_pspecs=pspecs, donate=False
            )
            stepper = None
            if cfg.sync_every > 1:
                from edl_tpu.train.trainer import LocalSyncStepper

                stepper = LocalSyncStepper(
                    loss_fn, tx, plan, mesh, donate=False
                )
                state = stepper.localize(state)

            # GC the epoch-scoped keys recorded at our own past
            # teardowns. Safe HERE (after _initialize_distributed):
            # every member has connected to this epoch's service, which
            # it only does after finishing the previous epoch's
            # teardown — nobody still reads those keys. EVERY worker
            # drains its own list (deletes are idempotent across
            # peers), so the keys go away even when rank 0 is a
            # freshly restarted process with no history.
            for k in self._gc_keys:
                cl.kv_del(k)
            self._gc_keys = self._gc_later
            self._gc_later = []
            if rank == 0:
                self._ensure_queue(cl)
            outcome = self._train_epoch(
                cfg, jax, cl, epoch, rank, world, plan, mesh, state, step,
                wl.batch_fn, members, stepper=stepper,
            )
            self._teardown_epoch(cl, epoch, rank, members, addr)
            if outcome == "stop":
                return self._finish(rank)
            # outcome == "reshard": state already snapshotted
            self._resharded += 1
            # monotonic max-write: a late joiner's small private count
            # must not clobber the job-wide one
            if self._resharded > int(cl.kv_get(self._k("reshards")) or "0"):
                cl.kv_put(self._k("reshards"), str(self._resharded))
            _clear_backends()
            if self._leaving:
                return self._depart(code=0)

    def _device_slices(self, cl, members, devs):
        """Per-device slice ids for this epoch's global device list,
        from each member's published slice KV (runtime/worker_main.py
        run()). Returns None — mesh build falls back to the hardware's
        own ``device.slice_index`` — when this worker has no declared
        slice or any peer's is missing: a half-declared topology must
        not silently build a wrong slice-major order."""
        if self.cfg.slice_id < 0:
            return None
        by_rank = {}
        for m in members:
            v = cl.kv_get(self._k("slice", m.name))
            if v is None or int(v) < 0:
                log.warn(
                    "member without slice id; building slice-blind mesh",
                    member=m.name,
                )
                return None
            # member rank == jax.distributed process id (rendezvous
            # passes me.rank to _initialize_distributed)
            by_rank[m.rank] = int(v)
        if any(d.process_index not in by_rank for d in devs):
            return None
        return [by_rank[d.process_index] for d in devs]

    def _ensure_queue(self, cl) -> None:
        cfg = self.cfg
        if not cl.kv_get(self._k("queue_inited")):
            # one task = one process's per-step rows; constant across
            # rescales because the growth axis scales with world
            cl.queue_init(
                cfg.n_samples,
                self._local_rows,
                passes=cfg.passes,
                lease_timeout_s=cfg.lease_timeout_s,
            )
            cl.kv_put(self._k("queue_inited"), "1")

    def _chunk(self) -> int:
        return self._local_rows

    @staticmethod
    def _pad_to(batch: Dict[str, np.ndarray], n: int) -> Dict[str, np.ndarray]:
        """Wrap-pad every leaf's leading dim to exactly ``n`` samples.
        SPMD peers must contribute identical local shapes every step, so
        a ragged tail task (n_samples % chunk) is padded by repeating
        its own samples — coverage accounting stays exact via acks; the
        repeats only even out the tensor shape."""
        have = next(iter(batch.values())).shape[0]
        if have == n:
            return batch
        idx = np.resize(np.arange(have), n)
        return {k: v[idx] for k, v in batch.items()}

    def _local_batch(self, cl, batch_fn):
        """Lease one task; fall back to replaying the previous local
        batch when the queue has no task for us this step (tail rounds —
        coverage still exactly-once via acks; replay only pads the SPMD
        shape). Returns (local_np_batch, task_id_or_None).

        Every batch carries real-row weights ``_w`` (1 = leased row,
        0 = wrap-padding / replay / zero filler), consumed by the model
        losses (models/losses.py row_mean): filler rows keep the SPMD
        shapes aligned but contribute ZERO gradient, so the update at a
        ragged tail equals the sequential gradient over real rows."""
        chunk = self._chunk()
        task = cl.lease(self.cfg.worker_id)
        if task is not None:
            have = task.end - task.start
            local = self._pad_to(batch_fn(task.start, task.end), chunk)
            w = np.zeros(chunk, np.float32)
            w[:have] = 1.0
            local["_w"] = w
            self._last_local = local
            return local, task.task_id
        if self._last_local is not None:
            replay = dict(self._last_local)
            replay["_w"] = np.zeros(chunk, np.float32)
            return replay, None
        # first-ever step with no task: zero batch of chunk shape (probe
        # only what the dataset has — a file-backed source bounds-checks,
        # and the dataset may be smaller than one process's rows)
        probe = self._pad_to(
            batch_fn(0, min(chunk, self.cfg.n_samples)), chunk
        )
        zero = {k: np.zeros_like(v) for k, v in probe.items()}
        zero["_w"] = np.zeros(chunk, np.float32)
        return zero, None

    def _train_epoch(
        self, cfg, jax, cl, epoch, rank, world, plan, mesh, state, step,
        batch_fn, members, stepper=None,
    ):
        """Lockstep loop. Returns "stop" | "reshard" with
        self._ram_snapshot holding this process's shards of the last
        completed (or last committed, after a crash) step.

        With ``stepper`` (delayed-sync DP) the live state is grouped
        (leading dp axis); every peer syncs at the same K boundary
        (derived from the shared step counter), and commit points merge
        to the consensus average first — both are collectives, which is
        safe exactly where they run: on a healthy mesh under a rank-0
        verb. The crash path cannot merge (the mesh just failed), so it
        skips the RAM snapshot and rolls back to the last commit."""
        from edl_tpu.runtime import checkpoint as ckpt

        go_key = self._k("go", str(epoch))
        sharding = plan.batch_sharding(mesh)
        first_loss_key = self._k("loss_first")
        while True:
            i = int(jax.device_get(state.step))
            if rank == 0:
                verb = self._decide(cl, epoch, i)
                cl.kv_put(go_key, f"{i}:{verb}")
            else:
                verb = self._await_go(cl, go_key, i, members)
            if verb in ("step", "ckpt"):
                local, task_id = self._local_batch(cl, batch_fn)
                gbatch = jax.tree_util.tree_map(
                    lambda x: jax.make_array_from_process_local_data(
                        sharding, x
                    ),
                    local,
                )
                try:
                    if stepper is not None:
                        new_state, metrics = stepper.step(state, gbatch)
                        if (i + 1) % cfg.sync_every == 0:
                            new_state = stepper.sync(new_state)
                    else:
                        new_state, metrics = step(state, gbatch)
                    loss = float(jax.device_get(metrics["loss"]))
                except Exception as e:
                    # peer died mid-collective: recover from last
                    # completed state (crash path; epoch will bump once
                    # the member TTL reaps the dead peer)
                    log.warn("step failed; recovering", step=i, error=str(e))
                    if task_id is not None:
                        cl.nack(task_id)
                    if stepper is None:
                        snap = ckpt.snapshot_local(state)
                        self._ram_snapshot = snap
                        self._crash_checkpoint(cl, snap, rank, world)
                    else:
                        # grouped state cannot move across a dp-width
                        # change and merging needs the (dead) mesh —
                        # keep the existing RAM snapshot untouched: it
                        # already holds the last MERGED commit
                        # (_coordinated_checkpoint), which is exactly
                        # the rollback point
                        log.warn(
                            "delayed-sync crash: rolling back to last commit"
                        )
                    self._await_peer_reaped(cl, epoch)
                    return "reshard"
                state = new_state
                if task_id is not None:
                    cl.ack(task_id)
                if cfg.step_sleep_s:
                    time.sleep(cfg.step_sleep_s)
                if rank == 0:
                    if not cl.kv_get(first_loss_key):
                        cl.kv_put(first_loss_key, repr(loss))
                    cl.kv_put(self._k("loss_last"), repr(loss))
                    cl.kv_put(self._k("progress"), str(i + 1))
                if verb == "ckpt":  # periodic commit of the NEW state,
                    # written behind the continuing step loop
                    self._coordinated_checkpoint(
                        cl, epoch,
                        stepper.merge(state) if stepper is not None else state,
                        rank, members, background=True,
                    )
            else:  # stop | reshard — commit the completed state
                self._coordinated_checkpoint(
                    cl, epoch,
                    stepper.merge(state) if stepper is not None else state,
                    rank, members,
                )
                return verb

    def _await_peer_reaped(self, cl, failed_epoch: int) -> None:
        """A collective just failed, so some peer is dead but may not
        have TTL-expired yet. Re-rendezvousing before the coordinator
        reaps it would rebuild the world WITH the corpse — and a
        jax.distributed connect timeout is fatal. Wait for the epoch to
        move, then one extra TTL for any other silent deaths."""
        deadline = time.monotonic() + self.cfg.rendezvous_timeout_s
        while cl.epoch() == failed_epoch:
            cl.expire()
            if time.monotonic() > deadline:  # pragma: no cover
                raise TimeoutError("dead peer never reaped")
            time.sleep(0.1)
        time.sleep(self.cfg.member_ttl_s)
        cl.expire()

    def _decide(self, cl, epoch: int, i: int) -> str:
        cl.expire()
        if self._leaving or cl.epoch() != epoch:
            return "reshard"
        ms = cl.members()
        if any(cl.kv_get(self._k("leaving", m.name)) for m in ms):
            return "reshard"
        if cl.queue_done():
            return "stop"
        if (
            self.cfg.ckpt_every
            and self.cfg.ckpt_dir
            and (i + 1) % self.cfg.ckpt_every == 0
        ):
            return "ckpt"  # step, then commit the resulting state
        return "step"

    def _await_go(self, cl, go_key: str, i: int, members) -> str:
        """Wait for rank 0's decision for step ``i``. A published
        decision always wins (rank 0 may already be inside the step's
        collective). Only when there is NO decision yet AND rank 0 has
        left membership (crashed + TTL-reaped, or departed) can it
        never publish again — treat that as a reshard. Note: a mere
        epoch bump is NOT a bail-out signal; rank 0 may be alive and
        about to publish ``step``, and abandoning it then would strand
        it inside the collective."""
        deadline = time.monotonic() + self.cfg.rendezvous_timeout_s
        prefix = f"{i}:"
        rank0 = next(m.name for m in members if m.rank == 0)
        while True:
            v = cl.kv_get(go_key)
            if v and v.startswith(prefix):
                return v.split(":", 1)[1]
            cl.expire()
            if rank0 not in {m.name for m in cl.members()}:
                log.warn("rank-0 worker gone; resharding", step=i)
                return "reshard"
            if time.monotonic() > deadline:
                raise TimeoutError(f"no go decision for step {i}")
            time.sleep(_POLL_S)

    def _dist_done_key(self, epoch: int, addr: str) -> str:
        """Dismissal key scoped to one service instance's address, so
        dismissing a dead host cannot kill its respawn at the same
        epoch."""
        return self._k("dist_done", str(epoch), addr.rsplit(":", 1)[1])

    def _teardown_epoch(self, cl, epoch: int, rank: int, members, addr: str) -> None:
        """Ordered disconnect from this epoch's (external) coordination
        service. A live leader — the lowest-rank surviving member, since
        rank 0 itself may be the casualty — waits for every other live
        member's disconnect mark, disconnects last, and dismisses the
        service host via ``dist_done``. Dismissing it earlier would
        abort still-connected peers (their error pollers treat a dead
        service as fatal)."""
        me = self.cfg.worker_id
        disc = lambda name: self._k("disc", str(epoch), name)  # noqa: E731
        # retire this epoch's coordination keys at the NEXT rendezvous
        # (they must survive until every peer has left the epoch; the
        # dist_done mark must outlive the service host's dismissal poll)
        self._gc_keys += (
            [self._k("go", str(epoch)), self._k("dist", str(epoch))]
            + [disc(m.name) for m in members]
        )
        self._gc_later.append(self._dist_done_key(epoch, addr))
        cl.expire()
        alive = {m.name for m in cl.members()}
        leader = min(
            (m.rank for m in members if m.name in alive), default=rank
        )
        if rank == leader:
            peers = [m.name for m in members if m.name != me]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                cl.expire()
                live = {m.name for m in cl.members()}
                if all(cl.kv_get(disc(p)) or p not in live for p in peers):
                    break
                time.sleep(_POLL_S)
            _shutdown_distributed()
            cl.kv_put(self._dist_done_key(epoch, addr), "1")
            return
        _shutdown_distributed()
        cl.kv_put(disc(me), "1")

    def _finish(self, rank: int) -> int:
        cl = self.client
        if rank == 0:
            cl.kv_put(self._k("phase"), "succeeded")
        log.info("job complete", worker=self.cfg.worker_id)
        cl.leave(self.cfg.worker_id)
        cl.release_worker(self.cfg.worker_id)
        return 0

    def _depart(self, code: int) -> int:
        cl = self.client
        log.info("departing (scale-down)", worker=self.cfg.worker_id)
        cl.release_worker(self.cfg.worker_id)
        cl.leave(self.cfg.worker_id)
        cl.kv_del(self._k("leaving", self.cfg.worker_id))
        self._linger_for_migration(cl)
        return code

    def _linger_for_migration(self, cl) -> None:
        """Drain-window P2P: after deregistering (so the new epoch can
        form), keep the process alive serving our RAM snapshot until the
        new world confirms it restored a step >= ours — the data plane
        of a migration to a disjoint worker set. Bounded by
        p2p_linger_s, extended while a peer is actively fetching."""
        snap = self._ram_snapshot
        srv = self._shard_server
        if not self.cfg.p2p or snap is None or srv is None:
            return
        deadline = time.monotonic() + self.cfg.p2p_linger_s
        while True:
            try:
                restored = int(cl.kv_get(self._k("restored_step")) or "-1")
            except Exception:
                return  # coordinator gone: the job is over
            if restored >= snap.step:
                return
            if time.monotonic() > deadline and srv.active == 0:
                log.warn(
                    "departing without restore confirmation",
                    snapshot_step=snap.step,
                    restored_step=restored,
                )
                return
            time.sleep(0.1)


def main(argv=None) -> int:
    import argparse

    # provisional shield: a scale-down SIGTERM that lands before the
    # worker has joined the job (registration happens inside run()) is
    # a clean no-op departure — exit 0 without touching membership.
    # The drain handler replaces this below. The only remaining window
    # is interpreter startup itself (same exposure as a pod deleted
    # during container start in the reference).
    signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))

    # configuration comes from the EDL_* env contract injected by the
    # controller (api/parser.py pod_env); argv exists for --help only
    argparse.ArgumentParser(
        prog="edl-worker",
        description="elastic worker entrypoint; configured via the EDL_* "
        "environment contract (EDL_JOB_NAME, EDL_COORDINATOR, EDL_WORKER_ID, "
        "EDL_WORKERS_MIN/MAX, EDL_FAULT_TOLERANT, EDL_ENTRY, ...)",
    ).parse_args(argv)
    from edl_tpu.utils.logging import configure

    configure(os.environ.get("EDL_LOG_LEVEL", "info"))
    cfg = WorkerConfig.from_env()
    worker = ElasticWorker(cfg)
    # install BEFORE the heavy jax import: a scale-down SIGTERM can land
    # while the worker is still starting up
    signal.signal(signal.SIGTERM, worker._on_sigterm)
    try:
        return worker.run()
    except entrypoint.FailureGateError as e:
        log.error("failure gate", error=str(e))
        return 2


if __name__ == "__main__":
    sys.exit(main())
