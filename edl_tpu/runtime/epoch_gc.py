"""Epoch-scoped coordinator-KV garbage collection — the deferred-delete
ledger of the elastic worker protocol.

Every epoch of the elastic runtime writes coordination keys into the
job coordinator's KV (go decisions, dist endpoints, disconnect marks,
restore decisions/marks, dismissal marks). They cannot be deleted when
written — peers still poll them — and they must not live forever, or a
long elastic job leaks KV without bound. The protocol's one safe delete
point is just after a rendezvous' ``jax.distributed`` connect: every
member has connected to the NEW epoch's service, which it only does
after finishing the previous epoch's teardown, so nobody still reads
the previous epoch's keys. (worker_main drains there; every worker
drains its own ledger — deletes are idempotent across peers, so keys
die even when rank 0 is a freshly restarted process with no history.)

Two deferral classes, and picking the wrong one is the protocol
foot-gun this class exists to make explicit (it cost two debugging
sessions in round 4):

- :meth:`defer`: delete at the NEXT drain. Correct ONLY for keys whose
  readers are all done before the epoch ends — e.g. teardown writes its
  own epoch's ``go``/``dist``/``disc`` keys at epoch exit, and the next
  drain happens one full rendezvous later.
- :meth:`defer_late`: survive one EXTRA drain. REQUIRED for any key
  written DURING an epoch that same-epoch peers may still poll after
  this worker reaches its own drain point — the restore decision
  (rank 0 drains while slower peers still poll it), restore marks
  (rank 0 collects them after everyone drained), and the service-host
  dismissal mark (the detached host polls it on its own clock).

The ledger is single-threaded by design: only the worker's epoch loop
touches it, in protocol order. It holds names, never values, and
deleting a key that a peer also deleted is a no-op.
"""

from __future__ import annotations

from typing import Callable, Iterable, List


class EpochKeyGC:
    """Deferred KV deletion with the two-phase epoch semantics above."""

    def __init__(self) -> None:
        self._due: List[str] = []  # deleted at the next drain
        self._late: List[str] = []  # promoted to _due at the next drain

    def defer(self, *keys: str) -> None:
        """Delete at the next drain (readers finish with the epoch)."""
        self._due.extend(keys)

    def defer_late(self, *keys: str) -> None:
        """Delete one drain LATER (same-epoch peers may still poll
        after this worker's own drain runs)."""
        self._late.extend(keys)

    def extend(self, keys: Iterable[str], late: bool = False) -> None:
        (self._late if late else self._due).extend(keys)

    @property
    def due(self) -> tuple:
        return tuple(self._due)

    @property
    def late(self) -> tuple:
        return tuple(self._late)

    def pending(self) -> int:
        return len(self._due) + len(self._late)

    def drain(self, kv_del: Callable[[str], None]) -> int:
        """Delete every due key, then promote late keys to due. Returns
        the number deleted. A kv_del failure aborts mid-drain with the
        remaining keys still owed (the next drain retries them) — a
        transient coordinator hiccup must not leak the rest forever."""
        deleted = 0
        try:
            while self._due:
                kv_del(self._due[0])
                self._due.pop(0)
                deleted += 1
        finally:
            if not self._due:
                self._due = self._late
                self._late = []
        return deleted
