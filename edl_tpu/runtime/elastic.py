"""ElasticTrainer — in-place mesh reshard instead of job restarts.

The genuinely new part of the framework (SURVEY §7 layer 4). The
reference achieves elasticity by killing/adding k8s pods and letting
Paddle's etcd runtime re-form (reference: pkg/autoscaler.go:361
retargets Parallelism; docker/paddle_k8s re-runs discovery). On TPU a
restart throws away compiled programs and device state, so the protocol
is instead:

    scale event → snapshot state to host RAM → rebuild the mesh over the
    new device set → re-shard state onto it → resume at the next step

The north-star metric (BASELINE.md) is the stall this costs: target
<30 s per reshard, zero restarts. The trainer times every reshard and
reports it via callback (feeding TrainingJobStatus.last_reshard_stall_s).

In-process, the device pool is the local ``jax.devices()`` list (tests:
8 virtual CPU devices). Multi-host, the same protocol runs with
``jax.distributed`` re-initialization between snapshot and rebuild —
the coordinator owns membership epochs (runtime/coordinator.py).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
import optax

from edl_tpu.api.job import MeshSpec
from edl_tpu.parallel.mesh import MeshPlan
from edl_tpu.runtime import checkpoint as ckpt
from edl_tpu.train.trainer import (
    LocalSyncStepper,
    TrainState,
    global_batch,
    make_train_step,
    shard_state,
)
from edl_tpu.obs import costmodel as _costmodel
from edl_tpu.obs import disttrace
from edl_tpu.obs import events as flight
from edl_tpu.obs import memledger
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils import tracing
from edl_tpu.utils.logging import Timer, kv_logger

log = kv_logger("elastic")


def _obs_reshard(ev: "ReshardEvent") -> None:
    """Reshard telemetry (the BASELINE north-star, scrapeable): stall
    histogram + path-labeled counter — previously this lived only in
    tracing spans a human had to dump."""
    r = obs_metrics.default_registry()
    r.histogram(
        "edl_reshard_stall_seconds", "traffic-stopping reshard window"
    ).observe(ev.stall_s)
    r.counter("edl_reshard_total", "elastic reshards", ("path",)).inc(
        path="host" if ev.fallback else "device"
    )


def _device_reshard(state: TrainState, plan: MeshPlan, mesh, pspecs) -> TrainState:
    """Move a live device-resident TrainState onto a (different) mesh by
    direct ``jax.device_put`` — XLA routes shard movement device-to-device
    where device sets overlap, which is the elastic fast path. Same
    placement rule as initial placement (shard_state), plus a fence."""
    new_state = shard_state(state, plan, mesh, pspecs)
    jax.block_until_ready(new_state.params)
    return new_state


@dataclass
class ReshardEvent:
    """One elastic rescale, as observed by the runtime."""

    from_workers: int
    to_workers: int
    stall_s: float  # snapshot + remesh + reshard (the traffic-stopping window)
    recompile_s: float  # first-step compile on the new mesh (overlappable)
    step: int
    # True when the direct device-to-device move failed and the reshard
    # went through host-RAM staging — the slow path whose cost scales
    # with per-host state bytes (see doc/reshard_stall.md for the bound)
    fallback: bool = False


@dataclass
class TrainReport:
    steps: int = 0
    examples: int = 0
    losses: List[float] = field(default_factory=list)
    reshards: List[ReshardEvent] = field(default_factory=list)
    train_seconds: float = 0.0

    @property
    def examples_per_sec(self) -> float:
        return self.examples / self.train_seconds if self.train_seconds else 0.0


class ElasticTrainer:
    """Runs a sharded training loop that can rescale between steps.

    Parameters
    ----------
    loss_fn : ``f(params, batch) -> scalar``
    tx : optax optimizer
    mesh_spec : user parallelism plan; remaining device factor goes to dp
    chips_per_worker : devices driven by each worker (host) process
    per_chip_batch : per-device batch size — global batch scales with the
        worker count, the reference's elastic-DP throughput semantics
    param_pspecs : optional model-provided PartitionSpec tree, or a
        callable ``plan -> tree`` re-evaluated at every (re)build so TP
        layouts track the current mesh plan
    devices : device pool override (defaults to ``jax.devices()``)
    """

    def __init__(
        self,
        loss_fn: Callable,
        tx: optax.GradientTransformation,
        mesh_spec: Optional[MeshSpec] = None,
        chips_per_worker: int = 1,
        per_chip_batch: int = 32,
        param_pspecs=None,
        devices: Optional[Sequence[jax.Device]] = None,
        on_reshard: Optional[Callable[[ReshardEvent], None]] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every_steps: int = 0,
        sync_every: int = 1,
        make_loss: Optional[Callable] = None,
        flops_per_example: Optional[float] = None,
        hbm_bytes_per_example: Optional[float] = None,
    ):
        self.loss_fn = loss_fn
        # mesh-aware loss factory ``(plan, mesh) -> loss_fn``, re-invoked
        # at every (re)build — required for strategies whose program
        # depends on the mesh layout (llama sp ring/Ulysses attention,
        # pp GPipe schedule), mirroring Workload.make_loss in the
        # process runtime. When given, ``loss_fn`` may be None.
        self.make_loss = make_loss
        self.tx = tx
        self.mesh_spec = mesh_spec or MeshSpec()
        self.chips_per_worker = chips_per_worker
        self.per_chip_batch = per_chip_batch
        self.param_pspecs = param_pspecs
        self._pspecs = None  # resolved per-plan in _build
        self.pool = list(devices) if devices is not None else list(jax.devices())
        self.on_reshard = on_reshard
        # periodic checkpointing (the reference's save_inference_model
        # cadence, example/ctr/ctr/train.py:169-180, made first-class)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_steps = checkpoint_every_steps
        # delayed-sync DP (local SGD): K local steps per dp group between
        # cross-group averages — the TPU analog of the reference's
        # --async_mode (example/ctr/ctr/train.py:75-79). 1 = fully sync.
        self.sync_every = max(int(sync_every), 1)
        self._stepper: Optional[LocalSyncStepper] = None

        self.n_workers = 0
        self.mesh = None
        self.plan: Optional[MeshPlan] = None
        self.state: Optional[TrainState] = None
        self._host_step = 0  # host mirror of state.step (avoids per-step syncs)
        self._step_fn = None
        self._scale_target: Optional[int] = None
        self.report = TrainReport()
        # hardware-efficiency observability (obs/costmodel.py): when
        # the workload declares its analytic cost per example, every
        # train_steps window publishes edl_mfu{phase="train"} /
        # edl_bw_util_ratio{phase="train"} from the measured
        # examples/sec — live roofline telemetry, not a bench-only
        # number. Per-DEVICE: the gauges are per-chip utilization.
        self.flops_per_example = flops_per_example
        self.hbm_bytes_per_example = hbm_bytes_per_example
        self._eff: Optional[_costmodel.EfficiencyMeter] = None
        # device memory ledger: this trainer's long-lived HBM (params
        # + optimizer moments), re-registered on every (re)placement
        # under stable keys so reshards replace rather than accumulate
        self._ledger = memledger.default_ledger()
        self._ledger_owner = f"trainer-{id(self)}"
        import weakref

        weakref.finalize(self, self._ledger.release_owner, self._ledger_owner)

    # -- lifecycle ---------------------------------------------------------

    @property
    def n_devices(self) -> int:
        return self.n_workers * self.chips_per_worker

    @property
    def global_batch_size(self) -> int:
        return self.per_chip_batch * self.n_devices

    def start(self, params, n_workers: int) -> None:
        """Initial mesh + state placement + step compile."""
        self._build(n_workers)
        host = TrainState.create(params, self.tx)
        self.state = shard_state(host, self.plan, self.mesh, self._pspecs)
        if self._stepper is not None:
            self.state = self._stepper.localize(self.state)
        self._ledger_register()
        self._host_step = 0
        log.info(
            "elastic trainer started",
            workers=n_workers,
            devices=self.n_devices,
            mesh=self.plan.describe(),
        )

    def resume(self, params, n_workers: int, checkpoint_path: str) -> None:
        """Start from a saved checkpoint (crash recovery / warm restart):
        ``params`` only provides the tree structure; values and the step
        counter come from disk and are sharded onto the fresh mesh."""
        self._build(n_workers)
        template = TrainState.create(params, self.tx)
        host = ckpt.load(checkpoint_path, template)
        self.state = ckpt.restore(host, self.plan, self.mesh, self._pspecs)
        if self._stepper is not None:
            self.state = self._stepper.localize(self.state)
        self._ledger_register()
        self._host_step = int(np.asarray(host.step))
        log.info(
            "elastic trainer resumed",
            workers=n_workers,
            step=int(np.asarray(host.step)),
            checkpoint=checkpoint_path,
        )

    def maybe_checkpoint(self, force: bool = False) -> Optional[str]:
        """Write ``checkpoint_dir/step-N`` when the cadence (or ``force``)
        says so; returns the path written."""
        if not self.checkpoint_dir or self.state is None:
            return None
        step = self._host_step  # host mirror: no device sync on the hot path
        if not force and (
            self.checkpoint_every_steps <= 0
            or step == 0
            or step % self.checkpoint_every_steps != 0
        ):
            return None
        path = os.path.join(self.checkpoint_dir, f"step-{step}")
        if os.path.exists(os.path.join(path, "state.npz")):
            return None  # already saved at this step
        # delayed-sync mode checkpoints the group AVERAGE (the consensus
        # model), not one group's drifted copy
        to_save = self.merged_state
        with tracing.span("checkpoint.save", step=step):
            ckpt.save(path, to_save, {"n_workers": self.n_workers})
        return path

    def _build(self, n_workers: int) -> None:
        n_dev = n_workers * self.chips_per_worker
        if n_dev > len(self.pool):
            raise ValueError(
                f"{n_workers} workers x {self.chips_per_worker} chips "
                f"exceed device pool ({len(self.pool)})"
            )
        self.plan = MeshPlan.from_spec(self.mesh_spec, n_dev)
        self.mesh = self.plan.build(self.pool[:n_dev])
        self.n_workers = n_workers
        self._pspecs = (
            self.param_pspecs(self.plan)
            if callable(self.param_pspecs)
            else self.param_pspecs
        )
        loss = (
            self.make_loss(self.plan, self.mesh)
            if self.make_loss is not None
            else self.loss_fn
        )
        self._step_fn = make_train_step(
            loss, self.tx, self.plan, self.mesh, self._pspecs
        )
        self._stepper = (
            LocalSyncStepper(loss, self.tx, self.plan, self.mesh)
            if self.sync_every > 1
            else None
        )

    def _ledger_register(self) -> None:
        """(Re)register the live state's HBM in the memory ledger —
        params and optimizer moments, under stable per-trainer keys
        (replace semantics: reshards and restores cannot drift the
        edl_hbm_bytes gauges)."""
        if self.state is None:
            return
        self._ledger.register_tree(
            self._ledger_owner, "params", self.state.params, "params"
        )
        self._ledger.register_tree(
            self._ledger_owner, "opt", self.state.opt_state, "opt"
        )

    @property
    def merged_state(self) -> Optional[TrainState]:
        """The consensus TrainState: in delayed-sync mode, the group
        average; otherwise the live state itself. Use for eval/export."""
        if self.state is not None and self._stepper is not None:
            return self._stepper.merge(self.state)
        return self.state

    # -- elastic surface ---------------------------------------------------

    def request_rescale(self, n_workers: int) -> None:
        """Signal from the control plane (autoscaler retarget); honored
        at the next step boundary — training never tears down."""
        if n_workers != self.n_workers:
            self._scale_target = n_workers

    def apply_chip_grant(self, total_chips: int) -> int:
        """Consume a chip-lease budget from the elasticity broker
        (edl_tpu/elasticity): retarget to as many whole workers as
        ``total_chips`` covers, floored at one worker — the trainer's
        end of the shared broker-grant interface. Returns the worker
        count requested."""
        if total_chips < 0:
            raise ValueError(f"total_chips must be >= 0, got {total_chips}")
        n_workers = max(1, total_chips // self.chips_per_worker)
        self.request_rescale(n_workers)
        return n_workers

    def _feasible(self, n_workers: int) -> bool:
        n_dev = n_workers * self.chips_per_worker
        if n_workers < 1 or n_dev > len(self.pool):
            return False
        try:
            MeshPlan.from_spec(self.mesh_spec, n_dev)
        except ValueError:
            return False
        return True

    def _resolve_target(self, target: int) -> Optional[int]:
        """Largest feasible worker count ≤ target (a retarget must never
        crash the loop — an infeasible count degrades to the nearest
        mesh-divisible one below it, or is ignored)."""
        for n in range(min(target, len(self.pool) // max(self.chips_per_worker, 1)), 0, -1):
            if self._feasible(n):
                return n
        return None

    def _maybe_rescale(self) -> None:
        target = self._scale_target
        if target is None:
            return
        self._scale_target = None
        target = self._resolve_target(target)
        if target is None or target == self.n_workers:
            if target is None:
                log.warn("ignoring infeasible rescale target")
            return
        prev = self.n_workers
        step_at = self._host_step
        # reshard_epoch: this trainer's reshard ordinal — the flight-
        # recorder correlation key tying begin/end/recompile together.
        # The whole rescale runs under a DERIVED trace root
        # ("reshard", ep): every reshard-phase span and event shares
        # trace id disttrace.derived_trace_id("reshard", ep), which is
        # how `edl trace --reshard-epoch N` selects the chain without
        # any id exchange.
        ep = len(self.report.reshards)
        log.info("reshard begin", from_workers=prev, to_workers=target)
        with disttrace.root("reshard", ep):
            self._rescale_traced(target, prev, step_at, ep)

    def _rescale_traced(self, target, prev, step_at, ep) -> None:
        used_fallback = False
        flight.emit("reshard.begin", reshard_epoch=ep, step=step_at,
                    from_workers=prev, to_workers=target)
        with Timer() as stall, tracing.span(
            "reshard", from_workers=prev, to_workers=target, step=step_at,
            reshard_epoch=ep,
        ):
            # delayed-sync groups are collapsed to their average before
            # the move: the new dp width means a new group count, and the
            # merge is the same one all-reduce a sync boundary costs
            old_state = self.merged_state
            with tracing.span("reshard.build_mesh", to_workers=target):
                self._build(target)  # new mesh over new device set
            try:
                # fast path: direct device-to-device reshard (rides ICI on
                # real hardware; surviving shards move, no host round trip)
                with tracing.span("reshard.device_transfer"):
                    self.state = _device_reshard(
                        old_state, self.plan, self.mesh, self._pspecs
                    )
            except (ValueError, TypeError, RuntimeError) as e:
                # transfer-layer failures fall back to host-RAM staging;
                # deterministic spec bugs will fail again here and surface
                used_fallback = True
                log.warn("device reshard failed; staging via host", error=str(e))
                with tracing.span("reshard.host_staging"):
                    # overlapped down/up pipeline: ~max(d2h, h2d), not sum
                    self.state = ckpt.staged_reshard(
                        old_state, self.plan, self.mesh, self._pspecs
                    )
            if self._stepper is not None:
                self.state = self._stepper.localize(self.state)
            del old_state
            # stable keys: the re-placed state REPLACES the ledger
            # entries — N reshards leave exactly one state's bytes
            self._ledger_register()
        ev = ReshardEvent(
            from_workers=prev,
            to_workers=target,
            stall_s=stall.elapsed,
            recompile_s=0.0,  # filled after the first step on the new mesh
            step=step_at,
            fallback=used_fallback,
        )
        self.report.reshards.append(ev)
        _obs_reshard(ev)
        flight.emit(
            "reshard.end", reshard_epoch=ep, step=step_at,
            from_workers=prev, to_workers=target,
            stall_s=round(stall.elapsed, 6),
            path="host" if used_fallback else "device",
        )
        log.info(
            "reshard done",
            from_workers=prev,
            to_workers=target,
            stall_s=round(stall.elapsed, 4),
            fallback=used_fallback,
        )
        if self.on_reshard:
            self.on_reshard(ev)

    # -- training loop -----------------------------------------------------

    def train_steps(self, data_fn: Callable[[int], Any], n_steps: int) -> TrainReport:
        """Run ``n_steps`` updates; ``data_fn(global_batch_size)`` yields a
        host batch each step (task-queue readers plug in here).

        Every step records its wall time and the data-wait share into
        the process registry (edl_train_step_seconds /
        edl_train_data_wait_seconds); the end-of-call materialization
        is the host-block share. Pure host bookkeeping — nothing is
        synced that the loop didn't already sync."""
        reg = obs_metrics.default_registry()
        h_step = reg.histogram(
            "edl_train_step_seconds",
            "full step wall time (data + dispatch + sync)",
        )
        h_data = reg.histogram(
            "edl_train_data_wait_seconds",
            "host wait for the next batch (data stall)",
        )
        h_block = reg.histogram(
            "edl_train_host_block_seconds",
            "host blocked on device results (sync stall)",
        )
        c_examples = reg.counter(
            "edl_train_examples_total", "training rows consumed"
        )
        t0 = time.perf_counter()
        raw_losses = []  # device arrays; materialized once after the loop
        try:
            self._train_steps_inner(
                data_fn, n_steps, h_step, h_data, c_examples, raw_losses
            )
        except Exception as e:
            # the trainer's black-box escape hatch: record the failure
            # and dump the flight ring (EDL_BLACKBOX_DIR) BEFORE
            # re-raising, so the crash is explainable post-hoc
            flight.emit(
                "trainer.crash", severity="error", step=self._host_step,
                error=f"{type(e).__name__}: {e}",
            )
            flight.crash_dump("trainer", e)
            raise
        tb = time.perf_counter()
        jax.block_until_ready(self.state.params)
        h_block.observe(time.perf_counter() - tb)
        self.report.train_seconds += time.perf_counter() - t0
        self.report.losses.extend(float(x) for x in raw_losses)
        if raw_losses:
            reg.gauge("edl_train_loss", "most recent training loss").set(
                float(raw_losses[-1])
            )
        if self.report.train_seconds > 0:
            reg.gauge(
                "edl_train_examples_per_sec",
                "training throughput over the last report window",
            ).set(self.report.examples_per_sec)
        if self.flops_per_example and self.report.train_seconds > 0:
            # live roofline: measured examples/s × the workload's
            # analytic cost, per chip — the scrapeable twin of the
            # bench's MFU figure (obs/costmodel.py owns the formulas)
            # re-resolved per window (get-or-create is dict hits) so a
            # test's registry swap takes effect, like _record_dispatch
            self._eff = _costmodel.EfficiencyMeter(registry=reg)
            eps_per_dev = self.report.examples_per_sec / max(
                self.n_devices, 1
            )
            self._eff.set_rates(
                "train",
                eps_per_dev * self.flops_per_example,
                eps_per_dev * (self.hbm_bytes_per_example or 0.0),
            )
        return self.report

    def _train_steps_inner(
        self, data_fn, n_steps, h_step, h_data, c_examples, raw_losses
    ) -> None:
        for _ in range(n_steps):
            self._maybe_rescale()
            ts = time.perf_counter()
            batch = data_fn(self.global_batch_size)
            dev_batch = global_batch(batch, self.plan, self.mesh)
            first_on_mesh = (
                bool(self.report.reshards)
                and self.report.reshards[-1].recompile_s == 0.0
            )
            tc = time.perf_counter()
            h_data.observe(tc - ts)
            if self._stepper is not None:
                self.state, metrics = self._stepper.step(self.state, dev_batch)
                if (self._host_step + 1) % self.sync_every == 0:
                    self.state = self._stepper.sync(self.state)
            else:
                self.state, metrics = self._step_fn(self.state, dev_batch)
            if first_on_mesh:
                jax.block_until_ready(metrics["loss"])
                recompile_s = time.perf_counter() - tc
                self.report.reshards[-1].recompile_s = recompile_s
                tracing.tracer().record(
                    "reshard.recompile", tc, recompile_s,
                    {"to_workers": self.n_workers},
                )
                obs_metrics.default_registry().histogram(
                    "edl_reshard_recompile_seconds",
                    "first-step compile on the new mesh",
                ).observe(recompile_s)
            self.report.steps += 1
            self._host_step += 1
            self.report.examples += self.global_batch_size
            c_examples.inc(self.global_batch_size)
            raw_losses.append(metrics["loss"])
            self.maybe_checkpoint()
            h_step.observe(time.perf_counter() - ts)
