"""Batch prediction from a published export — every family, one verb.

The reference's serving artifact is the inference model written by the
trainer and consumed OFFLINE by a separate process (CTR:
/root/reference/example/ctr/ctr/train.py:169-180 writes it each pass;
the tutorial scores batches of Criteo rows against it). The TPU
translation: exports carry an architecture record (``model`` in the
manifest, written by every workload), and this module rebuilds the
family's config + forward from that record alone — a consumer needs the
export directory and a batch of rows, not the training repo config.

``edl generate`` stays the llama *decoding* consumer (KV-cache
autoregression); :func:`predict_batch` is the *scoring* consumer for
every family:

====== ======================= ===========================================
family rows (npz keys)         outputs
====== ======================= ===========================================
ctr    dense [B,13] f32,       prob [B] (sigmoid click probability);
       sparse [B,26] i32,      auc when label present
       label [B] (optional)
resnet images [B,H,W,C] f32,   class [B] top-1; acc when label present
       label [B] (optional)
bert   tokens [B,T] i32,       pred [B,T] top-1 token per position;
       mask/targets (optional) masked_acc when mask+targets present
llama  tokens [B,T] i32        next_token [B] (argmax after the last
                               position); ppl over the batch when T >= 2
moe    tokens [B,T] i32        same as llama
====== ======================= ===========================================

Forwards run chunked (LM logits are [rows, T, vocab] f32 — one
unchunked call over a real batch would OOM the host), and ``--mesh``
loads the params sharded over a device mesh via the SAME generic
pspec rule training uses (``sharding.param_pspecs`` over a template
built from the manifest), so bigger-than-HBM exports score at all.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from edl_tpu.models.evals import CHUNK as _CHUNK  # one chunking rule


def _chunks(n: int):
    for s in range(0, n, _CHUNK):
        yield slice(s, min(s + _CHUNK, n))


def template_from_doc(doc: Dict[str, Any]):
    """ShapeDtypeStruct tree mirroring an export's param tree, built
    from manifest shapes/dtypes alone — what the generic sharding rule
    needs BEFORE any weight bytes load."""
    import jax

    from edl_tpu.runtime.export import _bf16, _restore_lists, _tree_insert

    tree: Dict[str, Any] = {}
    for key, shape in doc["shapes"].items():
        name = doc["dtypes"].get(key, "float32")
        dt = _bf16() if name == "bfloat16" else np.dtype(name)
        _tree_insert(
            tree, key.split("/"), jax.ShapeDtypeStruct(tuple(shape), dt)
        )
    return _restore_lists(tree)


def load_params_for_predict(
    export_dir: str, mesh_spec: Optional[str] = None
) -> Tuple[Any, Dict[str, Any]]:
    """(params, manifest) — host-resident, or sharded onto a device
    mesh when ``mesh_spec`` (e.g. ``"fsdp=4"``) is given. The sharded
    path reuses the generic training pspec rule over the manifest
    template, so any family's export (dict OR list nodes) shards
    without a model-specific layout."""
    from edl_tpu.runtime.export import load_export, load_export_sharded

    if not mesh_spec:
        return load_export(export_dir)
    import jax

    from edl_tpu.parallel import sharding as shd
    from edl_tpu.parallel.mesh import MeshPlan

    plan = MeshPlan.parse(mesh_spec, len(jax.devices()))
    mesh = plan.build()
    return load_export_sharded(
        export_dir,
        mesh,
        lambda d: shd.param_pspecs(template_from_doc(d), plan),
    )


def predict_batch(
    params: Any, doc: Dict[str, Any], rows: Dict[str, np.ndarray]
) -> Dict[str, Any]:
    """Family-dispatched scoring of ``rows`` against an export's
    params. Returns per-row outputs plus any metrics the provided
    labels allow (see module table). Raises ValueError for an export
    without a usable architecture record."""
    meta = doc.get("model") or {}
    family = meta.get("family")
    if family == "ctr":
        return _predict_ctr(params, rows)
    if family == "resnet":
        return _predict_resnet(params, meta, rows)
    if family == "bert":
        return _predict_bert(params, meta, rows)
    if family == "llama":
        return _predict_lm(params, meta, rows, family)
    if family == "moe":
        return _predict_lm(params, meta, rows, family)
    raise ValueError(
        f"export has no architecture record predict understands "
        f"(model={meta or None}); re-export with model_meta"
    )


def _need(rows: Dict[str, np.ndarray], *keys: str) -> None:
    missing = [k for k in keys if k not in rows]
    if missing:
        raise ValueError(
            f"input rows missing {missing}; have {sorted(rows)}"
        )


def _predict_ctr(params, rows) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import ctr

    _need(rows, "dense", "sparse")
    dense = np.asarray(rows["dense"], np.float32)
    sparse = np.asarray(rows["sparse"], np.int32)
    # one jit per `edl predict` invocation by design (the chunk loop
    # below reuses it); no steady-state path re-enters this function
    # edl: no-lint[recompile-hazard]
    fwd = jax.jit(ctr.forward)
    logits = np.concatenate([
        np.asarray(fwd(params, jnp.asarray(dense[c]), jnp.asarray(sparse[c])))
        for c in _chunks(len(dense))
    ])
    out: Dict[str, Any] = {"prob": 1.0 / (1.0 + np.exp(-logits))}
    if "label" in rows:
        import jax.numpy as jnp

        out["auc"] = float(
            ctr.batch_auc(
                jnp.asarray(logits),
                jnp.asarray(np.asarray(rows["label"]), jnp.float32),
            )
        )
    return out


def _predict_resnet(params, meta, rows) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import resnet

    _need(rows, "images")
    cfg = resnet.ResNetConfig.from_meta(meta)
    images = np.asarray(rows["images"], np.float32)
    # edl: no-lint[recompile-hazard] one jit per CLI predict invocation; cfg comes from the export being loaded
    fwd = jax.jit(lambda p, x: resnet.forward(p, x, cfg))
    cls = np.concatenate([
        np.asarray(jnp.argmax(fwd(params, jnp.asarray(images[c])), -1))
        for c in _chunks(len(images))
    ])
    out: Dict[str, Any] = {"class": cls}
    if "label" in rows:
        out["acc"] = float(
            (cls == np.asarray(rows["label"]).reshape(-1)).mean()
        )
    return out


def _predict_bert(params, meta, rows) -> Dict[str, Any]:
    import jax

    from edl_tpu.models import bert
    from edl_tpu.models.evals import masked_top1

    _need(rows, "tokens")
    cfg = bert.BertConfig.from_meta(meta)
    # edl: no-lint[recompile-hazard] one jit per CLI predict invocation; cfg comes from the export being loaded
    fwd = jax.jit(lambda p, t: bert.forward(p, t, cfg))
    # the SAME chunked masked-accuracy math the in-job eval publishes
    acc, pred = masked_top1(
        fwd, params, dict(rows, tokens=np.asarray(rows["tokens"], np.int32))
    )
    out: Dict[str, Any] = {"pred": pred}
    if "mask" in rows and "targets" in rows:
        out["masked_acc"] = acc
    return out


def _predict_lm(params, meta, rows, family: str) -> Dict[str, Any]:
    import jax

    from edl_tpu.models.evals import lm_scan

    _need(rows, "tokens")
    if family == "llama":
        from edl_tpu.models import llama as mod

        cfg = mod.LlamaConfig.from_meta(meta)
        fwd = jax.jit(lambda p, t: mod.forward(p, t, cfg))
    else:
        from edl_tpu.models import moe as mod

        cfg = mod.MoEConfig.from_meta(meta)
        fwd = jax.jit(lambda p, t: mod.forward(p, t, cfg)[0])
    # one chunked pass (models/evals): greedy next tokens + the SAME
    # CE accumulation the in-job perplexity eval publishes
    nxt, total, count = lm_scan(
        fwd, params, np.asarray(rows["tokens"], np.int32)
    )
    out: Dict[str, Any] = {"next_token": nxt}
    if count:
        out["ppl"] = float(np.exp(total / count))
    return out


def load_rows(
    path: Optional[str] = None,
    data_dir: Optional[str] = None,
    n_rows: int = 256,
) -> Dict[str, np.ndarray]:
    """Rows from an ``.npz`` file OR the head of a shards-dir dataset
    (runtime/shards.py — the same format the training pipeline reads)."""
    if (path is None) == (data_dir is None):
        raise ValueError("give exactly one of path / data_dir")
    if path is not None:
        with np.load(path, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}
    from edl_tpu.runtime.shards import FileShardSource

    src = FileShardSource(data_dir)
    return src.fetch_range(0, min(n_rows, src.n_samples))
