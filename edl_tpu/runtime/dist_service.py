"""Standalone JAX coordination-service host — one per membership epoch.

In stock JAX the rank-0 process hosts the coordination service
in-process, so rank-0 death destroys the rendezvous plane and the
remaining clients' error-pollers abort their processes — leader failure
is unrecoverable by construction. This helper externalizes the service
(the same move the reference makes by running etcd in the master pod
rather than inside a trainer — reference: pkg/jobparser.go:167-184):
workers are pure clients, and any worker's death — including the
collective's rank 0 — leaves the service healthy for the survivors'
orderly disconnect and re-rendezvous.

Spawned per epoch by the rank-0 worker (production: by the controller,
colocated with the job coordinator). Publishes its address at KV
``{job}/dist/{epoch}`` once listening. Exits when:

- ``{job}/dist_done/{epoch}/{port}`` is set (scoped to THIS instance's
  address, so dismissing a dead predecessor cannot kill its respawn);
- the job coordinator goes away (the job is over); or
- the membership epoch has moved past ours and stayed there for
  ``--orphan-grace`` seconds — a group that outlived an epoch bump
  reshards within seconds, so a long-stale epoch means nobody is (or
  ever will be) connected. While the epoch is current the service
  lives indefinitely: workers may be connected and mid-training.
"""

from __future__ import annotations

import argparse
import socket
import sys
import time


def _create_service(bind_host: str, world: int, heartbeat: int, attempts: int = 10):
    """Bind the service, retrying fresh ports (the probe-then-bind gap
    is racy; losing it must not be fatal)."""
    from jax._src.lib import _jax

    last = None
    for _ in range(attempts):
        s = socket.socket()
        s.bind((bind_host, 0))
        port = s.getsockname()[1]
        s.close()
        try:
            svc = _jax.get_distributed_runtime_service(
                f"{bind_host}:{port}",
                world,
                heartbeat_timeout=heartbeat,
                shutdown_timeout=10,
            )
            return svc, port
        except Exception as e:  # pragma: no cover - port race
            last = e
    raise RuntimeError(f"could not bind coordination service: {last}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--job", required=True)
    ap.add_argument("--epoch", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--coordinator", required=True, help="host:port")
    ap.add_argument("--bind-host", default="127.0.0.1")
    ap.add_argument("--heartbeat", type=int, default=10)
    ap.add_argument("--orphan-grace", type=float, default=600.0)
    a = ap.parse_args(argv)

    from edl_tpu import obs
    from edl_tpu.runtime.coordinator import CoordinatorClient

    host, port = a.coordinator.rsplit(":", 1)
    cl = CoordinatorClient(host, int(port), 10.0)

    svc, svc_port = _create_service(a.bind_host, a.world, a.heartbeat)
    cl.kv_put(f"{a.job}/dist/{a.epoch}", f"{a.bind_host}:{svc_port}")
    done_key = f"{a.job}/dist_done/{a.epoch}/{svc_port}"
    print(f"dist_service up epoch={a.epoch} port={svc_port}", flush=True)
    # fleet instrumentation: the rendezvous plane reports its own
    # liveness under the reserved "dist_service" source name, so the
    # coordinator's aggregated /metrics shows whether (and for which
    # epoch) a coordination-service host is up (obs/fleet.py,
    # coordinator_main.EXTRA_METRIC_SOURCES)
    t_up = time.monotonic()
    reg = obs.MetricsRegistry()
    g_up = reg.gauge(
        "edl_dist_service_up", "coordination-service host liveness", ("epoch",)
    )
    g_up.set(1, epoch=str(a.epoch))
    g_uptime = reg.gauge(
        "edl_dist_service_uptime_seconds", "coordination-service host uptime"
    )
    metrics_kv = obs.metrics_key(a.job, "dist_service")
    last_push = 0.0
    orphan_since = None
    try:
        while True:
            try:
                if time.monotonic() - last_push >= 5.0:
                    g_uptime.set(time.monotonic() - t_up)
                    cl.kv_put(metrics_kv, reg.snapshot_json())
                    last_push = time.monotonic()
                if cl.kv_get(done_key):
                    # we are the only reader: retire the mark ourselves
                    # so the coordinator KV stays O(live state) without
                    # the workers having to guess when our poll ran
                    try:
                        cl.kv_del(done_key)
                    # edl: no-lint[silent-failure] retiring the done-mark is best-effort housekeeping; dismissal proceeds either way
                    except Exception:
                        pass
                    print("dist_service dismissed", flush=True)
                    break
                if cl.epoch() != a.epoch:
                    orphan_since = orphan_since or time.monotonic()
                    if time.monotonic() - orphan_since > a.orphan_grace:
                        print("dist_service orphaned; exiting", flush=True)
                        break
                else:
                    orphan_since = None
            except Exception as e:
                # coordinator gone: the job is over — say so on the way
                # out (stdout IS this subprocess's log; edl check
                # silent-failure)
                print(
                    f"dist_service: coordinator unreachable ({e}); exiting",
                    flush=True,
                )
                break
            time.sleep(0.5)
    finally:
        try:  # last-gasp: the fleet view shows a clean DOWN, not staleness
            g_up.set(0, epoch=str(a.epoch))
            cl.kv_put(metrics_kv, reg.snapshot_json())
        # edl: no-lint[silent-failure] last-gasp publish during teardown; the coordinator being gone is the normal cause
        except Exception:
            pass
        svc.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
