"""Inference export — params-only servable artifacts.

The reference emits a servable model alongside training on a cadence and
at every pass end (``save_inference_model``, reference:
example/ctr/ctr/train.py:169-180, example/fit_a_line/fluid/
recognize_digits.py:84-88). The TPU translation: a **params-only,
dtype-cast** export directory with an atomically-updated ``latest``
pointer, written by the commit leader (worker runtime) or any trainer
process — decoupled from the full TrainState checkpoints, which carry
optimizer state and exist for resume/reshard, not serving.

Layout::

    <root>/step-00000042/params.npz     leaf path -> array
    <root>/step-00000042/manifest.json  step, dtype, shapes, source
    <root>/latest                       "step-00000042"  (renamed last)

bfloat16 leaves are stored as uint16 views (npz has no native bf16) and
restored through ml_dtypes on load. A consumer needs only
:func:`load_export` + the model's ``forward`` — no optimizer, no mesh.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

_FLOATS = ("float64", "float32", "float16", "bfloat16")

# serializes the read-check-rename publish against concurrent in-process
# writers (worker_main's background commit threads). Cross-process races
# are excluded by commit-leader election: exactly one process exports.
_publish_lock = threading.Lock()


def _bf16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _leaf_keys(tree):
    # the ONE key-derivation rule, shared with the checkpoint format —
    # in-process exports and checkpoint-assembled exports must produce
    # identically-keyed trees
    from edl_tpu.runtime.checkpoint import _leaf_keys as ck

    return ck(tree)


def _cast(arr: np.ndarray, dtype: str) -> np.ndarray:
    """Cast float arrays to the export dtype; ints/bools pass through."""
    if arr.dtype.name not in _FLOATS or dtype == "none":
        return arr
    if dtype == "bfloat16":
        return arr.astype(_bf16())
    return arr.astype(np.dtype(dtype))


def _store_view(arr: np.ndarray) -> Tuple[np.ndarray, str]:
    """(npz-safe array, recorded dtype name)."""
    if arr.dtype == _bf16():
        return arr.view(np.uint16), "bfloat16"
    return arr, arr.dtype.name


def _write_export(
    root: str,
    step: int,
    flat: Dict[str, np.ndarray],
    dtype: str,
    source: str,
    model: Optional[Dict[str, Any]] = None,
) -> str:
    d = os.path.join(root, f"step-{step:08d}")
    os.makedirs(d, exist_ok=True)
    payload, dtypes, shapes = {}, {}, {}
    for key, arr in flat.items():
        arr = _cast(np.asarray(arr), dtype)
        stored, name = _store_view(arr)
        payload[key] = stored
        dtypes[key] = name
        shapes[key] = list(arr.shape)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    os.close(fd)
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, os.path.join(d, "params.npz"))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
    os.close(fd)
    with open(tmp, "w") as f:
        json.dump(
            {
                "step": step,
                "dtype": dtype,
                "dtypes": dtypes,
                "shapes": shapes,
                "source": source,
                # architecture record (e.g. LlamaConfig.to_meta()):
                # lets a consumer rebuild the model without the repo
                # config that trained it
                "model": model or {},
            },
            f,
        )
    os.replace(tmp, os.path.join(d, "manifest.json"))
    # the latest pointer is the publish: renamed into place LAST, so a
    # consumer never sees a half-written export. Monotonic max-write —
    # a slow writer (stalled background commit) must not regress the
    # pointer past a newer publish (same rule as worker_main's
    # ckpt_step); its dir stays unpointed and is reaped by the GC. The
    # lock makes the read-check-rename atomic among this process's
    # threads (the only concurrent writers: leader election is
    # per-process).
    with _publish_lock:
        cur = export_status(root)
        if cur is None or int(cur["step"]) < step:
            fd, tmp = tempfile.mkstemp(dir=root)
            with os.fdopen(fd, "w") as f:
                f.write(os.path.basename(d))
            os.replace(tmp, os.path.join(root, "latest"))
        _gc_exports(root, keep=2)
    return d


def _gc_exports(root: str, keep: int = 2) -> None:
    """Reap superseded export dirs (newest ``keep`` pointed-or-newer
    survive) — without this every commit leaks a full model copy."""
    import shutil

    doc = export_status(root)
    if doc is None:
        return
    pointed = os.path.basename(doc["_dir"])
    dirs = sorted(d for d in os.listdir(root) if d.startswith("step-"))
    # keep the pointed dir, the newest keep-1 others at or below it,
    # and anything newer (an in-progress publish about to take over)
    older = [d for d in dirs if d <= pointed and d != pointed]
    victims = older[: max(0, len(older) - (keep - 1))]
    for d in victims:
        shutil.rmtree(os.path.join(root, d), ignore_errors=True)


def export_params(
    root: str,
    params: Any,
    step: int,
    dtype: str = "bfloat16",
    source: str = "in-process",
    model_meta: Optional[Dict[str, Any]] = None,
) -> str:
    """Export an in-process (possibly device-resident) param tree.
    Returns the export step directory."""
    import jax

    flat = {}
    for key, leaf in _leaf_keys(params):
        flat[key] = np.asarray(jax.device_get(leaf))
    return _write_export(root, step, flat, dtype, source, model=model_meta)


def export_from_checkpoint(
    ckpt_root: str, export_root: str, dtype: str = "bfloat16", ram=None,
    model_meta: Optional[Dict[str, Any]] = None,
) -> Optional[str]:
    """Assemble the params (only) of the newest committed sharded
    checkpoint into a servable export — the commit-leader path for
    param-sharded (fsdp) jobs where no single process holds the model.
    Host-side file work; no devices, no collectives. ``ram`` (a
    LocalSnapshot at the same step) serves this rank's pieces from
    memory instead of re-reading its own just-written shards. Returns
    the export dir, or None without a committed checkpoint."""
    from edl_tpu.runtime import checkpoint as ckpt

    manifest = ckpt.latest_manifest(ckpt_root)
    if manifest is None:
        return None
    step = int(manifest["step"])
    cur = export_status(export_root)
    if cur is not None and int(cur["step"]) >= step:
        return None  # already exported this (or a newer) step
    if ram is not None and ram.step != step:
        ram = None  # stale snapshot: trust only manifest-listed files
    index = ckpt._PieceIndex(manifest, ram)
    try:
        flat = {}
        for fq, shape in manifest["shapes"].items():
            if not fq.startswith("p:"):
                continue  # params only: optimizer state never ships
            shape = tuple(shape)
            arr = index.assemble(
                fq,
                tuple(slice(None) for _ in shape),
                shape,
                np.dtype(manifest["dtypes"][fq]),
            )
            flat[fq[2:]] = arr
    finally:
        index.close()
    return _write_export(
        export_root, step, flat, dtype, source=f"checkpoint:{ckpt_root}",
        model=model_meta,
    )


def export_status(root: str) -> Optional[Dict[str, Any]]:
    """Manifest of the latest published export (with ``_dir``), or
    None. The ``latest`` pointer is authoritative — unpointed step dirs
    are in-progress or abandoned."""
    ptr = os.path.join(root, "latest")
    if not os.path.exists(ptr):
        return None
    with open(ptr) as f:
        name = f.read().strip()
    mpath = os.path.join(root, name, "manifest.json")
    if not os.path.exists(mpath):
        return None
    with open(mpath) as f:
        doc = json.load(f)
    doc["_dir"] = os.path.join(root, name)
    return doc


def _iter_param_leaves(doc):
    """Yield (key-parts, np array) for every leaf of an export — THE
    npz/bf16/key-path decoding rule, shared by every load path. The zip
    stays open across the sweep, so a concurrent GC delete (POSIX
    unlink of an open file) cannot truncate a load mid-tree; the race
    window is only the open, which :func:`_load_latest` retries."""
    with np.load(os.path.join(doc["_dir"], "params.npz")) as z:
        for key in z.files:
            arr = z[key]
            if doc["dtypes"].get(key) == "bfloat16":
                arr = arr.view(_bf16())
            yield key.split("/"), arr


def _tree_insert(tree: Dict[str, Any], parts, leaf) -> None:
    node = tree
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = leaf


def _restore_lists(node):
    """Flat leaf paths erase the dict-vs-list distinction (a list index
    flattens to its decimal string): rebuild any {'0': .., '1': ..}
    dense integer-keyed dict as the list the model structure actually
    has (e.g. ctr's params['mlp'] layer stack) — a consumer's
    ``for layer in params['mlp']`` must iterate layers, not key
    strings."""
    if isinstance(node, dict):
        node = {k: _restore_lists(v) for k, v in node.items()}
        # exact reconstruction test: the key set must be precisely
        # {"0", ..., "n-1"} (canonical decimal — "00" or unicode digits
        # are NOT list indices and must stay a dict)
        if node and set(node) == {str(i) for i in range(len(node))}:
            return [node[str(i)] for i in range(len(node))]
    return node


def _load_latest(root: str, build):
    """(build(doc), doc) against the latest pointer, retrying when the
    keep=2 GC deletes the pointed dir between the pointer read and the
    npz open (a trainer publishing continuously makes this race real —
    every consumer gets the retry, not just the CLI fetch)."""
    doc = export_status(root)
    for _ in range(5):
        if doc is None:
            raise FileNotFoundError(f"no published export under {root}")
        try:
            return build(doc), doc
        except FileNotFoundError:
            newer = export_status(root)
            if newer is None or newer["_dir"] == doc["_dir"]:
                raise
            doc = newer
    raise FileNotFoundError(f"export under {root} kept vanishing mid-load")


def load_export_sharded(root: str, mesh, pspecs) -> Tuple[Any, Dict[str, Any]]:
    """(params tree, manifest) of the latest export, loaded DIRECTLY
    onto a device mesh: every leaf is placed with its PartitionSpec via
    ``jax.make_array_from_callback``, so each device materializes only
    its own shard — the serving path for exports bigger than one chip's
    HBM (a bf16 llama3-8b export is ~16 GB; a v5e chip has 16 GB).
    Host RAM touches one full leaf at a time (the npz read), never the
    whole tree at once.

    ``pspecs`` is a pytree of PartitionSpec mirroring the param tree —
    reuse the model's training layout (e.g.
    ``llama.param_pspecs(cfg, plan)``) — or a callable ``doc ->
    pspecs`` evaluated against the SAME manifest the params load from
    (so an architecture read and its weights cannot come from different
    exports when a publish lands mid-call); leaves missing from it load
    replicated. Reference analog: the serving consumer of
    save_inference_model (/root/reference/example/ctr/ctr/train.py:
    169-180), which had no multi-device story at all."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def build(doc):
        tree = pspecs(doc) if callable(pspecs) else pspecs

        def spec_for(parts) -> P:
            # descends dicts AND lists: pspecs trees mirror the param
            # structure, and several models carry list-valued layer
            # stacks (resnet 'stages', ctr 'mlp' — the same structures
            # _restore_lists rebuilds); a list node indexes by the
            # decimal leaf-path part, so those leaves shard instead of
            # silently falling back to replicated (ADVICE r4)
            node = tree
            for p in parts:
                if isinstance(node, (list, tuple)):
                    try:
                        node = node[int(p)]
                    except (ValueError, IndexError):
                        return P()
                elif isinstance(node, dict) and p in node:
                    node = node[p]
                else:
                    return P()
            return node if node is not None else P()

        params: Dict[str, Any] = {}
        for parts, arr in _iter_param_leaves(doc):
            sharding = NamedSharding(mesh, spec_for(parts))
            garr = jax.make_array_from_callback(
                arr.shape, sharding, lambda idx, a=arr: a[idx]
            )
            _tree_insert(params, parts, garr)
            del arr  # one full leaf on host at a time
        return _restore_lists(params)

    return _load_latest(root, build)


def load_export(root: str) -> Tuple[Any, Dict[str, Any]]:
    """(params tree, manifest) of the latest export. The tree is a
    nested dict rebuilt from the flat leaf paths — exactly the structure
    every model's ``forward`` consumes; a serving process needs no
    TrainState, optimizer, or mesh."""

    def build(doc):
        params: Dict[str, Any] = {}
        for parts, arr in _iter_param_leaves(doc):
            _tree_insert(params, parts, arr)
        return _restore_lists(params)

    return _load_latest(root, build)
