"""P2P restore plane — the worker-side brokering of shard_server.py.

The transfer mechanics live in ``runtime/shard_server.py`` (serve /
probe / fetch) and the assembly in ``runtime/checkpoint.py``
(``load_from_pieces``). This module is the PROTOCOL between them, from
the elastic worker's point of view (extracted from worker_main per
VERDICT r4 #4 — the epoch-loop module should orchestrate, not broker):

- one :class:`P2PRestorePlane` per worker process: starts the shard
  server over the worker's live RAM snapshot, establishes the per-job
  auth token in coordinator KV, publishes this worker's address;
- rank 0 maintains the job's server roster (single writer per epoch)
  and decides each epoch's restore source: the NEWEST step whose pieces
  (peers ∪ own RAM) tile the full state — geometric coverage,
  ``checkpoint.peer_coverage_ok`` — and is no older than the committed
  manifest; the decision is published for every restorer to follow;
- a worker that fails ASSEMBLING a decided step vetoes it (one KV key
  per step — blind, raceless writes) so the regroup's next decision
  falls through to the manifest instead of re-picking a doomed step;
- a departing worker lingers serving its snapshot until the new world
  confirms a restored step covering it (bounded by ``p2p_linger_s``,
  extended while a peer is mid-fetch) — the drain window of a
  migration to a disjoint worker set.

Epoch-scoped KV writes here (the restore decision) route through the
worker's :class:`~edl_tpu.runtime.epoch_gc.EpochKeyGC` ledger with
``defer_late`` — same-epoch peers still poll them after rank 0's own
drain point (the round-4 foot-gun the ledger documents).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from edl_tpu.utils.logging import kv_logger

log = kv_logger("p2p")

_POLL_S = 0.02
_VETO_TTL_EPOCHS = 4


def _veto_active(raw: Optional[str], epoch: int) -> bool:
    """Whether a per-step p2p veto KV value (the epoch it was written)
    is still in force. One key PER STEP, written blindly on failure:
    writes for different steps never race each other, so no veto can be
    lost to a read-modify-write interleaving (a single set-valued key
    would let a straggler's stale write resurrect a doomed step).
    Malformed values read as expired rather than wedging the decision."""
    if not raw:
        return False
    try:
        return epoch - int(raw) <= _VETO_TTL_EPOCHS
    except ValueError:
        return False


class P2PRestorePlane:
    """Worker-side P2P brokering: server lifecycle, roster, restore
    decision, veto, linger. ``key_fn`` is the worker's job-scoped KV
    key builder; ``get_snapshot`` returns the worker's CURRENT host-RAM
    snapshot (the server follows it across reshards); ``gc`` is the
    worker's epoch-key ledger."""

    def __init__(
        self,
        cfg,
        key_fn: Callable[..., str],
        gc,
        get_snapshot: Callable[[], Any],
    ):
        self.cfg = cfg
        self._k = key_fn
        self._gc = gc
        self._get_snapshot = get_snapshot
        self.server = None
        self.token: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self, client) -> None:
        """Start serving our snapshot and publish address + per-job
        token (ADVICE r4: the weight plane is gated by 'can read the
        job KV', not 'can reach the port'). First worker to look writes
        the token; everyone converges on the KV value (re-read after
        write — last write wins for all)."""
        if not self.cfg.p2p:
            return
        from edl_tpu.runtime.shard_server import ShardServer

        tok = client.kv_get(self._k("p2p_token"))
        if not tok:
            import secrets

            client.kv_put(self._k("p2p_token"), secrets.token_hex(16))
            tok = client.kv_get(self._k("p2p_token"))
        # written once at bring-up before any probe/server thread can
        # read it (start() precedes roster publication); immutable after
        # edl: no-lint[lockset-race]
        self.token = tok
        self.server = ShardServer(
            self._get_snapshot,
            check_token=lambda t: bool(t) and t == self.token,
        )
        client.kv_put(
            self._k("shardsrv", self.cfg.worker_id),
            f"{os.environ.get('EDL_HOST_ADDR', '127.0.0.1')}:"
            f"{self.server.port}",
        )

    # -- roster + probing ----------------------------------------------------

    def merge_roster(self, cl, members) -> list:
        """Rank 0 unions the current members into the job's shard-server
        roster (single writer per epoch: no read-modify-write races).
        Departed workers stay listed while recent — exactly the window
        in which a migration needs to find their lingering servers —
        and age out of the 16-name cap."""
        names = json.loads(cl.kv_get(self._k("shardsrv_names")) or "[]")
        for m in members:
            if m.name in names:
                names.remove(m.name)  # refresh recency
            names.append(m.name)
        # cap covers every CURRENT member (they sit at the tail, so the
        # cap can never age out a live worker's only addr publication)
        cap = max(16, len(members))
        for dropped in names[:-cap]:  # GC aged-out workers' addr keys
            cl.kv_del(self._k("shardsrv", dropped))
        names = names[-cap:]
        cl.kv_put(self._k("shardsrv_names"), json.dumps(names))
        return names

    def probe_peers(self, cl) -> Dict[str, Any]:
        """{name: (addr, step, entries)} for every reachable shard
        server on the roster except our own. Probes run in parallel —
        dead entries cost one bounded connect timeout, not a serial
        scan."""
        from edl_tpu.runtime.shard_server import fetch_index

        names = json.loads(cl.kv_get(self._k("shardsrv_names")) or "[]")
        out: Dict[str, Any] = {}
        lock = threading.Lock()

        def probe(name, addr):
            got = fetch_index(addr, timeout_s=1.0, token=self.token)
            if got is not None and got[0] >= 0:
                with lock:
                    out[name] = (addr, got[0], got[1])

        threads = []
        for name in names:
            if name == self.cfg.worker_id:
                continue
            addr = cl.kv_get(self._k("shardsrv", name))
            if not addr:
                continue
            t = threading.Thread(target=probe, args=(name, addr), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(5.0)
        with lock:
            # a straggler thread (slow peer past the bounded join) must
            # not mutate the dict the caller is iterating
            return dict(out)

    # -- the restore ---------------------------------------------------------

    def restore(
        self, cl, epoch, rank, members, like, state_sh, manifest,
        ram_snapshot,
    ):
        """Restore from peers' RAM snapshots over the drain window
        (VERDICT r3 #5). Rank 0 probes the roster, picks the NEWEST
        step whose pieces (peers + its own RAM) tile the full state and
        is at least as new as the committed manifest, and publishes the
        decision; everyone assembles that step from own-RAM + manifest
        (same step) + peer pieces (prefetched in one parallel pass).
        Returns None when the decision is to use disk/fresh (callers
        fall through)."""
        from edl_tpu.runtime import checkpoint as ckpt
        from edl_tpu.runtime.shard_server import RemotePieces

        # converge on the job token (a cold-start write race can leave
        # an early worker holding the losing value; KV is the truth)
        self.token = cl.kv_get(self._k("p2p_token")) or self.token
        dkey = self._k("restore", str(epoch))
        peers = None
        if rank == 0:
            self.merge_roster(cl, members)
            peers = self.probe_peers(cl)
            own = ram_snapshot
            m_step = int(manifest["step"]) if manifest is not None else -1
            cand = sorted(
                {s for (_, s, _) in peers.values()}
                | ({own.step} if own is not None else set()),
                reverse=True,
            )
            decision = "none"
            for s in cand:
                if s < m_step:
                    break  # never restore older than the committed truth
                # a worker that failed ASSEMBLING step s vetoed it
                # (peer advertised pieces but fetches failed) —
                # otherwise a deterministic decision re-picks the
                # doomed step every regroup until the failure abort,
                # even though the manifest fallback was available
                # (ADVICE r4). NO GC delete of expired veto keys: a
                # read-then-delete could race a straggler's fresh
                # blind write; boundedness comes from rarity.
                if _veto_active(
                    cl.kv_get(self._k("p2p_veto", str(s))), epoch
                ):
                    continue
                entries = [
                    e
                    for (_, ps, es) in peers.values()
                    if ps == s
                    for e in es
                ]
                if own is not None and own.step == s:
                    entries += [
                        ckpt._piece_key(k, o, tuple(a.shape))
                        for k, plist in own.pieces.items()
                        for o, a in plist
                    ]
                if ckpt.peer_coverage_ok(like, entries):
                    decision = f"p2p:{s}"
                    break
            cl.kv_put(dkey, decision)
        else:
            deadline = time.monotonic() + self.cfg.rendezvous_timeout_s
            rank0 = next((m.name for m in members if m.rank == 0), None)
            decision = cl.kv_get(dkey)
            while decision is None:
                # bail fast instead of burning the whole rendezvous
                # timeout: a DEAD rank 0 can never publish (same rule
                # as _await_go), and an epoch bump means the group is
                # regrouping anyway — unlike a step verb, an unpublished
                # RESTORE decision cannot have a collective in flight,
                # so abandoning it strands nobody
                cl.expire()
                if rank0 not in {m.name for m in cl.members()}:
                    raise RuntimeError(
                        "rank-0 worker died before the restore decision"
                    )
                if cl.epoch() != epoch:
                    raise RuntimeError(
                        "membership moved before the restore decision"
                    )
                if time.monotonic() > deadline:
                    raise TimeoutError("no restore decision from rank 0")
                time.sleep(_POLL_S)
                decision = cl.kv_get(dkey)
        # GC one epoch LATE (defer_late): rank 0 reaches the next GC
        # point while same-epoch peers may still be polling this key —
        # deleting it now would strand them for the full timeout
        self._gc.defer_late(dkey)
        # observability (tests/monitor): how the LAST restore happened
        if rank == 0:
            cl.kv_put(self._k("restore_last"), decision)
        if not decision.startswith("p2p:"):
            return None
        step = int(decision[4:])
        if peers is None:
            peers = self.probe_peers(cl)
        remotes = [
            RemotePieces(addr, entries, token=self.token)
            for (addr, s, entries) in peers.values()
            if s == step
        ]
        try:
            state = ckpt.load_from_pieces(
                step, like, state_sh,
                ram=ram_snapshot,
                manifest=manifest,
                remotes=remotes,
            )
        except Exception:
            # veto this step so the regroup's next decision falls
            # through to the manifest instead of re-picking it (the
            # veto key is NOT epoch-scoped: it must outlive this epoch;
            # one key per step — a blind, raceless write)
            try:
                cl.kv_put(self._k("p2p_veto", str(step)), str(epoch))
            except Exception as ve:
                # a lost veto means the regroup may re-pick this dead
                # step — loud, not silent (edl check silent-failure)
                log.warn(
                    "p2p veto publish failed; regroup may retry step",
                    step=step, error=str(ve),
                )
            raise
        finally:
            for r in remotes:
                r.close()
        log.info("restored via p2p", step=step, peers=len(remotes))
        return state

    # -- drain-window linger -------------------------------------------------

    def linger(self, cl) -> None:
        """Drain-window P2P: after deregistering (so the new epoch can
        form), keep the process alive serving our RAM snapshot until the
        new world confirms it restored a step >= ours — the data plane
        of a migration to a disjoint worker set. Bounded by
        p2p_linger_s, extended while a peer is actively fetching."""
        snap = self._get_snapshot()
        srv = self.server
        if not self.cfg.p2p or snap is None or srv is None:
            return
        deadline = time.monotonic() + self.cfg.p2p_linger_s
        while True:
            try:
                restored = int(cl.kv_get(self._k("restored_step")) or "-1")
            except Exception as e:
                # coordinator gone: the job is over — but exiting the
                # drain window mid-migration is worth one warn line on
                # the timeline (edl check silent-failure)
                log.warn("coordinator unreachable during p2p linger; "
                         "departing", error=str(e))
                return
            if restored >= snap.step:
                return
            if time.monotonic() > deadline and srv.active == 0:
                log.warn(
                    "departing without restore confirmation",
                    snapshot_step=snap.step,
                    restored_step=restored,
                )
                return
            time.sleep(0.1)
