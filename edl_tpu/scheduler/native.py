"""ctypes binding for the native (C++) scheduler planning core.

The reference's scheduler runs compiled (Go); here the dry-run fixed
point has a C++ twin (native/scheduler/sched.cc) kept semantically
identical to the Python planner in scheduler/autoscaler.py. The
Autoscaler uses it when available (``use_native=True``) and falls back
to Python silently — plans are interchangeable by construction
(cross-checked in tests/test_native_sched.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional

from edl_tpu.cluster.resource import ClusterResource
from edl_tpu.utils.logging import kv_logger

log = kv_logger("sched.native")

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "scheduler",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libedl_sched.so")

_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

POLICY_IDS = {"flexible": 0, "pow2": 1}


def ensure_native_built() -> bool:
    if os.path.exists(_LIB_PATH):
        return True
    with _build_lock:
        if os.path.exists(_LIB_PATH):
            return True
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
            return True
        except Exception as e:
            log.warn("native scheduler build failed", error=str(e))
            return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not ensure_native_built():
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    I64P = ctypes.POINTER(ctypes.c_int64)
    lib.edl_sched_plan.restype = ctypes.c_int
    lib.edl_sched_plan.argtypes = (
        [ctypes.c_int64] + [I64P] * 6          # jobs
        + [ctypes.c_int64] + [I64P] * 3        # hosts
        + [ctypes.c_int64] * 6                 # totals
        + [ctypes.c_double, ctypes.c_int32, I64P]
    )
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def plan_native(
    jobs: List,  # List[JobState] (scheduler.autoscaler)
    r: ClusterResource,
    max_load_desired: float,
    policy_name: str = "flexible",
) -> Optional[Dict[str, int]]:
    """Plan deltas with the native core; None when unavailable (caller
    falls back to the Python planner). ``r`` is not mutated."""
    lib = _load()
    if lib is None:
        return None
    pid = POLICY_IDS.get(policy_name)
    if pid is None:
        return None  # custom Python policy: only the Python planner knows it

    n = len(jobs)
    arr = lambda vals: (ctypes.c_int64 * len(vals))(*vals)
    job_min = arr([j.config.spec.worker.min_replicas for j in jobs])
    job_max = arr([j.config.spec.worker.max_replicas for j in jobs])
    job_par = arr([j.group.parallelism if j.group else 0 for j in jobs])
    job_chip = arr([j.chips_per_worker() for j in jobs])
    job_cpu = arr([j.cpu_request_milli() for j in jobs])
    job_mem = arr([j.mem_request_mega() for j in jobs])

    host_names = sorted(r.hosts.cpu_idle_milli)
    host_cpu = arr([r.hosts.cpu_idle_milli[h] for h in host_names])
    host_mem = arr([r.hosts.mem_free_mega.get(h, 0) for h in host_names])
    host_chip = arr([r.hosts.chips_free.get(h, 0) for h in host_names])

    out = (ctypes.c_int64 * n)()
    rc = lib.edl_sched_plan(
        n, job_min, job_max, job_par, job_chip, job_cpu, job_mem,
        len(host_names), host_cpu, host_mem, host_chip,
        r.chip_total, r.chip_limit,
        r.cpu_total_milli, r.cpu_request_milli,
        r.mem_total_mega, r.mem_request_mega,
        max_load_desired, pid, out,
    )
    if rc != 0:
        log.warn("native planner returned error", rc=rc)
        return None
    return {jobs[i].config.qualified_name: int(out[i]) for i in range(n)}
