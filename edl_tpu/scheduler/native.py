"""ctypes binding for the native (C++) scheduler planning core.

The reference's scheduler runs compiled (Go); here the dry-run fixed
point has a C++ twin (native/scheduler/sched.cc) kept semantically
identical to the Python planner in scheduler/autoscaler.py. The
Autoscaler uses it when available (``use_native=True``) and falls back
to Python silently — plans are interchangeable by construction
(cross-checked in tests/test_native_sched.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional

from edl_tpu.cluster import topology
from edl_tpu.cluster.resource import ClusterResource
from edl_tpu.utils.logging import kv_logger

log = kv_logger("sched.native")

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "scheduler",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libedl_sched.so")

_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

_SOURCES = ("sched.h", "sched.cc", "capi.cc", "Makefile")


def _lib_fresh() -> bool:
    """True when the built .so is newer than every source — the fast
    path that keeps routine planning from shelling out to make; a stale
    .so (old ABI) fails this and triggers a rebuild."""
    if not os.path.exists(_LIB_PATH):
        return False
    so_m = os.path.getmtime(_LIB_PATH)
    for s in _SOURCES:
        p = os.path.join(_NATIVE_DIR, s)
        if os.path.exists(p) and os.path.getmtime(p) > so_m:
            return False
    return True


def ensure_native_built() -> bool:
    if _lib_fresh():
        return True
    with _build_lock:  # threads of THIS process
        if _lib_fresh():
            return True
        try:
            # cross-PROCESS exclusion: concurrent controllers/workers
            # after a source change must not race make on one build dir
            # (a half-linked .so would be dlopen'd by the loser)
            import fcntl

            os.makedirs(os.path.join(_NATIVE_DIR, "build"), exist_ok=True)
            with open(os.path.join(_NATIVE_DIR, "build", ".lock"), "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                if not _lib_fresh():
                    subprocess.run(
                        ["make", "-C", _NATIVE_DIR],
                        check=True,
                        capture_output=True,
                        timeout=120,
                    )
            return True
        except Exception as e:
            log.warn("native scheduler build failed", error=str(e))
            return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not ensure_native_built():
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    I64P = ctypes.POINTER(ctypes.c_int64)
    I32P = ctypes.POINTER(ctypes.c_int32)
    lib.edl_sched_plan.restype = ctypes.c_int
    lib.edl_sched_plan.argtypes = (
        [ctypes.c_int64] + [I64P] * 6          # jobs: min/max/par/chip/cpu/mem
        + [I32P, I64P, I32P]                   # policy kind/cap/contiguous
        + [ctypes.c_int64] + [I64P] * 5        # hosts: cpu/mem/chip/block/index
        + [ctypes.c_int64] * 6                 # totals
        + [ctypes.c_double, I64P]
    )
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def _policy_triple(policy) -> Optional[tuple]:
    """(kind, cap, contiguous) for a native-expressible policy, else
    None (a custom Python callable only the Python planner can run)."""
    if policy is topology.flexible:
        return (0, 0, 0)
    if policy is topology.pow2:
        return (1, 0, 0)
    if isinstance(policy, topology.SliceShapePolicy):
        return (1, policy.cap, 1 if policy.contiguous else 0)
    return None


def plan_native(
    jobs: List,  # List[JobState] (scheduler.autoscaler)
    r: ClusterResource,
    max_load_desired: float,
    policies: List,  # one resolved SlicePolicy per job
) -> Optional[Dict[str, int]]:
    """Plan deltas with the native core; None when unavailable or any
    job's policy is not native-expressible (caller falls back to the
    Python planner). ``r`` is not mutated."""
    lib = _load()
    if lib is None:
        return None
    triples = [_policy_triple(p) for p in policies]
    if any(t is None for t in triples):
        return None

    n = len(jobs)
    arr = lambda vals: (ctypes.c_int64 * len(vals))(*vals)
    arr32 = lambda vals: (ctypes.c_int32 * len(vals))(*vals)
    job_min = arr([j.config.spec.worker.min_replicas for j in jobs])
    job_max = arr([j.config.spec.worker.max_replicas for j in jobs])
    job_par = arr([j.group.parallelism if j.group else 0 for j in jobs])
    job_chip = arr([j.chips_per_worker() for j in jobs])
    job_cpu = arr([j.cpu_request_milli() for j in jobs])
    job_mem = arr([j.mem_request_mega() for j in jobs])
    job_kind = arr32([t[0] for t in triples])
    job_cap = arr([t[1] for t in triples])
    job_contig = arr32([t[2] for t in triples])

    host_names = sorted(r.hosts.cpu_idle_milli)
    host_cpu = arr([r.hosts.cpu_idle_milli[h] for h in host_names])
    host_mem = arr([r.hosts.mem_free_mega.get(h, 0) for h in host_names])
    host_chip = arr([r.hosts.chips_free.get(h, 0) for h in host_names])
    # block ids ascend in block-NAME order so the C++ std::map walk
    # matches Python's sorted(by_block) iteration
    block_ids = {
        b: i for i, b in enumerate(sorted(set(r.hosts.ici_block.values())))
    }
    host_block = arr(
        [block_ids.get(r.hosts.ici_block.get(h), -1) for h in host_names]
    )
    host_index = arr([r.hosts.ici_index.get(h, -1) for h in host_names])

    out = (ctypes.c_int64 * n)()
    rc = lib.edl_sched_plan(
        n, job_min, job_max, job_par, job_chip, job_cpu, job_mem,
        job_kind, job_cap, job_contig,
        len(host_names), host_cpu, host_mem, host_chip, host_block, host_index,
        r.chip_total, r.chip_limit,
        r.cpu_total_milli, r.cpu_request_milli,
        r.mem_total_mega, r.mem_request_mega,
        max_load_desired, out,
    )
    if rc != 0:
        log.warn("native planner returned error", rc=rc)
        return None
    return {jobs[i].config.qualified_name: int(out[i]) for i in range(n)}
