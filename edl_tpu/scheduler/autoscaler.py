"""Autoscaler — retargets elastic jobs' worker counts to keep the fleet loaded.

Faithful port of the reference scaling algorithm
(reference: pkg/autoscaler.go:201-337,451-511) onto the TPU resource
model: the GPU trio becomes TPU chips (exclusively allocated, scaled to
full), CPU keeps the ``max_load_desired`` headroom guard, memory keeps
the hard guard, and host search gains a free-chip check. A pluggable
slice policy (edl_tpu.cluster.topology) restricts worker counts to
ICI-legal slice shapes — under the default ``flexible`` policy the
algorithm is step-for-step the reference's.

Algorithm per tick (reference: Run, pkg/autoscaler.go:451-485):
  census → pending-job check → candidate set → iterative dry-run to a
  fixed point (scale-up pass over most-starved first, scale-down pass
  over least-starved first) → apply new parallelism with retries.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from edl_tpu.api.job import Event, TrainingJob
from edl_tpu.cluster import topology
from edl_tpu.cluster.base import Cluster, ConflictError, WorkerGroup
from edl_tpu.cluster.resource import ClusterResource
from edl_tpu.utils.logging import kv_logger

log = kv_logger("autoscaler")

DEFAULT_LOOP_SECONDS = 5.0  # reference: defaultLoopDur pkg/autoscaler.go:31
UPDATE_RETRIES = 5  # reference: pkg/autoscaler.go:346


class HysteresisGate:
    """Per-key rescale damping — the cooldown machinery shared by the
    cluster autoscaler's job-retarget loop and the serving fleet's
    replica scaler (edl_tpu/serving/fleet.py).

    Both loops have the same failure mode: a marginal signal flips the
    decision every tick and each flip is expensive (a reshard stall for
    training, a replica drain+spawn for serving). The gate admits an
    action for ``key`` only when at least ``cooldown_s`` has elapsed
    since that key's last :meth:`record`; ``cooldown_s == 0`` admits
    everything (the undamped reference behavior). Callers may bypass
    the gate when an urgency signal says churn is the lesser evil
    (pending pods for training, an SLO breach for serving)."""

    def __init__(self, cooldown_s: float, clock=time.monotonic):
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        self.cooldown_s = cooldown_s
        self.clock = clock
        self._last: Dict[str, float] = {}

    def ready(self, key: str = "") -> bool:
        if self.cooldown_s <= 0:
            return True
        return (
            self.clock() - self._last.get(key, -1e18) >= self.cooldown_s
        )

    def record(self, key: str = "") -> None:
        self._last[key] = self.clock()


class ScaleGate:
    """The one damped scale-decision pipeline every elastic consumer
    shares: ``decide → (cooldown or bypass) → act → record``.

    Before this class the pattern lived copy-pasted in the serving
    fleet's :class:`~edl_tpu.serving.fleet.FleetScaler` and inline in
    the elasticity controller's handover loop — each re-implementing
    the same four lines around a :class:`HysteresisGate` and each free
    to drift (forget the record, invert the bypass). ``apply`` owns the
    sequencing; callers supply only the pure ``decide`` (returns an
    action label or None) and the side-effecting ``act``.

    ``bypass`` is the urgency escape hatch: when it returns True the
    cooldown is ignored (pending pods for training, an SLO breach for
    serving — churn is the lesser evil once users are hurting)."""

    def __init__(
        self,
        key: str,
        cooldown_s: float,
        clock=time.monotonic,
        bypass: Optional[Callable[[], bool]] = None,
    ):
        self.key = key
        self.gate = HysteresisGate(cooldown_s, clock=clock)
        self.bypass = bypass

    def apply(
        self,
        decide: Callable[[], Optional[str]],
        act: Callable[[str], None],
    ) -> Optional[str]:
        """One damped decision. Returns the action applied, or None
        (nothing to do, or held by the cooldown)."""
        action = decide()
        if action is None:
            return None
        if not self.gate.ready(self.key) and not (
            self.bypass is not None and self.bypass()
        ):
            return None
        act(action)
        self.gate.record(self.key)
        return action


@dataclass
class JobState:
    """Autoscaler view of one job (reference: `job`, pkg/autoscaler.go:34-37)."""

    config: TrainingJob
    group: Optional[WorkerGroup] = None

    def chips_per_worker(self) -> int:
        """reference: TrainerGPULimit pkg/autoscaler.go:39-42."""
        return self.config.spec.worker.chips_per_worker

    def cpu_request_milli(self) -> int:
        """reference: TrainerCPURequestMilli pkg/autoscaler.go:44-47."""
        return self.config.spec.worker.resources.requests.cpu_milli

    def mem_request_mega(self) -> int:
        """reference: TrainerMemRequestMega pkg/autoscaler.go:49-52."""
        return self.config.spec.worker.resources.requests.mem_mega

    def fulfillment(self) -> float:
        """Elastic-range satisfaction in [0,1]
        (reference: Fulfillment pkg/autoscaler.go:54-64)."""
        lo = self.config.spec.worker.min_replicas
        hi = self.config.spec.worker.max_replicas
        if lo == hi:
            return 1.0
        cur = self.group.parallelism if self.group else 0
        return (cur - lo) / (hi - lo)


def elastic(j: JobState) -> bool:
    """reference: pkg/autoscaler.go:132-134."""
    return j.config.elastic()


def needs_chips(j: JobState) -> bool:
    """TPU analog of the gpu filter (reference: pkg/autoscaler.go:137-139)."""
    return j.config.need_tpu()


def sorted_jobs(js: List[JobState], *filters: Callable[[JobState], bool]) -> List[JobState]:
    """Ascending by fulfillment; ties by chips, then CPU, then memory asc
    (reference: sortedJobs + jobs.Less, pkg/autoscaler.go:103-125,175-189)."""
    out = [j for j in js if all(f(j) for f in filters)]
    out.sort(
        key=lambda j: (
            j.fulfillment(),
            j.chips_per_worker(),
            j.cpu_request_milli(),
            j.mem_request_mega(),
        )
    )
    return out


def resolve_policy(policy, j: JobState) -> topology.SlicePolicy:
    """Per-job slice legality: the string ``"auto"`` derives it from the
    job's own accelerator_type (VERDICT r1 #5 — the reference applies
    one global rule to all jobs); a callable applies to every job."""
    if policy == "auto":
        return topology.policy_for_job(
            j.config.spec.accelerator_type, j.chips_per_worker()
        )
    return policy


def _fits(r: ClusterResource, name: str, cpu: int, mem: int, chips: int) -> bool:
    return (
        cpu <= r.hosts.cpu_idle_milli.get(name, 0)
        and mem <= r.hosts.mem_free_mega.get(name, 0)
        and chips <= r.hosts.chips_free.get(name, 0)
    )


def _contiguous_window(
    r: ClusterResource, j: JobState, n: int
) -> Optional[List[str]]:
    """An index-aligned run of ``n`` hosts within ONE ICI block, each
    with capacity for one worker — the sub-slice carving rule (the new
    workers of a grow step must be ICI-reachable as a unit; the census
    carries block/index per host, resource.Hosts). Blocks in name order,
    window starts ascending: deterministic and native-twin-matched."""
    cpu, mem, chips = (
        j.cpu_request_milli(),
        j.mem_request_mega(),
        j.chips_per_worker(),
    )
    by_block: Dict[str, Dict[int, str]] = {}
    for host, block in r.hosts.ici_block.items():
        by_block.setdefault(block, {})[r.hosts.ici_index.get(host, -1)] = host
    for block in sorted(by_block):
        idxs = by_block[block]
        for start in sorted(i for i in idxs if i >= 0 and i % n == 0):
            window = [idxs.get(start + k) for k in range(n)]
            if None in window:
                continue
            if all(_fits(r, h, cpu, mem, chips) for h in window):
                return window  # type: ignore[return-value]
    return None


def search_assignable_hosts(
    r: ClusterResource, j: JobState, n: int, contiguous: bool = False
) -> Optional[List[str]]:
    """Hosts (with multiplicity) that can absorb ``n`` more workers, or
    None if they don't all fit. Generalizes the reference's single-worker
    search for multi-worker slice-policy steps
    (reference: searchAssignableNode pkg/autoscaler.go:191-199).

    With ``contiguous`` (ICI-slice jobs) and a census that carries block
    topology, steps must be aligned windows inside one block — including
    single-host steps, which must still land ON a block (a DCN-only host
    can't join an ICI slice); a census without block info falls back to
    free placement (DCN-only fleets, and the reference-parity tests).
    """
    if contiguous and r.hosts.ici_block:
        return _contiguous_window(r, j, n)
    chips = j.chips_per_worker()
    cpu = j.cpu_request_milli()
    mem = j.mem_request_mega()
    free_cpu = dict(r.hosts.cpu_idle_milli)
    free_mem = dict(r.hosts.mem_free_mega)
    free_chip = dict(r.hosts.chips_free)
    placed: List[str] = []
    for _ in range(n):
        for name in sorted(free_cpu):
            if (
                cpu <= free_cpu[name]
                and mem <= free_mem.get(name, 0)
                and chips <= free_chip.get(name, 0)
            ):
                free_cpu[name] -= cpu
                free_mem[name] = free_mem.get(name, 0) - mem
                free_chip[name] = free_chip.get(name, 0) - chips
                placed.append(name)
                break
        else:
            return None
    return placed


def scale_dry_run(
    r: ClusterResource,
    j: JobState,
    cur_diff: int,
    max_load_desired: float,
    scale_down: bool,
    policy: topology.SlicePolicy = topology.flexible,
) -> int:
    """One dry-run step for one job; mutates ``r`` to account the proposed
    delta (reference: scaleDryRun pkg/autoscaler.go:201-291; the deferred
    resource adjustment there is the ``_account`` below).

    Returns the worker delta (±k; ±1 under the flexible policy).
    """
    cpu = j.cpu_request_milli()
    mem = j.mem_request_mega()
    chips = j.chips_per_worker()
    assigned_hosts: List[str] = []

    def _account(n: int) -> int:
        # reference: the deferred func at pkg/autoscaler.go:209-217
        r.chip_limit += chips * n
        r.cpu_request_milli += cpu * n
        r.mem_request_mega += mem * n
        for host in assigned_hosts:  # one entry per added worker
            r.hosts.cpu_idle_milli[host] -= cpu
            r.hosts.mem_free_mega[host] -= mem
            r.hosts.chips_free[host] -= chips
        return n

    planned = (j.group.parallelism if j.group else 0) + cur_diff
    hi = j.config.spec.worker.max_replicas
    lo = j.config.spec.worker.min_replicas

    if scale_down:
        # ---- scale-down pass (reference: pkg/autoscaler.go:230-249) ----
        if planned > hi:
            # over max: walk down one per fixed-point iteration
            # (reference: pkg/autoscaler.go:231-234); once within reach of
            # max, land on a policy-legal count.
            if planned - 1 > hi:
                return _account(-1)
            target = topology.floor_legal(planned - 1, policy, lo, hi)
            return _account(target - planned if target != planned else -1)
        chip_over = r.chip_limit > r.chip_total * max_load_desired
        cpu_over = r.cpu_request_milli > r.cpu_total_milli * max_load_desired
        if chip_over or cpu_over:
            if planned > lo:
                target = topology.next_legal(planned, -1, policy, lo, hi)
                return _account(target - planned)
            return 0  # cannot scale down further
        return 0  # not over target load: do not try to scale up here

    # ---- scale-up pass (reference: pkg/autoscaler.go:252-291) ----
    if planned >= hi:
        # clamp back to max, landing on a policy-legal count
        target = topology.floor_legal(planned, policy, lo, hi)
        return _account(min(target, hi) - planned)

    target = topology.next_legal(planned, +1, policy, lo, hi)
    step = target - planned
    if step <= 0:
        return 0

    if r.mem_total_mega - r.mem_request_mega <= mem * step:
        return 0  # insufficient memory (reference: :259-263)
    found = search_assignable_hosts(
        r, j, step, contiguous=getattr(policy, "contiguous", False)
    )
    if found is None:
        return 0  # the whole step must fit (reference: :264-267)

    # CPU respects the load ceiling; chips scale to full (reference
    # keeps GPU unguarded by maxLoadDesired, :269-278).
    cpu_ok = r.cpu_total_milli * max_load_desired - r.cpu_request_milli >= cpu * step
    if chips > 0 and not (r.chip_total - r.chip_limit >= chips * step):
        return 0
    if not cpu_ok:
        return 0
    assigned_hosts = found  # only account hosts for a step actually taken
    return _account(step)


def scale_all_jobs_dry_run(
    js: List[JobState],
    r: ClusterResource,
    max_load_desired: float,
    policy=topology.flexible,
) -> Dict[str, int]:
    """Iterate scale-up (most starved first) then scale-down (least starved
    first) passes until a fixed point (reference: scaleAllJobsDryRun
    pkg/autoscaler.go:296-337). Mutates ``r``; callers pass a copy.
    ``policy`` is a callable applied to every job, or ``"auto"`` for
    per-job resolution from accelerator_type."""
    diff: Dict[str, int] = {}
    # policies depend only on the static spec: resolve once, not per pass
    resolved = {j.config.qualified_name: resolve_policy(policy, j) for j in js}
    while True:
        no_change = True
        ordered = sorted_jobs(js, elastic)

        def dry_run(j: JobState, is_down: bool) -> None:
            nonlocal no_change
            name = j.config.qualified_name
            additional = scale_dry_run(
                r,
                j,
                diff.get(name, 0),
                max_load_desired,
                is_down,
                resolved[name],
            )
            log.debug(
                "dry run scale job",
                name=name,
                cur_diff=diff.get(name, 0),
                additional=additional,
            )
            diff[name] = diff.get(name, 0) + additional
            if additional != 0:
                no_change = False

        for j in ordered:
            dry_run(j, False)
        for j in reversed(ordered):
            dry_run(j, True)
        if no_change:
            break
    return diff


class Autoscaler:
    """Event-driven scaling loop (reference: Autoscaler pkg/autoscaler.go:67-95).

    ``tick()`` is the synchronous unit of work (one census + plan + apply);
    ``run()`` wraps it in the 5 s ticker/event loop.
    """

    def __init__(
        self,
        cluster: Cluster,
        max_load_desired: float = 1.0,  # reference default, pkg/autoscaler.go:89
        # a callable applied to every job (default: the reference's
        # unconstrained behavior), or "auto" to derive each job's slice
        # legality from its own spec.accelerator_type
        slice_policy=topology.flexible,
        loop_seconds: float = DEFAULT_LOOP_SECONDS,
        rescale_cooldown_s: float = 0.0,
        use_native: bool = False,
    ):
        # rescale_cooldown_s damps the reference algorithm's fulfillment
        # ping-pong (jobs trading one worker back and forth every tick):
        # a job rescaled less than cooldown ago is not retargeted unless
        # some job's pods are pending. 0 reproduces reference behavior.
        # No reference analog — on TPU every retarget is a reshard stall,
        # so churn is far more expensive than on k8s.
        self.cluster = cluster
        self.max_load_desired = max_load_desired
        self.slice_policy = slice_policy
        self.loop_seconds = loop_seconds
        self.rescale_cooldown_s = rescale_cooldown_s
        # plan with the C++ core (native/scheduler) when it is buildable
        # and the policy is a built-in; silently falls back to Python
        self.use_native = use_native
        self.jobs: Dict[str, JobState] = {}
        self._gate = HysteresisGate(rescale_cooldown_s)
        self._events: "queue.Queue[Event]" = queue.Queue()
        self._stop = threading.Event()

    # -- event intake (reference: OnAdd/OnUpdate/OnDel :159-171) -----------

    def on_add(self, job: TrainingJob) -> None:
        self._events.put(Event(Event.Type.ADD, job))

    def on_update(self, job: TrainingJob) -> None:
        self._events.put(Event(Event.Type.UPDATE, job))

    def on_del(self, job: TrainingJob) -> None:
        self._events.put(Event(Event.Type.DEL, job))

    # -- state maintenance -------------------------------------------------

    def _update_job_list(self, ev: Event) -> bool:
        """reference: updateJobList pkg/autoscaler.go:383-402."""
        if ev.type in (Event.Type.ADD, Event.Type.UPDATE):
            j = JobState(config=ev.job)
            self.jobs[ev.job.qualified_name] = j
            return self._retrieve_group(j)
        elif ev.type == Event.Type.DEL:
            self.jobs.pop(ev.job.qualified_name, None)
        return True

    def _retrieve_group(self, j: JobState) -> bool:
        """reference: tryToRetrieveTrainerJobInTrainingJob :424-447."""
        if j.group is None:
            try:
                j.group = self.cluster.get_worker_group(j.config)
            except KeyError:
                log.warn("worker group not yet created", job=j.config.name)
                return False
        return True

    def _find_pending_job(self) -> bool:
        """Any job with ALL pods pending? (reference: findPendingJob :406-422)."""
        for j in self.jobs.values():
            if not self._retrieve_group(j):
                continue
            total, _, pending = self.cluster.job_pods(j.config)
            if total > 0 and total == pending:
                return True
        return False

    def _any_pending_pods(self) -> bool:
        """Any worker pod pending anywhere — the cooldown-bypass signal
        (weaker than _find_pending_job's all-pods-pending)."""
        for j in self.jobs.values():
            if not self._retrieve_group(j):
                continue
            _, _, pending = self.cluster.job_pods(j.config)
            if pending > 0:
                return True
        return False

    def _reschedulable(self, have_pending: bool) -> List[JobState]:
        """Stable jobs (all pods running), or all jobs when something is
        pending (reference: findTrainingJobsMightBeRescheduled :487-511)."""
        out = []
        for j in self.jobs.values():
            if not self._retrieve_group(j):
                continue
            total, running, _ = self.cluster.job_pods(j.config)
            if total == running or have_pending:
                out.append(j)
        return out

    # -- the scaling tick --------------------------------------------------

    def drain_events(self) -> None:
        """Fold queued job events into the tracked set
        (reference: updateJobList on eventCh receipt :453-459)."""
        while True:
            try:
                self._update_job_list(self._events.get_nowait())
            except queue.Empty:
                return

    def tick(self) -> Dict[str, int]:
        """One census→plan→apply cycle; returns the applied target map
        (reference: the loop body of Run, pkg/autoscaler.go:460-484)."""
        self.drain_events()
        try:
            r = self.cluster.inquiry_resource()
        except Exception as e:  # reference: :461-465
            log.error("inquiry_resource failed", error=str(e))
            return {}
        # refresh group snapshots so fulfillment sees current parallelism
        for j in self.jobs.values():
            try:
                j.group = self.cluster.get_worker_group(j.config)
            except KeyError:
                j.group = None

        have_pending = self._find_pending_job()
        candidates = self._reschedulable(have_pending)
        if self.rescale_cooldown_s > 0 and not self._any_pending_pods():
            candidates = [
                j for j in candidates
                if self._gate.ready(j.config.qualified_name)
            ]
        diff = None
        if self.use_native:
            from edl_tpu.scheduler import native as native_sched

            resolved = [resolve_policy(self.slice_policy, j) for j in candidates]
            diff = native_sched.plan_native(
                candidates, r, self.max_load_desired, resolved
            )
        if diff is None:
            diff = scale_all_jobs_dry_run(
                candidates, r.copy(), self.max_load_desired, self.slice_policy
            )
        target = {
            name: self.jobs[name].group.parallelism + d
            for name, d in diff.items()
            if d != 0 and self.jobs.get(name) and self.jobs[name].group
        }
        if target:
            log.info("calculated scaling plan", target=target)
        self._scale_all(target)
        return target

    def _scale_all(self, target: Dict[str, int]) -> None:
        """reference: scaleAllJobs pkg/autoscaler.go:339-376."""
        for name, t in target.items():
            err: Optional[Exception] = None
            for _ in range(UPDATE_RETRIES):
                try:
                    group = self.cluster.get_worker_group(self.jobs[name].config)
                    if group.parallelism == t:
                        err = None
                        break
                    group.parallelism = t
                    self.cluster.update_worker_group(group)
                    self.jobs[name].group = group
                    self._gate.record(name)
                    accel = self.jobs[name].config.spec.accelerator_type
                    log.info(
                        "scaled job",
                        name=name,
                        target=t,
                        slice=topology.topology_name(accel, t)
                        if accel in topology.FAMILIES
                        else "",
                    )
                    err = None
                    break
                except (ConflictError, KeyError) as e:
                    err = e
            if err is not None:
                log.warn("error updating worker group", name=name, error=str(err))

    # -- loop --------------------------------------------------------------

    def run(self) -> None:
        """reference: Run pkg/autoscaler.go:451-485."""
        while not self._stop.is_set():
            try:
                self._update_job_list(self._events.get(timeout=self.loop_seconds))
            except queue.Empty:
                pass
            self.tick()

    def stop(self) -> None:
        self._stop.set()
