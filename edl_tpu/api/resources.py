"""Resource quantities and arithmetic.

TPU-native resource model. The reference accounts CPU/memory/GPU via k8s
``resource.Quantity`` maps (reference: pkg/cluster.go:32-61, pkg/utils.go:23-34).
Here the accelerator is TPU chips — an integral, exclusively-allocated
resource (like the reference's GPU *limit* accounting,
reference: pkg/autoscaler.go:39-42) — while host CPU (milli-cores) and
memory (MB) stay divisible request-style resources.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from fractions import Fraction
from typing import Dict, Mapping, Union

# Decimal and binary suffixes accepted by parse_quantity, as exact
# multipliers (k8s resource.Quantity grammar subset).
_SUFFIX = {
    "": Fraction(1),
    "m": Fraction(1, 1000),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "Ki": Fraction(2**10),
    "Mi": Fraction(2**20),
    "Gi": Fraction(2**30),
    "Ti": Fraction(2**40),
}

_QTY_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([a-zA-Z]*)\s*$")


def _parse_exact(value: Union[str, int, float]) -> Fraction:
    if isinstance(value, int):
        return Fraction(value)
    if isinstance(value, float):
        return Fraction(str(value))
    m = _QTY_RE.match(value)
    if not m:
        raise ValueError(f"invalid quantity: {value!r}")
    num, suffix = m.groups()
    if suffix not in _SUFFIX:
        raise ValueError(f"invalid quantity suffix: {value!r}")
    return Fraction(num) * _SUFFIX[suffix]


def parse_quantity(value: Union[str, int, float]) -> float:
    """Parse a k8s-style quantity string ("200m", "1k", "100Mi") to a float
    in base units. Mirrors the subset of ``resource.ParseQuantity`` the
    reference exercises (reference: pkg/autoscaler_internal_test.go:56-77).
    """
    return float(_parse_exact(value))


def cpu_milli(value: Union[str, int, float]) -> int:
    """CPU quantity → integer milli-cores, rounding up (Go
    ``Quantity.ScaledValue(resource.Milli)`` semantics: "1k" → 1_000_000).
    Exact (Fraction) arithmetic so "700m" is 700, never 701."""
    raw = _parse_exact(value) * 1000
    return -((-raw.numerator) // raw.denominator)


def mem_mega(value: Union[str, int, float]) -> int:
    """Memory quantity → integer megabytes (1e6), rounding up (Go
    ``ScaledValue(resource.Mega)`` semantics: "100Mi" → 105)."""
    raw = _parse_exact(value) / 10**6
    return -((-raw.numerator) // raw.denominator)


def chip_count(value: Union[str, int, float]) -> int:
    """TPU chip quantity → int. Chips are integral and exclusively
    allocated; fractional values are a spec error, not a truncation."""
    raw = _parse_exact(value)
    if raw.denominator != 1 or raw < 0:
        raise ValueError(f"tpu chips must be a non-negative integer, got {value!r}")
    return int(raw)


@dataclass
class ResourceSpec:
    """Per-replica resource ask.

    ``tpu_chips`` replaces the reference's ``alpha.kubernetes.io/nvidia-gpu``
    limit (reference: pkg/resource/training_job.go:194-207). Chips are
    exclusive: request == limit by construction.
    """

    cpu_milli: int = 0
    mem_mega: int = 0
    tpu_chips: int = 0

    @classmethod
    def parse(cls, d: Mapping) -> "ResourceSpec":
        """Parse a ``{cpu:, memory:, tpu:}`` mapping with k8s quantities."""
        if d is None:
            return cls()
        return cls(
            cpu_milli=cpu_milli(d.get("cpu", 0)),
            mem_mega=mem_mega(d.get("memory", 0)),
            tpu_chips=chip_count(d.get("tpu", d.get("tpu_chips", 0))),
        )

    def __add__(self, other: "ResourceSpec") -> "ResourceSpec":
        return ResourceSpec(
            self.cpu_milli + other.cpu_milli,
            self.mem_mega + other.mem_mega,
            self.tpu_chips + other.tpu_chips,
        )

    def scaled(self, n: int) -> "ResourceSpec":
        return ResourceSpec(self.cpu_milli * n, self.mem_mega * n, self.tpu_chips * n)

    def to_dict(self) -> Dict[str, Union[str, int]]:
        """Canonical-quantity mapping; inverse of parse (zeros omitted)."""
        out: Dict[str, Union[str, int]] = {}
        if self.cpu_milli:
            out["cpu"] = f"{self.cpu_milli}m"
        if self.mem_mega:
            out["memory"] = f"{self.mem_mega}M"
        if self.tpu_chips:
            out["tpu"] = self.tpu_chips
        return out


@dataclass
class ResourceRequirements:
    """requests/limits pair (reference: corev1.ResourceRequirements usage at
    pkg/apis/paddlepaddle/v1/types.go:72-90)."""

    requests: ResourceSpec = field(default_factory=ResourceSpec)
    limits: ResourceSpec = field(default_factory=ResourceSpec)

    @classmethod
    def parse(cls, d: Mapping) -> "ResourceRequirements":
        if d is None:
            return cls()
        return cls(
            requests=ResourceSpec.parse(d.get("requests")),
            limits=ResourceSpec.parse(d.get("limits")),
        )

    def to_dict(self) -> Dict[str, Dict]:
        out: Dict[str, Dict] = {}
        if self.requests.to_dict():
            out["requests"] = self.requests.to_dict()
        if self.limits.to_dict():
            out["limits"] = self.limits.to_dict()
        return out


def add_resource_list(dst: Dict[str, float], src: Mapping[str, float]) -> None:
    """Accumulate a resource map into ``dst`` in place
    (reference: pkg/utils.go:23-34 ``AddResourceList``)."""
    for k, v in src.items():
        dst[k] = dst.get(k, 0) + v
