"""TrainingJob — the declarative job spec (L0 API types).

TPU-native redesign of the reference's TrainingJob CRD/TPR
(reference: pkg/apis/paddlepaddle/v1/types.go:36-173,
 pkg/resource/training_job.go:109-238). Differences, by design:

- ``WorkerSpec`` (the trainer analog) asks for **TPU chips per worker**
  instead of GPU limits; an ``accelerator_type`` names the slice family
  (e.g. "v5e"). The elastic range stays ``min_replicas``/``max_replicas``
  (reference: min-instance/max-instance, types.go:86-87).
- ``PserverSpec`` is accepted for spec compatibility but maps to no
  runtime process: optimizer/parameter state is sharded in-mesh
  (FSDP/ZeRO over the ``jax.sharding.Mesh``). A non-zero pserver group
  is tolerated and reported in validation warnings.
- ``MasterSpec`` becomes the **coordinator**: the process that owns the
  membership registry, barrier, task queue and reshard signaling
  (replaces the reference's master + etcd sidecar,
  reference: pkg/jobparser.go:167-227).
- ``mesh`` describes the parallelism plan (dp/fsdp/tp/pp/sp/ep axis
  sizes) — new, first-class; the reference only has pserver DP.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from edl_tpu.api.resources import ResourceRequirements, ResourceSpec

try:
    import yaml  # type: ignore

    _HAVE_YAML = True
# edl: no-lint[silent-failure] optional-dependency probe; JSON manifests work without yaml
except Exception:  # pragma: no cover
    _HAVE_YAML = False

API_VERSION = "edl-tpu.org/v1"
KIND = "TrainingJob"

DEFAULT_PORT = 7164  # reference: pkg/jobparser.go:50-51
# default image for jobs that omit spec.image (reference default image,
# jobparser.go:59-60); docker/build.sh builds this tag
DEFAULT_IMAGE = "edl-tpu/worker:latest"
DEFAULT_PASSES = 1  # reference: pkg/jobparser.go:62-63
DEFAULT_ACCELERATOR = "v5e"


class JobPhase(str, enum.Enum):
    """Job lifecycle phase (reference: pkg/apis/paddlepaddle/v1/types.go:95-106,
    plus ``SCALING`` to surface in-place reshard — new in the TPU design)."""

    NONE = ""
    CREATING = "creating"
    RUNNING = "running"
    SCALING = "scaling"
    SUCCEEDED = "succeeded"
    FAILED = "failed"

    def terminal(self) -> bool:
        return self in (JobPhase.SUCCEEDED, JobPhase.FAILED)


class ResourceState(str, enum.Enum):
    """Per-child-resource state (reference: types.go:141-148)."""

    NONE = ""
    CREATING = "creating"
    READY = "ready"
    SUCCEEDED = "succeeded"
    FAILED = "failed"


@dataclass
class VolumeSpec:
    """A named pod volume (reference: TrainingJobSpec.Volumes,
    pkg/apis/paddlepaddle/v1/types.go:54). ``source`` is the k8s volume
    source passed through verbatim (hostPath, persistentVolumeClaim,
    emptyDir, configMap, …) — typed enough to validate, open enough to
    carry any cluster's storage."""

    name: str
    source: Dict[str, Any] = field(default_factory=dict)


@dataclass
class VolumeMountSpec:
    """Where a declared volume lands in every job pod (reference:
    TrainingJobSpec.VolumeMounts, types.go:55-56 — mounted into master,
    pserver, and trainer pods alike; here: coordinator + workers)."""

    name: str
    mount_path: str
    read_only: bool = False


@dataclass
class MasterSpec:
    """Coordinator spec (reference: MasterSpec, types.go:67-72). The
    etcd-endpoint field becomes the coordinator address."""

    coordinator_endpoint: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)


@dataclass
class PserverSpec:
    """Accepted for reference-spec compatibility (types.go:75-81); the TPU
    runtime shards parameters/optimizer state in-mesh instead."""

    min_replicas: int = 0
    max_replicas: int = 0
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)


# Axes over which a batch is split (each shard sees different examples);
# consumed by parallel/mesh.py. Lives here (the jax-free API layer) so
# manifest validation and mesh construction share one definition — only
# these axes may be a MeshSpec growth axis.
BATCH_AXES: tuple = ("dp", "fsdp")


@dataclass
class MeshSpec:
    """Parallelism plan: per-axis sizes of the device mesh each worker set
    trains over. 0/absent axes are squeezed. New in the TPU design (the
    reference's only strategy is pserver DP, SURVEY §2.5)."""

    dp: int = 0  # data parallel (pure replication)
    fsdp: int = 0  # fully-sharded DP (ZeRO-3 analog)
    tp: int = 0  # tensor parallel
    pp: int = 0  # pipeline parallel
    sp: int = 0  # sequence/context parallel (ring attention)
    ep: int = 0  # expert parallel (MoE)
    # which axis absorbs elastic membership change (the others keep
    # their pinned sizes across rescales); "dp" for pure replication
    # growth, "fsdp" for the flagship ZeRO-3-growth config
    growth: str = "dp"

    def axis_sizes(self) -> Dict[str, int]:
        return {
            k: v
            for k, v in (
                ("dp", self.dp),
                ("fsdp", self.fsdp),
                ("tp", self.tp),
                ("pp", self.pp),
                ("sp", self.sp),
                ("ep", self.ep),
            )
            if v > 1
        }

    def to_mesh_string(self) -> str:
        """The EDL_MESH env value (MeshPlan.parse grammar): pinned axes
        as ``axis=K`` terms plus the bare growth axis."""
        terms = [
            f"{k}={v}" for k, v in self.axis_sizes().items() if k != self.growth
        ]
        return ",".join([self.growth] + terms)


@dataclass
class WorkerSpec:
    """Elastic worker group (the trainer analog, reference:
    TrainerSpec types.go:84-92). Each worker is one host process driving
    ``tpu_chips`` chips; the elastic range is in workers."""

    entrypoint: str = ""
    workspace: str = ""
    min_replicas: int = 1
    max_replicas: int = 1
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)

    @property
    def chips_per_worker(self) -> int:
        return self.resources.limits.tpu_chips or self.resources.requests.tpu_chips


@dataclass
class TrainingJobSpec:
    """reference: TrainingJobSpec types.go:44-64."""

    image: str = ""
    host_network: bool = False
    port: int = 0
    ports_num: int = 0
    fault_tolerant: bool = False
    passes: int = 0
    accelerator_type: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    mesh: MeshSpec = field(default_factory=MeshSpec)
    # shared checkpoint store (a mounted volume path in pods) and the
    # periodic sharded-commit cadence in steps. Required for fsdp-growth
    # jobs: a crashed peer's primary shards only survive in the last
    # committed checkpoint. 0 = commit only at reshard/stop.
    checkpoint_dir: str = ""
    checkpoint_every: int = 0
    # on-disk dataset root (runtime/shards.py manifest layout), usually
    # under a volume mount — workers then train on real files through
    # the lease queue instead of synthetic batches
    data_dir: str = ""
    # extra worker environment (the runtime's EDL_* contract beyond
    # what the parser derives: EDL_MODEL, EDL_SYNC_EVERY, EDL_P2P,
    # EDL_EVAL_DIR, EDL_INT8_MXU, ... — worker_config.py is the full
    # list). Derived contract keys always win over these (validate()
    # warns on the collision); accepts a mapping or the k8s
    # [{name, value}] list form in YAML.
    env: Dict[str, str] = field(default_factory=dict)
    # pod volumes + mounts (reference: types.go:54-56) — how real jobs
    # see datasets and checkpoint stores
    volumes: List[VolumeSpec] = field(default_factory=list)
    volume_mounts: List[VolumeMountSpec] = field(default_factory=list)
    master: MasterSpec = field(default_factory=MasterSpec)
    pserver: PserverSpec = field(default_factory=PserverSpec)
    worker: WorkerSpec = field(default_factory=WorkerSpec)


@dataclass
class ResourceStatus:
    state: ResourceState = ResourceState.NONE
    replicas: int = 0
    ready_replicas: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class TrainingJobStatus:
    """reference: TrainingJobStatus types.go:151-173."""

    phase: JobPhase = JobPhase.NONE
    reason: str = ""
    master: ResourceStatus = field(default_factory=ResourceStatus)
    worker: ResourceStatus = field(default_factory=ResourceStatus)
    parallelism: int = 0  # current worker target (trainer Job .Spec.Parallelism analog)
    reshard_count: int = 0  # elastic reshard events so far (new: observability)
    last_reshard_stall_s: float = 0.0
    # reshards that fell back to host-RAM staging (the slow path whose
    # worst case doc/reshard_stall.md bounds) — a monitor alarm signal
    reshard_fallbacks: int = 0


def _env_value(v) -> str:
    """YAML scalar -> the EDL_* contract's string form. Booleans map to
    the contract's "1"/"0" — str(False) would be "False", which e.g.
    worker_config's ``!= "0"`` / ``== "1"`` checks silently misread
    (EDL_P2P: false would leave p2p ON)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    return str(v)


def _parse_env(raw) -> Dict[str, str]:
    """spec.env from YAML: a plain mapping, or the k8s container-style
    ``[{name, value}]`` list (what users paste from pod specs — but
    ONLY that shape: ``valueFrom`` etc. are hard errors, not silent
    empty strings). Scalars stringify (``EDL_INT8_MXU: 1`` -> "1",
    booleans -> "1"/"0")."""
    if not raw:
        return {}
    if isinstance(raw, list):
        out: Dict[str, str] = {}
        for e in raw:
            if (
                not isinstance(e, dict)
                or not e.get("name")
                or set(e) - {"name", "value"}
            ):
                raise ValueError(
                    "env list entries must be exactly {name, value} "
                    f"(k8s valueFrom etc. are not supported), got {e!r}"
                )
            out[str(e["name"])] = _env_value(e.get("value", ""))
        return out
    if isinstance(raw, dict):
        return {str(k): _env_value(v) for k, v in raw.items()}
    raise ValueError(
        f"spec.env must be a mapping or a [{{name, value}}] list, "
        f"got {type(raw).__name__}"
    )


def qualify(namespace: str, name: str) -> str:
    """Qualified job identity from (namespace, name) — the one rule
    behind ``TrainingJob.qualified_name``, shared by cluster backends
    that must address updaters without holding a TrainingJob (e.g.
    scale-listener notifications)."""
    if namespace in ("", "default"):
        return name
    return f"{namespace}/{name}"


@dataclass
class TrainingJob:
    """The job object: metadata + spec + status
    (reference: types.go:36-42)."""

    name: str
    namespace: str = "default"
    spec: TrainingJobSpec = field(default_factory=TrainingJobSpec)
    status: TrainingJobStatus = field(default_factory=TrainingJobStatus)
    labels: Dict[str, str] = field(default_factory=dict)

    @property
    def qualified_name(self) -> str:
        """Collision-free identity across namespaces. Bare name in the
        default namespace (so single-namespace callers and logs stay
        readable), ``namespace/name`` elsewhere — same-named jobs in
        different namespaces must not share controller/autoscaler
        state."""
        return qualify(self.namespace, self.name)

    # -- predicates (reference: pkg/resource/training_job.go:189-207) ------

    def elastic(self) -> bool:
        """True when the worker range is elastic (min < max)."""
        return self.spec.worker.min_replicas < self.spec.worker.max_replicas

    def need_tpu(self) -> bool:
        """TPU analog of NeedGPU (reference: training_job.go:205-207)."""
        return self.spec.worker.chips_per_worker > 0

    def chips_per_worker(self) -> int:
        return self.spec.worker.chips_per_worker

    # -- construction ------------------------------------------------------

    @classmethod
    def from_dict(cls, d: dict) -> "TrainingJob":
        """Build from a parsed YAML/JSON manifest mirroring the reference's
        examplejob.yaml shape (reference: example/fit_a_line/examplejob.yaml)."""
        meta = d.get("metadata", {})
        spec_d = d.get("spec", {})
        worker_d = spec_d.get("worker", spec_d.get("trainer", {})) or {}
        pserver_d = spec_d.get("pserver", {}) or {}
        master_d = spec_d.get("master", {}) or {}
        mesh_d = spec_d.get("mesh", {}) or {}

        def _minmax(g: dict, lo_default=0, hi_default=0):
            lo = g.get("min_replicas", g.get("min-instance", lo_default))
            hi = g.get("max_replicas", g.get("max-instance", hi_default))
            return int(lo), int(hi)

        wmin, wmax = _minmax(worker_d, 1, 0)
        pmin, pmax = _minmax(pserver_d)
        mesh_fields = {f for f in MeshSpec.__dataclass_fields__}
        bad_axes = set(mesh_d) - mesh_fields
        if bad_axes:
            raise ValueError(
                f"unknown mesh axes {sorted(bad_axes)}; valid: {sorted(mesh_fields)}"
            )
        try:
            growth = str(mesh_d.get("growth", "dp"))
            mesh = MeshSpec(
                growth=growth,
                **{k: int(v) for k, v in mesh_d.items() if k != "growth"},
            )
        except (TypeError, ValueError) as e:
            raise ValueError(f"invalid mesh spec {mesh_d!r}: {e}") from e
        if mesh.growth not in BATCH_AXES:
            # only batch axes can absorb elastic membership change (see
            # MeshPlan.parse); tp/pp/sp/ep growth would silently change
            # per-process batch rows under a fixed queue chunk
            raise ValueError(
                f"mesh growth axis must be one of {BATCH_AXES}, "
                f"got {mesh.growth!r}"
            )
        if mesh.axis_sizes().get(mesh.growth):
            raise ValueError(
                f"mesh axis {mesh.growth!r} is the growth axis; its size is "
                "set by the elastic worker count, not the manifest — remove "
                f"the pinned size or change 'growth'"
            )
        spec = TrainingJobSpec(
            image=spec_d.get("image", ""),
            host_network=bool(spec_d.get("host_network", False)),
            port=int(spec_d.get("port", 0)),
            ports_num=int(spec_d.get("ports_num", 0)),
            fault_tolerant=bool(spec_d.get("fault_tolerant", False)),
            passes=int(spec_d.get("passes", worker_d.get("passes", 0))),
            accelerator_type=spec_d.get("accelerator_type", ""),
            node_selector=dict(spec_d.get("node_selector", {})),
            mesh=mesh,
            checkpoint_dir=spec_d.get("checkpoint_dir", ""),
            checkpoint_every=int(spec_d.get("checkpoint_every", 0)),
            data_dir=spec_d.get("data_dir", ""),
            env=_parse_env(spec_d.get("env")),
            volumes=[
                VolumeSpec(
                    name=v.get("name", ""),
                    source={k: val for k, val in v.items() if k != "name"},
                )
                for v in spec_d.get("volumes", []) or []
            ],
            volume_mounts=[
                VolumeMountSpec(
                    name=m.get("name", ""),
                    mount_path=m.get("mount_path", m.get("mountPath", "")),
                    read_only=bool(m.get("read_only", m.get("readOnly", False))),
                )
                for m in (
                    spec_d.get("volume_mounts", spec_d.get("volumeMounts", []))
                    or []
                )
            ],
            master=MasterSpec(
                coordinator_endpoint=master_d.get(
                    "coordinator_endpoint", master_d.get("etcd-endpoint", "")
                ),
                resources=ResourceRequirements.parse(master_d.get("resources")),
            ),
            pserver=PserverSpec(
                min_replicas=pmin,
                max_replicas=pmax,
                resources=ResourceRequirements.parse(pserver_d.get("resources")),
            ),
            worker=WorkerSpec(
                entrypoint=worker_d.get("entrypoint", ""),
                workspace=worker_d.get("workspace", ""),
                min_replicas=wmin,
                max_replicas=wmax,
                resources=ResourceRequirements.parse(worker_d.get("resources")),
            ),
        )
        return cls(
            name=meta.get("name", d.get("name", "")),
            namespace=meta.get("namespace", "default"),
            labels=dict(meta.get("labels", {})),
            spec=spec,
        )

    def to_dict(self) -> dict:
        """Canonical manifest mapping; inverse of from_dict (legacy
        reference-era aliases are normalized away)."""
        s = self.spec
        worker: dict = {
            "min_replicas": s.worker.min_replicas,
            "max_replicas": s.worker.max_replicas,
        }
        if s.worker.entrypoint:
            worker["entrypoint"] = s.worker.entrypoint
        if s.worker.workspace:
            worker["workspace"] = s.worker.workspace
        if s.worker.resources.to_dict():
            worker["resources"] = s.worker.resources.to_dict()
        spec: dict = {"worker": worker}
        if s.image:
            spec["image"] = s.image
        if s.host_network:
            spec["host_network"] = True
        if s.port:
            spec["port"] = s.port
        if s.ports_num:
            spec["ports_num"] = s.ports_num
        if s.fault_tolerant:
            spec["fault_tolerant"] = True
        if s.passes:
            spec["passes"] = s.passes
        if s.accelerator_type:
            spec["accelerator_type"] = s.accelerator_type
        if s.node_selector:
            spec["node_selector"] = dict(s.node_selector)
        mesh = {k: v for k, v in s.mesh.axis_sizes().items()}
        if s.mesh.growth != "dp":
            mesh["growth"] = s.mesh.growth
        if mesh:
            spec["mesh"] = mesh
        if s.checkpoint_dir:
            spec["checkpoint_dir"] = s.checkpoint_dir
        if s.checkpoint_every:
            spec["checkpoint_every"] = s.checkpoint_every
        if s.data_dir:
            spec["data_dir"] = s.data_dir
        if s.env:
            spec["env"] = dict(s.env)
        if s.volumes:
            spec["volumes"] = [
                {"name": v.name, **v.source} for v in s.volumes
            ]
        if s.volume_mounts:
            spec["volume_mounts"] = [
                {
                    "name": m.name,
                    "mount_path": m.mount_path,
                    **({"read_only": True} if m.read_only else {}),
                }
                for m in s.volume_mounts
            ]
        master: dict = {}
        if s.master.coordinator_endpoint:
            master["coordinator_endpoint"] = s.master.coordinator_endpoint
        if s.master.resources.to_dict():
            master["resources"] = s.master.resources.to_dict()
        if master:
            spec["master"] = master
        if s.pserver.min_replicas or s.pserver.max_replicas:
            spec["pserver"] = {
                "min_replicas": s.pserver.min_replicas,
                "max_replicas": s.pserver.max_replicas,
            }
        meta: dict = {"name": self.name, "namespace": self.namespace}
        if self.labels:
            meta["labels"] = dict(self.labels)
        return {"metadata": meta, "spec": spec}

    @classmethod
    def from_yaml(cls, text: str) -> "TrainingJob":
        if not _HAVE_YAML:  # pragma: no cover
            raise RuntimeError("pyyaml unavailable")
        data = yaml.safe_load(text)
        if not isinstance(data, dict):
            raise ValueError(
                f"manifest must be a YAML mapping, got {type(data).__name__}"
            )
        return cls.from_dict(data)

    @classmethod
    def from_yaml_file(cls, path: str) -> "TrainingJob":
        with open(path) as f:
            return cls.from_yaml(f.read())


@dataclass
class Event:
    """Controller→autoscaler/updater event
    (reference: pkg/autoscaler.go:141-152)."""

    class Type(str, enum.Enum):
        ADD = "add"
        DEL = "del"
        UPDATE = "update"
        SCALE = "scale"

    type: "Event.Type"
    job: Optional[TrainingJob] = None


__all__ = [
    "API_VERSION",
    "KIND",
    "Event",
    "JobPhase",
    "MasterSpec",
    "MeshSpec",
    "PserverSpec",
    "ResourceRequirements",
    "ResourceState",
    "ResourceStatus",
    "ResourceSpec",
    "TrainingJob",
    "TrainingJobSpec",
    "TrainingJobStatus",
    "WorkerSpec",
]
