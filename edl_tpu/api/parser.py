"""JobParser — defaulting, validation, and TrainingJob → child-resource plans.

L2 of the layer map. The reference parses a TrainingJob into a master
ReplicaSet + pserver ReplicaSet + trainer batch Job
(reference: pkg/jobparser.go:36-41,47-71; pkg/updater/jobparser.go:40-64).
The TPU design parses into two plans:

- ``CoordinatorPlan`` — one coordinator process (master analog; owns
  membership, barriers, the elastic data queue, reshard signaling).
- ``WorkerGroupPlan`` — the elastic worker set, parallelism starting at
  ``min_replicas`` (reference: ParseToTrainer sets Parallelism=min,
  jobparser.go:120-128).

There is no pserver plan: parameter/optimizer state lives in-mesh.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from edl_tpu.api.job import (
    DEFAULT_ACCELERATOR,
    DEFAULT_IMAGE,
    DEFAULT_PASSES,
    DEFAULT_PORT,
    TrainingJob,
    VolumeMountSpec,
    VolumeSpec,
)


class ValidationError(ValueError):
    pass


@dataclass
class CoordinatorPlan:
    """Spec for the per-job coordinator process (replaces master RS +
    etcd sidecar, reference: pkg/jobparser.go:186-227)."""

    name: str
    namespace: str
    image: str
    port: int
    labels: Dict[str, str] = field(default_factory=dict)
    cpu_milli: int = 0
    mem_mega: int = 0
    volumes: List[VolumeSpec] = field(default_factory=list)
    volume_mounts: List[VolumeMountSpec] = field(default_factory=list)


@dataclass
class WorkerGroupPlan:
    """Spec for the elastic worker set (trainer batch Job analog,
    reference: pkg/jobparser.go:119-165)."""

    name: str
    namespace: str
    image: str
    entrypoint: str
    workspace: str
    parallelism: int
    min_replicas: int
    max_replicas: int
    chips_per_worker: int
    accelerator_type: str
    cpu_milli: int = 0
    mem_mega: int = 0
    fault_tolerant: bool = False
    passes: int = 1
    labels: Dict[str, str] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    restart_policy: str = "Never"  # reference: jobparser.go:160
    volumes: List[VolumeSpec] = field(default_factory=list)
    volume_mounts: List[VolumeMountSpec] = field(default_factory=list)


class JobParser:
    """Default parser (reference: DefaultJobParser, pkg/jobparser.go:43)."""

    def validate(self, job: TrainingJob) -> List[str]:
        """Fill defaults, enforce invariants; returns non-fatal warnings.

        Defaulting mirrors reference pkg/jobparser.go:47-65; the
        ``elastic ⇒ fault_tolerant`` rule mirrors jobparser.go:66-68.
        TPU additions: chips per worker must be a power of two (ICI
        slice legality) and an accelerator type is defaulted.
        """
        warnings: List[str] = []
        s = job.spec
        if not job.name:
            raise ValidationError("job name is required")
        if s.port == 0:
            s.port = DEFAULT_PORT
        if s.ports_num == 0:
            s.ports_num = 1
        if not s.image:
            s.image = DEFAULT_IMAGE
        if s.passes == 0:
            s.passes = DEFAULT_PASSES
        if not s.accelerator_type:
            s.accelerator_type = DEFAULT_ACCELERATOR
        w = s.worker
        if w.min_replicas <= 0:
            w.min_replicas = 1
        if w.max_replicas == 0:
            w.max_replicas = w.min_replicas
        if w.max_replicas < w.min_replicas:
            raise ValidationError(
                f"worker max_replicas ({w.max_replicas}) < min_replicas ({w.min_replicas})"
            )
        if job.elastic() and not s.fault_tolerant:
            # reference: pkg/jobparser.go:66-68
            raise ValidationError(
                "max_replicas must equal min_replicas when fault_tolerant is disabled"
            )
        chips = w.chips_per_worker
        if chips and chips & (chips - 1):
            raise ValidationError(
                f"tpu_chips per worker must be a power of two (got {chips})"
            )
        if s.pserver.min_replicas or s.pserver.max_replicas:
            warnings.append(
                "pserver group is ignored on TPU: parameter/optimizer state is "
                "sharded in-mesh (FSDP); remove spec.pserver"
            )
        if s.env:
            # keys the parser derives always win over spec.env — flag
            # the collision instead of silently dropping the user value
            shadowed = sorted(set(s.env) & set(self._derived_env(job)))
            if shadowed:
                warnings.append(
                    f"spec.env keys {shadowed} are derived by the parser "
                    "and will be overridden; set them through the spec "
                    "fields instead"
                )
        mesh_total = 1
        for v in s.mesh.axis_sizes().values():
            mesh_total *= v
        if chips and mesh_total > 1 and mesh_total % chips != 0 and chips % mesh_total:
            warnings.append(
                f"mesh plan ({mesh_total} devices) does not tile chips/worker ({chips})"
            )
        if job.elastic() and s.mesh.growth == "fsdp" and not s.checkpoint_dir:
            raise ValidationError(
                "elastic fsdp-growth jobs require spec.checkpoint_dir: state "
                "is sharded across workers, so rescale/recovery needs a "
                "shared checkpoint store"
            )
        # volumes/mounts (reference: types.go:54-56, plumbed into every
        # pod by the parsers)
        vol_names = [v.name for v in s.volumes]
        if len(vol_names) != len(set(vol_names)):
            raise ValidationError(f"duplicate volume names: {vol_names}")
        for v in s.volumes:
            if not v.name:
                raise ValidationError("volume without a name")
            if not v.source:
                raise ValidationError(f"volume {v.name!r} has no source")
        for m in s.volume_mounts:
            if m.name not in vol_names:
                raise ValidationError(
                    f"volume_mount {m.name!r} references no declared volume"
                )
            if not m.mount_path.startswith("/"):
                raise ValidationError(
                    f"volume_mount {m.name!r} mount_path must be absolute, "
                    f"got {m.mount_path!r}"
                )
        def _under_a_mount(path: str) -> bool:
            return any(
                path.startswith(m.mount_path.rstrip("/") + "/")
                or path == m.mount_path
                for m in s.volume_mounts
            )

        if s.checkpoint_dir and s.volumes and not _under_a_mount(s.checkpoint_dir):
            warnings.append(
                f"checkpoint_dir {s.checkpoint_dir!r} is not under any "
                "volume mount; workers may write to ephemeral pod storage"
            )
        if s.data_dir and s.volumes and not _under_a_mount(s.data_dir):
            warnings.append(
                f"data_dir {s.data_dir!r} is not under any volume mount; "
                "workers will find no dataset manifest at startup"
            )
        return warnings

    # -- plan builders -----------------------------------------------------

    def parse_to_coordinator(self, job: TrainingJob) -> CoordinatorPlan:
        """reference: ParseToMaster pkg/jobparser.go:186-227."""
        s = job.spec
        return CoordinatorPlan(
            name=f"{job.name}-coordinator",
            namespace=job.namespace,
            image=s.image,
            port=s.port,
            labels={"edl-job-coordinator": job.name},
            cpu_milli=s.master.resources.requests.cpu_milli,
            mem_mega=s.master.resources.requests.mem_mega,
            volumes=list(s.volumes),
            volume_mounts=list(s.volume_mounts),
        )

    def parse_to_workers(self, job: TrainingJob) -> WorkerGroupPlan:
        """reference: ParseToTrainer pkg/jobparser.go:119-165."""
        s = job.spec
        w = s.worker
        return WorkerGroupPlan(
            name=f"{job.name}-worker",
            namespace=job.namespace,
            image=s.image,
            entrypoint=w.entrypoint,
            workspace=w.workspace,
            parallelism=w.min_replicas,
            min_replicas=w.min_replicas,
            max_replicas=w.max_replicas,
            chips_per_worker=w.chips_per_worker,
            accelerator_type=s.accelerator_type,
            cpu_milli=w.resources.requests.cpu_milli,
            mem_mega=w.resources.requests.mem_mega,
            fault_tolerant=s.fault_tolerant,
            passes=s.passes,
            labels={"edl-job": job.name},
            env=self.pod_env(job),
            volumes=list(s.volumes),
            volume_mounts=list(s.volume_mounts),
        )

    def pod_env(self, job: TrainingJob) -> Dict[str, str]:
        """Env-var contract injected into every worker
        (reference: podEnv pkg/jobparser.go:263-311). TPU renames:
        EDL_* replaces PADDLE_INIT_*; the coordinator address replaces
        etcd discovery.

        ``spec.env`` rides underneath: the per-job runtime knobs the
        parser does NOT derive (EDL_MODEL, EDL_SYNC_EVERY, EDL_P2P*,
        EDL_EVAL_*, EDL_INT8_MXU, ...). Derived keys always win — a
        manifest overriding EDL_WORKERS_MIN would desync the
        autoscaler from the runtime (validate() warns on collisions).
        """
        return {**job.spec.env, **self._derived_env(job)}

    def _derived_env(self, job: TrainingJob) -> Dict[str, str]:
        """The contract keys the parser itself derives from the spec —
        the reserved set spec.env can never override."""
        s = job.spec
        return {
            "EDL_JOB_NAME": job.name,
            "EDL_NAMESPACE": job.namespace,
            "EDL_WORKERS": str(s.worker.min_replicas),
            "EDL_WORKERS_MIN": str(s.worker.min_replicas),
            "EDL_WORKERS_MAX": str(s.worker.max_replicas),
            "EDL_ENTRY": s.worker.entrypoint,
            "EDL_WORKSPACE": s.worker.workspace,
            "EDL_PORT": str(s.port),
            "EDL_CHIPS_PER_WORKER": str(s.worker.chips_per_worker),
            "EDL_ACCELERATOR": s.accelerator_type,
            "EDL_NUM_PASSES": str(s.passes),
            "EDL_FAULT_TOLERANT": "1" if s.fault_tolerant else "0",
            "EDL_MESH": s.mesh.to_mesh_string(),
            "EDL_CKPT_DIR": s.checkpoint_dir,
            "EDL_CKPT_EVERY": str(s.checkpoint_every),
            "EDL_DATA_DIR": s.data_dir,
            "EDL_COORDINATOR": s.master.coordinator_endpoint
            or f"{job.name}-coordinator:{s.port}",
        }
